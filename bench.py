"""Benchmark: llama causal-LM training throughput on one TPU chip.

Tracks BASELINE.md config 3 (llama pretraining, tokens/sec/chip + MFU).
The reference publishes no in-tree numbers (BASELINE.md — "published": {});
vs_baseline is therefore measured against the north-star target 40% MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

PEAK_BF16 = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def chip_peak_flops():
    if "PEAK_FLOPS" in os.environ:
        return float(os.environ["PEAK_FLOPS"])
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for k, v in PEAK_BF16.items():
        if k in gen:
            return v
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
        if "v5 lite" in kind or "v5e" in kind:
            return PEAK_BF16["v5e"]
        if "v5p" in kind or "v5" in kind:
            return PEAK_BF16["v5p"]
        if "v4" in kind:
            return PEAK_BF16["v4"]
        if "v6" in kind:
            return PEAK_BF16["v6e"]
    except Exception:
        pass
    return PEAK_BF16["v5e"]


def main():
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    if on_tpu:
        # 1.0B-param GQA llama sized for v5e 16G HBM.  Mixed precision
        # the TPU-idiomatic way: fp32 params (the param IS the master —
        # no separate copy) + bf16 compute + bf16 AdamW moments via the
        # fused Pallas kernel → resident state 8.0G, leaving ~6G for
        # activations.  That budget lets most layers skip recompute
        # entirely; the rest use SELECTIVE recompute (save q/k/v +
        # attention output + mid-residual; replay only the MLP matmuls
        # and the flash-attn forward).  Sharding stage 3 (no-op on 1
        # chip, but the exact north-star code path: BASELINE.md cfg 3).
        n_sel = int(os.environ.get("BENCH_RECOMPUTE_LAYERS", "8"))
        cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                          intermediate_size=6912, num_hidden_layers=14,
                          num_attention_heads=20, num_key_value_heads=4,
                          max_position_embeddings=2048, dtype="bfloat16",
                          param_dtype="float32",
                          recompute=n_sel > 0, recompute_layers=n_sel,
                          recompute_granularity="selective")
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq, steps = 2048, 8
    else:  # CPU smoke path so the script always runs
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, dtype="float32")
        batch, seq, steps = 2, 128, 3
        n_sel = 0

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters(),
                                 weight_decay=0.1,
                                 moment_dtype="bfloat16" if on_tpu else None)
    mesh = build_mesh(devices=jax.devices()[:1])
    step = ShardedTrainStep(model, opt, mesh, sharding_stage=3,
                            rematerialize=False)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)

    # warmup / compile (host transfer forces completion: the axon relay's
    # block_until_ready does not synchronize remote execution)
    loss = step(x, x)
    _ = float(np.asarray(loss.value))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, x)
    final_loss = float(np.asarray(loss.value))
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    model_flops = 6.0 * n_params * tokens_per_sec  # fwd+bwd dense decoder
    peak = chip_peak_flops()
    mfu = model_flops / peak
    # hardware utilization: each selectively-recomputed layer replays
    # the flash-attn forward + the gate/up MLP matmuls in the backward
    recompute_per_tok = n_sel * (2.0 * seq * cfg.num_attention_heads
                                 * cfg.head_dim
                                 + 4.0 * cfg.hidden_size
                                 * cfg.intermediate_size)
    hw_util = mfu * (6.0 * n_params + recompute_per_tok) / (6.0 * n_params)

    result = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": f"tokens/s/chip (mfu={mfu:.3f}, hw_util={hw_util:.3f}, "
                f"params={n_params/1e6:.0f}M, loss={final_loss:.3f})",
        "vs_baseline": round(mfu / 0.40, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
