"""Benchmark: llama causal-LM training throughput on one TPU chip.

Tracks BASELINE.md config 3 (llama pretraining, tokens/sec/chip + MFU).
The reference publishes no in-tree numbers (BASELINE.md — "published": {});
vs_baseline is therefore measured against the north-star target 40% MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

PEAK_BF16 = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def chip_peak_flops():
    if "PEAK_FLOPS" in os.environ:
        return float(os.environ["PEAK_FLOPS"])
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for k, v in PEAK_BF16.items():
        if k in gen:
            return v
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
        if "v5 lite" in kind or "v5e" in kind:
            return PEAK_BF16["v5e"]
        if "v5p" in kind or "v5" in kind:
            return PEAK_BF16["v5p"]
        if "v4" in kind:
            return PEAK_BF16["v4"]
        if "v6" in kind:
            return PEAK_BF16["v6e"]
    except Exception:
        pass
    return PEAK_BF16["v5e"]


def bench_llama():
    """BASELINE.md config 3: llama pretraining tokens/s/chip + MFU."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    offload = on_tpu and os.environ.get("BENCH_OFFLOAD", "") \
        not in ("", "0")
    if on_tpu:
        # 1.0B-param GQA llama sized for v5e 16G HBM.  Mixed precision
        # the TPU-idiomatic way: fp32 params (the param IS the master —
        # no separate copy) + bf16 compute + bf16 AdamW moments via the
        # fused Pallas kernel → resident state 8.0G, leaving ~6G for
        # activations.  That budget lets most layers skip recompute
        # entirely; the rest use SELECTIVE recompute (save q/k/v +
        # attention output + mid-residual; replay only the MLP matmuls
        # and the flash-attn forward).  Sharding stage 3 (no-op on 1
        # chip, but the exact north-star code path: BASELINE.md cfg 3).
        # r4 sweep: 3 selective-remat layers is the throughput/gap
        # sweet spot (mfu 0.538, hw_util-mfu 0.019); fewer layers OOM-
        # pressures XLA into slower schedules (0.522 at 0/2), more
        # layers replay needless matmuls (0.532 at 8)
        n_sel = int(os.environ.get("BENCH_RECOMPUTE_LAYERS", "3"))
        if offload:
            # 2.0B params — ~2x the fp32-params-resident ceiling.  bf16
            # params on device; fp32 master + moments parked in pinned
            # host memory and streamed through HBM inside the step
            # (ShardedTrainStep offload=True).
            cfg = LlamaConfig(vocab_size=8192, hidden_size=3584,
                              intermediate_size=9600,
                              num_hidden_layers=14,
                              num_attention_heads=28,
                              num_key_value_heads=4,
                              max_position_embeddings=2048,
                              dtype="bfloat16",
                              recompute=True, recompute_layers=None,
                              recompute_granularity="full")
            batch = int(os.environ.get("BENCH_BATCH", "2"))
        else:
            cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                              intermediate_size=6912,
                              num_hidden_layers=14,
                              num_attention_heads=20,
                              num_key_value_heads=4,
                              max_position_embeddings=2048,
                              dtype="bfloat16", param_dtype="float32",
                              recompute=n_sel > 0,
                              recompute_layers=n_sel,
                              recompute_granularity="selective")
            batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq, steps = 2048, 8
    else:  # CPU smoke path so the script always runs
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, dtype="float32")
        batch, seq, steps = 2, 128, 3
        n_sel = 0

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters(),
                                 weight_decay=0.1,
                                 multi_precision=offload,
                                 moment_dtype="bfloat16" if on_tpu
                                 else None)
    mesh = build_mesh(devices=jax.devices()[:1])
    step = ShardedTrainStep(model, opt, mesh, sharding_stage=3,
                            rematerialize=False, offload=offload)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)

    # warmup / compile (host transfer forces completion: the axon relay's
    # block_until_ready does not synchronize remote execution)
    loss = step(x, x)
    _ = float(np.asarray(loss.value))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, x)
    final_loss = float(np.asarray(loss.value))
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    model_flops = 6.0 * n_params * tokens_per_sec  # fwd+bwd dense decoder
    peak = chip_peak_flops()
    mfu = model_flops / peak
    # hardware utilization: each selectively-recomputed layer replays
    # only the gate/up MLP matmuls in the backward.  The q/k/v, o_proj
    # and down_proj matmuls sit in the remat regions too, but their
    # OUTPUTS are saved (region boundaries / resid_mid tag) or unused in
    # the backward, so jax's remat DCE drops them from the replay jaxpr;
    # norms/rope replay with no matmul flops
    if on_tpu and offload:
        # offload config full-remats EVERY layer: backward replays the
        # whole forward (~2N flops/token), not the selective gate/up set
        recompute_per_tok = 2.0 * n_params
    else:
        recompute_per_tok = n_sel * (4.0 * cfg.hidden_size
                                     * cfg.intermediate_size)
    hw_util = mfu * (6.0 * n_params + recompute_per_tok) / (6.0 * n_params)

    result = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": f"tokens/s/chip (mfu={mfu:.3f}, hw_util={hw_util:.3f}, "
                f"params={n_params/1e6:.0f}M, loss={final_loss:.3f})",
        "vs_baseline": round(mfu / 0.40, 3),
    }
    print(json.dumps(result))


def _class_correlated_images(n, num_classes, rng, noise=0.6):
    """Learnable synthetic CIFAR stand-in (zero-egress environment):
    per-class template + gaussian noise — convergence on a held-out
    split is real evidence the training machinery optimizes."""
    import numpy as np
    templates = rng.randn(num_classes, 3, 32, 32).astype(np.float32)
    labels = rng.randint(0, num_classes, n)
    imgs = templates[labels] + noise * rng.randn(n, 3, 32, 32)
    return imgs.astype(np.float32), labels.astype(np.int64)


def bench_resnet():
    """BASELINE.md config 1: ResNet-50 on CIFAR-10-shaped data —
    images/sec + top-1 convergence on a held-out split."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50, resnet18
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    rng = np.random.RandomState(0)
    if on_tpu:
        model = resnet50(num_classes=10)
        batch, n_train, n_test, epochs = 256, 4096, 1024, 3
    else:
        model = resnet18(num_classes=10)
        batch, n_train, n_test, epochs = 32, 64, 32, 1

    xs_all, ys_all = _class_correlated_images(n_train + n_test, 10, rng)
    xs, ys = xs_all[:n_train], ys_all[:n_train]
    xt, yt = xs_all[n_train:], ys_all[n_train:]
    opt = paddle.optimizer.Momentum(0.02, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=1e-4)
    loss_fn = lambda o, y: nn.functional.cross_entropy(o, y)
    step = TrainStep(model, loss_fn, opt)

    steps_per_epoch = n_train // batch
    # pre-stage the whole epoch as [K, b, ...] and fuse the K steps into
    # ONE device program per epoch (TrainStep.run_steps lax.scan):
    # per-step dispatch latency would otherwise dominate CIFAR-sized
    # compute on a tunneled chip
    sx = paddle.to_tensor(
        xs[: steps_per_epoch * batch].reshape(steps_per_epoch, batch,
                                              *xs.shape[1:]))
    sy = paddle.to_tensor(
        ys[: steps_per_epoch * batch].reshape(steps_per_epoch, batch))
    _ = float(np.asarray(step.run_steps(sx, sy).value[-1]))  # compile

    t0 = time.perf_counter()
    seen = 0
    for _ in range(epochs):
        losses = step.run_steps(sx, sy)
        seen += steps_per_epoch * batch
    final_loss = float(np.asarray(losses.value[-1]))
    dt = time.perf_counter() - t0
    images_per_sec = seen / dt

    # held-out top-1 (jitted eval — per-op eager would be host-bound)
    import jax.numpy as jnp
    from paddle_tpu.jit import to_static
    model.eval()
    eval_fwd = to_static(model)
    correct = tot = 0
    for i in range(0, n_test, batch):
        out = eval_fwd(paddle.to_tensor(xt[i:i + batch]))
        pred = np.asarray(jnp.argmax(out.value, axis=-1))
        correct += int((pred == yt[i:i + batch]).sum())
        tot += len(pred)
    top1 = correct / max(1, tot)

    result = {
        "metric": "resnet50_cifar_images_per_sec",
        "value": round(images_per_sec, 1),
        "unit": f"images/s (top1={top1:.3f} heldout after {epochs} "
                f"epochs, loss={final_loss:.3f})",
        "vs_baseline": round(top1 / 0.90, 3),
    }
    print(json.dumps(result))


def bench_bert():
    """BASELINE.md config 2: BERT-base pretraining, DP + sharding
    stage 1 — tokens/s/chip + MFU."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertForMaskedLM, BertConfig
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    paddle.seed(0)
    if on_tpu:
        # fp32 params ARE the masters (nn.set_compute_dtype flax idiom,
        # wired via cfg.dtype) + bf16 AdamW moments — same mixed
        # precision recipe that took llama to 0.537 MFU
        cfg = BertConfig(dtype="bfloat16")
        # b=64 fits now that params are fp32 masters with bf16 compute
        # (no duplicate master copies, bf16 logits): 0.481 MFU vs 0.444
        # at b=32 (r3 baseline: 0.276, b=64 OOMed)
        batch = int(os.environ.get("BENCH_BATCH", "64"))
        seq, steps = 512, 8
    else:
        cfg = BertConfig(vocab_size=128, hidden_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=128,
                         max_position_embeddings=64)
        batch, seq, steps = 2, 32, 2

    model = BertForMaskedLM(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    # fp32 moments: at 110M params the update is cheap, and bf16
    # moments force tail-padding copies on the ragged 23.4M tied
    # embedding (measured 0.379 vs 0.392 MFU)
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01)
    mesh = build_mesh(sharding=1, devices=jax.devices()[:1])
    step = ShardedTrainStep(model, opt, mesh, sharding_stage=1,
                            batch_axes=("dp", "sharding"))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      (steps, batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    # fuse the whole run into one scanned program (run_steps): per-step
    # dispatch latency is paid once
    losses = step.run_steps(x, x)
    _ = float(np.asarray(losses.value[-1]))

    t0 = time.perf_counter()
    losses = step.run_steps(x, x)
    final_loss = float(np.asarray(losses.value[-1]))
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    # encoder fwd+bwd ~ 6*N flops/token (N excl embeddings ~ attention
    # is small at seq 512); use full param count like the llama metric
    mfu = 6.0 * n_params * tokens_per_sec / chip_peak_flops()
    result = {
        "metric": "bert_base_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": f"tokens/s/chip (mfu={mfu:.3f}, "
                f"params={n_params/1e6:.0f}M, loss={final_loss:.3f})",
        "vs_baseline": round(mfu / 0.40, 3),
    }
    print(json.dumps(result))


def bench_unet():
    """BASELINE.md config 5: SD-style conditional UNet —
    epsilon-prediction training samples/sec."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.unet import (UNet2DConditionModel,
                                        unet_sd_config, unet_tiny_config)
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    if on_tpu:
        cfg = unet_sd_config()
        # r4: bf16 compute (fp32 masters) via nn.set_compute_dtype —
        # convs on the MXU at full bf16 rate
        cfg.dtype = os.environ.get("BENCH_UNET_DTYPE", "bfloat16")
        batch, hw, ctx_len, steps = 8, 64, 77, 6
    else:
        cfg = unet_tiny_config()
        batch, hw, ctx_len, steps = 2, 16, 8, 2

    model = UNet2DConditionModel(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    step = TrainStep(model, lambda o, y: model.compute_loss(o, y), opt)

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, cfg.in_channels, hw,
                                   hw).astype(np.float32))
    t = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int32))
    ctx = paddle.to_tensor(rng.randn(batch, ctx_len,
                                     cfg.cross_attention_dim)
                           .astype(np.float32))
    eps = paddle.to_tensor(rng.randn(batch, cfg.out_channels, hw,
                                     hw).astype(np.float32))

    loss = step(x, t, ctx, eps)
    _ = float(np.asarray(loss.value))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, t, ctx, eps)
    final_loss = float(np.asarray(loss.value))
    dt = time.perf_counter() - t0
    samples_per_sec = batch * steps / dt
    result = {
        "metric": "sd_unet_train_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": f"samples/s (params={n_params/1e6:.0f}M, latents "
                f"{hw}x{hw}, loss={final_loss:.3f})",
        "vs_baseline": 1.0,
    }
    print(json.dumps(result))


def bench_llama_decode():
    """Serving decode: KV-cached generate() on the 1B llama — whole
    generation is one jitted lax.scan program (inference/generation.py).
    Reports decode tokens/s/chip."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig

    paddle.seed(0)
    if on_tpu:
        # serving-appropriate bf16 weights (param_dtype unset): the
        # decode roofline below assumes 2 bytes/param, which must match
        # what the step actually reads
        cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                          intermediate_size=6912, num_hidden_layers=14,
                          num_attention_heads=20, num_key_value_heads=4,
                          max_position_embeddings=2048,
                          dtype="bfloat16")
        batch = int(os.environ.get("BENCH_BATCH", "8"))
        prompt_len, new_tokens = 128, 512
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, dtype="float32")
        batch, prompt_len, new_tokens = 2, 8, 16

    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    rng = np.random.RandomState(0)
    prompt = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size,
                    (batch, prompt_len)).astype(np.int32))

    out = model.generate(prompt, max_new_tokens=new_tokens)  # compile
    _ = np.asarray(out.value)
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=new_tokens)
    _ = np.asarray(out.value)
    dt = time.perf_counter() - t0
    tok_s = batch * new_tokens / dt
    # decode roofline: every token reads all params once (bf16 compute
    # stream) → tokens/s ≈ batch · HBM_BW / (2·N) when batched decode
    # is bandwidth-bound
    roofline = batch * 0.82e12 / (2.0 * n_params)
    result = {
        "metric": "llama_decode_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": f"tokens/s/chip (b={batch}, new={new_tokens}, "
                f"params={n_params/1e6:.0f}M, "
                f"hbm_roofline={roofline:.0f} tok/s)",
        "vs_baseline": round(tok_s / max(roofline, 1e-9), 3),
    }
    print(json.dumps(result))


def main():
    which = os.environ.get("BENCH_CONFIG", "llama").lower()
    if which in ("resnet", "resnet50", "cifar"):
        return bench_resnet()
    if which == "bert":
        return bench_bert()
    if which in ("unet", "sd", "diffusion"):
        return bench_unet()
    if which in ("decode", "llama_decode", "generate"):
        return bench_llama_decode()
    return bench_llama()


if __name__ == "__main__":
    main()
