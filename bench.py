"""Benchmarks: all five BASELINE.md configs + serving decode + offload.

Default run (no BENCH_CONFIG) measures EVERY config and prints one JSON
line per config — llama, offload-llama, bert, resnet, unet, decode — so
the driver-captured BENCH file records the full matrix, not just llama
(round-5 verdict item 3).  Each metric is the MEDIAN of BENCH_REPS
(default 3) timed repetitions of the same compiled program, with the
relative spread (max-min)/median reported alongside; compilation happens
once per config, outside the reps.

BENCH_CONFIG=llama|offload|bert|resnet|unet|decode|serve|longctx runs
one config; `python bench.py --only llama_serve_mixed` (metric OR
config name) re-measures a single metric in isolation with the same
reps>=3 + spread discipline.  Reference throughput instrumentation
analog: python/paddle/profiler/timer.py:351 (ips Benchmark).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

def chip_peak_flops():
    """Canonical bf16 peak — ONE table + sniffing for the whole repo
    (telemetry.costledger owns it; the cost ledger's roofline and
    these MFU lines can never quote different peaks).  Keeps bench's
    historic contract: PEAK_FLOPS env override, PALLAS_AXON_TPU_GEN
    relay hint, device sniffing, v5e fallback for smoke lines."""
    from paddle_tpu.telemetry.costledger import chip_peak_flops as _cpf
    return _cpf(default="v5e")


def _reps():
    return max(1, int(os.environ.get("BENCH_REPS", "3")))


_ENV_FP = None


def _env_fingerprint():
    """Environment fingerprint for this capture (ISSUE 12): jax/jaxlib
    versions, backend + device kind, and the bench-relevant flags/envs.
    The perf sentry (tools/perf_report.py) compares metric lines only
    between captures whose fingerprints match — a library bump or a
    flag flip must read as 'incomparable', never as a regression.
    THE derivation lives in telemetry.flightrec (ISSUE 14: incident
    bundles carry the same identity, so a rendered incident matches
    the BENCH baselines it drifted from)."""
    global _ENV_FP
    if _ENV_FP is None:
        from paddle_tpu.telemetry.flightrec import env_fingerprint
        _ENV_FP = env_fingerprint()
    return _ENV_FP


def _capture_id():
    """Stable id of the env fingerprint (BENCH_CAPTURE_ID overrides):
    the sentry's match key."""
    from paddle_tpu.telemetry.flightrec import capture_id
    return capture_id(_env_fingerprint())


def _measure(rep_fn):
    """rep_fn() -> throughput for one timed repetition of the already-
    compiled program.  Returns (median, rel_spread, all_values)."""
    vals = [float(rep_fn()) for _ in range(_reps())]
    med = float(np.median(vals))
    spread = (max(vals) - min(vals)) / med if med > 0 else 0.0
    return med, spread, vals


def _emit(metric, value, unit, vs_baseline, spread, vals, extra=None):
    rec = {
        "metric": metric,
        "value": round(value, 1) if value >= 10 else round(value, 3),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3),
        "reps": len(vals),
        "spread": round(spread, 3),
        # env fingerprint + capture id (ISSUE 12): the perf sentry's
        # cross-environment refusal key
        "capture_id": _capture_id(),
        "env": _env_fingerprint(),
    }
    if len(vals) < 2:
        # a one-shot line has no spread to judge a regression against
        # — the sentry skips it instead of false-firing
        rec["comparable"] = False
    if extra:
        rec.update(extra)
    # the telemetry snapshot rides every metric line: lifetime counters
    # (train.steps, serve.chunks, pp.train_batches, fault/watchdog/ckpt
    # — incremented sink or not) plus compile-cache totals of THIS
    # config's process (each config runs in its own subprocess).  The
    # step/chunk TIMING histograms stay empty here by design — observed
    # only while a sink is attached, and bench runs sink-less (the
    # zero-overhead assert).
    try:
        from paddle_tpu import telemetry
        rec["telemetry"] = telemetry.dump(compact=True)
    except Exception:
        pass
    print(json.dumps(rec), flush=True)


def _peak_hbm_fields():
    """Measured peak HBM of this config's step program(s) — XLA's own
    `memory_analysis()` via the telemetry memory ledger (ISSUE 10),
    replacing hand-derived peak claims.  Resolution may recompile the
    step once (same cost class as the phase probes); BENCH_MEM=0
    skips it."""
    if os.environ.get("BENCH_MEM", "1") == "0":
        return {}
    try:
        from paddle_tpu import telemetry
        mem = telemetry.memory_report(top_buffers=0)
        if mem["peak_hbm_bytes"]:
            out = {"peak_hbm_bytes": int(mem["peak_hbm_bytes"])}
            if mem["device_hbm_bytes"]:
                out["peak_hbm_share"] = round(
                    mem["peak_hbm_bytes"] / mem["device_hbm_bytes"], 3)
            return out
    except Exception:
        pass
    return {}


def _cost_fields():
    """Cost-ledger roofline fields for this config's step program(s)
    (ISSUE 12): FLOPs/bytes/intensity + the roofline bound and the
    predicted step time at the calibrated peaks, from the same
    resolution pass _peak_hbm_fields already paid for.  Bench runs
    sink-less, so no measured walls ride along (the drift check lives
    in the live telemetry plane).  BENCH_MEM=0 skips (shared gate: the
    ledgers resolve together)."""
    if os.environ.get("BENCH_MEM", "1") == "0":
        return {}
    try:
        from paddle_tpu import telemetry
        rep = telemetry.cost_report()
        rows = {}
        for label, rec in rep["programs"].items():
            if rec.get("status") != "ok":
                continue
            rows[label] = {"flops": rec["flops"],
                           "bytes_accessed": rec["bytes_accessed"],
                           "intensity": rec.get("intensity"),
                           "bound": rec.get("bound"),
                           "predicted_ms": rec.get("predicted_ms")}
        if rows:
            return {"cost": rows}
    except Exception:
        pass
    return {}


def _phase_fields(model, step, batch, seq, n_params, label,
                  remat_flops=0.0):
    """fwd/bwd/opt phase decomposition (the PROFILE_r05 method, shared
    with tools/profile_mfu.py) as JSON-ready fields, so BENCH_r* tracks
    the gap items the kernel fusions target — not just tokens/s.
    BENCH_PHASES=0 skips the extra phase compiles."""
    if os.environ.get("BENCH_PHASES", "1") == "0":
        return None
    repo = os.path.dirname(os.path.abspath(__file__))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from tools.profile_mfu import _profile
        r = _profile(model, step, batch, seq, n_params, label,
                     remat_flops)
    except Exception as e:  # phases are telemetry, never a bench failure
        return {"phases_error": str(e)[:120]}
    return {"phases": {
        "fwd_ms": round(r["t_fwd_ms"], 1),
        "bwd_ms": round(r["t_bwd_ms"], 1),
        "opt_ms": round(r["t_opt_ms"], 1),
        "full_ms": round(r["t_full_ms"], 1),
        "fwd_util": round(r["fwd_util"], 3),
        "bwd_util": round(r["bwd_util"], 3),
        "bwd_util_hw": round(r["bwd_util_hw"], 3),
        "step_mfu": round(r["mfu_full"], 3),
    }}


def bench_llama(offload=False):
    """BASELINE.md config 3: llama pretraining tokens/s/chip + MFU.
    offload=True is the ZeRO-3 host-offload config (params beyond the
    fp32-resident ceiling; fp32 master + moments in pinned host)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    requested_offload = offload      # metric name tracks the REQUEST
    offload = offload and on_tpu
    if on_tpu:
        # 1.0B-param GQA llama sized for v5e 16G HBM.  Mixed precision
        # the TPU-idiomatic way: fp32 params (the param IS the master —
        # no separate copy) + bf16 compute + bf16 AdamW moments via the
        # fused Pallas kernel → resident state 8.0G, leaving ~6G for
        # activations.  r4 sweep: 3 selective-remat layers is the
        # throughput/gap sweet spot (mfu 0.538, hw_util-mfu 0.019).
        n_sel = int(os.environ.get("BENCH_RECOMPUTE_LAYERS", "3"))
        if offload:
            size = os.environ.get("BENCH_OFFLOAD_SIZE", "4b")
            if size == "4b":
                # 4.0B params — ~4x the fp32-resident ceiling (verdict
                # item 5): bf16 params resident (8.1G), fp32 master +
                # moments (48G) parked in pinned host, streamed per-
                # block through HBM inside the step
                cfg = LlamaConfig(vocab_size=8192, hidden_size=4608,
                                  intermediate_size=12544,
                                  num_hidden_layers=20,
                                  num_attention_heads=36,
                                  num_key_value_heads=4,
                                  max_position_embeddings=2048,
                                  dtype="bfloat16",
                                  recompute=True, recompute_layers=None,
                                  recompute_granularity="full")
            else:
                cfg = LlamaConfig(vocab_size=8192, hidden_size=3584,
                                  intermediate_size=9600,
                                  num_hidden_layers=14,
                                  num_attention_heads=28,
                                  num_key_value_heads=4,
                                  max_position_embeddings=2048,
                                  dtype="bfloat16",
                                  recompute=True, recompute_layers=None,
                                  recompute_granularity="full")
            batch = int(os.environ.get("BENCH_BATCH", "2"))
        else:
            cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                              intermediate_size=6912,
                              num_hidden_layers=14,
                              num_attention_heads=20,
                              num_key_value_heads=4,
                              max_position_embeddings=2048,
                              dtype="bfloat16", param_dtype="float32",
                              recompute=n_sel > 0,
                              recompute_layers=n_sel,
                              recompute_granularity="selective")
            batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq, steps = 2048, 8
    else:  # CPU smoke path so the script always runs
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, dtype="float32")
        batch, seq, steps = 2, 128, 3
        n_sel = 0

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters(),
                                 weight_decay=0.1,
                                 multi_precision=offload,
                                 moment_dtype="bfloat16" if on_tpu
                                 else None)
    mesh = build_mesh(devices=jax.devices()[:1])
    if requested_offload:
        # explicit double-buffered streaming pipeline (parallel/
        # offload_pipeline.py): per-layer prefetch windows forward AND
        # backward, in-backward fused AdamW on each streamed slice —
        # replaces the scheduler-overlapped param_stream path that
        # measured 0.188x baseline in r5.  The CPU smoke run exercises
        # the same scanned program minus placement annotations.
        prefetch = int(os.environ.get("BENCH_OFFLOAD_PREFETCH", "1"))
        step = ShardedTrainStep(
            model, opt, mesh, sharding_stage=3, rematerialize=False,
            offload="stream", offload_prefetch_depth=prefetch,
            offload_cast_dtype="bfloat16" if on_tpu else None)
    else:
        step = ShardedTrainStep(model, opt, mesh, sharding_stage=3,
                                rematerialize=False)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    tokens_per_sec, spread, vals, floss = _timed_train_tokens(
        step, x, batch, seq, steps)
    final_loss = [floss]
    from paddle_tpu.telemetry.costledger import model_train_flops
    model_flops = model_train_flops(n_params, tokens_per_sec)
    peak = chip_peak_flops()
    mfu = model_flops / peak
    # hardware utilization: selective remat replays only gate/up MLP
    # matmuls; the offload pipeline full-remats every layer (the
    # backward scan recomputes each block from its input residual)
    if requested_offload:
        recompute_per_tok = 2.0 * n_params
    else:
        recompute_per_tok = n_sel * (4.0 * cfg.hidden_size
                                     * cfg.intermediate_size)
    hw_util = mfu * (6.0 * n_params + recompute_per_tok) / (6.0 * n_params)
    name = "llama_offload_train_tokens_per_sec_per_chip" \
        if requested_offload else "llama_train_tokens_per_sec_per_chip"
    unit = (f"tokens/s/chip (mfu={mfu:.3f}, hw_util={hw_util:.3f}, "
            f"params={n_params/1e6:.0f}M, loss={final_loss[0]:.3f}")
    if requested_offload:
        # achieved-overlap telemetry (ISSUE 2): analytic DMA bytes, a
        # measured streaming-only probe, and its share of the step wall
        # — dma_share→1 reads bandwidth-bound (the pipeline is doing
        # its job; buy bandwidth or shrink bytes), dma_share≪1 with
        # low MFU reads schedule-bound (overlap is broken; fix the
        # program)
        pipe = step._pipeline
        sb = pipe.stream_bytes_per_step()
        step_wall = batch * seq / tokens_per_sec
        dma_s = pipe.dma_probe()
        unit += (f", h2d={sb['h2d_bytes'] / 1e9:.2f}G/step, "
                 f"d2h={sb['d2h_bytes'] / 1e9:.2f}G/step, "
                 f"dma_share={min(dma_s / step_wall, 9.99):.2f}, "
                 f"prefetch_depth={sb['prefetch_depth']}")
    extra = {}
    if not requested_offload:
        extra = _phase_fields(model, step, batch, seq, n_params,
                              "llama", recompute_per_tok) or {}
    extra.update(_peak_hbm_fields())
    extra.update(_cost_fields())
    _emit(name, tokens_per_sec, unit + ")", mfu / 0.40, spread, vals,
          extra=extra or None)


def _timed_train_tokens(step, x, batch, seq, steps):
    """Shared train-bench timing harness: warmup/compile, then timed
    reps.  The host transfer (`float(np.asarray(...))`) forces
    completion — the axon relay's block_until_ready does not
    synchronize remote execution."""
    loss = step(x, x)
    _ = float(np.asarray(loss.value))
    final_loss = [0.0]

    def rep():
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, x)
        final_loss[0] = float(np.asarray(loss.value))
        return batch * seq * steps / (time.perf_counter() - t0)

    tokens_per_sec, spread, vals = _measure(rep)
    return tokens_per_sec, spread, vals, final_loss[0]


def bench_llama_overlap():
    """llama_sharded_overlap (ISSUE 16): the ZeRO-3 sharded trainer
    with bucketed gradient collectives overlapped with the backward
    (FLAGS_comm_overlap / parallel/comm_overlap.py).

    On TPU the step shards over every chip with the overlap engine
    armed; the exposed-comm column comes from the trainer's own plan
    through the cost ledger.  The CPU smoke run has one device (the
    plan is inactive by design — nothing to overlap), so the column is
    quoted from an 8-way MODELED plan over the same parameter list —
    the same estimator, same ledger path, no chip time.  Either way
    the leg emits `exposed_comm.on_ms` / `off_ms`, and perf_report.py
    gates on_ms < off_ms: the overlap engine must never PREDICT more
    exposed communication than the monolithic baseline."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.parallel.comm_overlap import CommOverlapPlan
    from paddle_tpu.distributed.topology import build_mesh
    from paddle_tpu import telemetry
    from paddle_tpu.telemetry import costledger

    if on_tpu:
        cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                          intermediate_size=6912,
                          num_hidden_layers=14,
                          num_attention_heads=20,
                          num_key_value_heads=4,
                          max_position_embeddings=2048,
                          dtype="bfloat16", param_dtype="float32",
                          recompute=True, recompute_layers=3,
                          recompute_granularity="selective")
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq, steps = 2048, 8
        bucket_mb = float(os.environ.get("BENCH_BUCKET_MB", "32"))
        n_shard = len(jax.devices())
    else:  # CPU smoke: tiny model, small buckets so the modeled plan
        #    still exercises the multi-bucket (n>=2) overlap shape
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, dtype="float32")
        batch, seq, steps = 2, 128, 3
        bucket_mb = float(os.environ.get("BENCH_BUCKET_MB", "0.25"))
        n_shard = len(jax.devices())

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters(),
                                 weight_decay=0.1)
    mesh = build_mesh(sharding=n_shard) if n_shard > 1 \
        else build_mesh(devices=jax.devices()[:1])
    step = ShardedTrainStep(model, opt, mesh, sharding_stage=3,
                            rematerialize=False, comm_overlap=True,
                            comm_bucket_mb=bucket_mb)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    tokens_per_sec, spread, vals, floss = _timed_train_tokens(
        step, x, batch, seq, steps)

    label = "ShardedTrainStep.step.s3"
    plan = step._overlap_plan
    if plan is None:
        # single-device smoke: model the 8-way plan over the same
        # param list and attach it to the ledger exactly as the
        # trainer would (verify() first — same static pre-flight)
        names = [n for n, _ in model.named_parameters()]
        shapes = [tuple(p.value.shape)
                  for _, p in model.named_parameters()]
        dts = [str(p.value.dtype) for _, p in model.named_parameters()]
        plan = CommOverlapPlan.modeled(
            names, shapes, dts, world=8, stage=3, bucket_mb=bucket_mb)
        plan.verify()
        costledger.note_comm(label, plan.comm_profile())

    exposed = {}
    try:
        rec = telemetry.cost_report()["programs"].get(label) or {}
        if "exposed_comm_ms" in rec:
            exposed = {
                "on_ms": rec["exposed_comm_ms"],
                "off_ms": rec["exposed_comm_ms_monolithic"],
                "comm_ms": rec["comm_ms"],
                "buckets": rec["comm_buckets"],
                "bytes": rec["comm_bytes"],
                "overlap_efficiency": rec["overlap_efficiency"],
                "modeled": step._overlap_plan is None,
            }
    except Exception as e:  # the column is telemetry, not the metric
        exposed = {"error": str(e)[:120]}

    from paddle_tpu.telemetry.costledger import model_train_flops
    mfu = model_train_flops(n_params, tokens_per_sec) \
        / chip_peak_flops()
    unit = (f"tokens/s/chip (mfu={mfu:.3f}, "
            f"params={n_params / 1e6:.0f}M, loss={floss:.3f}, "
            f"buckets={len(plan.buckets)}, shard={n_shard})")
    extra = {"exposed_comm": exposed,
             "comm_overlap": step._overlap_plan is not None,
             "bucket_mb": bucket_mb}
    extra.update(_peak_hbm_fields())
    extra.update(_cost_fields())
    _emit("llama_sharded_overlap_tokens_per_sec_per_chip",
          tokens_per_sec, unit, mfu / 0.40, spread, vals, extra=extra)


def _parse_hybrid_mesh(spec):
    """'dp2xmp2xsharding2' → {'dp_degree': 2, 'mp_degree': 2, ...}."""
    import re
    out = {}
    for m in re.finditer(r"(dp|mp|pp|sep|sharding)(\d+)", spec or ""):
        out[m.group(1) + "_degree"] = int(m.group(2))
    return out


def bench_llama_hybrid():
    """llama_hybrid (ISSUE 17): ONE strategy point of the composed N-D
    hybrid engine (parallel/hybrid_engine.py) — measured tokens/s/chip
    next to the cost ledger's per-axis exposed-comm columns and the
    roofline's predicted step time, so the record carries measured-vs-
    predicted MFU PER MESH SHAPE.

    On TPU the engine composes over every chip; BENCH_HYBRID_MESH
    ("dp2xmp4", "dp2xmp2xsharding2", ...) picks the point, default
    dp×mp over all chips.  The CPU smoke run has one device, so the
    measured wall comes from the engine's single-axis program (which
    the zero-overhead assert proves byte-identical to the plain
    trainer) and the quoted per-axis columns come from
    modeled_axis_profiles for the dp2×mp2×sharding2 8-way point over
    the SAME parameter list — same estimator, same ledger join that a
    real mesh would use, no chip time.  Either way the static
    pre-flight (engine.verify: composed collective-order check) runs
    before any timing, and perf_report.py gates the per-axis columns:
    they must sum to the program totals (no double-counting) and
    overlapped exposure must never exceed monolithic."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import HybridParallelEngine
    from paddle_tpu.parallel.hybrid_engine import modeled_axis_profiles
    from paddle_tpu import telemetry
    from paddle_tpu.telemetry import costledger

    n_dev = len(jax.devices())
    if on_tpu:
        cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                          intermediate_size=6912,
                          num_hidden_layers=14,
                          num_attention_heads=20,
                          num_key_value_heads=4,
                          max_position_embeddings=2048,
                          dtype="bfloat16", param_dtype="float32",
                          recompute=True, recompute_layers=3,
                          recompute_granularity="selective")
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq, steps = 2048, 8
        default = f"dp{max(1, n_dev // 2)}xmp{2 if n_dev >= 2 else 1}"
        degrees = _parse_hybrid_mesh(
            os.environ.get("BENCH_HYBRID_MESH", default))
    else:  # CPU smoke: one device — engine runs single-axis, columns
        #    are modeled for the quoted 8-way point below
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, dtype="float32")
        batch, seq, steps = 2, 128, 3
        degrees = {}

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters(),
                                 weight_decay=0.1)
    engine = HybridParallelEngine(model, opt, **degrees)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    engine.verify(x, x)  # static pre-flight before any chip time
    tokens_per_sec, spread, vals, floss = _timed_train_tokens(
        engine, x, batch, seq, steps)

    label = engine.cost_label()
    quoted = degrees
    if engine.mesh.size == 1:
        # quote the 8-way modeled point through the same ledger path
        quoted = {"dp_degree": 2, "mp_degree": 2, "sharding_degree": 2}
        params = [(tuple(p.value.shape), str(p.value.dtype))
                  for _, p in model.named_parameters()]
        dq = {k.replace("_degree", ""): v for k, v in quoted.items()}
        for prof in modeled_axis_profiles(params, cfg, dq,
                                          (batch, seq), stage=1):
            costledger.note_comm(label, prof)

    exposed = {}
    predicted_ms = None
    try:
        rec = telemetry.cost_report()["programs"].get(label) or {}
        predicted_ms = rec.get("predicted_ms")
        if "exposed_comm_ms" in rec:
            exposed = {
                "on_ms": rec["exposed_comm_ms"],
                "off_ms": rec["exposed_comm_ms_monolithic"],
                "comm_ms": rec["comm_ms"],
                "buckets": rec["comm_buckets"],
                "bytes": rec["comm_bytes"],
                "per_axis": rec.get("exposed_comm_by_axis"),
                "overlap_efficiency": rec["overlap_efficiency"],
                "modeled": engine.mesh.size == 1,
            }
    except Exception as e:  # the column is telemetry, not the metric
        exposed = {"error": str(e)[:120]}

    from paddle_tpu.telemetry.costledger import model_train_flops
    mfu = model_train_flops(n_params, tokens_per_sec) \
        / chip_peak_flops()
    measured_ms = batch * seq * 1e3 / tokens_per_sec
    mesh_name = "x".join(f"{k.replace('_degree', '')}{v}"
                         for k, v in quoted.items()) or "single"
    unit = (f"tokens/s/chip (mfu={mfu:.3f}, mesh={mesh_name}, "
            f"params={n_params / 1e6:.0f}M, loss={floss:.3f})")
    extra = {"exposed_comm": exposed, "mesh": mesh_name,
             "degrees": {k.replace("_degree", ""): v
                         for k, v in quoted.items()},
             "measured_step_ms": round(measured_ms, 3)}
    if predicted_ms is not None:
        extra["predicted_step_ms"] = predicted_ms
    extra.update(_peak_hbm_fields())
    extra.update(_cost_fields())
    _emit("llama_hybrid_tokens_per_sec_per_chip",
          tokens_per_sec, unit, mfu / 0.40, spread, vals, extra=extra)


def bench_longctx():
    """Long-context training (SURVEY §5.7): the same 1.0B llama at
    seq 16384 (8x the headline config), batch 1, through the Pallas
    flash-attention path — flash's O(seq) memory is what makes a 16k
    context FIT next to 8G of resident fp32+moment state on the 16G
    chip.  MFU here uses attention-INCLUSIVE model FLOPs per token:
    6N dense + 6·L·h·seq attention (PaLM's 12·L·h·seq causal-halved);
    at 16k the attention matmuls are 37% of the work, so the
    dense-only 6N basis would overstate utilization."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    if on_tpu:
        seq = int(os.environ.get("BENCH_LONGCTX_SEQ", "16384"))
        remat = os.environ.get("BENCH_LONGCTX_REMAT", "full")
        cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                          intermediate_size=6912, num_hidden_layers=14,
                          num_attention_heads=20, num_key_value_heads=4,
                          max_position_embeddings=seq,
                          dtype="bfloat16", param_dtype="float32",
                          recompute=remat != "none",
                          recompute_layers=None,
                          recompute_granularity=remat
                          if remat != "none" else "full")
        batch, steps = 1, 4
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=512, dtype="float32")
        batch, seq, steps = 1, 512, 2

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters(),
                                 weight_decay=0.1,
                                 moment_dtype="bfloat16" if on_tpu
                                 else None)
    mesh = build_mesh(devices=jax.devices()[:1])
    step = ShardedTrainStep(model, opt, mesh, sharding_stage=3,
                            rematerialize=False)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    tokens_per_sec, spread, vals, floss = _timed_train_tokens(
        step, x, batch, seq, steps)
    # attention-inclusive train FLOPs/token: 6N dense + 6·L·h·seq
    # attention — PaLM's 12·L·h·seq (fwd 2 + bwd 4 passes over the
    # 2·seq·h QK^T/AV matmul pair per layer) halved for causal masking
    attn_per_tok = 6.0 * cfg.num_hidden_layers * cfg.hidden_size * seq
    model_flops = (6.0 * n_params + attn_per_tok) * tokens_per_sec
    mfu = model_flops / chip_peak_flops()
    _emit("llama_longctx_train_tokens_per_sec_per_chip",
          tokens_per_sec,
          f"tokens/s/chip (seq={seq}, b={batch}, mfu={mfu:.3f} "
          f"attention-inclusive, params={n_params/1e6:.0f}M, "
          f"attn_share={attn_per_tok/(6.0*n_params+attn_per_tok):.2f}, "
          f"loss={floss:.3f})",
          mfu / 0.40, spread, vals)


def _class_correlated_images(n, num_classes, rng, noise=0.6):
    """Learnable synthetic CIFAR stand-in (zero-egress environment):
    per-class template + gaussian noise — convergence on a held-out
    split is real evidence the training machinery optimizes."""
    templates = rng.randn(num_classes, 3, 32, 32).astype(np.float32)
    labels = rng.randint(0, num_classes, n)
    imgs = templates[labels] + noise * rng.randn(n, 3, 32, 32)
    return imgs.astype(np.float32), labels.astype(np.int64)


def bench_resnet():
    """BASELINE.md config 1: ResNet-50 on CIFAR-10-shaped data —
    images/sec + top-1 convergence on a held-out split."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50, resnet18
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    rng = np.random.RandomState(0)
    if on_tpu:
        model = resnet50(num_classes=10)
        batch, n_train, n_test, epochs = 256, 4096, 1024, 3
    else:
        model = resnet18(num_classes=10)
        batch, n_train, n_test, epochs = 32, 64, 32, 1

    xs_all, ys_all = _class_correlated_images(n_train + n_test, 10, rng)
    xs, ys = xs_all[:n_train], ys_all[:n_train]
    xt, yt = xs_all[n_train:], ys_all[n_train:]
    opt = paddle.optimizer.Momentum(0.02, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=1e-4)
    loss_fn = lambda o, y: nn.functional.cross_entropy(o, y)
    step = TrainStep(model, loss_fn, opt)

    steps_per_epoch = n_train // batch
    # pre-stage the whole epoch as [K, b, ...] and fuse the K steps into
    # ONE device program per epoch (TrainStep.run_steps lax.scan):
    # per-step dispatch latency would otherwise dominate CIFAR-sized
    # compute on a tunneled chip
    sx = paddle.to_tensor(
        xs[: steps_per_epoch * batch].reshape(steps_per_epoch, batch,
                                              *xs.shape[1:]))
    sy = paddle.to_tensor(
        ys[: steps_per_epoch * batch].reshape(steps_per_epoch, batch))
    _ = float(np.asarray(step.run_steps(sx, sy).value[-1]))  # compile
    final_loss = [0.0]

    def rep():
        t0 = time.perf_counter()
        for _ in range(epochs):
            losses = step.run_steps(sx, sy)
        final_loss[0] = float(np.asarray(losses.value[-1]))
        return epochs * steps_per_epoch * batch \
            / (time.perf_counter() - t0)

    images_per_sec, spread, vals = _measure(rep)

    # held-out top-1 (jitted eval — per-op eager would be host-bound)
    import jax.numpy as jnp
    from paddle_tpu.jit import to_static
    model.eval()
    eval_fwd = to_static(model)
    correct = tot = 0
    for i in range(0, n_test, batch):
        out = eval_fwd(paddle.to_tensor(xt[i:i + batch]))
        pred = np.asarray(jnp.argmax(out.value, axis=-1))
        correct += int((pred == yt[i:i + batch]).sum())
        tot += len(pred)
    top1 = correct / max(1, tot)

    _emit("resnet50_cifar_images_per_sec", images_per_sec,
          f"images/s (top1={top1:.3f} heldout after "
          f"{epochs * _reps()} epochs, loss={final_loss[0]:.3f})",
          top1 / 0.90, spread, vals)


def bench_bert():
    """BASELINE.md config 2: BERT-base pretraining, DP + sharding
    stage 1 — tokens/s/chip + MFU."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertForMaskedLM, BertConfig
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    paddle.seed(0)
    if on_tpu:
        # fp32 params ARE the masters (nn.set_compute_dtype flax idiom)
        # + bf16 compute; b=64 fits with bf16 logits (r4: 0.481 MFU)
        cfg = BertConfig(dtype="bfloat16")
        batch = int(os.environ.get("BENCH_BATCH", "64"))
        seq, steps = 512, 8
    else:
        cfg = BertConfig(vocab_size=128, hidden_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=128,
                         max_position_embeddings=64)
        batch, seq, steps = 2, 32, 2

    model = BertForMaskedLM(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    # fp32 moments: at 110M params the update is cheap, and bf16
    # moments force tail-padding copies on the ragged tied embedding
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01)
    mesh = build_mesh(sharding=1, devices=jax.devices()[:1])
    step = ShardedTrainStep(model, opt, mesh, sharding_stage=1,
                            batch_axes=("dp", "sharding"))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      (steps, batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    # fuse the whole run into one scanned program (run_steps)
    losses = step.run_steps(x, x)
    _ = float(np.asarray(losses.value[-1]))
    final_loss = [0.0]

    def rep():
        t0 = time.perf_counter()
        losses = step.run_steps(x, x)
        final_loss[0] = float(np.asarray(losses.value[-1]))
        return batch * seq * steps / (time.perf_counter() - t0)

    tokens_per_sec, spread, vals = _measure(rep)
    from paddle_tpu.telemetry.costledger import model_train_flops
    mfu = model_train_flops(n_params, tokens_per_sec) \
        / chip_peak_flops()
    _emit("bert_base_train_tokens_per_sec_per_chip", tokens_per_sec,
          f"tokens/s/chip (mfu={mfu:.3f}, params={n_params/1e6:.0f}M, "
          f"loss={final_loss[0]:.3f})", mfu / 0.40, spread, vals,
          extra=_phase_fields(model, step, batch, seq, n_params, "bert"))


def bench_unet():
    """BASELINE.md config 5: SD-style conditional UNet —
    epsilon-prediction training samples/sec."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.unet import (UNet2DConditionModel,
                                        unet_sd_config, unet_tiny_config)
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    if on_tpu:
        cfg = unet_sd_config()
        # bf16 compute (fp32 masters): convs on the MXU at full rate
        cfg.dtype = os.environ.get("BENCH_UNET_DTYPE", "bfloat16")
        batch, hw, ctx_len, steps = 8, 64, 77, 6
    else:
        cfg = unet_tiny_config()
        batch, hw, ctx_len, steps = 2, 16, 8, 2

    model = UNet2DConditionModel(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    step = TrainStep(model, lambda o, y: model.compute_loss(o, y), opt)

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, cfg.in_channels, hw,
                                   hw).astype(np.float32))
    t = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int32))
    ctx = paddle.to_tensor(rng.randn(batch, ctx_len,
                                     cfg.cross_attention_dim)
                           .astype(np.float32))
    eps = paddle.to_tensor(rng.randn(batch, cfg.out_channels, hw,
                                     hw).astype(np.float32))

    loss = step(x, t, ctx, eps)
    _ = float(np.asarray(loss.value))
    final_loss = [0.0]

    def rep():
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, t, ctx, eps)
        final_loss[0] = float(np.asarray(loss.value))
        return batch * steps / (time.perf_counter() - t0)

    samples_per_sec, spread, vals = _measure(rep)
    _emit("sd_unet_train_samples_per_sec", samples_per_sec,
          f"samples/s (params={n_params/1e6:.0f}M, latents {hw}x{hw}, "
          f"loss={final_loss[0]:.3f})", 1.0, spread, vals)


def _serving_model():
    """The shared serving llama (1B GQA bf16 on TPU; tiny on CPU).
    Returns (model, cfg, batch, n_params, roofline_tok_s)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig

    paddle.seed(0)
    if on_tpu:
        # serving-appropriate bf16 weights: the decode roofline assumes
        # 2 bytes/param, which must match what the step reads
        cfg = LlamaConfig(vocab_size=8192, hidden_size=2560,
                          intermediate_size=6912, num_hidden_layers=14,
                          num_attention_heads=20, num_key_value_heads=4,
                          max_position_embeddings=2048,
                          dtype="bfloat16")
        batch = int(os.environ.get("BENCH_BATCH", "8"))
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, dtype="float32")
        batch = 2
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.value.shape))
                   for p in model.parameters())
    # decode roofline: every token reads all params once (bf16 stream)
    roofline = batch * 0.82e12 / (2.0 * n_params)
    return model, cfg, batch, n_params, roofline


def bench_llama_decode():
    """Serving decode: KV-cached generate() on the 1B llama — whole
    generation is one jitted lax.scan program (inference/generation.py).
    Reports decode tokens/s/chip."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle

    model, cfg, batch, n_params, roofline = _serving_model()
    prompt_len, new_tokens = (128, 512) if on_tpu else (8, 16)
    rng = np.random.RandomState(0)
    prompt = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size,
                    (batch, prompt_len)).astype(np.int32))

    out = model.generate(prompt, max_new_tokens=new_tokens)  # compile
    _ = np.asarray(out.value)

    def rep():
        t0 = time.perf_counter()
        out = model.generate(prompt, max_new_tokens=new_tokens)
        _ = np.asarray(out.value)
        return batch * new_tokens / (time.perf_counter() - t0)

    tok_s, spread, vals = _measure(rep)
    _emit("llama_decode_tokens_per_sec_per_chip", tok_s,
          f"tokens/s/chip (b={batch}, new={new_tokens}, "
          f"params={n_params/1e6:.0f}M, "
          f"hbm_roofline={roofline:.0f} tok/s)",
          tok_s / max(roofline, 1e-9), spread, vals)


def bench_llama_serve():
    """Continuous batching at MIXED prompt lengths: 16 staggered
    requests through one ContinuousBatcher with CHUNKED PREFILL —
    admission consumes prompts in decode-shaped chunks through the
    same compiled scan as live decode (inference/serving.py), so the
    workload compiles exactly two programs and prefill never stalls
    the batch.  Median-of-reps aggregate tokens/s + spread, like every
    other metric; each rep replays the same staggered 16-request
    workload through a fresh batcher (programs cached on the model)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    from paddle_tpu.inference import ContinuousBatcher

    model, cfg, batch, n_params, roofline = _serving_model()
    rngm = np.random.RandomState(1)
    if on_tpu:
        lens = [64, 128, 256, 192] * 4      # 16 requests over 8 slots
        n_new, chunk, max_len, pchunk = 128, 64, 640, 32
    else:
        lens = [4, 8, 6, 10]
        n_new, chunk, max_len, pchunk = 8, 4, 32, 4
    prompts = [rngm.randint(0, cfg.vocab_size, L).astype(np.int32)
               for L in lens]
    last_stats = {}
    hold = []       # keep the last batcher alive: the memory ledger's
    #                 serve providers are weakrefs (peak-HBM resolution
    #                 at emit time needs a live batcher)

    def serve_once():
        bat = ContinuousBatcher(model, max_batch_size=batch,
                                max_len=max_len, chunk=chunk,
                                prefill_chunk=pchunk)
        hold[:] = [bat]
        for p_ in prompts[:batch]:
            bat.submit(p_, n_new)
        t0 = time.perf_counter()
        bat.step()
        # remaining requests arrive while the batch is running
        for p_ in prompts[batch:]:
            bat.submit(p_, n_new)
        bat.run()
        dt = time.perf_counter() - t0
        last_stats.clear()
        last_stats.update(bat.stats())
        return bat.tokens_produced / dt

    serve_once()                            # compile (2 programs)
    tok_s, spread, vals = _measure(serve_once)
    st = last_stats
    _emit("llama_serve_mixed_tokens_per_sec", tok_s,
          f"aggregate tok/s, {len(prompts)} staggered reqs, prompt "
          f"lens {sorted(set(lens))}, b={batch} slots, chunk={chunk}, "
          f"prefill_chunk={pchunk}; occupancy="
          f"{st.get('avg_occupancy', 0):.2f}, "
          f"prefill/decode tokens={st.get('prefill_tokens', 0)}/"
          f"{st.get('decode_tokens', 0)}, "
          f"programs={st.get('compiled_programs', 0)}, "
          f"kv={st.get('kv_layout')}:"
          f"{st.get('kv_bytes', 0) / 1e6:.0f}MB",
          tok_s / max(roofline, 1e-9), spread, vals,
          extra={"kv_layout": st.get("kv_layout"),
                 "kv_bytes": st.get("kv_bytes", 0),
                 # per-request latency spans (ISSUE 10): TTFT/TPOT/e2e
                 # percentiles over the last rep's delivered requests
                 "latency": st.get("latency"),
                 **_peak_hbm_fields()})


def bench_llama_serve_prefix_shared():
    """Prefix-shared serving (ISSUE 7): 16 staggered requests that all
    open with one LONG system prompt, through the PAGED KV pool with
    prefix sharing — the shared pages prefill once and every later
    admission maps them (prefix_hit_tokens), so admission work shrinks
    to the per-request tail.  Reports aggregate tok/s, the prefix-hit
    rate, KV HBM bytes (and the int8 pool's bytes for the same
    geometry), plus the dense-path tok/s on the SAME workload — the
    >=1.3x acceptance ratio.  Off-TPU the smoke run also asserts the
    sharing actually happened (hit tokens > 0, strictly less prefill
    work than dense)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    from paddle_tpu.inference import ContinuousBatcher

    model, cfg, batch, n_params, roofline = _serving_model()
    rngm = np.random.RandomState(2)
    if on_tpu:
        sys_len, n_req = 384, 16
        tail_lens = [16, 48, 32, 64] * 4
        n_new, chunk, max_len, pchunk, ps = 128, 64, 768, 32, 32
    else:
        sys_len, n_req = 24, 4
        tail_lens = [4, 8, 6, 5]
        n_new, chunk, max_len, pchunk, ps = 8, 4, 48, 4, 8
    sys_prompt = rngm.randint(0, cfg.vocab_size, sys_len) \
        .astype(np.int32)
    prompts = [np.concatenate(
        [sys_prompt, rngm.randint(0, cfg.vocab_size, L)
         .astype(np.int32)]) for L in tail_lens[:n_req]]
    total_prompt = sum(len(p) for p in prompts)
    last_stats = {}

    hold = []       # liveness for the ledger's weakref'd serve providers

    def serve_once(layout="paged", sharing=True):
        bat = ContinuousBatcher(model, max_batch_size=batch,
                                max_len=max_len, chunk=chunk,
                                prefill_chunk=pchunk, kv_layout=layout,
                                page_size=ps, prefix_sharing=sharing)
        hold[:] = [bat]
        for p_ in prompts[:batch]:
            bat.submit(p_, n_new)
        t0 = time.perf_counter()
        bat.step()
        for p_ in prompts[batch:]:
            bat.submit(p_, n_new)
        bat.run()
        dt = time.perf_counter() - t0
        last_stats.clear()
        last_stats.update(bat.stats())
        return bat.tokens_produced / dt

    serve_once()                                   # compile paged
    serve_once("dense")                            # compile dense
    tok_s, spread, vals = _measure(serve_once)
    st = dict(last_stats)
    # resolve peak-HBM NOW, while the ledger's serve entries still
    # describe the PAGED batcher (the dense reps below re-register)
    peak_fields = _peak_hbm_fields()
    dense_tok = _measure(lambda: serve_once("dense"))[0]
    st_dense = dict(last_stats)
    hit_rate = st["prefix_hit_tokens"] / max(total_prompt, 1)
    # int8 pool bytes at identical geometry (the halved-KV-HBM claim;
    # pool dtype vs the full-precision pool, scales included) — pure
    # shape arithmetic, no throwaway pools allocated on the chip
    kv_full = ContinuousBatcher.paged_kv_bytes(
        model, max_batch_size=batch, max_len=max_len,
        prefill_chunk=pchunk, page_size=ps, kv_dtype="bfloat16")
    kv_int8 = ContinuousBatcher.paged_kv_bytes(
        model, max_batch_size=batch, max_len=max_len,
        prefill_chunk=pchunk, page_size=ps, kv_dtype="int8")
    if not on_tpu:
        # CPU smoke: the sharing must be REAL, not just plumbed
        assert st["prefix_hit_tokens"] > 0, st
        assert st["prefill_tokens"] < st_dense["prefill_tokens"], \
            (st["prefill_tokens"], st_dense["prefill_tokens"])
        assert st["admit_chunks"] <= st_dense["admit_chunks"]
        assert kv_int8 < 0.6 * kv_full, (kv_int8, kv_full)
    _emit("llama_serve_prefix_shared_tokens_per_sec", tok_s,
          f"aggregate tok/s, {n_req} staggered reqs sharing a "
          f"{sys_len}-token system prompt, b={batch} slots, "
          f"page_size={ps}; prefix_hit_rate={hit_rate:.2f}, "
          f"kv={st.get('kv_bytes', 0) / 1e6:.0f}MB "
          f"(int8 pool {kv_int8 / 1e6:.0f}MB vs bf16 "
          f"{kv_full / 1e6:.0f}MB), vs_dense={tok_s / max(dense_tok, 1e-9):.2f}x",
          tok_s / max(roofline, 1e-9), spread, vals,
          extra={"prefix_hit_tokens": int(st["prefix_hit_tokens"]),
                 "prefix_hit_rate": round(hit_rate, 3),
                 "kv_bytes": int(st.get("kv_bytes", 0)),
                 "kv_bytes_int8": int(kv_int8),
                 "kv_bytes_bf16": int(kv_full),
                 "evictions": int(st.get("evictions", 0)),
                 "vs_dense": round(tok_s / max(dense_tok, 1e-9), 3),
                 "dense_tokens_per_sec": round(dense_tok, 1),
                 **peak_fields})


def bench_llama_serve_speculative():
    """Speculative decoding + weight-only sizing (ISSUE 11): the
    mixed-length serve workload through the draft/verify scan, vs the
    plain batcher on the SAME workload.  On TPU the draft is an
    early-exit self-draft (first quarter of the layers); the CPU smoke
    instead self-speculates with the target as its own draft — the
    acceptance plumbing is then deterministic (accept_rate == 1), so
    the smoke can ASSERT accept_rate > 0, accepted_per_step > 1 and
    greedy bit-exactness vs the non-speculative batcher, which is the
    contract that matters off-TPU (TPU accept rates with trained
    weights land at the next driver capture).  Also reports the
    int8/int4 weight-pool bytes for this model (pure shape
    arithmetic — no second copy of the weights is packed)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.quantization.weight_only import (weight_pool_bytes,
                                                     packed_bytes)

    model, cfg, batch, n_params, roofline = _serving_model()
    rngm = np.random.RandomState(3)
    if on_tpu:
        lens = [64, 128, 256, 192] * 4
        n_new, chunk, max_len, pchunk = 128, 16, 640, 32
        spec_kw = dict(spec_tokens=4,
                       draft_layers=max(1, cfg.num_hidden_layers // 4))
    else:
        lens = [4, 8, 6, 10]
        n_new, chunk, max_len, pchunk = 8, 4, 48, 4
        spec_kw = dict(spec_tokens=3, draft_model=model)
    prompts = [rngm.randint(0, cfg.vocab_size, L).astype(np.int32)
               for L in lens]
    last_stats = {}
    hold = []

    def serve_once(speculative=True):
        bat = ContinuousBatcher(model, max_batch_size=batch,
                                max_len=max_len, chunk=chunk,
                                prefill_chunk=pchunk,
                                **(spec_kw if speculative else {}))
        hold[:] = [bat]
        rids = []
        for p_ in prompts[:batch]:
            rids.append(bat.submit(p_, n_new))
        t0 = time.perf_counter()
        bat.step()
        for p_ in prompts[batch:]:
            rids.append(bat.submit(p_, n_new))
        outs = bat.run()
        dt = time.perf_counter() - t0
        last_stats.clear()
        last_stats.update(bat.stats())
        return bat.tokens_produced / dt, rids, outs

    serve_once()                                # compile (2 programs)
    serve_once(False)                           # compile plain
    tok_s, spread, vals = _measure(lambda: serve_once()[0])
    _, rids, outs = serve_once()                # capture outputs
    st = dict(last_stats)
    peak_fields = _peak_hbm_fields()
    base_tok = _measure(lambda: serve_once(False)[0])[0]
    _, base_rids, base_outs = serve_once(False)
    accept = st.get("spec_accept_rate", 0.0)
    aps = st.get("spec_accepted_per_step", {})
    wb_now = weight_pool_bytes(model)
    if getattr(model, "_weight_only", None) is None:
        wb_int8 = packed_bytes(model, "int8")
        wb_int4 = packed_bytes(model, "int4")
    else:
        wb_int8 = wb_int4 = wb_now
    if not on_tpu:
        # CPU smoke: speculation must be REAL and bit-exact, not just
        # plumbed (the acceptance criteria of ISSUE 11)
        assert st["compiled_programs"] == 2, st
        assert accept > 0, st
        assert aps.get("mean", 0) > 1, st
        for a, b in zip(rids, base_rids):
            assert (outs[a] == base_outs[b]).all(), \
                "speculative output diverged from the plain batcher"
    _emit("llama_serve_speculative_tokens_per_sec", tok_s,
          f"aggregate tok/s, {len(prompts)} staggered reqs, "
          f"spec_tokens={st.get('spec_tokens')}, "
          f"accept_rate={accept:.2f}, accepted/step "
          f"p50={aps.get('p50', 0)}, vs_plain="
          f"{tok_s / max(base_tok, 1e-9):.2f}x; weight pool "
          f"{wb_now / 1e6:.0f}MB (int8 {wb_int8 / 1e6:.0f}MB / "
          f"int4 {wb_int4 / 1e6:.0f}MB)",
          tok_s / max(roofline, 1e-9), spread, vals,
          extra={"spec_tokens": st.get("spec_tokens"),
                 "accept_rate": accept,
                 "accepted_per_step": aps,
                 "vs_plain": round(tok_s / max(base_tok, 1e-9), 3),
                 "plain_tokens_per_sec": round(base_tok, 1),
                 "weight_pool_bytes": wb_now,
                 "weight_pool_bytes_int8": wb_int8,
                 "weight_pool_bytes_int4": wb_int4,
                 "weight_only": st.get("weight_only"),
                 **peak_fields})


def bench_llama_serve_fleet():
    """Serve-fleet router (ISSUE 15): a staggered shared-prefix
    workload through TWO in-process ContinuousBatcher replicas behind
    the prefix-aware SLO-aware ServeRouter, vs ONE replica of the same
    per-replica capacity on the same workload.  Reports aggregate
    tok/s, the prefix-ROUTE hit rate (routes whose chosen replica
    already held the prompt's prefix) and the vs_single_replica
    multiplier.  The router is HOST-plane only: the CPU smoke asserts
    both replicas actually served traffic, the run was requeue-free
    and complete, and the flags-off single-batcher serve HLO +
    program-cache keys are byte-identical with the router module
    imported and a whole fleet run behind it."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.inference.router import ServeRouter

    model, cfg, batch, n_params, roofline = _serving_model()
    rngm = np.random.RandomState(4)
    if on_tpu:
        sys_len, n_req = 256, 16
        tail_lens = [16, 48, 32, 64] * 4
        n_new, chunk, max_len, pchunk, ps = 128, 64, 640, 32, 32
        rb = max(1, batch // 2)         # per-replica slots
    else:
        sys_len, n_req = 24, 8
        tail_lens = [4, 8, 6, 5] * 2
        n_new, chunk, max_len, pchunk, ps = 8, 4, 48, 4, 8
        rb = 1
    sys_prompt = rngm.randint(0, cfg.vocab_size, sys_len) \
        .astype(np.int32)
    prompts = [np.concatenate(
        [sys_prompt, rngm.randint(0, cfg.vocab_size, L)
         .astype(np.int32)]) for L in tail_lens[:n_req]]
    geom = dict(max_batch_size=rb, max_len=max_len, chunk=chunk,
                prefill_chunk=pchunk, page_size=ps)
    # stagger rounds before the tail arrives: enough for the shared
    # system prompt to finish prefilling (its pages then sit in the
    # early replicas' prefix tries, so later routes can chase them) —
    # one admit chunk advances admit_steps*prefill_chunk prompt rows
    stagger = max(1, -(-sys_len // max(1, (chunk // 4) * pchunk)) + 1)

    def fingerprint():
        bat = ContinuousBatcher(model, **geom)
        keys = (bat._program_key(1, bat.chunk),
                bat._program_key(bat.prefill_chunk, bat.admit_steps))
        return keys, (bat.lower_step(mixed=False).as_text(),
                      bat.lower_step(mixed=True).as_text())

    keys0, hlo0 = fingerprint()
    last_stats = {}
    hold = []

    def fleet_once():
        bats = [ContinuousBatcher(model, **geom) for _ in range(2)]
        router = ServeRouter(batchers=bats)
        hold[:] = [router]
        n_first = max(2, 2 * rb)
        for p_ in prompts[:n_first]:
            router.submit(p_, n_new)
        t0 = time.perf_counter()
        for _ in range(stagger):
            router.step()
        for p_ in prompts[n_first:]:
            router.submit(p_, n_new)
        outs = router.run()
        dt = time.perf_counter() - t0
        last_stats.clear()
        last_stats.update(router.stats())
        return sum(len(v) for v in outs.values()) / dt

    def single_once():
        bat = ContinuousBatcher(model, **geom)
        hold[:] = [bat]
        n_first = max(2, 2 * rb)
        for p_ in prompts[:n_first]:
            bat.submit(p_, n_new)
        t0 = time.perf_counter()
        for _ in range(stagger):
            bat.step()
        for p_ in prompts[n_first:]:
            bat.submit(p_, n_new)
        outs = bat.run()
        return sum(len(v) for v in outs.values()) \
            / (time.perf_counter() - t0)

    fleet_once()                               # compile (shared progs)
    single_once()
    tok_s, spread, vals = _measure(fleet_once)
    st = dict(last_stats)
    single_tok = _measure(single_once)[0]
    keys1, hlo1 = fingerprint()
    assert keys0 == keys1, \
        "running the serve-fleet router changed single-batcher " \
        "program keys"
    assert hlo0 == hlo1, \
        "running the serve-fleet router changed the flags-off " \
        "single-batcher serve HLO"
    if not on_tpu:
        # CPU smoke: the fleet must be REAL — both replicas routed
        # traffic, nothing requeued/shed, every request completed,
        # and prefix-affinity actually steered at least one route
        routed = st["routed_by_replica"]
        assert all(v > 0 for v in routed.values()), st
        assert st["requests_requeued"] == 0 \
            and st["requests_shed"] == 0, st
        assert st["requests_completed"] == n_req, st
        assert st["prefix_route_hit_rate"] > 0, st
        assert all(r.get("dead") is False
                   for r in st["per_replica"]), st
    vs_single = tok_s / max(single_tok, 1e-9)
    _emit("llama_serve_fleet_tokens_per_sec", tok_s,
          f"aggregate tok/s, {n_req} staggered reqs sharing a "
          f"{sys_len}-token system prompt across 2 replicas x {rb} "
          f"slots; prefix_route_hit_rate="
          f"{st['prefix_route_hit_rate']:.2f}, routed="
          f"{st['routed_by_replica']}, decide p50="
          f"{st['decision_ms']['p50']}ms, "
          f"vs_single_replica={vs_single:.2f}x",
          tok_s / max(roofline, 1e-9), spread, vals,
          extra={"replicas": 2,
                 "slots_per_replica": rb,
                 "prefix_route_hit_rate": st["prefix_route_hit_rate"],
                 "routed_by_replica": {str(k): v for k, v in
                                       st["routed_by_replica"].items()},
                 "requeued": st["requests_requeued"],
                 "decision_ms": st["decision_ms"],
                 "vs_single_replica": round(vs_single, 3),
                 "single_replica_tokens_per_sec": round(single_tok, 1),
                 **_peak_hbm_fields()})


def bench_llama_serve_autoscale():
    """SLO-driven elastic autoscaler (ISSUE 19): the deterministic
    diurnal load curve through a ServeRouter fleet with an
    AutoscalerDaemon closing the loop (start at min_replicas, scale
    out into the peak, scale back in at the trough) vs a STATIC
    min-size fleet on the same schedule under the same bounded queue.
    Reports aggregate tok/s plus the action journal summary and the
    interactive attainment of both fleets.  The CPU smoke asserts the
    loop is REAL: >= 1 scale-out and >= 1 scale-in executed, flap
    count 0, zero requests shed by the autoscaled fleet (the static
    fleet DOES shed under the same pressure — that's the capacity the
    autoscaler buys), and interactive attainment >= the static
    baseline."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    import paddle_tpu as paddle
    from paddle_tpu.fleet import (AutoscalePolicy, AutoscalerDaemon,
                                  DiurnalLoadSim)
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.inference.router import ServeRouter
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from autoscale_report import analyze_journal

    model, cfg, batch, n_params, roofline = _serving_model()
    if on_tpu:
        ticks, period, low, high = 16, 8, 2, 12
        plen, n_new, chunk, max_len, pchunk, ps = 48, 64, 32, 384, 32, 32
        rb, qdepth, steps_per_tick = max(2, batch // 2), 16, 8
    else:
        # per-replica throughput = rb slots * steps_per_tick / (2
        # prefill + 6 decode steps) = 2 req/tick: one replica sits
        # below the 3.5 req/tick diurnal average (static fleet sheds),
        # three cover the peak of 6 (autoscaled fleet sheds nothing)
        ticks, period, low, high = 12, 6, 1, 6
        plen, n_new, chunk, max_len, pchunk, ps = 6, 6, 4, 48, 4, 8
        rb, qdepth, steps_per_tick = 2, 6, 8
    drain_ticks = 4
    geom = dict(max_batch_size=rb, max_len=max_len, chunk=chunk,
                prefill_chunk=pchunk, page_size=ps)
    sim = DiurnalLoadSim(vocab=cfg.vocab_size, seed=3, period=period,
                         low=low, high=high, prompt_len=plen,
                         max_new=n_new)
    policy = AutoscalePolicy(min_replicas=1, max_replicas=3, window=1,
                             cooldown=2, queue_high=0.75,
                             queue_low=0.5, lease_ttl_s=0.0)

    def mk():
        return ContinuousBatcher(model, **geom)

    last = {}

    def run_curve(autoscale):
        router = ServeRouter(batchers=[mk()])
        daemon = AutoscalerDaemon(router, policy=policy, spawn=mk) \
            if autoscale else None
        paddle.set_flags({"FLAGS_autoscale": bool(autoscale),
                          "FLAGS_serve_queue_depth": qdepth})
        gids = []
        t0 = time.perf_counter()
        try:
            # submission ticks, then load-free drain ticks so the
            # trailing trough gives the daemon room to scale back in
            for t in range(ticks + drain_ticks):
                if t < ticks:
                    for r in sim.requests(t):
                        gids.append(router.submit(
                            r["prompt"], r["max_new"], slo=r["slo"]))
                if daemon is not None:
                    daemon.tick()
                for _ in range(steps_per_tick):
                    router.step()
            outs = router.run()
        finally:
            paddle.set_flags({"FLAGS_autoscale": False,
                              "FLAGS_serve_queue_depth": 0})
        dt = time.perf_counter() - t0
        by_cls = {}
        for g in gids:
            rr = router._reqs[g]
            tot, ok = by_cls.get(rr.slo, (0, 0))
            by_cls[rr.slo] = (tot + 1, ok + (0 if rr.shed else 1))
        att = {c: round(ok / tot, 4)
               for c, (tot, ok) in by_cls.items()}
        st = router.stats()
        last.clear()
        last.update({"stats": st, "attainment": att,
                     "journal": daemon.journal() if daemon else [],
                     "tokens": sum(len(v) for v in outs.values())})
        return last["tokens"] / dt

    run_curve(True)                     # compile (programs shared)
    tok_s, spread, vals = _measure(lambda: run_curve(True))
    auto = dict(last)
    static_tok = _measure(lambda: run_curve(False))[0]
    static = dict(last)
    jr = analyze_journal(auto["journal"], cooldown=policy.cooldown)
    auto_att = auto["attainment"].get("interactive", 1.0)
    static_att = static["attainment"].get("interactive", 1.0)
    if not on_tpu:
        # the loop must be REAL: the curve forced >= 1 scale-out into
        # the peak and >= 1 scale-in at the trough, without a single
        # flap; the autoscaled fleet dropped NOTHING while the static
        # min fleet shed under the same bounded queue; and interactive
        # attainment is no worse than the static baseline
        assert jr["executed_by_kind"].get("scale_out", 0) >= 1, jr
        assert jr["executed_by_kind"].get("scale_in", 0) >= 1, jr
        assert jr["flaps"] == 0, jr
        assert not jr["pending"] and jr["epochs_unique"], jr
        assert auto["stats"]["requests_shed"] == 0, auto["stats"]
        assert static["stats"]["requests_shed"] > 0, static["stats"]
        assert auto_att >= static_att, (auto_att, static_att)
    vs_static = tok_s / max(static_tok, 1e-9)
    _emit("llama_serve_autoscale_tokens_per_sec", tok_s,
          f"aggregate tok/s over a {ticks}-tick diurnal curve "
          f"(rate {low}..{high}/tick), autoscaled 1..3 replicas x "
          f"{rb} slots; actions={jr['executed_by_kind']}, flaps="
          f"{jr['flaps']}, shed={auto['stats']['requests_shed']} "
          f"(static min-fleet shed "
          f"{static['stats']['requests_shed']}), attainment(int)="
          f"{auto_att:.2f} vs static {static_att:.2f}, "
          f"vs_static={vs_static:.2f}x",
          tok_s / max(roofline, 1e-9), spread, vals,
          extra={"actions": jr["executed_by_kind"],
                 "rollbacks": len(jr["rollbacks"]),
                 "flaps": jr["flaps"],
                 "shed": auto["stats"]["requests_shed"],
                 "static_shed": static["stats"]["requests_shed"],
                 "attainment_interactive": auto_att,
                 "static_attainment_interactive": static_att,
                 "replicas_final": auto["stats"]["live_replicas"],
                 "vs_static_min_fleet": round(vs_static, 3),
                 "static_tokens_per_sec": round(static_tok, 1),
                 **_peak_hbm_fields()})


def bench_llama_serve_disagg():
    """Disaggregated prefill/decode serving (ISSUE 20): the SAME
    fixed-size fleet (2 replicas) run role-split — prefill workers
    freeze finished prompts and stream their KV pages to decode
    workers, which admit at pos = prompt_len — vs run symmetric, on a
    mixed long-prefill/short-decode workload sharing a system prompt.
    Reports aggregate tok/s plus TTFT/TPOT p50 for both fleets and
    the hand-off counters.  The CPU smoke asserts the topology is
    REAL: hand-offs > 0, cross-replica prefix-import hits > 0, ZERO
    prefill tokens ever computed on the decode side, outputs
    bit-exact vs the symmetric fleet, nothing shed."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.inference.router import ServeRouter

    model, cfg, batch, n_params, roofline = _serving_model()
    rngm = np.random.RandomState(6)
    if on_tpu:
        sys_len, n_req = 256, 16
        tail_lens = [96, 16, 128, 24] * 4
        new_toks = [24, 96, 16, 64] * 4
        chunk, max_len, pchunk, ps = 64, 768, 32, 32
        rb = max(1, batch // 2)
    else:
        sys_len, n_req = 24, 8
        tail_lens = [10, 4, 12, 5] * 2
        new_toks = [4, 10, 4, 8] * 2
        chunk, max_len, pchunk, ps = 4, 64, 4, 8
        rb = 1
    sys_prompt = rngm.randint(0, cfg.vocab_size, sys_len) \
        .astype(np.int32)
    prompts = [np.concatenate(
        [sys_prompt, rngm.randint(0, cfg.vocab_size, L)
         .astype(np.int32)]) for L in tail_lens[:n_req]]
    geom = dict(max_batch_size=rb, max_len=max_len, chunk=chunk,
                prefill_chunk=pchunk, page_size=ps)
    last = {}

    def fleet_once(roles):
        bats = [ContinuousBatcher(model, **geom) for _ in range(2)]
        router = ServeRouter(batchers=bats, roles=roles)
        for p_, n_ in zip(prompts, new_toks):
            router.submit(p_, n_)
        t0 = time.perf_counter()
        outs = router.run()
        dt = time.perf_counter() - t0
        last.clear()
        last.update(stats=router.stats(), outs=outs,
                    decode=[r.bat.stats() for r in router._reps
                            if r.role == "decode"])
        return sum(len(v) for v in outs.values()) / dt

    fleet_once(None)                           # compile (shared progs)
    base_tok, base_spread, _ = _measure(lambda: fleet_once(None))
    base = {k: v for k, v in last.items()}
    fleet_once(["prefill", "decode"])
    tok_s, spread, vals = _measure(
        lambda: fleet_once(["prefill", "decode"]))
    st, outs = last["stats"], last["outs"]

    def _p50(s, k):
        lat = s["stats"]["latency"].get(k) or {}
        return float(lat.get("p50") or 0.0)

    ttft, tpot = _p50(last, "ttft_ms"), _p50(last, "tpot_ms")
    base_ttft, base_tpot = _p50(base, "ttft_ms"), _p50(base, "tpot_ms")
    cross = int(st["cross_prefix_hit_tokens"])
    if not on_tpu:
        # CPU smoke: the disaggregation must be REAL and lossless
        assert st["handoffs"] > 0, st
        assert st["handoff_staged"] == 0, st
        assert cross > 0, st
        assert st["requests_shed"] == 0, st
        assert st["requests_completed"] == n_req, st
        for ds in last["decode"]:
            assert ds["prefill_tokens"] == 0, \
                "decode worker recomputed prefill after hand-off"
        assert set(outs) == set(base["outs"])
        # role-split must not change a single sampled token
        for g in outs:
            assert np.array_equal(outs[g], base["outs"][g]), g
    else:
        # the perf contract is an accelerator property: on CPU the
        # host-plane hand-off (ms-scale page gather/scatter) swamps
        # the scheduling win the split buys on real prefill/decode
        # interference, so tok/s and TTFT gate on TPU only
        assert tok_s >= base_tok, (tok_s, base_tok)
        assert ttft <= base_ttft, (ttft, base_ttft)
    vs_sym = tok_s / max(base_tok, 1e-9)
    _emit("llama_serve_disagg_tokens_per_sec", tok_s,
          f"aggregate tok/s, {n_req} mixed reqs sharing a "
          f"{sys_len}-token system prompt on a FIXED 2x{rb}-slot "
          f"fleet split prefill/decode; handoffs={st['handoffs']} "
          f"({st['handoff_bytes']}B, p50="
          f"{st['handoff_ms']['p50']}ms), cross_prefix_hits={cross} "
          f"tok, ttft p50={ttft:.1f}ms (sym {base_ttft:.1f}ms), "
          f"tpot p50={tpot:.1f}ms (sym {base_tpot:.1f}ms), "
          f"vs_symmetric={vs_sym:.2f}x",
          tok_s / max(roofline, 1e-9), spread, vals,
          extra={"replicas": 2, "slots_per_replica": rb,
                 "handoffs": st["handoffs"],
                 "handoff_bytes": st["handoff_bytes"],
                 "handoff_ms": st["handoff_ms"],
                 "cross_prefix_hit_tokens": cross,
                 "replicated_pages": st["replicated_pages"],
                 "ttft_ms_p50": round(ttft, 3),
                 "tpot_ms_p50": round(tpot, 3),
                 "symmetric_ttft_ms_p50": round(base_ttft, 3),
                 "symmetric_tpot_ms_p50": round(base_tpot, 3),
                 "vs_symmetric_fleet": round(vs_sym, 3),
                 "symmetric_tokens_per_sec": round(base_tok, 1),
                 **_peak_hbm_fields()})


def bench_serve_all():
    """BENCH_CONFIG=serve runs the mixed-length leg, the prefix-shared
    leg, the speculative leg, the serve-fleet router leg AND the
    elastic-autoscaler leg (fresh vs-baseline numbers for all —
    BENCH_r05 predates the r6 batcher, the r12 paged pool, the r15
    draft/verify scan, the r19 router and the ISSUE-19 autoscaler)."""
    bench_llama_serve()
    bench_llama_serve_prefix_shared()
    bench_llama_serve_speculative()
    bench_llama_serve_fleet()
    bench_llama_serve_autoscale()
    bench_llama_serve_disagg()


CONFIGS = {
    "llama": bench_llama,
    "offload": lambda: bench_llama(offload=True),
    "overlap": bench_llama_overlap,
    "bert": bench_bert,
    "resnet": bench_resnet,
    "unet": bench_unet,
    "decode": bench_llama_decode,
    "serve": bench_serve_all,
    "longctx": bench_longctx,
    "hybrid": bench_llama_hybrid,
}

# one table resolves config aliases AND emitted metric names, for both
# BENCH_CONFIG= and `bench.py --only <metric-or-config>` (the
# in-isolation re-measure interface — reps + spread like a full run)
_ALIASES = {
    "resnet50": "resnet", "cifar": "resnet", "sd": "unet",
    "diffusion": "unet", "generate": "decode", "serving": "serve",
    "llama_serve_mixed": "serve",
    "llama_serve_mixed_tokens_per_sec": "serve",
    "serve_prefix": "serve",
    "llama_serve_prefix_shared": "serve",
    "llama_serve_prefix_shared_tokens_per_sec": "serve",
    "serve_spec": "serve",
    "llama_serve_speculative": "serve",
    "llama_serve_speculative_tokens_per_sec": "serve",
    "serve_fleet": "serve",
    "fleet_serve": "serve",
    "llama_serve_fleet": "serve",
    "llama_serve_fleet_tokens_per_sec": "serve",
    "autoscale": "serve",
    "serve_autoscale": "serve",
    "llama_serve_autoscale": "serve",
    "llama_serve_autoscale_tokens_per_sec": "serve",
    "disagg": "serve",
    "serve_disagg": "serve",
    "llama_serve_disagg": "serve",
    "llama_serve_disagg_tokens_per_sec": "serve",
    "llama_decode": "decode",
    "llama_decode_tokens_per_sec_per_chip": "decode",
    "llama_train_tokens_per_sec_per_chip": "llama",
    "llama_offload_train_tokens_per_sec_per_chip": "offload",
    "comm_overlap": "overlap",
    "llama_sharded_overlap": "overlap",
    "llama_sharded_overlap_tokens_per_sec_per_chip": "overlap",
    "bert_base_train_tokens_per_sec_per_chip": "bert",
    "resnet50_cifar_images_per_sec": "resnet",
    "sd_unet_train_samples_per_sec": "unet",
    "llama_longctx_train_tokens_per_sec_per_chip": "longctx",
    "hybrid_parallel": "hybrid",
    "llama_hybrid": "hybrid",
    "llama_hybrid_tokens_per_sec_per_chip": "hybrid",
}


def _assert_analysis_zero_overhead():
    """FLAGS off ⇒ the verifier never touches the replay hot path: the
    Executor replay-cache key set is identical before/after loading the
    analysis subsystem AND across repeat runs, and VERIFY_CALLS does not
    move during flags-off replays (the zero-overhead contract of
    paddle_tpu/analysis — verification must be free when not asked
    for).  Cheap (tiny program), runs before every bench config."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from paddle_tpu.analysis import verifier

    static.enable_static()
    try:
        main_p = static.Program()
        with static.program_guard(main_p, static.Program()):
            x = static.data("x", [2, 4], "float32")
            w = paddle.to_tensor(np.ones((4, 3), np.float32))
            loss = paddle.matmul(x, w).mean()
        exe = static.Executor()
        xv = np.ones((2, 4), np.float32)
        exe.run(main_p, feed={"x": xv}, fetch_list=[loss])
        keys = set(main_p._exec_cache)
        calls = verifier.VERIFY_CALLS
        for _ in range(3):
            exe.run(main_p, feed={"x": xv}, fetch_list=[loss])
        assert verifier.VERIFY_CALLS == calls, \
            "verifier ran on the replay hot path with FLAGS off"
        assert set(main_p._exec_cache) == keys, \
            "flags-off replays changed the replay-cache key set"
    finally:
        static.disable_static()


def _assert_fault_tolerance_zero_overhead():
    """FLAGS off ⇒ the fault-tolerant runtime costs the step path
    nothing: no guard ops compiled into the train step (no is_finite /
    old-vs-new selects), no checkpoint IO, and the fault registry never
    counts a hit (its unset fast path is one cached string compare).
    Cheap (tiny MLP), runs before every bench config."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fault
    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.distributed.topology import build_mesh
    from paddle_tpu.parallel import ShardedTrainStep

    assert not fault.is_active(), \
        "FLAGS_fault_injection armed during a bench run"

    class _MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 8)

        def forward(self, x):
            return self.fc(x)

    paddle.seed(0)
    m = _MLP()
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    step = ShardedTrainStep(
        m, opt, build_mesh(devices=jax.devices()[:1]),
        loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.ones((4, 8), np.float32))
    hlo = step.compiled_hlo(x, y, optimized=False)
    assert "is_finite" not in hlo and "is-finite" not in hlo, \
        "guard ops compiled into the flags-off train step"
    writes, hits = ckpt.WRITE_CALLS, fault.hit_counts()
    for _ in range(2):
        step(x, y)
    assert ckpt.WRITE_CALLS == writes, \
        "flags-off train steps performed checkpoint IO"
    assert fault.hit_counts() == hits, \
        "flags-off train steps consulted the fault registry"

    # elastic reshard machinery (ISSUE 13) is flags-off free: with
    # FLAGS_ckpt_save_sharded off, (a) the trainer HLO is untouched by
    # toggling the flag (it is pure host-plane — the step never sees
    # it), and (b) checkpoint MANIFEST bytes and shard container bytes
    # are byte-identical across an arm/disarm cycle — the r9 on-disk
    # format survives the elastic merge exactly
    import os
    import shutil
    import tempfile

    def _save_bytes():
        d = tempfile.mkdtemp(prefix="bench_ckpt_")
        try:
            ckpt.save_state_dict(
                {"w": paddle.to_tensor(np.ones((8, 8), np.float32))}, d)
            with open(os.path.join(d, "metadata.json"), "rb") as f:
                manifest = f.read()
            with open(os.path.join(d, "0.distcp"), "rb") as f:
                shard = f.read()
            return manifest, shard
        finally:
            shutil.rmtree(d, ignore_errors=True)

    hlo_before = step.compiled_hlo(x, y, optimized=False)
    man_before, shard_before = _save_bytes()
    paddle.set_flags({"FLAGS_ckpt_save_sharded": True})
    try:
        man_armed, _ = _save_bytes()   # armed save must still work
        assert man_armed
    finally:
        paddle.set_flags({"FLAGS_ckpt_save_sharded": False})
    man_after, shard_after = _save_bytes()
    assert man_after == man_before, \
        "FLAGS_ckpt_save_sharded toggle changed flags-off manifests"
    assert shard_after == shard_before, \
        "FLAGS_ckpt_save_sharded toggle changed flags-off shard bytes"
    assert step.compiled_hlo(x, y, optimized=False) == hlo_before, \
        "FLAGS_ckpt_save_sharded toggle changed the train-step HLO"


def _assert_mfu_fusion_zero_overhead():
    """FLAGS_fused_ce / FLAGS_bf16_adamw_moments are toggle-stable:
    building the same tiny-llama step before, during and after toggling
    the flags must yield (a) identical flags-off StableHLO text both
    times — arming and disarming the flags leaves zero residue in the
    flags-off program — (b) a different program with the flags on (the
    fusions really engage), and (c) no 'ef' key in the flags-off
    optimizer state.  (This checks toggle idempotence, not identity
    with the pre-PR program: the flags-off loss/norm code paths were
    themselves deduplicated in this PR, value-pinned by regression
    tests.)
    Cheap (tiny llama, lowering only — no compile/execute), runs before
    every bench config."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 512, (2, 16)).astype(np.int32))

    def build(fused, bf16m):
        set_flags({"FLAGS_fused_ce": fused,
                   "FLAGS_bf16_adamw_moments": bf16m})
        try:
            paddle.seed(0)
            m = LlamaForCausalLM(llama_tiny_config())
            opt = paddle.optimizer.AdamW(
                1e-3, parameters=m.parameters(), weight_decay=0.1)
            step = ShardedTrainStep(
                m, opt, build_mesh(devices=jax.devices()[:1]),
                sharding_stage=0)
            hlo = step.compiled_hlo(ids, ids, optimized=False)
            state_keys = set(step._opt_states[0])
        finally:
            set_flags({"FLAGS_fused_ce": False,
                       "FLAGS_bf16_adamw_moments": False})
        return hlo, state_keys

    off1, keys_off = build(False, False)
    on, keys_on = build(True, True)
    off2, _ = build(False, False)
    assert off1 == off2, \
        "flags-off train step is not byte-identical across flag toggles"
    assert on != off1, "MFU-fusion flags changed nothing in the program"
    assert "ef" not in keys_off and "ef" in keys_on, \
        f"optimizer state keys wrong: off={keys_off}, on={keys_on}"


def _assert_comm_overlap_zero_overhead():
    """FLAGS_comm_overlap is toggle-stable (ISSUE 16): building the
    same tiny-llama step before, during and after toggling the flag
    must yield identical flags-off StableHLO text both times — arming
    and disarming the overlap engine leaves zero residue in the
    flags-off program.  On a single-device mesh the flag-ON program
    must ALSO be byte-identical (no cross-rank comm exists to overlap
    — the plan correctly declines to build); the multi-device
    "genuinely engages + stays bit-exact" half is tier-1-pinned on the
    8-virtual-device mesh (tests/test_comm_overlap.py), which this
    bench process does not have.  Cheap (tiny llama, lowering only),
    runs before every bench config."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 512, (2, 16)).astype(np.int32))

    def build(overlap):
        set_flags({"FLAGS_comm_overlap": overlap})
        try:
            paddle.seed(0)
            m = LlamaForCausalLM(llama_tiny_config())
            opt = paddle.optimizer.AdamW(
                1e-3, parameters=m.parameters(), weight_decay=0.1)
            step = ShardedTrainStep(
                m, opt, build_mesh(devices=jax.devices()[:1]),
                sharding_stage=0)
            hlo = step.compiled_hlo(ids, ids, optimized=False)
            plan = step._overlap_plan
        finally:
            set_flags({"FLAGS_comm_overlap": False})
        return hlo, plan

    off1, _ = build(False)
    on, plan_on = build(True)
    off2, _ = build(False)
    assert off1 == off2, \
        "flags-off train step is not byte-identical across comm_overlap toggles"
    assert plan_on is None, \
        "comm-overlap plan built on a single-device mesh (no comm to overlap)"
    assert on == off1, \
        "comm_overlap changed the single-device program (must be inert)"


def _assert_hybrid_zero_overhead():
    """The hybrid engine is residue-free on a single axis (ISSUE 17):
    a HybridParallelEngine at the trivial strategy point (all degrees
    1) must compile the SAME program as a directly-built
    ShardedTrainStep — byte-identical flags-off StableHLO — and
    toggling FLAGS_sep_ring_attention with no sep axis in the mesh
    must leave that program byte-identical too (the flag is read at
    trace time and routes through the ring kernel only when the
    activation scope carries a sep axis of size > 1).  The composed
    multi-axis half (parity to fp32 tolerance on the 8-virtual-device
    mesh) is tier-1-pinned in tests/test_hybrid_engine.py, which this
    bench process does not have the devices for.  Cheap (tiny llama,
    lowering only), runs before every bench config."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    from paddle_tpu.parallel import HybridParallelEngine, ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 512, (2, 16)).astype(np.int32))

    def build(engine, ring):
        set_flags({"FLAGS_sep_ring_attention": ring})
        try:
            paddle.seed(0)
            m = LlamaForCausalLM(llama_tiny_config())
            opt = paddle.optimizer.AdamW(
                1e-3, parameters=m.parameters(), weight_decay=0.1)
            if engine:
                eng = HybridParallelEngine(m, opt)
                step = eng.step
            else:
                step = ShardedTrainStep(
                    m, opt, build_mesh(devices=jax.devices()[:1]),
                    sharding_stage=0)
            hlo = step.compiled_hlo(ids, ids, optimized=False)
        finally:
            set_flags({"FLAGS_sep_ring_attention": False})
        return hlo

    direct = build(False, False)
    hybrid = build(True, False)
    hybrid_ring = build(True, True)
    assert hybrid == direct, \
        "trivial-point HybridParallelEngine program differs from the " \
        "directly-built ShardedTrainStep (must be byte-identical)"
    assert hybrid_ring == direct, \
        "FLAGS_sep_ring_attention changed the program with no sep axis " \
        "in the mesh (must be inert)"


def _assert_telemetry_zero_overhead():
    """No sink attached + FLAGS_compile_cache_dir unset ⇒ the telemetry
    plane costs the hot paths nothing: the compiled train-step HLO is
    byte-identical to flags-off (arming and disarming a sink + the
    incident flight recorder + the compile cache leaves zero residue
    in the program — with FLAGS_numerics_stats unset; ON, the flag
    must genuinely change the program, asserted below), and flags-off
    static-executor replays neither grow the replay-cache key set nor
    emit events.  Cheap (tiny MLP + tiny program), runs before every
    bench config."""
    import tempfile
    import numpy as np
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from paddle_tpu import telemetry
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.distributed.topology import build_mesh
    from paddle_tpu.parallel import ShardedTrainStep

    assert not telemetry.active(), \
        "a telemetry sink is attached during a bench run"
    assert telemetry.cache_dir() is None, \
        "FLAGS_compile_cache_dir armed during a bench run"

    def build_hlo():
        class _MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = paddle.nn.Linear(8, 8)

            def forward(self, x):
                return self.fc(x)

        paddle.seed(0)
        m = _MLP()
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = ShardedTrainStep(
            m, opt, build_mesh(devices=jax.devices()[:1]),
            loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        return step, x, step.compiled_hlo(x, x, optimized=False)

    _, _, hlo_off = build_hlo()
    with tempfile.TemporaryDirectory() as d:
        import os as _os
        sink = telemetry.attach_jsonl(_os.path.join(d, "s.jsonl"))
        # arm the WHOLE observability surface at once: sink + compile
        # cache + fleet identity + straggler detector flag — the r11
        # byte-identical contract extends to the ISSUE 10 fleet plane
        # (rank tagging, memory-ledger registration, fleet flags are
        # all host-side)
        telemetry.set_rank(0, 2)
        # the incident flight recorder joins the armed surface (ISSUE
        # 14): it is a plain sink (ring append + trigger lookup), so
        # attaching it — with FLAGS_numerics_stats left unset — must
        # leave the compiled step AND its cache keys byte-identical.
        # Scope it: a production recorder armed via FLAGS_flightrec_dir
        # must be back in place when the assert finishes
        _prev_rec = telemetry.flightrec.detach()
        telemetry.flightrec.attach(_os.path.join(d, "incidents"))
        # FLAGS_mfu_floor joins the armed surface (ISSUE 12): the cost
        # ledger's drift floor is host-plane only, so arming it must
        # leave the compiled step byte-identical too
        set_flags({"FLAGS_compile_cache_dir":
                   _os.path.join(d, "cache"),
                   "FLAGS_straggler_skew_ms": 50.0,
                   "FLAGS_mfu_floor": 0.5})
        try:
            step, x, hlo_armed = build_hlo()
            step(x, x)                      # exercise the armed path
        finally:
            set_flags({"FLAGS_compile_cache_dir": "",
                       "FLAGS_straggler_skew_ms": 0.0,
                       "FLAGS_mfu_floor": 0.0})
            telemetry.disable_persistent_cache()
            telemetry.flightrec.detach()
            telemetry.flightrec.restore(_prev_rec)
            telemetry.remove_sink(sink)
    _, _, hlo_off2 = build_hlo()
    assert hlo_off == hlo_armed == hlo_off2, \
        "telemetry sink / compile-cache / fleet / cost-ledger / " \
        "flight-recorder arming changed the train-step program"
    # the numerics plane is a PROGRAM switch (ISSUE 14): ON it must
    # actually change the build (per-layer reductions in-graph) — a
    # vacuous flag would make the byte-identical assert above prove
    # nothing about it
    set_flags({"FLAGS_numerics_stats": True})
    try:
        _, _, hlo_num = build_hlo()
    finally:
        set_flags({"FLAGS_numerics_stats": False})
    assert hlo_num != hlo_off, \
        "FLAGS_numerics_stats did not reach the compiled train step"
    # scrub the assert's own footprint (steps/compile records from the
    # tiny MLP) so the telemetry snapshot embedded in this config's
    # metric lines reflects ONLY the config's run — then put the
    # production flight recorder back (reset() detaches every sink,
    # which would otherwise undo the finally-block restore above)
    telemetry.reset()
    telemetry.clear_report()
    telemetry.flightrec.restore(_prev_rec)

    # static-executor replay hot path: flags-off replays must not grow
    # the replay-cache key set or publish events
    static.enable_static()
    try:
        main_p = static.Program()
        with static.program_guard(main_p, static.Program()):
            xs = static.data("x", [2, 4], "float32")
            w = paddle.to_tensor(np.ones((4, 3), np.float32))
            loss = paddle.matmul(xs, w).mean()
        exe = static.Executor()
        xv = np.ones((2, 4), np.float32)
        exe.run(main_p, feed={"x": xv}, fetch_list=[loss])
        keys = set(main_p._exec_cache)
        probe = telemetry.MemorySink()
        telemetry.add_sink(probe)
        try:
            for _ in range(3):
                exe.run(main_p, feed={"x": xv}, fetch_list=[loss])
        finally:
            telemetry.remove_sink(probe)
        assert set(main_p._exec_cache) == keys, \
            "replays with a sink attached changed the replay-cache keys"
        assert not probe.records, \
            "flags-off executor replays published telemetry events"
    finally:
        static.disable_static()


def _assert_serve_robustness_zero_overhead():
    """The serve-plane robustness layer (ISSUE 9: SLO admission,
    deadlines, load shedding, fault recovery) is HOST-plane control
    flow only: with the flags off NOTHING about the compiled serve
    step may change, and with the flags ON the programs must be the
    very same ones — program-cache keys AND lowered step HLO
    byte-identical across the flag toggle, exactly 2 compiled programs
    under a mixed-SLO multi-length workload (prompt length and SLO mix
    never reach a program shape).  Cheap (1-layer tiny llama); runs
    before every bench config."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import telemetry
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)

    paddle.seed(3)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            intermediate_size=64,
                            num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=64)
    model = LlamaForCausalLM(cfg)
    geom = dict(max_batch_size=2, max_len=32, chunk=4, prefill_chunk=4)

    def fingerprint():
        bat = ContinuousBatcher(model, **geom)
        keys = (bat._program_key(1, bat.chunk),
                bat._program_key(bat.prefill_chunk, bat.admit_steps))
        hlo = (bat.lower_step(mixed=False).as_text(),
               bat.lower_step(mixed=True).as_text())
        return bat, keys, hlo

    _, keys_off, hlo_off = fingerprint()
    # the flight recorder joins the armed surface here too (ISSUE 14):
    # with it attached (and FLAGS_numerics_stats unset) the serve-step
    # HLO and program-cache keys must stay byte-identical
    import tempfile as _tempfile
    _fr_dir = _tempfile.mkdtemp(prefix="bench-flightrec-")
    _prev_rec = telemetry.flightrec.detach()   # scope: restore below
    telemetry.flightrec.attach(_fr_dir)
    set_flags({"FLAGS_serve_queue_depth": 8,
               "FLAGS_serve_default_deadline_ms": 60000.0})
    try:
        bat_on, keys_on, hlo_on = fingerprint()
        rng = np.random.RandomState(0)
        for L, slo in ((3, "interactive"), (7, "batch"),
                       (5, "best_effort"), (9, "interactive"),
                       (11, "batch")):
            bat_on.submit(rng.randint(1, 64, L).astype(np.int32), 4,
                          slo=slo)
        outs = bat_on.run()
        st = bat_on.stats()
    finally:
        set_flags({"FLAGS_serve_queue_depth": 0,
                   "FLAGS_serve_default_deadline_ms": 0.0})
        telemetry.flightrec.detach()
        telemetry.flightrec.restore(_prev_rec)
        import shutil as _shutil
        _shutil.rmtree(_fr_dir, ignore_errors=True)
    assert keys_off == keys_on, \
        f"robustness flags / flight recorder leaked into serve " \
        f"program keys: {keys_off} vs {keys_on}"
    assert hlo_off == hlo_on, \
        "robustness flags / flight-recorder arming changed the " \
        "lowered serve-step HLO"
    assert st["compiled_programs"] == 2, \
        f"mixed-SLO multi-length workload compiled " \
        f"{st['compiled_programs']} programs (want 2)"
    assert st["requests_shed"] == 0 \
        and st["requests_completed"] == len(outs), st
    _, _, hlo_off2 = fingerprint()
    assert hlo_off == hlo_off2, \
        "serve-step HLO changed after the flag round-trip"


def _assert_autoscale_zero_overhead():
    """ISSUE 19 flags-off contract: the elastic autoscaler is a HOST
    control loop that must cost NOTHING when off.  With FLAGS_autoscale
    unset a constructed AutoscalerDaemon's tick() is one flag read —
    zero KV-plane traffic (no lease, no journal, no recovery scan) —
    and importing the fleet package + building a daemon leaves the
    serve-step program-cache keys and lowered HLO byte-identical across
    the flag round-trip.  Cheap (1-layer tiny llama); runs before
    every bench config."""
    import paddle_tpu as paddle
    from paddle_tpu.fleet import AutoscalerDaemon
    from paddle_tpu.fleet.autoscaler import _LocalKV
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.inference.router import ServeRouter
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)

    paddle.seed(3)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            intermediate_size=64,
                            num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=64)
    model = LlamaForCausalLM(cfg)
    geom = dict(max_batch_size=2, max_len=32, chunk=4, prefill_chunk=4)

    def fingerprint():
        bat = ContinuousBatcher(model, **geom)
        keys = (bat._program_key(1, bat.chunk),
                bat._program_key(bat.prefill_chunk, bat.admit_steps))
        hlo = (bat.lower_step(mixed=False).as_text(),
               bat.lower_step(mixed=True).as_text())
        return bat, keys, hlo

    class _CountingKV:
        """Every KV verb the daemon could issue, counted."""

        def __init__(self, inner):
            self._inner = inner
            self.calls = 0

        def __getattr__(self, name):
            attr = getattr(self._inner, name)
            if not callable(attr):
                return attr

            def wrapped(*a, **k):
                self.calls += 1
                return attr(*a, **k)
            return wrapped

    _, keys_off, hlo_off = fingerprint()
    kv = _CountingKV(_LocalKV())
    router = ServeRouter(batchers=[ContinuousBatcher(model, **geom)])
    daemon = AutoscalerDaemon(router, kv=kv)
    for _ in range(4):
        out = daemon.tick()
        assert out.get("status") == "disabled", out
    assert kv.calls == 0, \
        f"FLAGS_autoscale off but the daemon issued {kv.calls} " \
        f"KV-plane calls (the zero-overhead gate is the flag check)"
    set_flags({"FLAGS_autoscale": True})
    try:
        _, keys_on, hlo_on = fingerprint()
    finally:
        set_flags({"FLAGS_autoscale": False})
    assert keys_off == keys_on, \
        f"FLAGS_autoscale leaked into serve program keys: " \
        f"{keys_off} vs {keys_on}"
    assert hlo_off == hlo_on, \
        "FLAGS_autoscale changed the lowered serve-step HLO"
    _, _, hlo_off2 = fingerprint()
    assert hlo_off == hlo_off2, \
        "serve-step HLO changed after the autoscale flag round-trip"


def _assert_disagg_zero_overhead():
    """ISSUE 20 flags-off contract: disaggregation must cost NOTHING
    when unused.  With FLAGS_serve_disagg off a unified serve run —
    hand-off/replication code imported, a whole router fleet behind
    it — leaves the single-batcher serve program-cache keys and
    lowered HLO byte-identical across the flag round-trip, compiles
    ZERO page export/import programs, and the no-op replication sweep
    issues zero KV-plane verbs.  Cheap (1-layer tiny llama); runs
    before every bench config."""
    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.inference.generation import _program_cache_contains
    from paddle_tpu.inference.router import ServeRouter
    from paddle_tpu.inference.serving import (pack_handoff,   # noqa: F401
                                              unpack_handoff)

    paddle.seed(3)
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            intermediate_size=64,
                            num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=64)
    model = LlamaForCausalLM(cfg)
    geom = dict(max_batch_size=2, max_len=32, chunk=4, prefill_chunk=4)

    def fingerprint():
        bat = ContinuousBatcher(model, **geom)
        keys = (bat._program_key(1, bat.chunk),
                bat._program_key(bat.prefill_chunk, bat.admit_steps))
        hlo = (bat.lower_step(mixed=False).as_text(),
               bat.lower_step(mixed=True).as_text())
        return bat, keys, hlo

    bat0, keys_off, hlo_off = fingerprint()
    page_keys = [("serve_page_export", bat0.num_pages, bat0.page_size,
                  bat0.pages_per_slot, bat0._kv_dtype),
                 ("serve_page_import", bat0.num_pages, bat0.page_size,
                  bat0.pages_per_slot, bat0._kv_dtype)]
    # a flags-off unified fleet run: no role ever set, so no freeze,
    # no hand-off, no page program may compile
    rng = np.random.RandomState(1)
    router = ServeRouter(batchers=[ContinuousBatcher(model, **geom)
                                   for _ in range(2)])
    for L in (5, 7, 6):
        router.submit(rng.randint(1, 64, L).astype(np.int32), 4)
    outs = router.run()
    assert len(outs) == 3 and router.stats()["handoffs"] == 0
    for k in page_keys:
        assert not _program_cache_contains(model, k), \
            f"flags-off serve compiled a hand-off page program: {k}"
    set_flags({"FLAGS_serve_disagg": True,
               "FLAGS_router_migration_budget": 4})
    try:
        _, keys_on, hlo_on = fingerprint()
    finally:
        set_flags({"FLAGS_serve_disagg": False,
                   "FLAGS_router_migration_budget": 0})
    assert keys_off == keys_on, \
        f"FLAGS_serve_disagg leaked into serve program keys: " \
        f"{keys_off} vs {keys_on}"
    assert hlo_off == hlo_on, \
        "FLAGS_serve_disagg changed the lowered serve-step HLO"
    _, keys_off2, hlo_off2 = fingerprint()
    assert keys_off == keys_off2 and hlo_off == hlo_off2, \
        "serve programs changed after the disagg flag round-trip"


def _assert_decode_roofline_zero_overhead():
    """ISSUE 11 flags-off contract: FLAGS_weight_only_dtype and the
    speculation flags leave the flags-off programs byte-identical.
    (a) the serve-step HLO and program keys of an UNQUANTIZED,
    non-speculative batcher are identical before/during/after a flag
    toggle cycle; (b) the llama TRAIN step never reads the flags at
    all (HLO identical with them armed); (c) the protection is real:
    under the armed flag the program-cache fingerprint changes, so a
    program traced at flags-off can never be replayed (stale-replay
    guard), and speculation swaps the decode program key; (d) restored
    defaults hit the original programs warm.  Cheap (1-layer tiny
    llama, lowering only); runs before every bench config."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.inference.generation import _program_cache_contains
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            intermediate_size=64,
                            num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=64)
    model = LlamaForCausalLM(cfg)
    geom = dict(max_batch_size=2, max_len=32, chunk=4, prefill_chunk=4)

    def fingerprint(**kw):
        bat = ContinuousBatcher(model, weight_only_dtype="none",
                                **geom, **kw)
        keys = (bat._program_key(1, bat.chunk),
                bat._program_key(bat.prefill_chunk, bat.admit_steps))
        hlo = (bat.lower_step(mixed=False).as_text(),
               bat.lower_step(mixed=True).as_text())
        return bat, keys, hlo

    def train_hlo():
        paddle.seed(8)
        m = LlamaForCausalLM(llama_tiny_config())
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters(),
                                     weight_decay=0.1)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 512, (2, 16)).astype(np.int32))
        step = ShardedTrainStep(m, opt,
                                build_mesh(devices=jax.devices()[:1]),
                                sharding_stage=0)
        return step.compiled_hlo(ids, ids, optimized=False)

    bat0, keys_off, hlo_off = fingerprint()
    probe_key = keys_off[0]
    # build the real decode program so the cache-miss guard below has
    # something to protect
    bat0._step_fn(1, bat0.chunk)
    assert _program_cache_contains(model, probe_key)
    t_off = train_hlo()
    set_flags({"FLAGS_weight_only_dtype": "int8"})
    try:
        _, keys_on, hlo_on = fingerprint()
        # the flags-off-traced program is UNREACHABLE under the armed
        # flag (fingerprinted cache key) even though the lowered HLO of
        # an unquantized model is unchanged — that is the stale-replay
        # guard, not a recompile of different code
        assert not _program_cache_contains(model, probe_key), \
            "weight-only flag flip did not invalidate cached programs"
        assert keys_on == keys_off, \
            "weight-only flag leaked into the serve program keys"
        assert hlo_on == hlo_off, \
            "weight-only flag changed an unquantized serve-step HLO"
        assert train_hlo() == t_off, \
            "weight-only flag changed the llama train-step HLO"
    finally:
        set_flags({"FLAGS_weight_only_dtype": "none"})
    assert _program_cache_contains(model, probe_key), \
        "restored flags no longer hit the original serve programs"
    # speculation swaps the decode program (key and HLO both differ) —
    # and restoring the default gives back the original byte-for-byte
    bat_s, keys_spec, hlo_spec = fingerprint(spec_tokens=2,
                                             draft_layers=1)
    assert keys_spec[0] != keys_off[0], \
        "speculation did not change the decode program key"
    assert hlo_spec[0] != hlo_off[0], \
        "speculation did not change the decode program"
    # donation lint over every new program shape: the draft/verify
    # decode scan and the draft-carrying admit scan must alias every
    # carry (a forgotten donate_argnum doubles the KV pool in HBM)
    from paddle_tpu.analysis import lint_serve_programs
    findings = lint_serve_programs(bat_s) + lint_serve_programs(bat0)
    assert not findings, \
        f"serve programs hold undonated carries: {findings}"
    _, keys_off2, hlo_off2 = fingerprint()
    assert keys_off2 == keys_off and hlo_off2 == hlo_off, \
        "serve programs changed after the speculation round-trip"


def main():
    _assert_serve_robustness_zero_overhead()
    _assert_autoscale_zero_overhead()
    _assert_disagg_zero_overhead()
    _assert_decode_roofline_zero_overhead()
    _assert_analysis_zero_overhead()
    _assert_fault_tolerance_zero_overhead()
    _assert_mfu_fusion_zero_overhead()
    _assert_comm_overlap_zero_overhead()
    _assert_hybrid_zero_overhead()
    _assert_telemetry_zero_overhead()
    which = os.environ.get("BENCH_CONFIG", "all").lower()
    if "--only" in sys.argv:
        i = sys.argv.index("--only")
        if i + 1 >= len(sys.argv):
            print(json.dumps({"metric": "bench_config_error", "value": 0,
                              "unit": "--only requires a metric/config "
                                      "name", "vs_baseline": 0.0}),
                  flush=True)
            return 2
        which = sys.argv[i + 1].lower()
    which = _ALIASES.get(which, which)
    # legacy interface: BENCH_OFFLOAD=1 turns the llama config into the
    # offload config (r4 drivers invoke it this way)
    if os.environ.get("BENCH_OFFLOAD", "") not in ("", "0") \
            and which in ("llama", "offload", "all"):
        return bench_llama(offload=True)
    if which in CONFIGS:
        return CONFIGS[which]()
    if which != "all":
        print(json.dumps({"metric": "bench_config_error", "value": 0,
                          "unit": f"unknown BENCH_CONFIG={which!r}; "
                                  f"choose {sorted(CONFIGS)} or 'all'",
                          "vs_baseline": 0.0}), flush=True)
        return 2
    # default: the full matrix, llama first (headline metric lands even
    # if a shared-chip hiccup cuts the run short).  Each config runs in
    # its OWN subprocess: the previous config's params/opt-state would
    # otherwise stay resident in this process's jax client and OOM the
    # 16G chip for every config after the first.
    import subprocess
    here = os.path.abspath(__file__)
    budget = float(os.environ.get("BENCH_CONFIG_TIMEOUT", "1500"))
    for name in CONFIGS:
        env = dict(os.environ)
        env["BENCH_CONFIG"] = name
        # the chip is SHARED: a transient co-tenant allocation can OOM
        # a config that normally fits (observed once on the offload leg
        # at 15.8/16G peak) — retry RESOURCE_EXHAUSTED once after a
        # pause before recording an error
        for attempt in (0, 1):
            try:
                proc = subprocess.run(
                    [sys.executable, here], env=env, text=True,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    timeout=budget)
            except subprocess.TimeoutExpired:
                print(json.dumps({"metric": f"{name}_bench_error",
                                  "value": 0,
                                  "unit": f"timeout {budget}s",
                                  "vs_baseline": 0.0}), flush=True)
                break
            out = proc.stdout.strip()
            if proc.returncode == 0 and out:
                print(out, flush=True)
                break
            # retry only a FATAL oom: nonzero rc with the error in the
            # stderr tail (a recovered/logged OOM inside an otherwise
            # distinct failure shouldn't burn the re-run budget)
            if attempt == 0 and proc.returncode != 0 \
                    and "RESOURCE_EXHAUSTED" in (proc.stderr or "")[-2000:]:
                time.sleep(60)
                continue
            tail = (proc.stderr or proc.stdout or "")[-200:]
            print(json.dumps({"metric": f"{name}_bench_error",
                              "value": 0,
                              "unit": f"rc={proc.returncode}: {tail}",
                              "vs_baseline": 0.0}), flush=True)
            break
    return None


if __name__ == "__main__":
    sys.exit(main() or 0)
