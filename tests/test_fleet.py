"""Fleet-wide observability (ISSUE 10): rank-aware telemetry, the
coordinator aggregator + straggler/desync detector, the HBM memory
ledger, per-request serve spans, and the sink drain-flush contract.

The contracts under test:

  * every emitted event carries (rank, world) once the fleet identity
    is announced — trainers, watchdog, fault registry, checkpoint
    runtime all inherit it from the bus (satellite: they were
    anonymous);
  * FleetSink publishes per-rank step summaries into the launch KV
    store; FleetAggregator judges per-step cross-rank wall/arrival
    skew, emits fleet.straggler naming the slow rank, arms/disarms
    the comm watchdog, and emits fleet.desync on step-counter spread
    or per-step collective-kind mismatch;
  * 2-process e2e (acceptance): per-rank JSONL logs merge into ONE
    chrome trace with a lane per rank, and a mode=delay fault injected
    into rank 1 makes the coordinator fire fleet.straggler while both
    ranks complete bit-exact;
  * telemetry.memory_report() returns non-empty per-program byte
    accounting for every trainer and the serve step (XLA's own
    memory_analysis, not hand-derived), and lint_peak_hbm flags a
    planted over-budget program;
  * ContinuousBatcher stamps queue→admit→first-token→finish per
    request: stats() carries TTFT/TPOT/e2e/queue percentiles and
    per-SLO attainment, serve.request events feed the report CLI;
  * JSONL/chrome sinks flush on interpreter exit, so a SIGTERM drain
    loses nothing (subprocess kill mid-run, tail asserted on disk);
  * tools/fleet_report.py --selftest (tier-1 wiring, like
    telemetry_report --selftest).
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import telemetry
from paddle_tpu.telemetry.fleet import (FleetSink, FleetAggregator,
                                        merge_jsonl_traces, load_jsonl)
from paddle_tpu.distributed.launch.master import KVServer, KVClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    """Sinks detached, rank identity dropped, memory ledger empty on
    both sides of every test (the plane is process-global)."""
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture()
def kv_store():
    server = KVServer(0, host="127.0.0.1").start()
    try:
        yield KVClient(f"127.0.0.1:{server.port}")
    finally:
        server.stop()


def _mlp_step():
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    m = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                             paddle.nn.Linear(16, 8))
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    step = TrainStep(m, lambda o, y: paddle.nn.functional.mse_loss(o, y),
                     opt)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    return step, x


# ---------------------------------------------------------------------------
# rank-aware records

class TestRankTagging:
    def test_events_carry_rank_and_world(self):
        telemetry.set_rank(3, 4)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            telemetry.emit("probe", a=1)
        finally:
            telemetry.remove_sink(sink)
        (rec,) = sink.records
        assert rec["rank"] == 3 and rec["world"] == 4

    def test_single_process_world_omits_world_field(self):
        telemetry.set_rank(0, 1)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            telemetry.emit("probe")
        finally:
            telemetry.remove_sink(sink)
        (rec,) = sink.records
        assert rec["rank"] == 0 and "world" not in rec

    def test_uninitialized_stays_untagged(self):
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            telemetry.emit("probe")
        finally:
            telemetry.remove_sink(sink)
        assert "rank" not in sink.records[0]

    def test_runtime_producers_inherit_rank(self, tmp_path):
        """Satellite: watchdog, fault-registry and checkpoint events
        were anonymous — with the identity announced they all carry
        the rank label without any call-site change."""
        from paddle_tpu.distributed import fault
        from paddle_tpu.distributed import checkpoint as ckpt
        from paddle_tpu.distributed.watchdog import CommTaskManager
        telemetry.set_rank(2, 4)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            with fault.scope("step.begin:mode=delay:secs=0"):
                fault.hit("step.begin", key="probe")
            ckpt.save_checkpoint(
                {"w": paddle.to_tensor(np.ones((2, 2), np.float32))},
                str(tmp_path), 1)
            mgr = CommTaskManager(poll_interval=0.02)
            task = mgr.start_task("rank probe hang", timeout=0.05)
            try:
                deadline = time.time() + 5
                while not mgr.timeout_log and time.time() < deadline:
                    time.sleep(0.02)
            finally:
                task.done()
                mgr.shutdown()
        finally:
            telemetry.remove_sink(sink)
        by_event = {}
        for r in sink.records:
            by_event.setdefault(r["event"], r)
        for ev in ("fault.hit", "ckpt.commit", "watchdog.timeout"):
            assert ev in by_event, sorted(by_event)
            assert by_event[ev]["rank"] == 2, by_event[ev]
            assert by_event[ev]["world"] == 4

    def test_train_step_events_tagged(self):
        telemetry.set_rank(1, 2)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            step, x = _mlp_step()
            step(x, x)
        finally:
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records if r["event"] == "train.step"]
        assert evs and evs[0]["rank"] == 1 and evs[0]["world"] == 2

    def test_init_parallel_env_announces_rank(self):
        from paddle_tpu.distributed import env as denv
        prev = denv._initialized
        denv._initialized = False
        try:
            denv.init_parallel_env()
            assert telemetry.rank_info() == (0, 1)
        finally:
            denv._initialized = prev

    def test_dump_carries_identity(self):
        telemetry.set_rank(5, 8)
        d = telemetry.dump()
        assert d["rank"] == {"rank": 5, "world": 8}


# ---------------------------------------------------------------------------
# histogram percentiles (satellite)

class TestPercentiles:
    def test_histogram_percentiles_and_summary(self):
        h = telemetry.histogram("lat")
        for v in range(1, 101):
            h.observe(float(v))
        pct = h.percentiles((50, 90, 99))
        assert pct["p50"] == pytest.approx(50, abs=1)
        assert pct["p90"] == pytest.approx(90, abs=1)
        assert pct["p99"] == pytest.approx(99, abs=1)
        s = h.summary()
        assert {"p50", "p90", "p99"} <= set(s)
        d = telemetry.dump()
        assert d["histograms"]["lat"]["p90"] == s["p90"]

    def test_percentiles_of_empty(self):
        assert telemetry.percentiles_of([], (50, 99)) \
            == {"p50": 0.0, "p99": 0.0}


# ---------------------------------------------------------------------------
# fleet sink + aggregator

def _publish(kv, rank, step, wall_ms, ts=None, world=2, job="j",
             kinds=None, cold=False):
    s = FleetSink(kv, job_id=job, rank=rank, world=world, every=1)
    if kinds is not None:
        s.record({"event": "collective.schedule", "kinds": kinds})
    rec = {"event": "train.step", "step": step,
           "ts": float(step) if ts is None else ts,
           "wall_ms": wall_ms, "step_ms": wall_ms, "k": 1}
    if cold:
        rec["cold"] = True
    s.record(rec)
    s.close()       # synchronous drain: the summary is in the store


class TestAggregator:
    def test_straggler_detected_and_attributed(self, kv_store):
        for step in (1, 2, 3):
            for rank in (0, 1):
                wall = 100.0 if (rank == 1 and step == 3) else 10.0
                _publish(kv_store, rank, step, wall)
        probe = telemetry.add_sink(telemetry.MemorySink())
        try:
            agg = FleetAggregator(kv_store, job_id="j", world=2,
                                  skew_ms=50.0)
            rep = agg.poll()
        finally:
            telemetry.remove_sink(probe)
        evs = [r for r in probe.records
               if r["event"] == "fleet.straggler"]
        assert len(evs) == 1
        assert evs[0]["straggler"] == 1 and evs[0]["step"] == 3
        assert evs[0]["skew_ms"] == pytest.approx(90.0)
        assert rep["max_skew_ms"] == pytest.approx(90.0)
        assert rep["stragglers"] == {1: 1}
        # steps are judged exactly once: a second poll is silent
        probe2 = telemetry.add_sink(telemetry.MemorySink())
        try:
            agg.poll()
        finally:
            telemetry.remove_sink(probe2)
        assert not [r for r in probe2.records
                    if r["event"] == "fleet.straggler"]

    def test_below_threshold_records_skew_silently(self, kv_store):
        for rank in (0, 1):
            _publish(kv_store, rank, 1, 10.0 + rank)
        probe = telemetry.add_sink(telemetry.MemorySink())
        try:
            rep = FleetAggregator(kv_store, job_id="j", world=2,
                                  skew_ms=50.0).poll()
        finally:
            telemetry.remove_sink(probe)
        assert rep["skews"] and not rep["stragglers"]
        assert not [r for r in probe.records
                    if r["event"] == "fleet.straggler"]

    def test_cold_steps_not_judged(self, kv_store):
        for rank in (0, 1):
            _publish(kv_store, rank, 1, 1000.0 if rank else 1.0,
                     cold=True)
        rep = FleetAggregator(kv_store, job_id="j", world=2,
                              skew_ms=10.0).poll()
        assert not rep["skews"] and not rep["stragglers"]

    def test_straggler_arms_and_disarms_watchdog(self, kv_store):
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.distributed.watchdog import get_comm_task_manager
        set_flags({"FLAGS_stop_check_timeout": 600})
        try:
            agg = FleetAggregator(kv_store, job_id="j", world=2,
                                  skew_ms=50.0)
            for rank in (0, 1):
                _publish(kv_store, rank, 1, 100.0 if rank else 10.0)
            rep = agg.poll()
            assert rep["watchdog_armed"] == [1]
            assert "fleet.straggler rank1" in \
                get_comm_task_manager().active_tasks()
            # rank 1 catches up -> disarmed
            for rank in (0, 1):
                _publish(kv_store, rank, 2, 10.0)
            rep = agg.poll()
            assert rep["watchdog_armed"] == []
            assert "fleet.straggler rank1" not in \
                get_comm_task_manager().active_tasks()
        finally:
            set_flags({"FLAGS_stop_check_timeout": 0})
            agg.close()

    def test_tombstoned_rank_never_reads_as_straggler(self, kv_store):
        """ISSUE 19 satellite: a rank retired by a scale-in tombstones
        itself — its stale summaries leave the judged set, the
        effective world shrinks so the survivors' steps keep being
        judged, and no spurious fleet.straggler ever fires."""
        from paddle_tpu.telemetry.fleet import tombstone_rank
        for step in (1, 2):
            for rank in (0, 1):
                _publish(kv_store, rank, step, 10.0)
        # rank 1 retires mid-run through the sink's own retire() path
        s = FleetSink(kv_store, job_id="j", rank=1, world=2, every=1)
        s.retire()
        assert kv_store.get("j/fleet/1/tombstone") is not None
        # the survivor keeps stepping alone; rank 1's stale summaries
        # are still on the plane
        for step in (3, 4):
            _publish(kv_store, 0, step, 10.0)
        probe = telemetry.add_sink(telemetry.MemorySink())
        try:
            agg = FleetAggregator(kv_store, job_id="j", world=2,
                                  skew_ms=50.0)
            agg.straggler_counts[1] = 3       # stale verdicts clear too
            rep = agg.poll()
        finally:
            telemetry.remove_sink(probe)
        assert not [r for r in probe.records
                    if r["event"] == "fleet.straggler"]
        assert rep["tombstoned"] == [1]
        assert rep["world_effective"] == 1
        assert rep["ranks"] == [0]
        assert rep["stragglers"] == {}
        # the survivor's solo steps WERE judged (world shrank — the
        # aggregator isn't waiting forever for the retired rank)
        assert rep["steps_judged"] == 4
        # idempotent across polls and across a re-retire
        assert tombstone_rank(kv_store, "j", 1)
        rep2 = agg.poll()
        assert rep2["tombstoned"] == [1] and rep2["stragglers"] == {}

    def test_desync_on_step_spread(self, kv_store):
        _publish(kv_store, 0, 30, 10.0)
        _publish(kv_store, 1, 1, 10.0)
        probe = telemetry.add_sink(telemetry.MemorySink())
        try:
            agg = FleetAggregator(kv_store, job_id="j", world=2,
                                  skew_ms=0.0, desync_steps=8)
            agg.poll()
            agg.poll()              # edge-triggered: no second event
        finally:
            telemetry.remove_sink(probe)
        evs = [r for r in probe.records if r["event"] == "fleet.desync"]
        assert len(evs) == 1
        assert evs[0]["reason"] == "step-spread"
        assert evs[0]["spread"] == 29

    def test_desync_on_collective_mismatch(self, kv_store):
        _publish(kv_store, 0, 1, 10.0, kinds={"psum": 2})
        _publish(kv_store, 1, 1, 10.0, kinds={"psum": 3})
        probe = telemetry.add_sink(telemetry.MemorySink())
        try:
            FleetAggregator(kv_store, job_id="j", world=2,
                            skew_ms=0.0).poll()
        finally:
            telemetry.remove_sink(probe)
        evs = [r for r in probe.records if r["event"] == "fleet.desync"]
        assert evs and evs[0]["reason"] == "collectives"

    def test_collective_kinds_ride_one_summary_only(self, kv_store):
        """Regression: a probe's kind counts attach to the NEXT
        summary only — a stale mix smeared onto every later step
        would read as a permanent desync."""
        s = FleetSink(kv_store, job_id="c1", rank=0, world=1, every=1)
        s.record({"event": "collective.schedule",
                  "kinds": {"psum": 2}})
        for step in (1, 2):
            s.record({"event": "train.step", "step": step,
                      "ts": float(step), "wall_ms": 1.0,
                      "step_ms": 1.0, "k": 1})
        s.close()
        one = json.loads(kv_store.get("c1/fleet/0/s00000001"))
        two = json.loads(kv_store.get("c1/fleet/0/s00000002"))
        assert one["collectives"] == {"psum": 2}
        assert "collectives" not in two

    def test_sink_prunes_its_window(self, kv_store):
        s = FleetSink(kv_store, job_id="w", rank=0, world=1, every=1,
                      window=4)
        for step in range(1, 11):
            s.record({"event": "train.step", "step": step,
                      "ts": float(step), "wall_ms": 1.0,
                      "step_ms": 1.0, "k": 1})
        s.close()
        keys = set(kv_store.prefix("w/fleet"))
        step_keys = {k for k in keys if not k.endswith("/latest")}
        assert len(step_keys) == 4          # rolling window
        assert "w/fleet/0/s00000010" in step_keys
        latest = json.loads(kv_store.get("w/fleet/0/latest"))
        assert latest["step"] == 10

    def test_sink_prunes_strided_steps(self, kv_store):
        """Regression: fused multi-step trainers publish steps k, 2k,
        3k... — the window must prune the keys actually published,
        not `step - window` (which is never a published key when the
        stride doesn't divide the window)."""
        s = FleetSink(kv_store, job_id="ws", rank=0, world=1, every=1,
                      window=3)
        for step in range(5, 55, 5):        # stride 5, 10 publishes
            s.record({"event": "train.step", "step": step,
                      "ts": float(step), "wall_ms": 1.0,
                      "step_ms": 1.0, "k": 5})
        s.close()
        step_keys = {k for k in kv_store.prefix("ws/fleet")
                     if not k.endswith("/latest")}
        assert len(step_keys) == 3, step_keys
        assert "ws/fleet/0/s00000050" in step_keys

    def test_sink_never_blocks_on_a_stalled_coordinator(self, kv_store):
        """Regression: the publisher is decoupled behind a bounded
        queue — with the coordinator stalled (each publish slow),
        record() returns immediately and overflow is counted as
        dropped, never stalling the step loop."""
        s = FleetSink(kv_store, job_id="stall", rank=0, world=1,
                      every=1)
        s._publish = lambda msg: time.sleep(0.02)   # stalled KV
        t0 = time.perf_counter()
        for step in range(1, 61):
            s.record({"event": "train.step", "step": step,
                      "ts": float(step), "wall_ms": 1.0,
                      "step_ms": 1.0, "k": 1})
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.5, elapsed       # 60 records, no KV waits
        assert s.dropped > 0                # bounded queue overflowed
        s.close()


# ---------------------------------------------------------------------------
# trace merge

class TestMerge:
    def test_one_lane_per_rank(self, tmp_path):
        logs = []
        for rank in (0, 1):
            p = str(tmp_path / f"r{rank}.jsonl")
            with open(p, "w") as f:
                for step in (1, 2):
                    f.write(json.dumps(
                        {"ts": time.time(), "event": "train.step",
                         "rank": rank, "step": step, "wall_ms": 1.0,
                         "dur_ms": 1.0}) + "\n")
            logs.append(p)
        out = str(tmp_path / "merged.json")
        doc = merge_jsonl_traces(logs, out_path=out)
        lanes = {e["pid"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        assert lanes == {0, 1}
        names = {e["pid"]: e["args"]["name"]
                 for e in doc["traceEvents"]
                 if e.get("name") == "process_name"}
        assert names == {0: "rank 0", 1: "rank 1"}
        assert json.load(open(out))["traceEvents"]

    def test_untagged_log_uses_positional_rank(self, tmp_path):
        p = str(tmp_path / "solo.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"ts": 1.0, "event": "x"}) + "\n")
        doc = merge_jsonl_traces([p], ranks=[7])
        evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
        assert evs[0]["pid"] == 7

    def test_torn_tail_line_dropped(self, tmp_path):
        p = str(tmp_path / "torn.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"ts": 1.0, "event": "x"}) + "\n")
            f.write('{"ts": 2.0, "event": "tr')     # crash mid-write
        assert len(load_jsonl(p)) == 1


# ---------------------------------------------------------------------------
# 2-process e2e (acceptance criterion)

_WORKER = r"""
import json
import os
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import telemetry
from paddle_tpu.telemetry.fleet import FleetSink, init_from_env
from paddle_tpu.distributed.launch.master import KVClient

rank, world = init_from_env()
kv = KVClient(os.environ["KV_ENDPOINT"])
sink = telemetry.attach_jsonl(os.environ["FLEET_LOG"])
telemetry.add_sink(FleetSink(kv, job_id="e2e", every=1))

from paddle_tpu.jit import TrainStep
paddle.seed(0)
m = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                         paddle.nn.Linear(16, 8))
opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
step = TrainStep(m, lambda o, y: paddle.nn.functional.mse_loss(o, y),
                 opt)
x = paddle.to_tensor(np.ones((4, 8), np.float32))
loss = None
for _ in range(6):
    loss = step(x, x)
print("RESULT " + json.dumps(
    {"rank": rank, "loss": float(np.asarray(loss.value))}))
"""


class TestTwoProcessE2E:
    def test_delay_fault_fires_straggler_and_merge_lanes(
            self, kv_store, tmp_path):
        """The acceptance e2e: two ranks train the same 6 steps; rank 1
        runs under an injected per-step delay fault.  The coordinator's
        aggregator must fire fleet.straggler naming rank 1, both ranks
        must finish bit-exact (the delay changes no math), and the two
        JSONL logs must merge into one trace with a lane per rank."""
        procs, logs = [], []
        for rank in (0, 1):
            log = str(tmp_path / f"rank{rank}.jsonl")
            logs.append(log)
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONPATH=REPO + os.pathsep
                       + os.environ.get("PYTHONPATH", ""),
                       PADDLE_TRAINER_ID=str(rank),
                       PADDLE_TRAINERS_NUM="2",
                       KV_ENDPOINT=kv_store.endpoint,
                       FLEET_LOG=log)
            if rank == 1:
                env["FLAGS_fault_injection"] = \
                    "step.begin:mode=delay:secs=0.15:times=*"
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER], env=env, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        results = {}
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err[-2000:]
            line = next(l for l in out.splitlines()
                        if l.startswith("RESULT "))
            rec = json.loads(line[len("RESULT "):])
            results[rec["rank"]] = rec["loss"]

        # bit-exact completion: the straggler's math is unchanged
        assert results[0] == results[1]

        # coordinator detects the planted straggler from the KV
        # summaries (arrival skew accumulates ~0.15s/step on rank 1)
        probe = telemetry.add_sink(telemetry.MemorySink())
        try:
            agg = FleetAggregator(kv_store, job_id="e2e", world=2,
                                  skew_ms=100.0)
            rep = agg.poll()
            agg.close()
        finally:
            telemetry.remove_sink(probe)
        evs = [r for r in probe.records
               if r["event"] == "fleet.straggler"]
        assert evs, rep
        assert all(e["straggler"] == 1 for e in evs), evs
        assert rep["stragglers"].get(1, 0) >= 1

        # per-rank logs are rank-tagged and merge into rank lanes
        for rank, log in enumerate(logs):
            steps = [e for e in load_jsonl(log)
                     if e["event"] == "train.step"]
            assert len(steps) == 6
            assert all(e["rank"] == rank and e["world"] == 2
                       for e in steps)
        doc = merge_jsonl_traces(logs)
        lanes = {e["pid"] for e in doc["traceEvents"]
                 if e.get("ph") != "M"}
        assert lanes == {0, 1}


# ---------------------------------------------------------------------------
# HBM memory ledger

class TestMemoryLedger:
    def test_trainstep_accounted(self):
        step, x = _mlp_step()
        step(x, x)
        rep = telemetry.memory_report()
        rec = rep["programs"]["jit.TrainStep.step"]
        assert rec["status"] == "ok"
        assert rec["argument_bytes"] > 0
        assert rec["peak_bytes"] > 0
        assert rep["peak_hbm_bytes"] >= rec["peak_bytes"]

    def test_sharded_trainer_accounted(self):
        import jax
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh

        paddle.seed(0)
        m = paddle.nn.Linear(8, 8)
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = ShardedTrainStep(
            m, opt, build_mesh(devices=jax.devices()[:1]),
            sharding_stage=3,
            loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        step(x, x)
        rep = telemetry.memory_report()
        rec = rep["programs"]["ShardedTrainStep.step.s3"]
        assert rec["status"] == "ok" and rec["peak_bytes"] > 0

    def test_offload_pipeline_accounted(self):
        import jax
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             LlamaConfig)
        from paddle_tpu.parallel import OffloadPipelineStep
        from paddle_tpu.distributed.topology import build_mesh

        paddle.seed(7)
        m = LlamaForCausalLM(LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2,
            num_key_value_heads=2, max_position_embeddings=32,
            dtype="float32"))
        opt = paddle.optimizer.AdamW(1e-2,
                                     parameters=m.parameters())
        step = OffloadPipelineStep(
            m, opt, build_mesh(devices=jax.devices()[:1]))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randint(0, 64, (2, 16))
                             .astype(np.int32))
        step(x, x)
        rep = telemetry.memory_report()
        rec = rep["programs"]["OffloadPipelineStep.step"]
        assert rec["status"] == "ok" and rec["peak_bytes"] > 0

    def test_serve_step_accounted(self):
        from paddle_tpu.inference import ContinuousBatcher
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(3)
        cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                                intermediate_size=64,
                                num_attention_heads=2,
                                num_key_value_heads=2, vocab_size=64)
        model = LlamaForCausalLM(cfg)
        bat = ContinuousBatcher(model, max_batch_size=2, max_len=32,
                                chunk=4, prefill_chunk=4)
        rep = telemetry.memory_report()
        for label in ("serve_step.decode", "serve_step.admit"):
            rec = rep["programs"][label]
            assert rec["status"] == "ok", rec
            # the KV pool rides the carry: arguments dominate
            assert rec["argument_bytes"] > bat.kv_cache_bytes()

    def test_resolution_is_side_effect_free_for_serve(self):
        """The ledger resolves through lower_step(record=False): it
        must not inflate compiled_programs or defeat the first-use
        timing exclusion (the r12 probe contract)."""
        from paddle_tpu.inference import ContinuousBatcher
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(3)
        cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                                intermediate_size=64,
                                num_attention_heads=2,
                                num_key_value_heads=2, vocab_size=64)
        model = LlamaForCausalLM(cfg)
        bat = ContinuousBatcher(model, max_batch_size=1, max_len=32,
                                chunk=4, prefill_chunk=4)
        telemetry.memory_report()
        assert bat.compiled_programs == 0
        rng = np.random.RandomState(0)
        bat.submit(rng.randint(1, 64, 4).astype(np.int32), 4)
        bat.run()
        assert bat.stats()["compiled_programs"] <= 2

    def test_dump_never_resolves(self):
        step, x = _mlp_step()
        step(x, x)
        d = telemetry.dump()
        assert d["memory"]["programs"]["jit.TrainStep.step"][
            "status"] == "pending"
        assert d["memory"]["peak_hbm_bytes"] == 0

    def test_lint_peak_hbm_flags_planted_over_budget(self):
        from paddle_tpu.analysis import lint_peak_hbm
        step, x = _mlp_step()
        step(x, x)
        findings = lint_peak_hbm(budget_bytes=1)
        assert findings
        assert all(f.code == "peak-hbm-over-budget" for f in findings)
        assert any("jit.TrainStep.step" in f.message
                   for f in findings)
        assert lint_peak_hbm(budget_bytes=10 ** 15) == []

    def test_lint_peak_hbm_single_compiled(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.analysis import lint_peak_hbm
        lowered = jax.jit(lambda a, b: a @ b).lower(
            jnp.ones((64, 64)), jnp.ones((64, 64)))
        assert lint_peak_hbm(lowered, budget_bytes=1,
                             label="planted")[0].code \
            == "peak-hbm-over-budget"
        assert lint_peak_hbm(lowered.compile(),
                             budget_bytes=10 ** 12) == []

    def test_aot_capture_not_clobbered_and_matches_lazy(self, tmp_path):
        """With FLAGS_compile_cache_dir armed the AOT path captures
        stats for FREE at its own compile — note_jit (registered
        before aot_for) must not clobber them back to pending, and the
        lazy provider's numbers must agree with the captured ones."""
        from paddle_tpu.framework.flags import set_flags
        step, x = _mlp_step()
        step(x, x)
        lazy = telemetry.memory_report()["programs"][
            "jit.TrainStep.step"]
        assert lazy["status"] == "ok"
        telemetry.reset()
        set_flags({"FLAGS_compile_cache_dir": str(tmp_path / "c")})
        try:
            paddle.seed(0)
            step2, x2 = _mlp_step()
            step2(x2, x2)
        finally:
            set_flags({"FLAGS_compile_cache_dir": ""})
            telemetry.disable_persistent_cache()
        snap = telemetry.memledger.snapshot()["programs"][
            "jit.TrainStep.step"]
        assert snap["status"] == "ok", snap     # free capture survived
        for k in ("argument_bytes", "output_bytes", "temp_bytes"):
            assert snap[k] == lazy[k], (k, snap, lazy)

    def test_mem_program_events_published_on_resolve(self):
        step, x = _mlp_step()
        step(x, x)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            telemetry.memory_report()
        finally:
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records if r["event"] == "mem.program"]
        assert evs and evs[0]["label"] == "jit.TrainStep.step"
        assert evs[0]["peak_bytes"] > 0


# ---------------------------------------------------------------------------
# per-request serve spans

@pytest.fixture(scope="module")
def span_model():
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    paddle.seed(13)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            intermediate_size=64,
                            num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=64)
    return LlamaForCausalLM(cfg)


class TestServeSpans:
    def test_stats_latency_block(self, span_model):
        from paddle_tpu.inference import ContinuousBatcher
        rng = np.random.RandomState(2)
        bat = ContinuousBatcher(span_model, max_batch_size=2,
                                max_len=32, chunk=4, prefill_chunk=4)
        bat.submit(rng.randint(1, 64, 4).astype(np.int32), 5,
                   slo="interactive", deadline_ms=60000)
        bat.submit(rng.randint(1, 64, 6).astype(np.int32), 5,
                   slo="batch")
        bat.run()
        st = bat.stats()
        lat = st["latency"]
        assert lat["e2e_ms"]["count"] == 2
        assert lat["ttft_ms"]["count"] == 2
        assert lat["queue_ms"]["count"] == 2
        # spans nest: queue <= ttft <= e2e at matching percentiles
        assert lat["queue_ms"]["p50"] <= lat["ttft_ms"]["p99"]
        assert lat["ttft_ms"]["p99"] <= lat["e2e_ms"]["p99"]
        assert lat["tpot_ms"]["count"] == 2
        att = st["slo_attainment"]
        assert att["interactive"]["with_deadline"] == 1
        assert att["interactive"]["deadline_met"] == 1
        assert att["interactive"]["attainment"] == 1.0
        assert att["batch"]["completed"] == 1

    def test_request_events_and_ordering(self, span_model):
        from paddle_tpu.inference import ContinuousBatcher
        rng = np.random.RandomState(4)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            bat = ContinuousBatcher(span_model, max_batch_size=2,
                                    max_len=32, chunk=4,
                                    prefill_chunk=4)
            for L in (4, 7):
                bat.submit(rng.randint(1, 64, L).astype(np.int32), 5)
            bat.run()
        finally:
            telemetry.remove_sink(sink)
        reqs = [r for r in sink.records
                if r["event"] == "serve.request"]
        assert len(reqs) == 2
        for e in reqs:
            for k in ("req", "slo", "tokens", "queue_ms", "ttft_ms",
                      "e2e_ms"):
                assert k in e, e
            assert e["queue_ms"] <= e["ttft_ms"] <= e["e2e_ms"]
        # timing histograms observed while the sink was live
        d = telemetry.dump()
        assert d["histograms"]["serve.ttft_ms"]["count"] == 2
        assert d["histograms"]["serve.e2e_ms"]["count"] == 2

    def test_shed_requests_take_no_latency_sample(self, span_model):
        from paddle_tpu.inference import ContinuousBatcher
        rng = np.random.RandomState(5)
        bat = ContinuousBatcher(span_model, max_batch_size=1,
                                max_len=32, chunk=4, prefill_chunk=4)
        bat.submit(rng.randint(1, 64, 4).astype(np.int32), 4,
                   slo="batch", deadline_ms=0.001)
        bat.submit(rng.randint(1, 64, 4).astype(np.int32), 4,
                   slo="batch", deadline_ms=0.001)
        time.sleep(0.01)
        bat.run()
        st = bat.stats()
        served = st["requests_completed"]
        assert st["requests_shed"] >= 1
        assert st["latency"]["e2e_ms"]["count"] == served
        att = st["slo_attainment"]["batch"]
        assert att["shed"] == st["requests_shed"]

    def test_requeued_request_spans_describe_final_decode(
            self, span_model):
        """A faulted slot's re-decode restarts admit/first-token: the
        delivered spans describe the decode the user got, with
        e2e still measured from the original submit."""
        from paddle_tpu.inference import ContinuousBatcher
        from paddle_tpu.distributed import fault
        rng = np.random.RandomState(6)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            with fault.scope("serve.decode:step=1:times=1"
                             ":mode=corrupt"):
                bat = ContinuousBatcher(span_model, max_batch_size=1,
                                        max_len=32, chunk=4,
                                        prefill_chunk=4)
                bat.submit(rng.randint(1, 64, 4).astype(np.int32), 5)
                bat.run()
        finally:
            telemetry.remove_sink(sink)
        st = bat.stats()
        assert st["requests_requeued"] == 1
        reqs = [r for r in sink.records
                if r["event"] == "serve.request"]
        assert len(reqs) == 1 and reqs[0]["requeues"] == 1
        assert reqs[0]["ttft_ms"] <= reqs[0]["e2e_ms"]


# ---------------------------------------------------------------------------
# sink drain flush (satellite)

_FLUSH_WORKER = r"""
import os
import signal
import sys
import time
from paddle_tpu import telemetry

signal.signal(signal.SIGTERM, lambda *a: sys.exit(1))
telemetry.attach_jsonl(os.environ["LOG"], flush_every=100000)
telemetry.attach_chrome_trace(os.environ["TRACE"])
for i in range(25):
    telemetry.emit("step.mark", step=i)
with open(os.environ["READY"], "w") as f:
    f.write("ready")
while True:
    time.sleep(0.05)
"""


class TestSinkDrainFlush:
    def test_sigterm_mid_run_keeps_the_tail(self, tmp_path):
        """Kill a worker mid-run: the buffered JSONL tail (flush_every
        huge) and the chrome trace must still land on disk via the
        atexit drain path — the last emitted step is recoverable."""
        log = str(tmp_path / "steps.jsonl")
        trace = str(tmp_path / "trace.json")
        ready = str(tmp_path / "ready")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""),
                   LOG=log, TRACE=trace, READY=ready)
        proc = subprocess.Popen([sys.executable, "-c", _FLUSH_WORKER],
                                env=env, text=True,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE)
        try:
            deadline = time.time() + 120
            while not os.path.exists(ready) \
                    and time.time() < deadline:
                assert proc.poll() is None, \
                    proc.communicate()[1][-2000:]
                time.sleep(0.05)
            assert os.path.exists(ready), "worker never came up"
            proc.send_signal(signal.SIGTERM)
            proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        events = load_jsonl(log)
        marks = [e for e in events if e["event"] == "step.mark"]
        assert len(marks) == 25
        assert marks[-1]["step"] == 24      # the TAIL survived
        doc = json.load(open(trace))
        assert len([e for e in doc["traceEvents"]
                    if e["name"] == "step.mark"]) == 25

    def test_close_unregisters_atexit(self, tmp_path):
        import atexit
        sink = telemetry.JsonlSink(str(tmp_path / "s.jsonl"))
        sink.close()
        # double-unregister must not raise; closed sink's drain is a
        # no-op
        atexit.unregister(sink._drain_flush)
        sink._drain_flush()


# ---------------------------------------------------------------------------
# CLI wiring (satellite: tier-1 runs the fleet selftest)

class TestFleetReportCLI:
    def test_selftest(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import fleet_report as cli
        finally:
            sys.path.pop(0)
        assert cli.main(["--selftest"]) == 0

    def test_offline_report_and_trace(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import fleet_report as cli
        finally:
            sys.path.pop(0)
        logs = []
        for rank in (0, 1):
            p = str(tmp_path / f"r{rank}.jsonl")
            with open(p, "w") as f:
                for step in (1, 2, 3):
                    wall = 80.0 if (rank == 1 and step == 2) else 8.0
                    f.write(json.dumps(
                        {"ts": float(step), "event": "train.step",
                         "rank": rank, "step": step,
                         "wall_ms": wall}) + "\n")
                f.write(json.dumps(
                    {"ts": 4.0, "event": "mem.program",
                     "rank": rank, "label": f"prog{rank}",
                     "argument_bytes": 10, "output_bytes": 4,
                     "temp_bytes": 6, "alias_bytes": 0,
                     "generated_code_bytes": 0,
                     "peak_bytes": 20 + rank}) + "\n")
            logs.append(p)
        rep = cli.analyze_fleet([load_jsonl(p) for p in logs],
                                skew_ms=50.0)
        assert rep["steps_compared"] == 3
        assert rep["stragglers"] == {"1": 1}
        top = rep["skew_table"][0]
        assert top["step"] == 2 and top["flagged"]
        assert rep["memory"]["peak_hbm_bytes"] == 21
        assert cli.render(rep)
        trace = str(tmp_path / "m.json")
        assert cli.main(logs + ["--trace", trace, "--json"]) == 0
        assert json.load(open(trace))["traceEvents"]

    def test_offline_rank_collision_reassigned(self, tmp_path):
        """Regression: an untagged log whose positional index matches
        a tagged rank must get a free lane (and a warning), never
        silently replace the tagged rank's steps."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import fleet_report as cli
        finally:
            sys.path.pop(0)
        tagged = str(tmp_path / "tagged.jsonl")
        with open(tagged, "w") as f:
            f.write(json.dumps({"ts": 1.0, "event": "train.step",
                                "rank": 1, "step": 1,
                                "wall_ms": 5.0}) + "\n")
        untagged = str(tmp_path / "untagged.jsonl")
        with open(untagged, "w") as f:
            f.write(json.dumps({"ts": 1.0, "event": "train.step",
                                "step": 1, "wall_ms": 7.0}) + "\n")
        rep = cli.analyze_fleet([load_jsonl(tagged),
                                 load_jsonl(untagged)])
        assert set(rep["ranks"]) == {"1", "2"}
        (c,) = rep["rank_collisions"]
        assert c["claimed"] == 1 and c["assigned"] == 2
        assert "WARNING" in cli.render(rep)
