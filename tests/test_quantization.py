"""QAT / PTQ: fake-quant numerics, STE gradients, config priority,
observer calibration + convert.

Reference test model: test/quantization/test_qat_*.py, test_ptq.py.
"""
import copy

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import QAT, PTQ, QuantConfig
from paddle_tpu.quantization.quanters import (
    FakeQuanterWithAbsMaxObserver, FakeQuanterWithAbsMaxObserverLayer,
    _fake_quant)
from paddle_tpu.quantization.observers import AbsmaxObserver


def a(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)
        self.conv = nn.Conv2D(3, 4, 3, padding=1)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


class TestFakeQuant:
    def test_grid(self):
        import jax.numpy as jnp
        x = jnp.asarray(np.linspace(-1, 1, 11, dtype=np.float32))
        q = _fake_quant(x, jnp.float32(1.0), 8)
        # values land on the symmetric int8 grid scale/127
        grid = np.round(np.asarray(q) * 127)
        np.testing.assert_allclose(np.asarray(q), grid / 127, atol=1e-6)
        np.testing.assert_allclose(np.asarray(q), np.asarray(x),
                                   atol=1.0 / 127)

    def test_ste_gradient(self):
        import jax, jax.numpy as jnp
        g = jax.grad(lambda x: jnp.sum(
            _fake_quant(x, jnp.float32(1.0), 8) ** 2))(
            jnp.asarray([0.5, -0.25], jnp.float32))
        # straight-through: d/dx sum(q^2) ~ 2q
        assert np.isfinite(np.asarray(g)).all()
        np.testing.assert_allclose(np.asarray(g),
                                   2 * np.asarray([0.5, -0.25]),
                                   atol=0.05)


class TestQAT:
    def test_quantize_swaps_layers(self):
        paddle.seed(0)
        net = Net()
        q = FakeQuanterWithAbsMaxObserver(moving_rate=0.9)
        qat = QAT(QuantConfig(activation=q, weight=q))
        qnet = qat.quantize(net)
        from paddle_tpu.quantization import QuantedLinear, QuantedConv2D
        assert isinstance(qnet.fc1, QuantedLinear)
        assert isinstance(qnet.conv, QuantedConv2D)
        # original untouched (not inplace)
        assert isinstance(net.fc1, nn.Linear)

    def test_forward_close_and_trainable(self):
        paddle.seed(0)
        net = Net()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        ref = a(net(x))
        q = FakeQuanterWithAbsMaxObserver()
        qnet = QAT(QuantConfig(activation=q, weight=q)).quantize(net)
        out = a(qnet(x))
        # int8 fake quant stays close to float
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05
        # gradients flow through STE to weights
        loss = (qnet(x) ** 2).mean()
        loss.backward()
        assert qnet.fc1.weight.grad is not None
        assert np.isfinite(a(qnet.fc1.weight.grad)).all()

    def test_config_priority_name_over_type(self):
        paddle.seed(0)
        net = Net()
        q = FakeQuanterWithAbsMaxObserver()
        cfg = QuantConfig(activation=None, weight=None)
        cfg.add_type_config(nn.Linear, activation=q, weight=q)
        cfg.add_name_config("fc2", activation=None, weight=None)
        qnet = QAT(cfg).quantize(net)
        from paddle_tpu.quantization import QuantedLinear
        assert isinstance(qnet.fc1, QuantedLinear)
        # fc2's name config has no quanters -> swapped wrapper without
        # quanters is fine, but weight_quanter must be None
        assert qnet.fc2.weight_quanter is None \
            if hasattr(qnet.fc2, "weight_quanter") \
            else isinstance(qnet.fc2, nn.Linear)

    def test_quanter_scale_tracks_ema(self):
        q = FakeQuanterWithAbsMaxObserverLayer(moving_rate=0.5)
        x1 = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
        q(x1)
        assert abs(float(a(q.scales())) - 2.0) < 1e-6
        x2 = paddle.to_tensor(np.array([4.0], np.float32))
        q(x2)
        assert abs(float(a(q.scales())) - 3.0) < 1e-6  # 0.5*2 + 0.5*4


class TestPTQ:
    def test_calibrate_convert(self):
        paddle.seed(0)
        net = Net()
        obs = AbsmaxObserver(quant_bits=8)
        ptq = PTQ(QuantConfig(activation=obs, weight=obs))
        qnet = ptq.quantize(net)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(16, 8).astype(np.float32))
        ref = a(net(x))
        cal = a(qnet(x))  # observers are identity during calibration
        np.testing.assert_allclose(cal, ref, atol=1e-6)
        ptq.convert(qnet)
        out = a(qnet(x))
        assert not np.allclose(out, ref, atol=1e-7)  # now quantized
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05
