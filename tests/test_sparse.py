"""Sparse COO/CSR: real sparse compute vs dense reference, no
densification in matmul, gradient flow through values.

Reference test model: test/legacy_test/test_sparse_*_op.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse as sp


def a(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def mk_coo():
    # 3x4 with 4 nonzeros
    indices = np.array([[0, 0, 1, 2], [0, 3, 1, 2]])
    values = np.array([1.0, 2.0, 3.0, -4.0], np.float32)
    dense = np.zeros((3, 4), np.float32)
    dense[indices[0], indices[1]] = values
    return sp.sparse_coo_tensor(indices, values, (3, 4)), dense


class TestCreation:
    def test_coo_roundtrip(self):
        t, dense = mk_coo()
        assert t.is_sparse_coo() and not t.is_sparse_csr()
        assert t.nnz == 4
        np.testing.assert_allclose(a(t.to_dense()), dense)
        assert a(t.indices()).shape == (2, 4)
        np.testing.assert_allclose(a(t.values()),
                                   [1.0, 2.0, 3.0, -4.0])

    def test_csr_roundtrip(self):
        crows = [0, 2, 3, 4]
        cols = [0, 3, 1, 2]
        vals = np.array([1.0, 2.0, 3.0, -4.0], np.float32)
        t = sp.sparse_csr_tensor(crows, cols, vals, (3, 4))
        assert t.is_sparse_csr()
        dense = np.zeros((3, 4), np.float32)
        dense[[0, 0, 1, 2], cols] = vals
        np.testing.assert_allclose(a(t.to_dense()), dense)
        np.testing.assert_allclose(a(t.crows()), crows)


class TestCompute:
    def test_spmm_matches_dense(self):
        t, dense = mk_coo()
        y = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        out = sp.matmul(t, paddle.to_tensor(y))
        np.testing.assert_allclose(a(out), dense @ y, atol=1e-5)

    def test_dense_at_sparse(self):
        t, dense = mk_coo()
        x = np.random.RandomState(1).randn(5, 3).astype(np.float32)
        out = sp.matmul(paddle.to_tensor(x), t)
        np.testing.assert_allclose(a(out), x @ dense, atol=1e-5)

    def test_spmm_no_densify(self, monkeypatch):
        """the sparse matmul path must NOT call todense on the lhs."""
        from jax.experimental.sparse import BCOO
        called = {"n": 0}
        orig = BCOO.todense

        def spy(self):
            called["n"] += 1
            return orig(self)
        monkeypatch.setattr(BCOO, "todense", spy)
        t, dense = mk_coo()
        y = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        _ = sp.matmul(t, paddle.to_tensor(y))
        assert called["n"] == 0

    def test_add_subtract_multiply(self):
        t1, d1 = mk_coo()
        indices = np.array([[0, 1, 2], [0, 1, 3]])
        values = np.array([5.0, -1.0, 2.0], np.float32)
        t2 = sp.sparse_coo_tensor(indices, values, (3, 4))
        d2 = np.zeros((3, 4), np.float32)
        d2[indices[0], indices[1]] = values
        np.testing.assert_allclose(a(sp.add(t1, t2).to_dense()), d1 + d2,
                                   atol=1e-6)
        np.testing.assert_allclose(a(sp.subtract(t1, t2).to_dense()),
                                   d1 - d2, atol=1e-6)
        np.testing.assert_allclose(a(sp.multiply(t1, t2).to_dense()),
                                   d1 * d2, atol=1e-6)

    def test_unary_keep_pattern(self):
        t, dense = mk_coo()
        r = sp.relu(t)
        assert r.nnz == t.nnz
        np.testing.assert_allclose(a(r.to_dense()), np.maximum(dense, 0))
        np.testing.assert_allclose(a(sp.sin(t).to_dense()),
                                   np.where(dense != 0, np.sin(dense), 0),
                                   atol=1e-6)

    def test_masked_matmul(self):
        rng = np.random.RandomState(0)
        x = rng.randn(3, 6).astype(np.float32)
        y = rng.randn(6, 4).astype(np.float32)
        t, mask = mk_coo()
        out = sp.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y), t)
        full = x @ y
        expect = np.where(mask != 0, full, 0)
        np.testing.assert_allclose(a(out.to_dense()), expect, atol=1e-5)

    def test_transpose(self):
        t, dense = mk_coo()
        tt = sp.transpose(t, [1, 0])
        np.testing.assert_allclose(a(tt.to_dense()), dense.T)


class TestGrad:
    def test_grad_flows_to_dense_operand(self):
        t, dense = mk_coo()
        y = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 5).astype(np.float32))
        y.stop_gradient = False
        out = sp.matmul(t, y)
        loss = (out ** 2).sum()
        loss.backward()
        assert y.grad is not None
        ref = 2 * dense.T @ (dense @ a(y))
        np.testing.assert_allclose(a(y.grad), ref, atol=1e-4)


class TestShapesAndCsr:
    def test_mismatched_add_raises(self):
        t1, _ = mk_coo()
        t2 = sp.sparse_coo_tensor(np.array([[0], [0]]),
                                  np.array([7.0], np.float32), (5, 5))
        with pytest.raises(ValueError):
            sp.add(t1, t2)

    def test_unary_preserves_csr(self):
        t = sp.sparse_csr_tensor([0, 1, 2], [0, 1],
                                 np.array([1.0, -2.0], np.float32),
                                 (2, 2))
        r = sp.relu(t)
        assert r.is_sparse_csr()
        np.testing.assert_allclose(a(r.crows()), [0, 1, 2])
