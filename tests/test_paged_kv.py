"""Paged KV cache with prefix sharing and int8 KV (ISSUE 7).

The contracts under test:

  * PARITY — the paged path (pool + page table + gather twin) is
    BIT-exact against the dense per-slot ring buffers at full-precision
    KV, and within tolerance at int8 KV; sharing a prefix changes no
    request's tokens (copy-on-write divergence included).
  * SHARING — an admission whose prompt prefix matches resident pages
    skips those prefill chunks entirely (prefill_tokens +
    prefix_hit_tokens == total prompt tokens, and the prefill work
    measurably drops vs the unshared run).
  * PRESSURE — a pool smaller than total demand evicts cached prefix
    pages LRU-first and defers admissions; every request still
    completes, still bit-exact.
  * r6 CONTRACTS stay pinned on the paged path: exactly 2 compiled
    step programs per batcher shape with and without prefix hits,
    every carry (pool, scales, page tables included) donated AND
    aliased, and a forced program-cache clear mid-life re-traces
    without disturbing counters (the r11 serve pattern).
  * KV-LAYOUT program-cache guard: toggling FLAGS_kv_cache_dtype or
    pool geometry mid-process can never replay a stale compiled
    program.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatcher
from paddle_tpu.inference.paged_kv import PageAllocator
from paddle_tpu.models.llama import (LlamaForCausalLM,
                                     llama_tiny_config)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _isolated(model, ids, n):
    out = model.generate(paddle.to_tensor(np.asarray([ids], np.int32)),
                         max_new_tokens=n)
    return np.asarray(out.value)[0]


# ---------------------------------------------------------------------------
# parity: paged vs dense


def test_paged_matches_dense_bitexact(model):
    """Same staggered workload through a paged and a dense batcher:
    identical tokens, request for request (and both match isolation —
    the gather twin's masked rows exp to exactly 0)."""
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (4, 11, 7)]
    outs = {}
    for layout in ("paged", "dense"):
        bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                                chunk=4, prefill_chunk=4,
                                kv_layout=layout, page_size=8)
        rids = [bat.submit(p, 7) for p in prompts]
        got = bat.run()
        outs[layout] = [got[r] for r in rids]
        assert bat.stats()["kv_layout"] == layout
    for pg, dn, p in zip(outs["paged"], outs["dense"], prompts):
        np.testing.assert_array_equal(pg, dn)
        np.testing.assert_array_equal(pg, _isolated(model, p, 7))


def test_paged_bf16_kv_deterministic(model):
    """Explicit kv_dtype plumbing: a bf16 pool reports its dtype and
    two identical runs produce identical tokens.  (The bit-exactness
    contract binds at EQUAL KV dtypes — covered above, where the
    module model's bf16 compute dtype is also the KV dtype on both
    paths; an explicitly down-cast pool is a precision choice, not a
    parity bug.)"""
    rng = np.random.RandomState(5)
    p = rng.randint(1, 128, 9).astype(np.int32)
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=32,
                            chunk=4, prefill_chunk=4, page_size=8,
                            kv_dtype="bfloat16")
    rid = bat.submit(p, 6)
    out1 = bat.run()[rid]
    assert bat.stats()["kv_dtype"] == "bfloat16"
    bat2 = ContinuousBatcher(model, max_batch_size=1, max_len=32,
                             chunk=4, prefill_chunk=4, page_size=8,
                             kv_dtype="bfloat16")
    rid2 = bat2.submit(p, 6)
    np.testing.assert_array_equal(out1, bat2.run()[rid2])
    assert len(out1) == 6


def test_int8_kv_logit_parity(model):
    """int8 KV quantization: per-page per-head scales keep the decode
    logits within a few percent of the fp32 dense path (unit-level —
    token-level greedy flips are legal under quantization)."""
    import jax.numpy as jnp
    B, ps, P_slot = 2, 8, 6
    pt = jnp.asarray(
        np.arange(1, 1 + B * P_slot).reshape(B, P_slot), jnp.int32)
    dense = model.init_cache(B, P_slot * ps)
    paddle.set_flags({"FLAGS_kv_cache_dtype": "int8"})
    try:
        paged = model.init_paged_cache(1 + B * P_slot, ps)
        assert paged["k"].dtype == jnp.int8
        assert "k_scale" in paged and "v_scale" in paged
    finally:
        paddle.set_flags({"FLAGS_kv_cache_dtype": "auto"})
    rng = np.random.RandomState(0)
    pos = jnp.zeros((B,), jnp.int32)
    for C in (5, 3, 1, 1):
        ids = jnp.asarray(rng.randint(1, 128, (B, C)), jnp.int32)
        lg_d, dense = model.forward_cached(ids, dense, pos)
        lg_p, paged = model.forward_cached_paged(ids, paged, pt, pos)
        ref = np.asarray(lg_d, np.float32)
        got = np.asarray(lg_p, np.float32)
        rel = np.max(np.abs(ref - got)) / (np.max(np.abs(ref)) + 1e-9)
        assert rel < 0.1, f"int8 KV drifted {rel:.3f} at C={C}"
        pos = pos + C


def test_int8_kv_halves_pool_bytes(model):
    """The int8 pool reports (just over) half the KV HBM of the
    full-precision pool of identical geometry — scales are the only
    overhead."""
    kw = dict(max_batch_size=2, max_len=32, chunk=4, prefill_chunk=4,
              page_size=8)
    full = ContinuousBatcher(model, kv_dtype="float32", **kw)
    quant = ContinuousBatcher(model, kv_dtype="int8", **kw)
    rng = np.random.RandomState(1)
    p = rng.randint(1, 128, 6).astype(np.int32)
    for bat in (full, quant):
        rid = bat.submit(p, 5)
        out = bat.run()[rid]
        assert len(out) == 5
    b_full = full.stats()["kv_bytes"]
    b_q = quant.stats()["kv_bytes"]
    assert b_q < 0.3 * b_full, (b_q, b_full)  # int8 vs fp32: ~4x
    # the allocation-free estimator (bench's sizing probe) matches the
    # real instance byte for byte
    for bat, dt in ((full, "float32"), (quant, "int8")):
        est = ContinuousBatcher.paged_kv_bytes(
            model, max_batch_size=2, max_len=32, prefill_chunk=4,
            page_size=8, kv_dtype=dt)
        assert est == bat.kv_cache_bytes(), (dt, est,
                                             bat.kv_cache_bytes())


# ---------------------------------------------------------------------------
# prefix sharing + copy-on-write


def test_prefix_sharing_skips_prefill(model):
    """Staggered requests sharing a long system prompt: every output
    still bit-matches isolation, the shared pages are prefilled ONCE
    (prefill_tokens + prefix_hit_tokens == total prompt tokens), and
    the prefill work drops vs the sharing-disabled run."""
    rng = np.random.RandomState(3)
    sys_p = rng.randint(1, 128, 24).astype(np.int32)  # 3 pages at ps=8
    tails = [rng.randint(1, 128, L).astype(np.int32)
             for L in (5, 9, 3, 7)]
    prompts = [np.concatenate([sys_p, t]) for t in tails]
    total = sum(len(p) for p in prompts)

    stats = {}
    for sharing in (True, False):
        bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                                chunk=4, prefill_chunk=4, page_size=8,
                                prefix_sharing=sharing)
        rids = [bat.submit(prompts[0], 6)]
        bat.step()
        rids += [bat.submit(p, 6) for p in prompts[1:]]
        outs = bat.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid],
                                          _isolated(model, p, 6))
        stats[sharing] = bat.stats()
    shared, unshared = stats[True], stats[False]
    assert shared["prefix_hit_tokens"] > 0
    assert shared["prefix_hit_tokens"] + shared["prefill_tokens"] \
        == total
    assert unshared["prefix_hit_tokens"] == 0
    assert shared["prefill_tokens"] < unshared["prefill_tokens"]
    # fewer admission-mode chunks: skipped prefill is skipped WORK
    assert shared["admit_chunks"] <= unshared["admit_chunks"]


def test_cow_divergence_matches_unshared(model):
    """Two requests sharing a prefix that diverges MID-page: the
    second maps the full pages, copy-on-writes the divergence page,
    and must produce exactly the tokens of an unshared run."""
    rng = np.random.RandomState(9)
    base = rng.randint(1, 128, 20).astype(np.int32)   # 2.5 pages (ps=8)
    a = np.concatenate([base, rng.randint(1, 128, 4).astype(np.int32)])
    b = np.concatenate([base, rng.randint(1, 128, 6).astype(np.int32)])
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                            chunk=4, prefill_chunk=4, page_size=8)
    r1, r2 = bat.submit(a, 5), bat.submit(b, 5)
    outs = bat.run()
    np.testing.assert_array_equal(outs[r1], _isolated(model, a, 5))
    np.testing.assert_array_equal(outs[r2], _isolated(model, b, 5))
    st = bat.stats()
    # b matched 2 full pages (16 tokens) + 4 rows of page 2 via CoW
    assert st["prefix_hit_tokens"] == 20, st["prefix_hit_tokens"]


def test_whole_prompt_resident_still_emits(model):
    """A prompt IDENTICAL to a resident one shares everything except
    the final token (the match is capped at plen-1): the last token
    must prefill so its logit seeds the first sampled token."""
    rng = np.random.RandomState(2)
    p = rng.randint(1, 128, 17).astype(np.int32)   # 2 pages + 1 row
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=48,
                            chunk=4, prefill_chunk=4, page_size=8)
    r1, r2 = bat.submit(p, 6), bat.submit(p, 6)
    outs = bat.run()
    want = _isolated(model, p, 6)
    np.testing.assert_array_equal(outs[r1], want)
    np.testing.assert_array_equal(outs[r2], want)
    assert bat.stats()["prefix_hit_tokens"] == 16


# ---------------------------------------------------------------------------
# pool pressure


def test_eviction_under_pressure_completes_all(model):
    """Pool smaller than total demand: cached prefix pages are evicted
    LRU-first to serve new admissions, further admissions defer to
    later boundaries, and every request still completes bit-exact."""
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (17, 19, 18, 21)]
    # each request needs ~5-6 pages (ps=8); 11 usable pages force both
    # cached-page eviction and deferred admission across the workload
    bat = ContinuousBatcher(model, max_batch_size=4, max_len=48,
                            chunk=4, prefill_chunk=4, page_size=8,
                            num_pages=12)
    rids = [bat.submit(p, 5) for p in prompts]
    outs = bat.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid],
                                      _isolated(model, p, 5))
    st = bat.stats()
    assert st["evictions"] > 0, st
    # at drain nothing is MAPPED — whatever stays resident is cached
    # prefix pages (refcount 0, reclaimable)
    assert st["kv_pages_used"] == st["kv_pages_cached"], st


def test_pool_too_small_raises(model):
    rng = np.random.RandomState(1)
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=48,
                            chunk=4, prefill_chunk=4, page_size=8,
                            num_pages=3)
    bat.submit(rng.randint(1, 128, 20).astype(np.int32), 8)
    with pytest.raises(RuntimeError, match="cannot ever hold"):
        bat.run()


# ---------------------------------------------------------------------------
# r6 contracts on the paged path


def test_paged_two_programs_with_prefix_hits(model):
    """recompile_guard pins the 2-programs-per-shape contract across
    admissions WITH and WITHOUT prefix hits, and across a forced
    program-cache clear mid-run (the r11 serve pattern): counters
    survive, the re-trace is bounded, prompt length never recompiles."""
    from paddle_tpu.analysis import recompile_guard
    rng = np.random.RandomState(13)
    sys_p = rng.randint(1, 128, 16).astype(np.int32)
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4, page_size=8)
    rids = []
    for L in (3, 7, 11, 6):                    # no-hit admissions
        rids.append(bat.submit(
            rng.randint(1, 128, L).astype(np.int32), 4))
    for L in (5, 9):                           # prefix-hit admissions
        rids.append(bat.submit(np.concatenate(
            [sys_p, rng.randint(1, 128, L).astype(np.int32)]), 4))
    with recompile_guard(max_programs=2, match="serve_step") as g:
        outs = bat.run()
    assert sorted(outs) == sorted(rids)
    assert bat.compiled_programs == 2
    assert len([k for k in g.cache_builds
                if isinstance(k, tuple) and k
                and k[0] == "serve_step"]) <= 2

    # forced program-cache clear mid-life: the next chunk re-traces
    # (bounded at the same 2 programs) and stats survive
    before = bat.stats()
    model.__dict__.get("_gen_compiled", {}).clear()
    r_more = bat.submit(np.concatenate(
        [sys_p, rng.randint(1, 128, 4).astype(np.int32)]), 4)
    with recompile_guard(max_programs=2, match="serve_step"):
        outs2 = bat.run()
    after = bat.stats()
    assert len(outs2[r_more]) == 4
    assert bat.compiled_programs == 2
    assert after["chunks"] > before["chunks"]
    assert after["prefix_hit_tokens"] >= before["prefix_hit_tokens"]


def test_paged_carries_all_donated(model):
    """lint_donation over the lowered step programs: the page pool,
    the scales, the page table and every other carry must be aliased
    to an output — a silently-undonated pool would double serving's
    dominant HBM buffer every chunk."""
    from paddle_tpu.analysis import lint_donation
    for kv_dtype in (None, "int8"):
        bat = ContinuousBatcher(model, max_batch_size=2, max_len=32,
                                chunk=4, prefill_chunk=4, page_size=8,
                                kv_dtype=kv_dtype)
        for mixed in (False, True):
            findings = lint_donation(bat.lower_step(mixed=mixed))
            assert not findings, [f.message for f in findings]


# ---------------------------------------------------------------------------
# KV-layout program-cache guard (ISSUE 7 small fix)


def test_program_cache_keys_guard_kv_layout(model):
    """Toggling FLAGS_kv_cache_dtype (or pool geometry) mid-process
    must re-build cached programs, never replay stale ones: the
    program cache key carries the KV-layout fingerprint."""
    from paddle_tpu.inference.generation import (
        _model_program_cache, _kv_layout_fingerprint)
    builds = []

    def build():
        builds.append(1)
        return lambda: None

    key = ("kvguard_probe", 1, 2)
    _model_program_cache(model, key, build)
    _model_program_cache(model, key, build)
    assert len(builds) == 1                    # warm hit
    fp0 = _kv_layout_fingerprint()
    paddle.set_flags({"FLAGS_kv_cache_dtype": "int8"})
    try:
        assert _kv_layout_fingerprint() != fp0
        _model_program_cache(model, key, build)
        assert len(builds) == 2                # layout flip rebuilds
        paddle.set_flags({"FLAGS_kv_page_size": 32})
        _model_program_cache(model, key, build)
        assert len(builds) == 3                # geometry flip rebuilds
    finally:
        paddle.set_flags({"FLAGS_kv_cache_dtype": "auto",
                          "FLAGS_kv_page_size": 16})
    _model_program_cache(model, key, build)
    assert len(builds) == 3                    # restored layout: warm hit


# ---------------------------------------------------------------------------
# host-side allocator / trie units


def test_allocator_refcounts_and_lru_eviction():
    al = PageAllocator(num_pages=6, page_size=4)
    assert al.pages_free == 5
    a = al.alloc(2)
    b = al.alloc(2)
    assert al.pages_used == 4 and al.pages_free == 1
    # register a's pages as prompt chunks and cache them
    n1 = al.register_chunk(None, [1, 2, 3, 4], a[0])
    n2 = al.register_chunk(n1, [5, 6, 7, 8], a[1])
    al.complete_node(n1), al.complete_node(n2)
    for p in a:
        al.release_page(p)
    assert al.pages_cached == 2 and al.pages_free == 1
    # pressure: allocating 3 must evict BOTH cached pages (leaf first)
    c = al.alloc(3)
    assert c is not None and al.evictions == 2
    assert al.pages_cached == 0
    # beyond capacity: fails cleanly
    assert al.alloc(2) is None
    for p in b + c:
        al.release_page(p)
    assert al.pages_free == 5


def test_admit_never_evicts_its_own_matched_pages():
    """Regression: under pressure, admit() must pin its matched prefix
    pages BEFORE allocating privates — otherwise the eviction loop can
    reclaim those very pages and recycle them as this plan's privates
    (a silent shared/private alias corrupting the shared K/V)."""
    al = PageAllocator(num_pages=6, page_size=4)     # 5 usable
    sys_p = list(range(10, 18))                      # exactly 2 pages
    plan_a = al.admit(sys_p + [1, 2], covered_pages=3)
    for n in plan_a.nodes:
        al.complete_node(n)
    al.release_plan(plan_a)
    assert al.pages_cached == 2 and al.pages_free == 3
    held = al.alloc(2)                               # free -> 1
    # B matches both cached pages and needs 2 privates with only 1
    # free: the ONLY reclaimable pages are B's own match — admission
    # must defer, not cannibalize itself
    plan_b = al.admit(sys_p + [9, 9, 9, 9], covered_pages=4)
    assert plan_b is None
    # and the pins rolled back: the match is still cached, nothing
    # leaked a refcount
    assert al.pages_cached == 2 and al.pages_free == 1
    for p in held:
        al.release_page(p)
    # with pressure relieved the same admission succeeds, alias-free
    plan_b = al.admit(sys_p + [9, 9, 9, 9], covered_pages=4)
    assert plan_b is not None and plan_b.n_shared_pages == 2
    assert len(set(plan_b.pages)) == len(plan_b.pages)


def test_cow_source_pinned_until_copy():
    """The CoW source page arrives pinned from admit() (pressure must
    not reclaim it before the device copy); releasing it afterwards
    returns it to the cached state."""
    al = PageAllocator(num_pages=8, page_size=4)
    prompt = list(range(20, 30))                     # 2 full pages + 2
    plan_a = al.admit(prompt, covered_pages=3)
    for n in plan_a.nodes:
        al.complete_node(n)
    al.release_plan(plan_a)
    # diverge mid-page-2: full match page 0, CoW from page 1's node
    plan_b = al.admit(prompt[:6] + [99, 98, 97, 96], covered_pages=3)
    assert plan_b is not None and plan_b.cow is not None
    src, dst = plan_b.cow
    assert src not in plan_b.pages and dst == plan_b.pages[1]
    assert al._ref.get(src, 0) == 1                  # pinned for copy
    al.release_page(src)                             # batcher, post-copy
    assert al._ref.get(src, 0) == 0
    al.release_plan(plan_b)


def test_allocator_match_and_partial():
    al = PageAllocator(num_pages=8, page_size=4)
    prompt = list(range(10, 22))              # 3 pages
    plan = al.admit(prompt, covered_pages=4)
    assert plan is not None and plan.shared_tokens == 0
    assert len(plan.nodes) == 3
    for n in plan.nodes:
        al.complete_node(n)
    # full + partial match: same 8 tokens, then diverge mid-page
    probe = prompt[:9] + [99, 98, 97]
    full, partial = al.match_prefix(probe, max_share=len(probe) - 1)
    assert len(full) == 2
    assert partial is not None and partial[1] == 1
    # incomplete nodes never match
    al2 = PageAllocator(num_pages=8, page_size=4)
    plan2 = al2.admit(prompt, covered_pages=4)
    full2, partial2 = al2.match_prefix(prompt, max_share=8)
    assert not full2 and partial2 is None
    al2.release_plan(plan2)
    assert al2.pages_free == 7                # pending nodes dropped


# ---------------------------------------------------------------------------
# eviction-under-pressure interleaved with copy-on-write (ISSUE 9
# satellite): the CoW source sits between trie match and device copy
# while the SAME admission's private allocation is evicting under
# pressure — the pinned source must survive and never alias a private


def test_cow_admission_evicts_others_never_its_source():
    """An admission that full-matches one chain, CoW-matches its next
    page, and needs more privates than the free list holds: the
    eviction loop must reclaim OTHER cached chains and must never
    touch the (pinned) CoW source or the matched page — the window
    between match_prefix and the device copy is exactly where a
    reclaimed source would silently alias a private page."""
    al = PageAllocator(num_pages=7, page_size=4)          # 6 usable
    # chain A: two complete cached pages (the future match + source)
    plan_a = al.admit(list(range(10, 18)) + [1, 2], covered_pages=3)
    for n in plan_a.nodes:
        al.complete_node(n)
    al.release_plan(plan_a)
    # chain C: two more complete cached pages (the eviction victims)
    plan_c = al.admit(list(range(50, 58)) + [3, 4], covered_pages=3)
    for n in plan_c.nodes:
        al.complete_node(n)
    al.release_plan(plan_c)
    assert al.pages_cached == 4 and al.pages_free == 2
    # D: full-match A page 1, diverge mid A page 2 (m=2), 3 privates
    # needed with only 2 free -> pressure evicts from chain C
    evicted_before = al.evictions
    plan_d = al.admit([10, 11, 12, 13, 14, 15, 99, 98, 97, 96],
                      covered_pages=4)
    assert plan_d is not None and plan_d.cow is not None
    src, dst = plan_d.cow
    assert al.evictions > evicted_before
    assert al.cow_copies == 1
    # the pinned source survived the eviction sweep and is not among
    # the plan's pages (it will be copied into dst, a fresh private)
    assert src not in plan_d.pages and dst == plan_d.pages[1]
    assert al._node_of.get(src) is not None
    assert al._ref.get(src, 0) == 1                       # copy pin
    assert len(set(plan_d.pages)) == len(plan_d.pages)
    # matched tokens: one full page + the 2-token partial
    assert plan_d.shared_tokens == 4 + 2
    al.release_page(src)                                  # post-copy
    al.release_plan(plan_d)


def test_cow_admissions_interleave_pressure_bitexact(model):
    """Batcher-level: staggered admissions where a CoW divergence and
    pool-pressure evictions interleave — every request still completes
    bit-exact (the copied page's content equals what an unshared
    prefill would have written, even though its source was under
    eviction pressure while mapped)."""
    rng = np.random.RandomState(21)
    sys_p = rng.randint(1, 128, 12).astype(np.int32)   # 1.5 pages @8
    tails = [rng.randint(1, 128, 4).astype(np.int32) for _ in range(2)]
    fresh = rng.randint(1, 128, 16).astype(np.int32)
    prompts = [np.concatenate([sys_p, tails[0]]),      # seeds the trie
               np.concatenate([sys_p, tails[1]]),      # CoW at page 2
               fresh]                                  # needs evictions
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=48,
                            chunk=4, prefill_chunk=8, page_size=8,
                            num_pages=8)
    rids = [bat.submit(prompts[0], 6)]
    bat.step()
    rids += [bat.submit(prompts[1], 6), bat.submit(prompts[2], 6)]
    outs = bat.run()
    st = bat.stats()
    assert st["cow_copies"] >= 1, st
    assert st["evictions"] >= 1, st
    assert st["prefix_hit_tokens"] > 0, st
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid],
                                      _isolated(model, p, 6))
    assert st["requests_submitted"] == st["requests_completed"] == 3
