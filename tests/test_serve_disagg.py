"""Disaggregated prefill/decode serving + fleet-tier prefix cache
(ISSUE 20).

The contracts under test:

  * HAND-OFF — a frozen prompt's KV pages survive export -> wire
    (pack/unpack, bfloat16-safe) -> import byte-identical, and the
    decode side admits at pos = prompt_len: its prefill_tokens stat
    stays at zero forever (the zero-recompute contract).
  * FLEET — a prefill/decode split fleet serves a mixed workload
    bit-exact vs the same replicas run unified, with no duplicate
    streamed tokens across the hand-off and leak-free page pools on
    BOTH ends (pages_used == pages_cached after drain).
  * DEGRADED — with no decode-capable sink anywhere, the frozen slot
    unfreezes and finishes on the prefill worker rather than deadlock;
    killing the prefill worker mid-freeze leaves no orphan pages.
  * FLEET-TIER CACHE — the migration budget replicates a hot prefix
    to the replica traffic lands on (cross-replica import hits), and
    a retired replica's digest-bearing view drops from discovery so
    probes never steer at a tombstone.
  * AUTOSCALER — the role-imbalance policy is a pure function: a
    sustained prefill/decode pressure skew flips the least-loaded
    replica of the relaxed role, never below one per role; chaos
    coverage rides `chaos_check --serve --disagg` tier-1.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.inference import ContinuousBatcher, ServeRouter
from paddle_tpu.inference.router import (ReplicaPublisher,
                                         discover_replicas,
                                         pick_replica)
from paddle_tpu.inference.serving import pack_handoff, unpack_handoff
from paddle_tpu.models.llama import (LlamaForCausalLM,
                                     llama_tiny_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _bat(model, **kw):
    geom = dict(max_batch_size=1, max_len=64, chunk=4, prefill_chunk=4)
    geom.update(kw)
    return ContinuousBatcher(model, **geom)


def _prompts(n=6, shared=24):
    rng = np.random.RandomState(5)
    base = rng.randint(1, 127, size=shared).tolist()
    out = []
    for k in range(n):
        tail = rng.randint(1, 127, size=4 + k).tolist()
        out.append(np.asarray(base + tail if k % 2 == 0
                              else rng.randint(1, 127, 6 + k).tolist(),
                              np.int32))
    return out


# ---------------------------------------------------------------------------
# hand-off primitive: byte-identical pages, zero recompute
# ---------------------------------------------------------------------------

def test_handoff_pages_byte_identical(model):
    """Exported pages land bit-identical in the decode pool: gather
    the grafted prompt chain on the import side and compare raw rows
    (per KV buffer, bfloat16 included) against the exported data."""
    pre = _bat(model, role="prefill")
    dec = _bat(model, role="decode")
    prompt = _prompts(1)[0]
    rid = pre.submit(prompt, max_new_tokens=6)
    for _ in range(64):
        pre.step()
        if pre._handoff_ready:
            break
    assert rid in pre._handoff_ready
    meta, data = pre.export_handoff(rid)
    # wire round-trip must be lossless (bfloat16 has no npy codec —
    # pack views through uint16)
    blob = pack_handoff(meta, data)
    meta2, data2 = unpack_handoff(blob)
    assert meta2["pos"] == meta["pos"]
    assert np.array_equal(meta2["prompt"], meta["prompt"])
    for name in data:
        assert np.array_equal(np.asarray(data[name]),
                              np.asarray(data2[name])), name
    lid = dec.import_handoff(meta2, data2)
    assert lid is not None
    # the full prompt chunks grafted into the decode trie: their pages
    # must hold the exact rows the prefill side shipped
    n_tok, dst_pages = dec._alloc.export_chain(meta["prompt"])
    assert n_tok >= dec.page_size and dst_pages
    for name in data:
        src = np.asarray(data[name])[:len(dst_pages)]
        dst = np.asarray(dec._cache[name][np.asarray(dst_pages)])
        assert np.array_equal(src, dst), name
    dec.run()
    assert dec.stats()["prefill_tokens"] == 0
    assert dec.stats()["handoffs_in"] == 1


def test_disagg_fleet_bit_exact_vs_unified(model):
    """2-replica unified reference vs the same replicas split
    prefill/decode: identical outputs, identical streams (no token
    delivered twice across the hand-off), zero decode-side prefill,
    leak-free pools on both ends."""
    prompts, mnt = _prompts(), [12, 6, 10, 8, 14, 7]

    def run(roles):
        streamed = {}
        router = ServeRouter(batchers=[_bat(model, max_batch_size=2)
                                       for _ in range(2)], roles=roles)
        cb = lambda g, burst, done: \
            streamed.setdefault(g, []).extend(burst)
        gids = [router.submit(p, max_new_tokens=m, on_token=cb)
                for p, m in zip(prompts, mnt)]
        res = router.run()
        return router, {g: res[g] for g in gids}, streamed

    _, ref, ref_stream = run(None)
    router, out, streamed = run(["prefill", "decode"])
    st = router.stats()
    assert st["requests_shed"] == 0
    assert st["handoffs"] > 0
    assert st["handoff_staged"] == 0
    for g in ref:
        assert np.array_equal(ref[g], out[g]), g
        assert streamed[g] == list(out[g]), g
        assert ref_stream[g] == list(ref[g]), g
    dec = router._reps[1].bat
    assert dec.role == "decode"
    assert dec.stats()["prefill_tokens"] == 0
    assert dec.stats()["handoffs_in"] == st["handoffs"]
    for rep in router._reps:
        s = rep.bat.stats()
        assert s["kv_pages_used"] == s["kv_pages_cached"], rep.idx
    assert st["cross_prefix_hit_tokens"] >= 0
    assert st["handoff_ms"]["count"] == st["handoffs"]


def test_unfreeze_fallback_without_decode_sink(model):
    """A prefill-only fleet must not deadlock its own admissions: with
    no decode-capable sink the frozen slot unfreezes and decodes in
    place, and the output still matches the unified reference."""
    prompt = _prompts(1)[0]
    ref = _bat(model)
    rid = ref.submit(prompt, max_new_tokens=5)
    want = ref.run()[rid]

    router = ServeRouter(batchers=[_bat(model, role="prefill")],
                         roles=["prefill"])
    gid = router.submit(prompt, max_new_tokens=5)
    out = router.run()
    assert np.array_equal(out[gid], want)
    st = router.stats()
    assert st["handoffs"] == 0 and st["requests_shed"] == 0


def test_interrupted_handoff_leaves_no_orphans(model):
    """Kill the prefill worker while it holds a frozen (hand-off
    ready) slot: the request requeues and completes elsewhere, and no
    survivor pool leaks pages (pages_used == pages_cached after
    drain)."""
    # short prompts + long decodes: the decode sinks saturate, so a
    # frozen slot survives the sweep (export defers until a sink has
    # a free slot) long enough for the kill to land mid-hand-off
    rng = np.random.RandomState(5)
    prompts = [np.asarray(rng.randint(1, 127, 8 + k), np.int32)
               for k in range(4)]
    mnt = [40, 40, 12, 12]
    bats = [_bat(model, role=r)
            for r in ("prefill", "decode", "decode")]
    router = ServeRouter(batchers=bats,
                         roles=["prefill", "decode", "decode"])
    gids = [router.submit(p, max_new_tokens=m)
            for p, m in zip(prompts, mnt)]
    killed = False
    for _ in range(64):
        router.step()
        if not killed and router._reps[0].bat._handoff_ready:
            router.kill_replica(0)
            killed = True
        if not any(r.bat.queued or r.bat.active
                   for r in router._live()) \
                and not router._handoff_staged:
            break
    assert killed, "prefill replica never froze a slot"
    out = router.run()
    assert all(len(out[g]) == m for g, m in zip(gids, mnt))
    st = router.stats()
    assert st["requests_shed"] == 0
    for rep in router._live():
        s = rep.bat.stats()
        assert s["kv_pages_used"] == s["kv_pages_cached"], rep.idx


def test_serve_disagg_flag_default_split(model):
    """FLAGS_serve_disagg splits an in-house fleet prefill-first with
    the odd replica on decode (decode capacity is the scarcer side)."""
    set_flags({"FLAGS_serve_disagg": True})
    try:
        r = ServeRouter(model=model, replicas=3, max_batch_size=1,
                        max_len=64, chunk=4, prefill_chunk=4)
        assert [x.role for x in r._reps] == \
            ["prefill", "decode", "decode"]
        assert [x.bat.role for x in r._reps] == \
            ["prefill", "decode", "decode"]
    finally:
        set_flags({"FLAGS_serve_disagg": False})


# ---------------------------------------------------------------------------
# fleet-tier prefix cache
# ---------------------------------------------------------------------------

def test_migration_budget_replicates_hot_prefix(model):
    """Load steers a same-prefix request away from the holder; the
    budgeted sweep copies the prefix to where traffic landed, so the
    NEXT same-prefix admit hits imported (cross-replica) pages."""
    rng = np.random.RandomState(5)
    shared = rng.randint(1, 127, size=24).tolist()
    set_flags({"FLAGS_router_migration_budget": 8,
               "FLAGS_router_prefix_weight": 0.001})
    try:
        b0, b1 = _bat(model, max_batch_size=2), \
            _bat(model, max_batch_size=2)
        p = np.asarray(shared + [5, 9], np.int32)
        b1.submit(p, 4)
        b1.run()                      # warm the holder's trie
        router = ServeRouter(batchers=[b0, b1])
        # one queued filler loads the holder so pick steers the next
        # same-prefix request to the cold replica
        b1.submit(np.asarray(shared + [7, 7], np.int32), 8)
        router.submit(p, 4)
        router.step()
        assert router.stats()["replicated_pages"] > 0
        router.submit(np.asarray(shared + [5, 9, 3], np.int32), 4)
        router.run()
        st = router.stats()
        assert st["cross_prefix_hit_tokens"] > 0
        assert st["requests_shed"] == 0
    finally:
        set_flags({"FLAGS_router_migration_budget": 0,
                   "FLAGS_router_prefix_weight": 1.0})


def test_tombstone_drops_digest_from_probes():
    """Regression (satellite): a retired prefill worker's published
    digest must vanish from discovery — otherwise cross-replica
    probes keep steering traffic at a corpse."""
    from paddle_tpu.fleet.autoscaler import _LocalKV
    kv = _LocalKV()
    digest = [[3, 123456789], [6, 987654321]]
    p0 = ReplicaPublisher(kv, job_id="j", replica=0)
    p1 = ReplicaPublisher(kv, job_id="j", replica=1)
    p0.publish({"queued": 0, "active": 0, "slots": 1, "role": "prefill",
                "draining": False, "shed_rate": 0.0,
                "trie_digest": digest, "page_size": 4})
    p1.publish({"queued": 0, "active": 0, "slots": 1, "role": "decode",
                "draining": False, "shed_rate": 0.0})
    got = discover_replicas(kv, job_id="j")
    assert set(got) == {0, 1}
    assert got[0]["trie_digest"] == digest
    assert got[0]["role"] == "prefill" and got[1]["role"] == "decode"
    assert p0.retire()
    got = discover_replicas(kv, job_id="j")
    assert set(got) == {1}, "tombstoned replica still discoverable"
    assert not any(v.get("trie_digest") for v in got.values())


def test_pick_replica_probes_digest_cross_replica():
    """A digest-bearing view scores prefix affinity WITHOUT a local
    probe: the digest hit must win placement over an idle cold
    replica exactly like a resident prefix_hit_tokens would."""
    from paddle_tpu.inference.paged_kv import PageAllocator
    alloc = PageAllocator(num_pages=8, page_size=4)
    toks = list(range(1, 13))
    node = None
    for i in range(0, 12, 4):
        pages = alloc.alloc(1)
        node = alloc.register_chunk(node, toks[i:i + 4], pages[0])
        alloc.complete_node(node)
    digest = alloc.trie_digest()
    views = [
        {"replica": 0, "queued": 0, "active": 0, "slots": 1,
         "draining": False, "shed_rate": 0.0},
        {"replica": 1, "queued": 0, "active": 0, "slots": 1,
         "draining": False, "shed_rate": 0.0,
         "trie_digest": digest, "page_size": 4},
    ]
    # equal load: only the digest hit (12 tokens, 3 full chunks)
    # separates the replicas — the probe must steer to the holder
    prompt = np.asarray(toks + [99], np.int32)
    assert pick_replica(views, prefix_weight=1.0, prompt=prompt) == 1
    # a cold prompt scores zero on the digest: deterministic tie-break
    cold = np.asarray([88, 77, 66, 55, 44], np.int32)
    assert pick_replica(views, prefix_weight=1.0, prompt=cold) == 0


# ---------------------------------------------------------------------------
# autoscaler role repair (pure policy)
# ---------------------------------------------------------------------------

def _role_view(pp, dp, reps):
    return {"routable": len([r for r in reps if not r["draining"]]),
            "draining": 0, "queued": 0, "occupancy": 0.5,
            "shed_rate": 0.0, "attainment": {},
            "prefill_pressure": pp, "decode_pressure": dp,
            "replicas": reps}


def _rep(i, role, queued=0, active=0, draining=False):
    return {"replica": i, "role": role, "queued": queued,
            "active": active, "slots": 1, "draining": draining,
            "handoff_ready": 0}


def test_role_flip_decide_unit():
    """Sustained prefill pressure flips the least-loaded decode
    replica; the floor (one replica per role) is never crossed; the
    streak resets on a neutral tick."""
    from paddle_tpu.fleet.autoscaler import (AutoscalePolicy,
                                             PolicyState, decide,
                                             observe)
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, window=2,
                          cooldown=2, queue_high=99.0, queue_low=0.0,
                          role_imbalance=2.0, lease_ttl_s=0.0)
    st = PolicyState()
    reps = [_rep(0, "prefill", queued=3), _rep(1, "decode"),
            _rep(2, "decode", active=1)]
    v = _role_view(3.0, 0.0, reps)
    observe(st, v, pol)
    assert st.prefill_streak == 1
    a = decide(v, pol, st)
    assert a.kind == "none"          # streak below window
    observe(st, v, pol)
    a = decide(v, pol, st)
    assert a.kind == "role_flip" and a.role == "prefill"
    assert a.replica == 1            # least-loaded decode replica
    assert "pressure" in a.reason
    # floor: a lone decode replica never flips
    lone = [_rep(0, "prefill", queued=3), _rep(1, "decode")]
    st2 = PolicyState()
    v2 = _role_view(3.0, 0.0, lone)
    observe(st2, v2, pol)
    observe(st2, v2, pol)
    assert decide(v2, pol, st2).kind == "none"
    # neutral tick clears the streak
    observe(st, _role_view(1.0, 1.0, reps), pol)
    assert st.prefill_streak == 0 and st.decode_streak == 0
    # symmetric decode-pressure branch needs a sparable prefill
    # replica (the lone one above is floor-protected)
    reps3 = [_rep(0, "prefill", queued=1), _rep(1, "prefill"),
             _rep(2, "decode", active=1)]
    st3 = PolicyState()
    v3 = _role_view(0.0, 3.0, reps3)
    observe(st3, v3, pol)
    observe(st3, v3, pol)
    a = decide(v3, pol, st3)
    assert a.kind == "role_flip" and a.role == "decode"
    assert a.replica == 1            # least-loaded prefill replica
    # and a lone prefill replica never flips to decode
    st4 = PolicyState()
    v4 = _role_view(0.0, 3.0, reps)
    observe(st4, v4, pol)
    observe(st4, v4, pol)
    assert decide(v4, pol, st4).kind == "none"


def test_fleet_view_splits_role_pressure(model):
    """fleet_view publishes prefill/decode pressure only for a split
    fleet, counting frozen hand-off-ready slots as DECODE demand (the
    work exists, it just has not landed yet)."""
    from paddle_tpu.fleet.autoscaler import fleet_view
    router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
    v = fleet_view(router)
    assert "prefill_pressure" not in v       # unified fleet: no split
    router2 = ServeRouter(batchers=[_bat(model, role="prefill"),
                                    _bat(model, role="decode")],
                          roles=["prefill", "decode"])
    v2 = fleet_view(router2)
    assert v2["prefill_pressure"] == 0.0
    assert v2["decode_pressure"] == 0.0
    assert v2["handoff_ready"] == 0


# ---------------------------------------------------------------------------
# tier-1 chaos wiring
# ---------------------------------------------------------------------------

def test_chaos_disagg_selftest_cli():
    """Tier-1 wiring: prefill worker killed mid-hand-off AND decode
    worker killed mid-decode — every request completes bit-exact vs
    the unified reference, no duplicate streamed tokens, survivor
    pools leak-free, zero decode-side prefill — exit 0."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check as cli
    finally:
        sys.path.pop(0)
    assert cli.main(["--serve", "--disagg"]) == 0
