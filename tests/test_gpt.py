"""GPT family (baseline config 4 surface): training convergence + the
hybrid TP+ZeRO train step on the virtual mesh."""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu.models.gpt import (GPTForCausalLM, gpt_tiny_config,
                                   shard_gpt_tp)


def test_gpt_trains():
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny_config())
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, 256, (4, 32)).astype(np.int32))
    losses = [float(np.asarray(step(ids, ids).value)) for _ in range(5)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


def test_gpt_hybrid_tp_zero3():
    """Config-4 shape: dp x sharding x mp on the virtual 8-mesh with
    ZeRO-3 + tied-embedding head."""
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh
    paddle.seed(0)
    cfg = gpt_tiny_config(num_hidden_layers=2, hidden_size=64,
                          intermediate_size=128, num_attention_heads=4,
                          vocab_size=128)
    m = GPTForCausalLM(cfg)
    mesh = build_mesh(dp=2, sharding=2, mp=2,
                      devices=jax.devices()[:8])
    shard_gpt_tp(m, mesh)
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    st = ShardedTrainStep(m, opt, mesh, sharding_stage=3)
    ids = paddle.to_tensor(np.random.RandomState(1).randint(
        0, 128, (8, 16)).astype(np.int32))
    losses = [float(np.asarray(st(ids, ids).value)) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_gpt_hapi_model_fit():
    """High-level Model.fit drives GPT pretraining end to end
    (reference: hapi model.py fit with a language-model loss)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import Dataset

    paddle.seed(0)
    net = GPTForCausalLM(gpt_tiny_config())

    class LMData(Dataset):
        def __init__(self, n=32):
            rng = np.random.RandomState(0)
            self.ids = rng.randint(0, 256, (n, 24)).astype(np.int32)

        def __len__(self):
            return len(self.ids)

        def __getitem__(self, i):
            return self.ids[i], self.ids[i]

    model = Model(net)
    opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
    model.prepare(opt, loss=lambda o, y: net.compute_loss(o, y))
    hist = model.fit(LMData(), batch_size=8, epochs=2, verbose=0)
    losses = [float(np.asarray(l)) for l in
              (hist["loss"] if isinstance(hist, dict) else [])] \
        if hist else []
    # convergence evidence comes from eval on the train data
    out = model.evaluate(LMData(), batch_size=8, verbose=0)
    assert np.isfinite(list(out.values())[0])
