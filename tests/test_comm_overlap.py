"""Comm/compute overlap engine (parallel/comm_overlap.py): bucketed
gradient collectives issued with the backward.

Correctness contract pinned here (ISSUE 16 acceptance criteria):

  * bucket assembly is size-targeted, reverse-topological, dtype-safe
    (the boundary-case zoo: giant param, many tiny params, mixed
    dtypes, empty list);
  * with comm_overlap=True the per-step losses and updated params are
    BIT-EXACT vs the monolithic path, for ZeRO stages 1/2/3 on the
    8-device host mesh — flatten/concat/unflatten is exact and the
    reduction runs over the same participants either way;
  * every supported (stage, pp-schedule) combination passes the static
    collective-order check before any chip time;
  * estimate_exposed_comm predicts overlap-on strictly below
    overlap-off whenever there are >= 2 buckets and compute to hide
    under (the perf_report bench gate's model);
  * the grad-comm dtype lint proves the reduce runs at the requested
    width (no silent bf16 -> fp32 upcast).
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.analysis.collectives import (
    CollectiveEvent, CollectiveOrderError, assert_collective_order,
    estimate_exposed_comm)
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, PipelineLayer)
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup, build_mesh, set_hybrid_communicate_group)
from paddle_tpu.parallel import ShardedTrainStep
from paddle_tpu.parallel.comm_overlap import (
    CommOverlapPlan, build_buckets, resolve_comm_dtype)
from paddle_tpu.parallel.pipeline import PipelineEngine


def _need8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")


# ---------------------------------------------------------------------------
# bucket assembly
# ---------------------------------------------------------------------------

class TestBucketAssembly:
    def test_size_target_closes_buckets(self):
        # 8 params x 1KB at a 2KB target -> 4 buckets of 2 params
        names = [f"p{i}" for i in range(8)]
        shapes = [(256,)] * 8          # 256 * 4B = 1KB each
        dtypes = ["float32"] * 8
        bs = build_buckets(names, shapes, dtypes, bucket_mb=2 / 1024)
        assert len(bs) == 4
        assert all(len(b.indices) == 2 for b in bs)
        assert sum(b.nbytes for b in bs) == 8 * 1024

    def test_reverse_topological_order(self):
        # the backward produces last-layer grads first: bucket 0 must
        # hold the LAST registered params
        names = ["first", "mid", "last"]
        bs = build_buckets(names, [(4,)] * 3, ["float32"] * 3,
                           bucket_mb=1.0)
        assert bs[0].names == ("last", "mid", "first")
        bs = build_buckets(names, [(300,)] * 3, ["float32"] * 3,
                           bucket_mb=1 / 1024)
        assert [b.names for b in bs] == [("last",), ("mid",), ("first",)]

    def test_giant_param_gets_own_bucket(self):
        # a single param over the target (the embedding case) closes
        # the running bucket and takes one of its own
        names = ["small_a", "giant", "small_b"]
        shapes = [(8,), (1 << 20,), (8,)]
        bs = build_buckets(names, shapes, ["float32"] * 3,
                           bucket_mb=0.5)
        assert [b.names for b in bs] == [
            ("small_b",), ("giant",), ("small_a",)]
        assert bs[1].nbytes == (1 << 20) * 4

    def test_many_tiny_params_fuse(self):
        names = [f"t{i}" for i in range(100)]
        bs = build_buckets(names, [(2,)] * 100, ["float32"] * 100,
                           bucket_mb=32.0)
        assert len(bs) == 1
        assert bs[0].numel == 200

    def test_dtype_separation(self):
        # bf16 and fp32 params never share a fused buffer
        names = ["a", "b", "c", "d"]
        dtypes = ["float32", "bfloat16", "bfloat16", "float32"]
        bs = build_buckets(names, [(4,)] * 4, dtypes, bucket_mb=32.0)
        assert [b.comm_dtype for b in bs] == [
            "float32", "bfloat16", "float32"]
        assert bs[1].names == ("c", "b")

    def test_empty_param_list(self):
        assert build_buckets([], [], [], bucket_mb=32.0) == []

    def test_divisor_pads_for_reduce_scatter(self):
        bs = build_buckets(["p"], [(10,)], ["float32"], bucket_mb=1.0,
                           divisor=8)
        assert bs[0].numel == 10 and bs[0].padded_numel == 16
        # payload bytes exclude the pad
        assert bs[0].nbytes == 40

    def test_resolve_comm_dtype(self):
        assert resolve_comm_dtype("float32", "auto") == "float32"
        assert resolve_comm_dtype("bfloat16", "auto") == "bfloat16"
        assert resolve_comm_dtype("float32", "bfloat16") == "bfloat16"


# ---------------------------------------------------------------------------
# static schedule + event model
# ---------------------------------------------------------------------------

class TestStaticSchedule:
    def _plan(self, stage, n_params=6, bucket_mb=0.001):
        names = [f"p{i}" for i in range(n_params)]
        return CommOverlapPlan.modeled(
            names, [(128,)] * n_params, ["float32"] * n_params,
            world=8, stage=stage, bucket_mb=bucket_mb)

    @pytest.mark.parametrize("stage", [0, 1, 2, 3])
    def test_plan_verifies_per_stage(self, stage):
        plan = self._plan(stage)
        assert plan.active
        plan.verify()                      # raises on divergence
        evs = plan.events()
        reduces = [e for e in evs if e.kind in ("psum", "reduce_scatter")]
        assert len(reduces) == len(plan.buckets)
        # issue order: bucket 0 reduces first
        assert [e.bucket for e in reduces] == list(
            range(len(plan.buckets)))
        if stage >= 2:
            assert all(e.kind == "reduce_scatter" for e in reduces)
        else:
            assert all(e.kind == "psum" for e in reduces)
        if stage >= 3:
            gathers = [e for e in evs if e.kind == "all_gather"]
            # prefetch in FORWARD order = reversed bucket issue order
            assert [e.bucket for e in gathers] == list(
                range(len(plan.buckets) - 1, -1, -1))

    def test_order_divergence_is_caught(self):
        plan = self._plan(2)
        sched = plan.schedules(world=4)
        sched[2] = list(reversed(sched[2]))    # rank 2 swaps buckets
        with pytest.raises(CollectiveOrderError):
            assert_collective_order(sched)

    def test_collective_event_back_compat(self):
        # pre-existing 3-positional-arg call sites must keep working
        ev = CollectiveEvent("psum", ("k",), ("dp",))
        assert ev.bytes == 0 and ev.bucket == -1
        rich = CollectiveEvent("psum", ("k",), ("dp",), bytes=1 << 20,
                               bucket=2)
        assert "bucket 2" in rich.describe()

    @pytest.mark.parametrize("schedule,vpp", [
        ("FThenB", 1), ("1F1B", 1), ("ZB", 1), ("VPP", 2),
        ("ZB-VPP", 2)])
    def test_pipeline_schedules_verify_with_overlap(self, schedule, vpp):
        """Every supported pp schedule passes the static order check
        with grad-bucket drains woven in, and emits grad_rs events
        carrying bytes + bucket ids."""
        _need8()
        paddle.set_flags({"FLAGS_comm_bucket_mb": 0.0001})
        try:
            paddle.seed(42)
            pl = PipelineLayer(
                [LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
                loss_fn=lambda o, y: ((o - y) ** 2).mean())
            hcg = HybridCommunicateGroup(pp_degree=2)
            set_hybrid_communicate_group(hcg)
            kw = {"num_virtual_stages": vpp} if vpp > 1 else {}
            eng = PipelineEngine(pl, mesh=hcg.mesh, **kw)
            eng.verify_schedule(4, schedule, comm_overlap=True)
            evs = eng.collective_events(4, schedule, comm_overlap=True)
            rs = [e for es in evs.values() for e in es
                  if e.kind == "grad_rs"]
            assert rs and all(e.bytes > 0 and e.bucket >= 0 for e in rs)
        finally:
            paddle.set_flags({"FLAGS_comm_bucket_mb": 32.0})


# ---------------------------------------------------------------------------
# exposed-comm estimator (the perf_report gate's model)
# ---------------------------------------------------------------------------

class TestExposedCommEstimate:
    def test_overlap_strictly_below_monolithic(self):
        sizes = [1 << 20] * 4
        on = estimate_exposed_comm(sizes, compute_ms=50.0,
                                   bytes_per_sec=1e9)
        off = estimate_exposed_comm(sizes, compute_ms=50.0,
                                    bytes_per_sec=1e9, overlap=False)
        assert on["exposed_ms"] < off["exposed_ms"]
        assert off["exposed_ms"] == pytest.approx(off["comm_ms"])
        assert 0.0 <= on["overlap_efficiency"] <= 1.0

    def test_single_bucket_gains_nothing(self):
        # n=1: the lone collective still waits for the full backward
        on = estimate_exposed_comm([1 << 20], compute_ms=50.0,
                                   bytes_per_sec=1e9)
        off = estimate_exposed_comm([1 << 20], compute_ms=50.0,
                                    bytes_per_sec=1e9, overlap=False)
        assert on["exposed_ms"] == pytest.approx(off["exposed_ms"])

    def test_zero_compute_fully_exposed(self):
        on = estimate_exposed_comm([1 << 20] * 4, compute_ms=0.0,
                                   bytes_per_sec=1e9)
        assert on["exposed_ms"] == pytest.approx(on["comm_ms"])

    def test_accepts_events_and_ints(self):
        evs = [CollectiveEvent("psum", ("k",), ("dp",), bytes=1000,
                               bucket=i) for i in range(3)]
        a = estimate_exposed_comm(evs, compute_ms=1.0,
                                  bytes_per_sec=1e9)
        b = estimate_exposed_comm([1000] * 3, compute_ms=1.0,
                                  bytes_per_sec=1e9)
        assert a == b
        assert a["bytes"] == 3000 and a["buckets"] == 3


# ---------------------------------------------------------------------------
# bit-exactness on the 8-device host mesh (the tier-1 pin)
# ---------------------------------------------------------------------------

class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(16, 32)
        self.l2 = nn.Linear(32, 16)
        self.l3 = nn.Linear(16, 4)

    def forward(self, x):
        h = nn.functional.relu(self.l1(x))
        h = nn.functional.relu(self.l2(h))
        return self.l3(h)


class TestBitExact:
    def _run(self, stage, overlap, steps=3):
        paddle.seed(42)
        m = _MLP()
        opt = paddle.optimizer.SGD(0.05, parameters=m.parameters())
        mesh = build_mesh(sharding=8)
        st = ShardedTrainStep(
            m, opt, mesh, sharding_stage=stage,
            loss_fn=lambda o, y: nn.functional.cross_entropy(o, y),
            comm_overlap=overlap, comm_bucket_mb=0.001)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (16, 1)).astype(np.int64))
        losses = [float(np.asarray(st(x, y).value)) for _ in range(steps)]
        params = {n: np.asarray(v.value).copy()
                  for n, v in m.state_dict().items()}
        return losses, params, st

    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_bucketed_matches_monolithic_bitwise(self, stage):
        _need8()
        l_off, p_off, _ = self._run(stage, False)
        l_on, p_on, st = self._run(stage, True)
        assert l_on == l_off                      # exact, not allclose
        for n in p_off:
            np.testing.assert_array_equal(p_on[n], p_off[n])
        # the plan was built, split the grads, and passed its static
        # pre-flight at build time
        assert st._overlap_plan is not None
        assert len(st._overlap_plan.buckets) >= 2
        sched = st.overlap_schedule()
        assert sched and len(sched) == 8

    @pytest.mark.parametrize("stage", [2, 3])
    def test_dtype_lint_clean_at_auto(self, stage):
        """Satellite 1: the jaxpr-level audit proves every bucket's
        reduce runs at the requested wire width (stage 2 via the fused
        constraint, stage 3 via the layout-neutral barrier chain)."""
        _need8()
        _, _, st = self._run(stage, True, steps=1)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (16, 1)).astype(np.int64))
        assert st.lint_comm_dtype(x, y) == []

    def test_pipeline_drain_bit_exact(self):
        """Grad-bucket drains inside the schedule bubble change WHEN
        Parameter.grad is written, never its value."""
        _need8()
        paddle.set_flags({"FLAGS_comm_bucket_mb": 0.0001})
        try:
            rng = np.random.RandomState(7)
            x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
            y = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))

            def run(overlap):
                paddle.seed(42)
                pl = PipelineLayer(
                    [LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
                    loss_fn=lambda o, t: ((o - t) ** 2).mean())
                hcg = HybridCommunicateGroup(pp_degree=2)
                set_hybrid_communicate_group(hcg)
                eng = PipelineEngine(pl, mesh=hcg.mesh)
                opt = paddle.optimizer.SGD(
                    0.05, parameters=pl.parameters())
                out = []
                for _ in range(2):
                    loss = eng.train_batch([x, y], 4, schedule="1F1B",
                                           comm_overlap=overlap)
                    opt.step()
                    opt.clear_grad()
                    out.append(float(np.asarray(loss.value)))
                return out, eng

            l_off, _ = run(False)
            l_on, eng = run(True)
            assert l_on == l_off
            assert eng._drained        # drains actually executed
        finally:
            paddle.set_flags({"FLAGS_comm_bucket_mb": 32.0})


# ---------------------------------------------------------------------------
# fleet plane: the bucketed host reduce (tools/chaos_check.py --comm-overlap)
# ---------------------------------------------------------------------------

class TestFleetBucketedReduce:
    """`chaos_check --fleet --comm-overlap` swaps the monolithic host
    all_reduce for one call per grad bucket in issue order.  Pin the
    reassembly math here (cheap, in-process): the bucketed exchange is
    element-for-element identical to the monolithic one — the property
    that makes the elastic kill/shrink-resume bit-exact with buckets
    in flight (no torn bucket state can reach a checkpoint)."""

    def _cli(self):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "chaos_check", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(
                    __file__))), "tools", "chaos_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_bucketed_reduce_matches_monolithic(self):
        cli = self._cli()
        model, _ = cli.fleet_model()

        calls = []

        class _HC:
            def all_reduce(self, v):   # a fake 2-rank sum of twins
                calls.append(len(v))
                return np.asarray(v, np.float32) * 2.0

        fn = cli.fleet_bucketed_reduce(_HC(), model, bucket_mb=0.0005)
        n = 1 + sum(int(np.prod(p.value.shape))
                    for _, p in model.named_parameters())
        flat = np.random.RandomState(3).randn(n).astype(np.float32)
        got = fn(flat)
        np.testing.assert_array_equal(got, flat * 2.0)
        # one collective per bucket, never per-param, never monolithic
        assert len(calls) == len(fn.buckets) >= 2
        # every element rides exactly one bucket; the loss scalar too
        assert sum(calls) == n
        assert calls[0] == 1 + sum(
            int(np.prod(s)) for s in fn.buckets[0].shapes)

    def test_bucket_issue_order_is_rank_invariant(self):
        # the deadlock guard: every rank must derive the SAME bucket
        # sequence from its local model clone
        cli = self._cli()
        m1, _ = cli.fleet_model()
        m2, _ = cli.fleet_model()

        class _HC:
            def all_reduce(self, v):
                return v

        b1 = cli.fleet_bucketed_reduce(_HC(), m1).buckets
        b2 = cli.fleet_bucketed_reduce(_HC(), m2).buckets
        assert [(b.idx, b.names) for b in b1] \
            == [(b.idx, b.names) for b in b2]
