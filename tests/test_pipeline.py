"""Pipeline parallelism: loss equality vs non-pipelined execution.

Reference test pattern: test/collective/fleet/hybrid_parallel_pp_*.py —
a PipelineLayer trained through train_batch must match the same model
trained unpipelined on one device (same init, same data).
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, SharedLayerDesc, PipelineLayer, PipelineParallel)
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup, set_hybrid_communicate_group)
from paddle_tpu.parallel.pipeline import (
    PipelineEngine, partition_uniform, partition_by_params)


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)
        self.norm = nn.LayerNorm(d)

    def forward(self, x):
        return self.norm(x + self.fc2(nn.functional.gelu(self.fc1(x))))


def _mse(out, y):
    return ((out - y) ** 2).mean()


def _make_descs(d, depth):
    return [LayerDesc(Block, d) for _ in range(depth)] + [
        LayerDesc(nn.Linear, d, d)]


def _data(d, batch=8):
    rng = np.random.RandomState(7)
    x = rng.randn(batch, d).astype(np.float32)
    y = rng.randn(batch, d).astype(np.float32)
    return paddle.to_tensor(x), paddle.to_tensor(y)


def _train_ref(model, data, steps, lr=0.05):
    """Unpipelined baseline: same loss (mean over full batch) + SGD."""
    opt = paddle.optimizer.SGD(lr, parameters=model.parameters())
    x, y = data
    losses = []
    for _ in range(steps):
        loss = _mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.value)))
    return losses


@pytest.mark.parametrize("pp,micro,schedule", [
    (2, 4, "1F1B"), (4, 8, "1F1B"), (2, 4, "FThenB"),
])
def test_pp_loss_matches_single_device(pp, micro, schedule):
    d, depth, steps = 8, 3, 3
    paddle.seed(42)
    ref = PipelineLayer(_make_descs(d, depth), loss_fn=_mse)
    paddle.seed(42)
    pl = PipelineLayer(_make_descs(d, depth), loss_fn=_mse)

    data = _data(d)
    ref_losses = _train_ref(ref, data, steps)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": pp}
    strategy.pipeline_configs = {"accumulate_steps": micro,
                                 "schedule_mode": schedule}
    hcg = HybridCommunicateGroup(pp_degree=pp)
    set_hybrid_communicate_group(hcg)
    model = PipelineParallel(pl, hcg=hcg, strategy=strategy)
    opt = paddle.optimizer.SGD(0.05, parameters=pl.parameters())
    pp_losses = [float(np.asarray(
        model.train_batch(data, opt).value)) for _ in range(steps)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5, atol=1e-6)


def test_pp_param_count_partition():
    weights = [100, 100, 100, 1, 1, 1, 100, 100]
    b = partition_by_params(weights, 2)
    assert b[0] == 0 and b[-1] == 8 and len(b) == 3
    left = sum(weights[:b[1]])
    right = sum(weights[b[1]:])
    assert abs(left - right) <= 150  # roughly balanced

    assert partition_uniform(10, 3) == [0, 4, 7, 10]


def test_pp_shared_embedding_tied():
    """Tied first/last weights (SharedLayerDesc) stay in sync and get
    summed gradients."""
    d, vocab = 8, 16

    def head_fwd(layer, x):
        return paddle.matmul(x, layer.weight, transpose_y=True)

    def make():
        return PipelineLayer(
            [SharedLayerDesc("embed", nn.Embedding, None, "weight",
                             vocab, d),
             LayerDesc(Block, d),
             SharedLayerDesc("embed", nn.Embedding, head_fwd, "weight",
                             vocab, d)],
            loss_fn=lambda out, y: paddle.nn.functional.cross_entropy(
                out, y))

    paddle.seed(3)
    ref = make()
    paddle.seed(3)
    pl = make()

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randint(0, vocab, (8, 4)).astype(np.int64))
    y = paddle.to_tensor(rng.randint(0, vocab, (8, 4)).astype(np.int64))

    ref_losses = []
    opt_ref = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
    for _ in range(2):
        loss = ref.loss_fn(ref(x), y)
        loss.backward()
        opt_ref.step()
        opt_ref.clear_grad()
        ref_losses.append(float(np.asarray(loss.value)))

    hcg = HybridCommunicateGroup(pp_degree=2)
    set_hybrid_communicate_group(hcg)
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 2}
    model = PipelineParallel(pl, hcg=hcg, strategy=strategy)
    opt = paddle.optimizer.SGD(0.1, parameters=pl.parameters())
    pp_losses = [float(np.asarray(
        model.train_batch([x, y], opt).value)) for _ in range(2)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5, atol=1e-6)


def _peak_in_flight(order):
    """Max simultaneously-held forward activations implied by an order
    list: +1 per f, released by b (plain) or w (zero-bubble)."""
    has_w = any(k == "w" for k, _, _ in order)
    release = "w" if has_w else "b"
    in_flight = peak = 0
    for kind, _, _ in order:
        if kind == "f":
            in_flight += 1
        elif kind == release:
            in_flight -= 1
        peak = max(peak, in_flight)
    return peak


def test_pp_1f1b_in_flight_bound():
    """1F1B order: stage 0 of a 4-stage pipeline never holds more than
    pp in-flight forwards (vs m for FThenB)."""
    hcg = HybridCommunicateGroup(pp_degree=4)
    set_hybrid_communicate_group(hcg)
    pl = PipelineLayer(_make_descs(8, 3), loss_fn=_mse)
    eng = PipelineEngine(pl, mesh=hcg.mesh)
    m = 8
    assert _peak_in_flight(eng._1f1b_order(0, m)) == 4
    assert [k for k, _, _ in eng._fthenb_order(0, m)].count("f") == m


def test_pp_zb_h1_in_flight_bound():
    """ZB-H1: W release lags B by at most pp-1-s slots, so peak in-flight
    stays O(pp) — independent of m — while W work fills the tail."""
    hcg = HybridCommunicateGroup(pp_degree=4)
    set_hybrid_communicate_group(hcg)
    pl = PipelineLayer(_make_descs(8, 3), loss_fn=_mse)
    eng = PipelineEngine(pl, mesh=hcg.mesh)
    m = 12
    for s in range(4):
        order = eng._zb_h1_order(s, m)
        assert [k for k, _, _ in order].count("w") == m
        assert _peak_in_flight(order) <= 2 * (4 - s), s


def test_pp_interleaved_order_structure():
    """VPP order: every (chunk, micro) f/b appears exactly once and the
    in-flight bound stays below FThenB's m·vpp."""
    hcg = HybridCommunicateGroup(pp_degree=2)
    set_hybrid_communicate_group(hcg)
    pl = PipelineLayer(_make_descs(8, 7), loss_fn=_mse)
    eng = PipelineEngine(pl, mesh=hcg.mesh, num_virtual_stages=2)
    m = 4
    for s in range(2):
        order = eng._interleaved_order(s, m)
        fs = [(v, i) for k, v, i in order if k == "f"]
        bs = [(v, i) for k, v, i in order if k == "b"]
        want = {(c * 2 + s, i) for c in range(2) for i in range(m)}
        assert set(fs) == want and len(fs) == len(want)
        assert set(bs) == want and len(bs) == len(want)
        assert _peak_in_flight(order) < m * 2


@pytest.mark.parametrize("pp,vpp,micro,schedule", [
    (2, 2, 4, "VPP"), (2, 2, 4, "FThenB"), (2, 1, 4, "ZB"),
    (4, 1, 8, "ZB-H1"), (2, 3, 2, "VPP"), (2, 2, 4, "ZB-VPP"),
    (2, 2, 8, "ZB-VPP"),
])
def test_pp_schedules_match_single_device(pp, vpp, micro, schedule):
    """Every schedule in the zoo reproduces the unpipelined loss
    trajectory exactly (same init/data/optimizer)."""
    d, depth, steps = 8, 5, 2
    paddle.seed(42)
    ref = PipelineLayer(_make_descs(d, depth), loss_fn=_mse)
    paddle.seed(42)
    pl = PipelineLayer(_make_descs(d, depth), loss_fn=_mse,
                       num_virtual_pipeline_stages=vpp)

    data = _data(d)
    ref_losses = _train_ref(ref, data, steps)

    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": micro,
                                 "schedule_mode": schedule}
    hcg = HybridCommunicateGroup(pp_degree=pp)
    set_hybrid_communicate_group(hcg)
    model = PipelineParallel(pl, hcg=hcg, strategy=strategy)
    opt = paddle.optimizer.SGD(0.05, parameters=pl.parameters())
    pp_losses = [float(np.asarray(
        model.train_batch(data, opt).value)) for _ in range(steps)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5, atol=1e-6)


def test_pp_mp_composition():
    """pp=2 × mp=2 (+ zb and vpp variants): tensor-parallel layers inside
    pipeline stages; loss must match the single-device baseline."""
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear)

    d = 8

    class TPBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = ColumnParallelLinear(d, 2 * d, gather_output=False,
                                            has_bias=True)
            self.row = RowParallelLinear(2 * d, d, input_is_parallel=True)

        def forward(self, x):
            return self.row(nn.functional.gelu(self.col(x)))

    class PlainBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = nn.Linear(d, 2 * d)
            self.row = nn.Linear(2 * d, d)

        def forward(self, x):
            return self.row(nn.functional.gelu(self.col(x)))

    def make(cls, vpp=1):
        return PipelineLayer([LayerDesc(cls) for _ in range(4)],
                             loss_fn=_mse,
                             num_virtual_pipeline_stages=vpp)

    data = _data(d)
    paddle.seed(11)
    ref = make(PlainBlock)
    ref_losses = _train_ref(ref, data, 2)

    for schedule, vpp in [("1F1B", 1), ("ZB", 1), ("VPP", 2)]:
        hcg = HybridCommunicateGroup(pp_degree=2, mp_degree=2)
        set_hybrid_communicate_group(hcg)
        paddle.seed(11)
        pl = make(TPBlock, vpp)
        strategy = fleet.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 2,
                                     "schedule_mode": schedule}
        model = PipelineParallel(pl, hcg=hcg, strategy=strategy)
        opt = paddle.optimizer.SGD(0.05, parameters=pl.parameters())
        losses = [float(np.asarray(
            model.train_batch(data, opt).value)) for _ in range(2)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-5,
                                   atol=1e-6, err_msg=schedule)


def test_pp_eval_batch():
    """eval_batch: forward-only over the stage programs matches the
    unpipelined forward, with and without loss."""
    d = 8
    paddle.seed(5)
    ref = PipelineLayer(_make_descs(d, 3), loss_fn=_mse)
    paddle.seed(5)
    pl = PipelineLayer(_make_descs(d, 3), loss_fn=_mse)
    data = _data(d)
    hcg = HybridCommunicateGroup(pp_degree=2)
    set_hybrid_communicate_group(hcg)
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 2}
    model = PipelineParallel(pl, hcg=hcg, strategy=strategy)
    x, y = data
    want_out = ref(x)
    want_loss = float(np.asarray(_mse(want_out, y).value))
    got_loss = float(np.asarray(model.eval_batch(data).value))
    np.testing.assert_allclose(got_loss, want_loss, rtol=2e-5)
    out = model.eval_batch(data, compute_loss=False)
    np.testing.assert_allclose(np.asarray(out.value),
                               np.asarray(want_out.value), rtol=2e-5,
                               atol=1e-6)


def test_pp_deadlock_detection():
    """A self-inconsistent order list must be reported as a deadlock, not
    hang (parallel/pipeline.py dependency executor)."""
    hcg = HybridCommunicateGroup(pp_degree=2)
    set_hybrid_communicate_group(hcg)
    pl = PipelineLayer(_make_descs(8, 3), loss_fn=_mse)
    eng = PipelineEngine(pl, mesh=hcg.mesh)
    # backward scheduled before its forward on every stage: never ready
    eng._orders = lambda m, schedule: [
        [("b", s, 0), ("f", s, 0)] for s in range(eng.pp)]
    x, y = _data(8, batch=2)
    with pytest.raises(RuntimeError, match="deadlock"):
        eng.train_batch([x, y], 1, schedule="1F1B")
