"""Pipeline parallelism: loss equality vs non-pipelined execution.

Reference test pattern: test/collective/fleet/hybrid_parallel_pp_*.py —
a PipelineLayer trained through train_batch must match the same model
trained unpipelined on one device (same init, same data).
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, SharedLayerDesc, PipelineLayer, PipelineParallel)
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup, set_hybrid_communicate_group)
from paddle_tpu.parallel.pipeline import (
    PipelineEngine, partition_uniform, partition_by_params)


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)
        self.norm = nn.LayerNorm(d)

    def forward(self, x):
        return self.norm(x + self.fc2(nn.functional.gelu(self.fc1(x))))


def _mse(out, y):
    return ((out - y) ** 2).mean()


def _make_descs(d, depth):
    return [LayerDesc(Block, d) for _ in range(depth)] + [
        LayerDesc(nn.Linear, d, d)]


def _data(d, batch=8):
    rng = np.random.RandomState(7)
    x = rng.randn(batch, d).astype(np.float32)
    y = rng.randn(batch, d).astype(np.float32)
    return paddle.to_tensor(x), paddle.to_tensor(y)


def _train_ref(model, data, steps, lr=0.05):
    """Unpipelined baseline: same loss (mean over full batch) + SGD."""
    opt = paddle.optimizer.SGD(lr, parameters=model.parameters())
    x, y = data
    losses = []
    for _ in range(steps):
        loss = _mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.value)))
    return losses


@pytest.mark.parametrize("pp,micro,schedule", [
    (2, 4, "1F1B"), (4, 8, "1F1B"), (2, 4, "FThenB"),
])
def test_pp_loss_matches_single_device(pp, micro, schedule):
    d, depth, steps = 8, 3, 3
    paddle.seed(42)
    ref = PipelineLayer(_make_descs(d, depth), loss_fn=_mse)
    paddle.seed(42)
    pl = PipelineLayer(_make_descs(d, depth), loss_fn=_mse)

    data = _data(d)
    ref_losses = _train_ref(ref, data, steps)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": pp}
    strategy.pipeline_configs = {"accumulate_steps": micro,
                                 "schedule_mode": schedule}
    hcg = HybridCommunicateGroup(pp_degree=pp)
    set_hybrid_communicate_group(hcg)
    model = PipelineParallel(pl, hcg=hcg, strategy=strategy)
    opt = paddle.optimizer.SGD(0.05, parameters=pl.parameters())
    pp_losses = [float(np.asarray(
        model.train_batch(data, opt).value)) for _ in range(steps)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5, atol=1e-6)


def test_pp_param_count_partition():
    weights = [100, 100, 100, 1, 1, 1, 100, 100]
    b = partition_by_params(weights, 2)
    assert b[0] == 0 and b[-1] == 8 and len(b) == 3
    left = sum(weights[:b[1]])
    right = sum(weights[b[1]:])
    assert abs(left - right) <= 150  # roughly balanced

    assert partition_uniform(10, 3) == [0, 4, 7, 10]


def test_pp_shared_embedding_tied():
    """Tied first/last weights (SharedLayerDesc) stay in sync and get
    summed gradients."""
    d, vocab = 8, 16

    def head_fwd(layer, x):
        return paddle.matmul(x, layer.weight, transpose_y=True)

    def make():
        return PipelineLayer(
            [SharedLayerDesc("embed", nn.Embedding, None, "weight",
                             vocab, d),
             LayerDesc(Block, d),
             SharedLayerDesc("embed", nn.Embedding, head_fwd, "weight",
                             vocab, d)],
            loss_fn=lambda out, y: paddle.nn.functional.cross_entropy(
                out, y))

    paddle.seed(3)
    ref = make()
    paddle.seed(3)
    pl = make()

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randint(0, vocab, (8, 4)).astype(np.int64))
    y = paddle.to_tensor(rng.randint(0, vocab, (8, 4)).astype(np.int64))

    ref_losses = []
    opt_ref = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
    for _ in range(2):
        loss = ref.loss_fn(ref(x), y)
        loss.backward()
        opt_ref.step()
        opt_ref.clear_grad()
        ref_losses.append(float(np.asarray(loss.value)))

    hcg = HybridCommunicateGroup(pp_degree=2)
    set_hybrid_communicate_group(hcg)
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 2}
    model = PipelineParallel(pl, hcg=hcg, strategy=strategy)
    opt = paddle.optimizer.SGD(0.1, parameters=pl.parameters())
    pp_losses = [float(np.asarray(
        model.train_batch([x, y], opt).value)) for _ in range(2)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5, atol=1e-6)


def test_pp_1f1b_in_flight_bound():
    """1F1B order: stage 0 of a 4-stage pipeline never holds more than
    pp in-flight forwards (vs m for FThenB)."""
    hcg = HybridCommunicateGroup(pp_degree=4)
    set_hybrid_communicate_group(hcg)
    pl = PipelineLayer(_make_descs(8, 3), loss_fn=_mse)
    eng = PipelineEngine(pl, mesh=hcg.mesh)
    m = 8
    order = eng._stage_order(0, m, "1F1B")
    in_flight = peak = 0
    for kind, _ in order:
        in_flight += 1 if kind == "f" else -1
        peak = max(peak, in_flight)
    assert peak == 4
    assert [k for k, _ in eng._stage_order(0, m, "FThenB")].count("f") == m
