"""Static Program tape: feed/fetch replay, partial-graph fetch, append_op.

Reference behavior being matched: `test/legacy_test/test_executor_*`-style
Executor.run semantics — build a program once, run it repeatedly with new
feeds, fetch any variable (including gradients) — and raw
`Block.append_op` program construction (base/framework.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


@pytest.fixture(autouse=True)
def _static_mode():
    static.enable_static()
    yield
    static.disable_static()


def _mlp_program():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        paddle.seed(3)
        fc1 = nn.Linear(8, 16)
        fc2 = nn.Linear(16, 2)
        h = paddle.nn.functional.relu(fc1(x))
        out = fc2(h)
        loss = (out * out).mean()
    return main, startup, x, fc1, fc2, h, out, loss


def _np_forward(fc1, fc2, xv):
    w1 = np.asarray(fc1.weight.value)
    b1 = np.asarray(fc1.bias.value)
    w2 = np.asarray(fc2.weight.value)
    b2 = np.asarray(fc2.bias.value)
    h = np.maximum(xv @ w1 + b1, 0)
    return h, h @ w2 + b2


class TestFeedFetchReplay:
    def test_rerun_with_new_feeds_recomputes(self):
        main, startup, x, fc1, fc2, h, out, loss = _mlp_program()
        exe = static.Executor()
        exe.run(startup)
        for seed in (0, 1):
            xv = np.random.RandomState(seed).randn(4, 8).astype(np.float32)
            (got,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
            _, want = _np_forward(fc1, fc2, xv)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_partial_graph_fetch_interior_var(self):
        main, startup, x, fc1, fc2, h, out, loss = _mlp_program()
        exe = static.Executor()
        xv = np.random.RandomState(7).randn(4, 8).astype(np.float32)
        (got_h,) = exe.run(main, feed={"x": xv}, fetch_list=[h])
        want_h, _ = _np_forward(fc1, fc2, xv)
        np.testing.assert_allclose(got_h, want_h, rtol=1e-5, atol=1e-5)

    def test_multiple_fetches_and_scalar_loss(self):
        main, startup, x, fc1, fc2, h, out, loss = _mlp_program()
        exe = static.Executor()
        xv = np.random.RandomState(11).randn(4, 8).astype(np.float32)
        got_out, got_loss = exe.run(main, feed={"x": xv},
                                    fetch_list=[out, loss])
        _, want = _np_forward(fc1, fc2, xv)
        np.testing.assert_allclose(got_out, want, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(got_loss, (want * want).mean(),
                                   rtol=1e-5, atol=1e-6)

    def test_param_update_visible_on_next_run(self):
        """Replay reads parameters' CURRENT values (reference: Scope
        persistence between Executor.run calls)."""
        main, startup, x, fc1, fc2, h, out, loss = _mlp_program()
        exe = static.Executor()
        xv = np.random.RandomState(2).randn(4, 8).astype(np.float32)
        (before,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        fc2.bias.set_value(np.asarray(fc2.bias.value) + 1.0)
        (after,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(after, before + 1.0, rtol=1e-5,
                                   atol=1e-5)

    def test_fetch_unrecorded_var_rejected(self):
        main, startup, *_ = _mlp_program()
        exe = static.Executor()
        stray = paddle.to_tensor(np.zeros((2, 2), np.float32))
        with pytest.raises(ValueError, match="not a recorded variable"):
            exe.run(main, feed={"x": np.zeros((4, 8), np.float32)},
                    fetch_list=[stray])


class TestGradients:
    def test_gradient_fetch_replays_with_new_feed(self):
        main, startup, x, fc1, fc2, h, out, loss = _mlp_program()
        with static.program_guard(main, startup):
            (dW,) = static.gradients(loss, [fc1.weight])
        exe = static.Executor()
        for seed in (5, 6):
            xv = np.random.RandomState(seed).randn(4, 8).astype(np.float32)
            (got,) = exe.run(main, feed={"x": xv}, fetch_list=[dW])
            # reference value via finite jax grad on the same math
            import jax
            import jax.numpy as jnp

            def f(w1):
                hh = jnp.maximum(jnp.asarray(xv) @ w1
                                 + fc1.bias.value, 0)
                o = hh @ fc2.weight.value + fc2.bias.value
                return (o * o).mean()

            want = jax.grad(f)(fc1.weight.value)
            np.testing.assert_allclose(got, np.asarray(want),
                                       rtol=1e-4, atol=1e-5)

    def test_gradient_wrt_placeholder(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            y = (x * x).sum()
            (dx,) = static.gradients(y, [x])
        exe = static.Executor()
        xv = np.array([1.0, -2.0, 3.0], np.float32)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=[dx])
        np.testing.assert_allclose(got, 2 * xv, rtol=1e-6)


class TestAppendOp:
    def test_program_built_from_append_ops(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 3], "float32")
            w = static.data("w", [3, 4], "float32")
        blk = main.global_block()
        mm = blk.append_op("matmul_v2", inputs={"X": x, "Y": w})
        act = blk.append_op("relu", inputs={"X": mm})
        out = blk.append_op("scale", inputs={"X": act},
                            attrs={"scale": 2.0, "bias": 1.0})
        exe = static.Executor()
        xv = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        wv = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        (got,) = exe.run(main, feed={"x": xv, "w": wv}, fetch_list=[out])
        np.testing.assert_allclose(got, np.maximum(xv @ wv, 0) * 2 + 1,
                                    rtol=1e-5, atol=1e-6)

    def test_append_op_attrs_and_named_output(self):
        main = static.Program()
        blk = main.global_block()
        with static.program_guard(main):
            x = static.data("x", [4, 4], "float32")
        y = blk.create_var(name="y", shape=[4, 4])
        blk.append_op("softmax", inputs={"X": x}, outputs={"Out": y},
                      attrs={"axis": -1})
        exe = static.Executor()
        xv = np.random.RandomState(3).randn(4, 4).astype(np.float32)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=["y"])
        e = np.exp(xv - xv.max(-1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                                    rtol=1e-5, atol=1e-6)

    def test_unsupported_append_op_refuses_with_guidance(self):
        main = static.Program()
        with pytest.raises(NotImplementedError, match="to_static"):
            main.append_op("fancy_custom_op")


class TestReviewRegressions:
    def test_inplace_op_not_double_applied_on_replay(self):
        """An in-place mutation recorded on the tape must replay from
        the PRE-update snapshot, not re-apply over the live value."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            w = paddle.to_tensor(np.array([1., 2.], np.float32))
            paddle.increment(w, 10.0)
            out = x + w
        exe = static.Executor()
        (got,) = exe.run(main, feed={"x": np.zeros(2, np.float32)},
                         fetch_list=[out])
        np.testing.assert_allclose(got, [11., 12.])

    def test_dce_without_targets_rejected(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            _ = x * 2.0
        with pytest.raises(ValueError, match="requires targets"):
            static.apply_pass(main, "dead_code_elimination")

    def test_append_op_numpy_and_scalar_inputs(self):
        main = static.Program()
        blk = main.global_block()
        with static.program_guard(main):
            x = static.data("x", [2, 3], "float32")
        out = blk.append_op("elementwise_add",
                            inputs={"X": x, "Y": np.ones((2, 3),
                                                         np.float32)})
        exe = static.Executor()
        xv = np.random.RandomState(4).randn(2, 3).astype(np.float32)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(got, xv + 1.0, rtol=1e-6)

    def test_append_op_rewrite_named_var_keeps_earlier_readers(self):
        """Write y, read it, write y again: the first reader must keep
        the first value (SSA rename), and name-fetch sees the last."""
        main = static.Program()
        blk = main.global_block()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
        y = blk.create_var(name="y", shape=[2])
        blk.append_op("scale", inputs={"X": x}, outputs={"Out": y},
                      attrs={"scale": 2.0})
        r = blk.append_op("scale", inputs={"X": y}, attrs={"scale": 10.0})
        blk.append_op("scale", inputs={"X": x}, outputs={"Out": y},
                      attrs={"scale": 3.0})
        exe = static.Executor()
        xv = np.array([1., 2.], np.float32)
        got_r, got_y = exe.run(main, feed={"x": xv},
                               fetch_list=[r, "y"])
        np.testing.assert_allclose(got_r, xv * 20.0)
        np.testing.assert_allclose(got_y, xv * 3.0)


class TestReviewRegressions2:
    def test_append_op_rewrite_outside_guard_freezes_leaf(self):
        """SSA rename must freeze the old vid's leaf even when append_op
        runs OUTSIDE a program_guard (no recording stack)."""
        main = static.Program()
        blk = main.global_block()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
        y = paddle.to_tensor(np.array([1., 1.], np.float32))
        r = blk.append_op("scale", inputs={"X": y}, attrs={"scale": 5.0})
        blk.append_op("elementwise_add", inputs={"X": x, "Y": x},
                      outputs={"Out": y})
        exe = static.Executor()
        (got,) = exe.run(main, feed={"x": np.zeros(2, np.float32)},
                         fetch_list=[r])
        np.testing.assert_allclose(got, [5., 5.])

    def test_constant_folding_keeps_parameters_dynamic(self):
        """Folding must not freeze trainable/persistable leaves — their
        updates between runs stay visible."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            net = nn.Linear(2, 2)
            out = net(x)
        static.apply_pass(main, "constant_folding")
        exe = static.Executor()
        xv = np.ones(2, np.float32)
        (before,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        net.bias.set_value(np.asarray(net.bias.value) + 7.0)
        (after,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(after, before + 7.0, rtol=1e-5)

    def test_gradients_honors_target_gradients(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            y = x * x
            (dx,) = static.gradients(
                y, [x],
                target_gradients=[np.array([1., 0., 2.], np.float32)])
        exe = static.Executor()
        xv = np.array([1., 2., 3.], np.float32)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=[dx])
        np.testing.assert_allclose(got, 2 * xv * [1., 0., 2.],
                                   rtol=1e-6)

    def test_gradients_target_gradients_replay_fresh(self):
        """Cotangents are op INPUTS, not record-time closure constants:
        a placeholder target_gradient must be substituted per feed
        (advisor r5 item 2 — pre-fix this replayed the build-time
        zeros for every run)."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            w = static.data("w", [3], "float32")
            y = x * x
            (dx,) = static.gradients(y, [x], target_gradients=[w])
        exe = static.Executor()
        xv = np.array([1., 2., 3.], np.float32)
        for wv in ([1., 0., 2.], [0., 1., 5.]):
            wv = np.array(wv, np.float32)
            (got,) = exe.run(main, feed={"x": xv, "w": wv},
                             fetch_list=[dx])
            np.testing.assert_allclose(got, 2 * xv * wv, rtol=1e-6)

    def test_unknown_feed_key_rejected(self):
        main, startup, x, fc1, fc2, h, out, loss = _mlp_program()
        exe = static.Executor()
        with pytest.raises(KeyError, match="not data"):
            exe.run(main, feed={"X": np.zeros((4, 8), np.float32)},
                    fetch_list=[out])


class TestPasses:
    def test_dead_code_elimination(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            kept = x * 2.0
            _dead = (x + 5.0) * 3.0  # unfetched branch
        n_before = len(main.ops)
        static.apply_pass(main, "dead_code_elimination", targets=[kept])
        assert len(main.ops) < n_before
        exe = static.Executor()
        (got,) = exe.run(main, feed={"x": np.array([1., 2.], np.float32)},
                         fetch_list=[kept])
        np.testing.assert_allclose(got, [2., 4.])

    def test_constant_folding(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            c = paddle.to_tensor(np.array([3., 4.], np.float32))
            folded = c * 2.0           # placeholder-free -> foldable
            out = x + folded
        static.apply_pass(main, "constant_folding")
        types = [op.type for op in main.ops]
        assert all("mul" not in t for t in types) or len(main.ops) == 1
        exe = static.Executor()
        (got,) = exe.run(main, feed={"x": np.array([1., 1.], np.float32)},
                         fetch_list=[out])
        np.testing.assert_allclose(got, [7., 9.])


class TestAdvisorRegressionsR6:
    """r5 advisor items 1/3/4: replay-cache staleness after passes, AMP
    cast fidelity on the recorded tape, append_op missing-var UX."""

    def test_pass_then_rerecord_invalidates_replay_cache(self):
        """A pass followed by recording more ops can restore the same
        op COUNT over a different op slice; the replay cache must key
        on the tape version, not just len(ops) (stale hit would replay
        the pre-pass slice with the post-pass leaf values)."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            w = paddle.to_tensor(np.array([1., 2.], np.float32))
            y = w * 2.0            # placeholder-free -> foldable
            out = x + y
        exe = static.Executor()
        feed = {"x": np.zeros(2, np.float32)}
        (r1,) = exe.run(main, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(r1, [2., 4.])
        n_before = len(main.ops)
        static.apply_pass(main, "constant_folding")
        with static.program_guard(main):
            _ = out * 1.0          # restore the pre-pass op count
        assert len(main.ops) == n_before
        (r2,) = exe.run(main, feed=feed, fetch_list=[out])
        # a stale cache hit replays y = w*2 over y's folded value
        # (giving [4., 8.]); the version-keyed cache recompiles
        np.testing.assert_allclose(r2, [2., 4.])

    def test_amp_recorded_tape_replays_with_casts(self):
        """Ops taped under amp.auto_cast must replay WITH the input
        casts that actually executed (dispatch records a cast-
        reapplying wrapper), so Executor.run matches the eager
        build-time dtype/numerics."""
        import jax.numpy as jnp
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            w = paddle.to_tensor(np.eye(2, dtype=np.float32) * 3.0)
            with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
                out = paddle.matmul(x, w)   # whitelisted -> bf16
        assert out.value.dtype == jnp.bfloat16
        exe = static.Executor()
        xv = np.array([[1., 2.], [3., 4.]], np.float32)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=[out],
                         return_numpy=False)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got.astype(jnp.float32)),
                                   xv * 3.0, rtol=1e-2)

    def test_append_op_auto_creates_named_output(self):
        """A string output name with no pre-created var auto-creates it
        (reference base/framework.py append_op) instead of crashing in
        np.asarray(None)."""
        main = static.Program()
        blk = main.global_block()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
        blk.append_op("relu", inputs={"X": x}, outputs={"Out": "y"})
        exe = static.Executor()
        xv = np.array([[-1., 2.], [3., -4.]], np.float32)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=["y"])
        np.testing.assert_allclose(got, np.maximum(xv, 0))

    def test_append_op_missing_input_raises_clear_error(self):
        main = static.Program()
        with pytest.raises(ValueError, match="nope"):
            main.append_op("relu", inputs={"X": "nope"})
