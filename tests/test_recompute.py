"""recompute / recompute_sequential: gradient-checkpointing parity.

Reference test model: test_dygraph_recompute — recomputed forward must
give identical loss and gradients to the plain forward.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import recompute, recompute_sequential


class Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc1 = nn.Linear(h, h * 2)
        self.fc2 = nn.Linear(h * 2, h)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x))) + x


def _train(use_rc, seq=False, steps=3):
    paddle.seed(7)
    blocks = nn.LayerList([Block(8) for _ in range(3)])
    opt = paddle.optimizer.SGD(0.1, parameters=blocks.parameters())
    x0 = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32))
    losses = []
    for _ in range(steps):
        x = x0
        if seq:
            x = recompute_sequential({"segments": 2}, blocks, x)
        else:
            for b in blocks:
                x = recompute(b, x) if use_rc else b(x)
        loss = (x ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.value)))
    return losses


class TestRecompute:
    def test_matches_plain_backward(self):
        assert np.allclose(_train(False), _train(True), atol=1e-6)

    def test_sequential_matches(self):
        assert np.allclose(_train(False), _train(True, seq=True),
                           atol=1e-6)

    def test_under_jit_trainstep(self):
        """recompute inside a jitted TrainStep (llama per-layer path)."""
        from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
        import jax.numpy as jnp

        def run_cfg(rc):
            paddle.seed(0)
            cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                              intermediate_size=64, num_hidden_layers=2,
                              num_attention_heads=4, num_key_value_heads=2,
                              max_position_embeddings=64, dtype="float32",
                              recompute=rc)
            m = LlamaForCausalLM(cfg)
            opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
            from paddle_tpu.jit import TrainStep
            step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
            ids = paddle.to_tensor(np.random.RandomState(1).randint(
                0, 64, (2, 16)).astype(np.int32))
            return [float(np.asarray(step(ids, ids).value))
                    for _ in range(3)]

        np.testing.assert_allclose(run_cfg(False), run_cfg(True),
                                   rtol=1e-5, atol=1e-5)

    def test_pure_function_requires_explicit_params(self):
        # a pure fn of Tensors works when params are explicit args
        w = paddle.to_tensor(np.ones((4, 4), np.float32))
        w.stop_gradient = False
        x = paddle.to_tensor(np.ones((2, 4), np.float32))

        out = recompute(lambda a, b: paddle.matmul(a, b), x, w)
        loss = out.sum()
        loss.backward()
        assert w.grad is not None
        np.testing.assert_allclose(np.asarray(w.grad.value),
                                   np.full((4, 4), 2.0), atol=1e-6)


class TestSelectiveRecompute:
    """recompute_granularity="selective" (jax.checkpoint policy over
    checkpoint_name tags): loss trajectory must match full recompute and
    no recompute exactly — policies change memory, not math."""

    def _run(self, rc, granularity="full", param_dtype=None):
        from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
        from paddle_tpu.jit import TrainStep
        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, dtype="float32",
                          param_dtype=param_dtype, recompute=rc,
                          recompute_granularity=granularity)
        m = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
        ids = paddle.to_tensor(np.random.RandomState(1).randint(
            0, 64, (2, 16)).astype(np.int32))
        return [float(np.asarray(step(ids, ids).value)) for _ in range(3)]

    def test_selective_matches_plain(self):
        np.testing.assert_allclose(self._run(False),
                                   self._run(True, "selective"),
                                   rtol=1e-5, atol=1e-5)

    def test_selective_matches_full(self):
        np.testing.assert_allclose(self._run(True, "full"),
                                   self._run(True, "selective"),
                                   rtol=1e-5, atol=1e-5)

    def test_selective_under_sharded_trainer(self):
        """selective remat inside the hybrid-parallel jitted step."""
        import jax
        from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh
        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, dtype="float32",
                          recompute=True,
                          recompute_granularity="selective")
        m = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        mesh = build_mesh(dp=2, sharding=2,
                          devices=jax.devices()[:4])
        st = ShardedTrainStep(m, opt, mesh, sharding_stage=3)
        ids = paddle.to_tensor(np.random.RandomState(1).randint(
            0, 64, (4, 16)).astype(np.int32))
        losses = [float(np.asarray(st(ids, ids).value)) for _ in range(3)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]


class TestParamDtype:
    """fp32 params + low-precision compute (flax param_dtype idiom):
    params stay fp32, activations run in the compute dtype."""

    def test_params_fp32_activations_bf16(self):
        import jax.numpy as jnp
        from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=1,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, dtype="bfloat16",
                          param_dtype="float32")
        m = LlamaForCausalLM(cfg)
        for n, p in m.named_parameters():
            assert p.value.dtype == jnp.float32, n
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 64, (2, 8)).astype(np.int32))
        out = m(ids)
        assert out.value.dtype == jnp.bfloat16
        loss = m.compute_loss(out, ids)
        assert loss.value.dtype == jnp.float32

    def test_fp32_params_match_fp32_compute_closely(self):
        """param_dtype=fp32 + dtype=fp32 is exactly the fp32 model; the
        bf16-compute variant must track it within bf16 tolerance."""
        from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig

        def loss_of(dtype):
            paddle.seed(3)
            cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                              intermediate_size=64, num_hidden_layers=2,
                              num_attention_heads=4, num_key_value_heads=2,
                              max_position_embeddings=64, dtype=dtype,
                              param_dtype="float32")
            m = LlamaForCausalLM(cfg)
            ids = paddle.to_tensor(np.random.RandomState(1).randint(
                0, 64, (2, 16)).astype(np.int32))
            return float(np.asarray(
                m.compute_loss(m(ids), ids).value))

        assert abs(loss_of("float32") - loss_of("bfloat16")) < 0.1
