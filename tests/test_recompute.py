"""recompute / recompute_sequential: gradient-checkpointing parity.

Reference test model: test_dygraph_recompute — recomputed forward must
give identical loss and gradients to the plain forward.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import recompute, recompute_sequential


class Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc1 = nn.Linear(h, h * 2)
        self.fc2 = nn.Linear(h * 2, h)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x))) + x


def _train(use_rc, seq=False, steps=3):
    paddle.seed(7)
    blocks = nn.LayerList([Block(8) for _ in range(3)])
    opt = paddle.optimizer.SGD(0.1, parameters=blocks.parameters())
    x0 = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32))
    losses = []
    for _ in range(steps):
        x = x0
        if seq:
            x = recompute_sequential({"segments": 2}, blocks, x)
        else:
            for b in blocks:
                x = recompute(b, x) if use_rc else b(x)
        loss = (x ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.value)))
    return losses


class TestRecompute:
    def test_matches_plain_backward(self):
        assert np.allclose(_train(False), _train(True), atol=1e-6)

    def test_sequential_matches(self):
        assert np.allclose(_train(False), _train(True, seq=True),
                           atol=1e-6)

    def test_under_jit_trainstep(self):
        """recompute inside a jitted TrainStep (llama per-layer path)."""
        from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
        import jax.numpy as jnp

        def run_cfg(rc):
            paddle.seed(0)
            cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                              intermediate_size=64, num_hidden_layers=2,
                              num_attention_heads=4, num_key_value_heads=2,
                              max_position_embeddings=64, dtype="float32",
                              recompute=rc)
            m = LlamaForCausalLM(cfg)
            opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
            from paddle_tpu.jit import TrainStep
            step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
            ids = paddle.to_tensor(np.random.RandomState(1).randint(
                0, 64, (2, 16)).astype(np.int32))
            return [float(np.asarray(step(ids, ids).value))
                    for _ in range(3)]

        np.testing.assert_allclose(run_cfg(False), run_cfg(True),
                                   rtol=1e-5, atol=1e-5)

    def test_pure_function_requires_explicit_params(self):
        # a pure fn of Tensors works when params are explicit args
        w = paddle.to_tensor(np.ones((4, 4), np.float32))
        w.stop_gradient = False
        x = paddle.to_tensor(np.ones((2, 4), np.float32))

        out = recompute(lambda a, b: paddle.matmul(a, b), x, w)
        loss = out.sum()
        loss.backward()
        assert w.grad is not None
        np.testing.assert_allclose(np.asarray(w.grad.value),
                                   np.full((4, 4), 2.0), atol=1e-6)
