"""Regression tests for round-1 advisor findings (ADVICE.md).

Each test pins a specific fixed defect:
  1. distributed checkpoint multi-rank shard merge
  2. GradScaler explicit-unscale_ + step double-unscale
  3. Lamb exclude_from_weight_decay_fn
  4. AdamW lr_ratio
  5. cross_entropy weight on the soft-label path
"""
import json
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Parameter, Tensor


def test_dist_checkpoint_merges_all_rank_files(tmp_path):
    # two rank files, each holding half of a [4, 2] tensor; the merged
    # load must contain BOTH halves (round-1 bug: last file won)
    full = np.arange(8, dtype=np.float32).reshape(4, 2)
    path = str(tmp_path)
    meta = {"w": {"global_shape": [4, 2], "dtype": "float32", "rank": 0,
                  "sharded": True}}
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f)
    for rank, rows in ((0, (0, 2)), (1, (2, 4))):
        shards = {"w": {"local": [full[rows[0]:rows[1]]],
                        "index": [[(rows[0], rows[1]), (0, 2)]]}}
        with open(os.path.join(path, f"{rank}.distcp"), "wb") as f:
            pickle.dump(shards, f)
    from paddle_tpu.distributed.checkpoint import load_state_dict
    target = {"w": Tensor(np.zeros((4, 2), np.float32))}
    load_state_dict(target, path)
    np.testing.assert_allclose(np.asarray(target["w"].value), full)


def test_grad_scaler_no_double_unscale():
    scale = 1024.0
    g = np.full((3,), 2.0, np.float32)

    def run(explicit_unscale):
        p = Parameter(np.zeros((3,), np.float32))
        opt = paddle.optimizer.SGD(1.0, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=scale,
                                       use_dynamic_loss_scaling=True)
        p.grad = Tensor(g * scale)  # grads of a scaled loss
        if explicit_unscale:
            scaler.unscale_(opt)  # user pattern: unscale, clip, step
        scaler.step(opt)
        scaler.update()
        return np.asarray(p.value)

    # both paths must apply exactly one unscale: p = -lr * g
    np.testing.assert_allclose(run(False), -g, rtol=1e-6)
    np.testing.assert_allclose(run(True), -g, rtol=1e-6)


def test_grad_scaler_rejects_second_unscale():
    p = Parameter(np.zeros((3,), np.float32))
    opt = paddle.optimizer.SGD(1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                   use_dynamic_loss_scaling=True)
    p.grad = Tensor(np.ones((3,), np.float32))
    scaler.unscale_(opt)
    with pytest.raises(RuntimeError):
        scaler.unscale_(opt)


def test_lamb_exclude_from_weight_decay():
    init = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    grad = np.array([0.01, 0.2, -0.05, 0.1], np.float32)

    def run(exclude):
        p = Parameter(init.copy(), name="norm.weight")
        opt = paddle.optimizer.Lamb(
            learning_rate=0.1, lamb_weight_decay=0.5, parameters=[p],
            exclude_from_weight_decay_fn=(
                (lambda name: "norm" in name) if exclude else None))
        p.grad = Tensor(grad.copy())
        opt.step()
        return np.asarray(p.value)

    excluded, decayed = run(True), run(False)
    assert not np.allclose(excluded, decayed)


def test_adamw_lr_ratio_applies():
    def run(ratio):
        p = Parameter(np.ones((4,), np.float32))
        opt = paddle.optimizer.AdamW(
            learning_rate=0.1, weight_decay=0.0, parameters=[p],
            lr_ratio=(lambda _p: ratio) if ratio is not None else None)
        p.grad = Tensor(np.full((4,), 0.5, np.float32))
        opt.step()
        return np.asarray(p.value)

    base, halved = run(None), run(0.5)
    delta_base = 1.0 - base
    delta_half = 1.0 - halved
    np.testing.assert_allclose(delta_half, 0.5 * delta_base, rtol=1e-5)


def test_cross_entropy_soft_label_weight():
    rng = np.random.RandomState(0)
    logits = rng.randn(5, 3).astype(np.float32)
    tgt = rng.dirichlet(np.ones(3), size=5).astype(np.float32)
    w = np.array([0.2, 1.0, 3.0], np.float32)

    out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(tgt),
                          weight=paddle.to_tensor(w), soft_label=True,
                          reduction="none")
    logp = np.log(np.exp(logits) /
                  np.exp(logits).sum(-1, keepdims=True))
    # reference formula: per-sample weight = label·weight times the
    # UNWEIGHTED soft cross-entropy
    wsample = (tgt * w[None, :]).sum(-1)
    expect = wsample * (-(tgt * logp).sum(-1))
    np.testing.assert_allclose(np.asarray(out.value), expect, rtol=1e-5)

    m = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(tgt),
                        weight=paddle.to_tensor(w), soft_label=True,
                        reduction="mean")
    np.testing.assert_allclose(np.asarray(m.value),
                               expect.sum() / wsample.sum(), rtol=1e-5)


def test_grad_scaler_two_optimizers_both_unscaled():
    scale = 512.0
    g = np.full((2,), 4.0, np.float32)
    p1 = Parameter(np.zeros((2,), np.float32))
    p2 = Parameter(np.zeros((2,), np.float32))
    o1 = paddle.optimizer.SGD(1.0, parameters=[p1])
    o2 = paddle.optimizer.SGD(1.0, parameters=[p2])
    scaler = paddle.amp.GradScaler(init_loss_scaling=scale,
                                   use_dynamic_loss_scaling=True)
    p1.grad = Tensor(g * scale)
    p2.grad = Tensor(g * scale)
    scaler.step(o1)
    scaler.step(o2)  # must ALSO be unscaled (per-optimizer tracking)
    scaler.update()
    np.testing.assert_allclose(np.asarray(p1.value), -g, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(p2.value), -g, rtol=1e-6)


def test_deepcopied_layer_gets_its_own_grads():
    """deepcopy used to keep VarRefs whose weakrefs resolved to the
    SOURCE tensors, so a copied model's backward wrote grads to the
    original parameters and the copy never trained."""
    import copy
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    paddle.seed(0)
    net = nn.Linear(4, 2)
    net2 = copy.deepcopy(net)
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    loss = (net2(x) ** 2).mean()
    loss.backward()
    assert net2.weight.grad is not None
    assert net.weight.grad is None  # original untouched


def test_trainstep_updates_batchnorm_running_stats():
    """Jitted TrainStep must thread buffer mutations (BN running
    mean/var) out of the step — round-3 regression: they were computed
    under _swapped_state and silently discarded, so eval() used the
    init stats and eval accuracy was random."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8),
                          nn.ReLU(), nn.Linear(8, 2))
    opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
    step = TrainStep(model, lambda o, y: nn.functional.cross_entropy(
        o, y), opt)
    x = paddle.to_tensor(
        (np.random.RandomState(0).randn(16, 8) * 3 + 1)
        .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randint(
        0, 2, (16,)).astype(np.int64))
    sd = model.state_dict()
    bn_mean_name = [n for n in sd if "mean" in n][0]
    before = np.asarray(sd[bn_mean_name].value).copy()
    for _ in range(3):
        step(x, y)
    after = np.asarray(model.state_dict()[bn_mean_name].value)
    assert not np.allclose(before, after), \
        "BN running mean never updated through the jitted step"
    # and the sharded trainer path too
    import jax
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh
    paddle.seed(0)
    model2 = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8),
                           nn.ReLU(), nn.Linear(8, 2))
    opt2 = paddle.optimizer.SGD(0.1, parameters=model2.parameters())
    mesh = build_mesh(dp=2, devices=jax.devices()[:2])
    st = ShardedTrainStep(model2, opt2, mesh, sharding_stage=0,
                          loss_fn=lambda o, y:
                          nn.functional.cross_entropy(o, y))
    sd2 = model2.state_dict()
    before2 = np.asarray(sd2[bn_mean_name].value).copy()
    for _ in range(3):
        st(x, y)
    after2 = np.asarray(model2.state_dict()[bn_mean_name].value)
    assert not np.allclose(before2, after2)


def test_trainstep_run_steps_matches_loop():
    """K scanned steps (TrainStep.run_steps) must produce the same
    params/losses as K individual step() calls (host-loop elision)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep

    def make():
        paddle.seed(5)
        m = nn.Sequential(nn.Linear(6, 6), nn.Tanh(), nn.Linear(6, 2))
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        return m, TrainStep(m, lambda o, y:
                            nn.functional.cross_entropy(o, y), opt)

    rng = np.random.RandomState(0)
    xs = rng.randn(4, 8, 6).astype(np.float32)      # K=4 steps of b=8
    ys = rng.randint(0, 2, (4, 8)).astype(np.int64)

    m1, s1 = make()
    loop_losses = [float(np.asarray(
        s1(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i])).value))
        for i in range(4)]

    m2, s2 = make()
    scanned = np.asarray(s2.run_steps(paddle.to_tensor(xs),
                                      paddle.to_tensor(ys)).value)
    np.testing.assert_allclose(scanned, loop_losses, rtol=1e-5,
                               atol=1e-6)
    w1 = np.asarray(m1.state_dict()["0.weight"].value)
    w2 = np.asarray(m2.state_dict()["0.weight"].value)
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)


def test_sharded_trainer_run_steps_matches_loop():
    """ShardedTrainStep.run_steps == K sequential calls on a dp x
    sharding mesh (scan fusion under GSPMD)."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    def make():
        paddle.seed(9)
        m = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 2))
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        mesh = build_mesh(dp=2, sharding=2, devices=jax.devices()[:4])
        st = ShardedTrainStep(m, opt, mesh, sharding_stage=2,
                              loss_fn=lambda o, y:
                              nn.functional.cross_entropy(o, y))
        return m, st

    rng = np.random.RandomState(0)
    xs = rng.randn(3, 8, 8).astype(np.float32)
    ys = rng.randint(0, 2, (3, 8)).astype(np.int64)

    m1, s1 = make()
    loop = [float(np.asarray(
        s1(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i])).value))
        for i in range(3)]
    m2, s2 = make()
    scanned = np.asarray(s2.run_steps(paddle.to_tensor(xs),
                                      paddle.to_tensor(ys)).value)
    np.testing.assert_allclose(scanned, loop, rtol=1e-5, atol=1e-6)
    w1 = np.asarray(m1.state_dict()["0.weight"].value)
    w2 = np.asarray(m2.state_dict()["0.weight"].value)
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)


def test_run_steps_advances_lr_scheduler():
    """A per-step LRScheduler inside a fused run_steps window must see
    its per-step values (not the window-entry LR held constant): K
    scanned steps == K individual step()+scheduler.step() calls."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep

    def make():
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(6, 6), nn.Tanh(), nn.Linear(6, 2))
        sched = paddle.optimizer.lr.StepDecay(
            learning_rate=5e-2, step_size=1, gamma=0.5)
        opt = paddle.optimizer.SGD(sched, parameters=m.parameters())
        return m, sched, TrainStep(m, lambda o, y:
                                   nn.functional.cross_entropy(o, y), opt)

    rng = np.random.RandomState(3)
    xs = rng.randn(4, 8, 6).astype(np.float32)
    ys = rng.randint(0, 2, (4, 8)).astype(np.int64)

    m1, sched1, s1 = make()
    loop = []
    for i in range(4):
        loop.append(float(np.asarray(
            s1(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i])).value)))
        sched1.step()

    # run_steps advances the scheduler itself (the host loop is fused);
    # the caller must not also step it for those K steps
    m2, sched2, s2 = make()
    scanned = np.asarray(s2.run_steps(paddle.to_tensor(xs),
                                      paddle.to_tensor(ys)).value)
    np.testing.assert_allclose(scanned, loop, rtol=1e-5, atol=1e-6)
    w1 = np.asarray(m1.state_dict()["0.weight"].value)
    w2 = np.asarray(m2.state_dict()["0.weight"].value)
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)
    assert sched2.last_epoch == sched1.last_epoch
