"""Sparse-MoE llama family (Mixtral-style; reference capability:
fused_moe + the MoE meta_parallel stack).

Pins: the MoE decoder trains (loss decreases, aux loss flows), the
KV-cached generate path routes through the experts, and expert
parallelism over the mesh reproduces the unsharded math.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import (LlamaForCausalLM,
                                     llama_moe_tiny_config)


def test_moe_llama_trains():
    paddle.seed(11)
    cfg = llama_moe_tiny_config()
    m = LlamaForCausalLM(cfg)
    # experts exist: stacked [E, d, 2*dh] swiglu weights per layer
    sd = dict(m.named_parameters())
    w1 = [v for n, v in sd.items() if n.endswith("mlp.w1")]
    assert w1 and tuple(w1[0].shape) == (4, 128, 512)
    from paddle_tpu.jit import TrainStep
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32))
    losses = [float(np.asarray(step(ids, ids).value))
              for _ in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_llama_aux_loss_contributes():
    paddle.seed(3)
    cfg = llama_moe_tiny_config()
    m = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(1)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
    out = m(ids)
    with_aux = float(np.asarray(m.compute_loss(out, ids).value))
    m.config.moe_aux_weight = 0.0
    no_aux = float(np.asarray(m.compute_loss(out, ids).value))
    assert with_aux != no_aux          # gshard aux actually flows


def test_moe_llama_generate():
    paddle.seed(5)
    cfg = llama_moe_tiny_config()
    m = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(2)
    prompt = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (2, 8)).astype(np.int32))
    out = m.generate(prompt, max_new_tokens=6)
    arr = np.asarray(out.value)
    assert arr.shape == (2, 6)
    assert (arr >= 0).all() and (arr < cfg.vocab_size).all()


def test_moe_llama_expert_parallel_matches_dense():
    """EP over an 8-way mesh reproduces the unsharded forward."""
    import jax
    from paddle_tpu.distributed.topology import (
        HybridCommunicateGroup, set_hybrid_communicate_group)

    rng = np.random.RandomState(7)
    # fp32: bf16 would differ by reduction-order ulps under sharding
    cfg = llama_moe_tiny_config(moe_num_experts=8, dtype="float32")
    ids = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)

    def build(with_mesh):
        if with_mesh:
            hcg = HybridCommunicateGroup(dp_degree=8,
                                         devices=jax.devices()[:8])
            set_hybrid_communicate_group(hcg)
        else:
            set_hybrid_communicate_group(None)
        paddle.seed(13)
        m = LlamaForCausalLM(cfg)
        return np.asarray(m(paddle.to_tensor(ids)).value)

    dense = build(False)
    ep = build(True)
    set_hybrid_communicate_group(None)
    np.testing.assert_allclose(ep, dense, rtol=1e-4, atol=1e-4)
