"""Real dataset archive parsing (VERDICT r3 item 8): CIFAR pickle
batches, MNIST idx-gzip, aclImdb tarball — tiny fixture archives are
generated in the reference formats and must round-trip through the
same loaders the reference's file formats use; absent archives keep
the deterministic synthetic fallback.
"""
import gzip
import io
import os
import pickle
import tarfile

import numpy as np
import pytest


def _make_cifar(tmp_path, n=20):
    rng = np.random.RandomState(0)
    path = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(path, "w:gz") as tf:
        for name, count in [("data_batch_1", n), ("test_batch", n // 2)]:
            d = {b"data": rng.randint(0, 256, (count, 3072),
                                      dtype=np.uint8).tobytes() and
                 rng.randint(0, 256, (count, 3072)).astype(np.uint8),
                 b"labels": rng.randint(0, 10, count).tolist()}
            raw = pickle.dumps(d)
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(raw)
            tf.addfile(info, io.BytesIO(raw))
    return str(path)


def _make_mnist(tmp_path, n=12):
    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (n, 28, 28)).astype(np.uint8)
    lbls = rng.randint(0, 10, n).astype(np.uint8)
    ip = tmp_path / "train-images-idx3-ubyte.gz"
    lp = tmp_path / "train-labels-idx1-ubyte.gz"
    with gzip.open(ip, "wb") as f:
        f.write(b"\x00" * 16 + imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(b"\x00" * 8 + lbls.tobytes())
    return str(ip), str(lp), imgs, lbls


def _make_imdb(tmp_path):
    path = tmp_path / "aclImdb_v1.tar.gz"
    docs = {
        "aclImdb/train/pos/0_9.txt": b"a great great movie",
        "aclImdb/train/neg/0_2.txt": b"a terrible movie plot",
        "aclImdb/test/pos/0_8.txt": b"great plot",
        "aclImdb/test/neg/0_3.txt": b"terrible terrible",
    }
    with tarfile.open(path, "w:gz") as tf:
        for name, raw in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(raw)
            tf.addfile(info, io.BytesIO(raw))
    return str(path)


def test_cifar10_parses_reference_format(tmp_path):
    from paddle_tpu.vision.datasets import Cifar10
    path = _make_cifar(tmp_path)
    train = Cifar10(data_file=path, mode="train")
    test = Cifar10(data_file=path, mode="test")
    assert len(train) == 20 and len(test) == 10
    img, lbl = train[0]
    assert img.shape == (3, 32, 32) and 0 <= lbl < 10


def test_mnist_parses_idx_format(tmp_path):
    from paddle_tpu.vision.datasets import MNIST
    ip, lp, imgs, lbls = _make_mnist(tmp_path)
    ds = MNIST(image_path=ip, label_path=lp, mode="train")
    assert len(ds) == 12
    img, lbl = ds[3]
    np.testing.assert_array_equal(np.asarray(img, np.uint8)[0], imgs[3])
    assert lbl == int(lbls[3])


def test_imdb_parses_aclimdb_tarball(tmp_path):
    from paddle_tpu.text import Imdb
    path = _make_imdb(tmp_path)
    train = Imdb(data_file=path, mode="train", cutoff=10)
    test = Imdb(data_file=path, mode="test", cutoff=10)
    assert len(train) == 2 and len(test) == 2
    assert set(np.asarray(train.labels)) == {0, 1}
    # vocab built from train docs; 'great' must be a known id shared
    # across splits, and encodings must use it consistently
    gid = train.word_idx["great"]
    doc, lbl = test[0] if test.labels[0] == 1 else test[1]
    assert gid in list(np.asarray(doc))


def test_synthetic_fallback_still_works():
    from paddle_tpu.vision.datasets import Cifar10
    from paddle_tpu.text import Imdb
    ds = Cifar10(data_file=None, mode="train", n_synthetic=32)
    assert len(ds) == 32
    im = Imdb(data_file="/nonexistent/path.tar.gz", mode="train",
              n_synthetic=8)
    assert len(im.docs) == 8
