"""MoE / expert parallelism tests.

Reference test pattern: moe equivalence (1 expert == dense), routing
determinism on the device mesh, capacity drops, aux loss sanity.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.distributed.models.moe import (
    MoELayer, NaiveGate, SwitchGate, GShardGate, ExpertMLP, _topk_dispatch)
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup, set_hybrid_communicate_group)


def _dense_mlp_from_moe(moe):
    """Extract expert 0's weights as a dense MLP computation."""
    w1 = np.asarray(moe.w1.value)[0]
    b1 = np.asarray(moe.b1.value)[0, 0]
    w2 = np.asarray(moe.w2.value)[0]
    b2 = np.asarray(moe.b2.value)[0, 0]

    def f(x):
        h = jax.nn.gelu(x @ w1 + b1)
        return h @ w2 + b2
    return f


def test_single_expert_equals_dense():
    set_hybrid_communicate_group(None)
    paddle.seed(0)
    d, h = 8, 16
    moe = MoELayer(d_model=d, d_hidden=h, num_experts=1, gate="switch",
                   capacity_factor=100.0)
    x = np.random.RandomState(0).randn(2, 6, d).astype(np.float32)
    out = moe(paddle.to_tensor(x))
    expect = _dense_mlp_from_moe(moe)(x.reshape(-1, d)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out.value), expect, rtol=2e-5,
                               atol=2e-5)


def test_identical_experts_equal_dense_top2():
    """With all experts holding the SAME weights and ample capacity, any
    top-2 routing must reproduce the dense MLP (combine weights sum to
    1)."""
    set_hybrid_communicate_group(None)
    paddle.seed(1)
    d, h, E = 8, 16, 4
    moe = MoELayer(d_model=d, d_hidden=h, num_experts=E, gate="gshard",
                   capacity_factor=100.0)
    for p in (moe.w1, moe.b1, moe.w2, moe.b2):
        arr = np.array(p.value)  # writable copy
        arr[1:] = arr[0]
        p.set_value(arr)
    x = np.random.RandomState(1).randn(3, 5, d).astype(np.float32)
    out = moe(paddle.to_tensor(x))
    expect = _dense_mlp_from_moe(moe)(x.reshape(-1, d)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out.value), expect, rtol=2e-5,
                               atol=2e-5)


def test_moe_backward_and_aux_loss():
    set_hybrid_communicate_group(None)
    paddle.seed(2)
    d, h, E = 8, 16, 4
    moe = MoELayer(d_model=d, d_hidden=h, num_experts=E, gate="gshard")
    opt = paddle.optimizer.AdamW(1e-2, parameters=moe.parameters())
    x = paddle.to_tensor(
        np.random.RandomState(2).randn(4, 8, d).astype(np.float32))
    y = paddle.to_tensor(
        np.random.RandomState(3).randn(4, 8, d).astype(np.float32))
    losses = []
    for _ in range(5):
        out = moe(x)
        loss = ((out - y) ** 2).mean() + 0.01 * moe.l_aux
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.value)))
    assert losses[-1] < losses[0]
    aux = float(np.asarray(moe.l_aux.value))
    assert np.isfinite(aux) and aux >= 1.0 - 1e-5  # E*sum(me*ce) >= 1


def test_capacity_drops_tokens():
    """capacity_factor small → overflow tokens get zero output."""
    gates = jnp.asarray(np.tile([[0.9, 0.05, 0.03, 0.02]], (8, 1)),
                        jnp.float32)  # all tokens pick expert 0
    dispatch, combine, aux = _topk_dispatch(gates, 1, capacity=2)
    kept = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert kept.sum() == 2  # only 2 fit
    np.testing.assert_array_equal(kept[:2], 1)
    np.testing.assert_array_equal(kept[2:], 0)


def test_moe_expert_parallel_on_mesh():
    """Experts sharded over the dp axis: same values as single device."""
    set_hybrid_communicate_group(None)
    paddle.seed(4)
    d, h, E = 8, 16, 8
    moe_ref = MoELayer(d_model=d, d_hidden=h, num_experts=E, gate="switch")
    x = np.random.RandomState(4).randn(2, 8, d).astype(np.float32)
    ref = np.asarray(moe_ref(paddle.to_tensor(x)).value)

    set_hybrid_communicate_group(HybridCommunicateGroup(dp_degree=8))
    paddle.seed(4)
    moe_ep = MoELayer(d_model=d, d_hidden=h, num_experts=E, gate="switch",
                      ep_axis="dp")
    # expert dim must actually be sharded over dp
    from jax.sharding import NamedSharding
    sh = moe_ep.w1.value.sharding
    assert isinstance(sh, NamedSharding) and sh.spec[0] == "dp", sh
    out = np.asarray(moe_ep(paddle.to_tensor(x)).value)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
    set_hybrid_communicate_group(None)


def test_naive_gate_dense_path_equals_dense():
    """NaiveGate (no capacity) uses the dense no-drop path; with identical
    experts it must equal the dense MLP."""
    set_hybrid_communicate_group(None)
    paddle.seed(6)
    d, h, E = 8, 16, 4
    moe = MoELayer(d_model=d, d_hidden=h, num_experts=E, gate="naive",
                   top_k=2)
    for p in (moe.w1, moe.b1, moe.w2, moe.b2):
        arr = np.array(p.value)
        arr[1:] = arr[0]
        p.set_value(arr)
    x = np.random.RandomState(6).randn(2, 5, d).astype(np.float32)
    out = moe(paddle.to_tensor(x))
    expect = _dense_mlp_from_moe(moe)(x.reshape(-1, d)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out.value), expect, rtol=2e-5,
                               atol=2e-5)


def test_reference_style_expert_list():
    set_hybrid_communicate_group(None)
    paddle.seed(5)
    d, h = 8, 16
    experts = [ExpertMLP(d, h) for _ in range(2)]
    moe = MoELayer(gate="naive", experts=experts, d_model=d, top_k=2)
    x = np.random.RandomState(5).randn(2, 4, d).astype(np.float32)
    out = moe(paddle.to_tensor(x))
    assert list(out.shape) == [2, 4, d]
    # differentiable end-to-end
    loss = (out ** 2).mean()
    loss.backward()
    assert experts[0].fc1.weight.grad is not None or \
        experts[1].fc1.weight.grad is not None
