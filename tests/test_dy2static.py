"""dy2static control-flow conversion (jit/dy2static.py).

Modeled on reference test/dygraph_to_static (ifelse/loop tests): tensor-
dependent `if`/`while` must compile into lax.cond / lax.while_loop under
to_static, and unconvertible constructs must graph-break to eager with a
warning, never a crash.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import to_static


def test_tensor_ifelse_compiles():
    @to_static
    def f(x):
        if (x.sum() > 0):
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    pos = paddle.to_tensor(np.float32([1.0, 2.0]))
    neg = paddle.to_tensor(np.float32([-1.0, -2.0]))
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # graph break would raise
        np.testing.assert_allclose(np.asarray(f(pos).value), [2.0, 4.0])
        np.testing.assert_allclose(np.asarray(f(neg).value),
                                   [-2.0, -3.0])


def test_untaken_branch_does_not_execute():
    """Regression (r4 advisor, medium): branches must go INTO lax.cond
    so the untaken side never runs — `if s > 0: y = x / s` with s == 0
    must not evaluate x/0 (which poisons gradients through the select
    with NaN even though the false branch is chosen)."""
    @to_static
    def f(x, s):
        if (s > 0):
            y = x / s
        else:
            y = x * 0.0
        return y.sum()

    x = paddle.to_tensor(np.float32([1.0, 2.0]))
    zero = paddle.to_tensor(np.float32(0.0))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert float(np.asarray(f(x, zero).value)) == 0.0
    two = paddle.to_tensor(np.float32(2.0))
    assert float(np.asarray(f(x, two).value)) == pytest.approx(1.5)

    # gradient-level check via jax.grad over the transformed function:
    # d/dx at s=0 must be exactly 0, not NaN-through-select
    import jax
    from paddle_tpu.jit.dy2static import ast_transform
    from paddle_tpu.framework.tensor import Tensor

    def g(x, s):
        if (s > 0):
            y = x / s
        else:
            y = x * 0.0
        return y.sum()

    tg = ast_transform(g)
    grad = jax.grad(lambda xv, sv: tg(Tensor(xv), Tensor(sv))._value)
    gv = np.asarray(grad(np.float32([1.0, 2.0]), np.float32(0.0)))
    assert np.all(np.isfinite(gv)) and np.allclose(gv, 0.0)


def test_tensor_while_loop_compiles():
    @to_static
    def f(x):
        i = paddle.to_tensor(np.float32(0.0))
        s = x * 0.0
        while (i < 5.0):
            s = s + x
            i = i + 1.0
        return s

    x = paddle.to_tensor(np.float32([1.0, 3.0]))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        np.testing.assert_allclose(np.asarray(f(x).value), [5.0, 15.0])


def test_cond_in_model():
    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if (h.mean() > 0):
                out = nn.functional.relu(h)
            else:
                out = h * 0.1
            return out

    paddle.seed(0)
    m = to_static(Gate())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 4).astype(np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = m(x)
    # eager reference
    m2 = Gate()
    m2.set_state_dict({k: v for k, v in m.state_dict().items()})
    ref = m2.forward_function(x) if hasattr(m2, "forward_function") \
        else m2(x)
    np.testing.assert_allclose(np.asarray(out.value),
                               np.asarray(ref.value), rtol=1e-5)


def test_loop_in_model():
    class Decoder(nn.Layer):
        """Reference analog: dygraph_to_static seq2seq decode loop."""

        def __init__(self):
            super().__init__()
            self.cell = nn.Linear(4, 4)

        def forward(self, x, steps):
            i = paddle.to_tensor(np.float32(0.0))
            h = x
            while (i < steps):
                h = paddle.tanh(self.cell(h))
                i = i + 1.0
            return h

    paddle.seed(1)
    m = Decoder()
    sm = to_static(Decoder())
    sm.set_state_dict({k: v for k, v in m.state_dict().items()})
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(2, 4).astype(np.float32))
    steps = paddle.to_tensor(np.float32(3.0))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = sm(x, steps)
    h = x
    for _ in range(3):
        h = paddle.tanh(m.cell(h))
    np.testing.assert_allclose(np.asarray(out.value),
                               np.asarray(h.value), rtol=1e-5,
                               atol=1e-6)


def test_graph_break_fallback():
    """A construct the AST pass cannot convert (data-dependent Python
    range) must warn and fall back to eager, returning the right
    answer (reference: SOT graph break)."""
    @to_static
    def f(x):
        n = int(np.asarray(x.value).max())  # concretizes the tracer
        s = x * 0.0
        for _ in range(n):
            s = s + x
        return s

    x = paddle.to_tensor(np.float32([2.0, 1.0]))
    with pytest.warns(RuntimeWarning, match="graph break"):
        out = f(x)
    np.testing.assert_allclose(np.asarray(out.value), [4.0, 2.0])
    # subsequent calls run eager without re-warning
    out2 = f(x)
    np.testing.assert_allclose(np.asarray(out2.value), [4.0, 2.0])


def test_one_sided_assignment_not_broken():
    """An if that binds a name in only one branch must keep plain
    Python semantics (review-found regression: the synthesized branch
    read an unbound local)."""
    @to_static
    def f(x, flag):
        if flag:
            extra = 1.0
            x = x + extra
        return x * 2.0

    x = paddle.to_tensor(np.float32([1.0]))
    np.testing.assert_allclose(np.asarray(f(x, False).value), [2.0])
    np.testing.assert_allclose(np.asarray(f(x, True).value), [4.0])


def test_while_write_only_result_carried():
    """A loop variable only WRITTEN in the body must come out of the
    converted loop with its final value."""
    @to_static
    def f(x):
        i = paddle.to_tensor(np.float32(0.0))
        last = x * 0.0
        while (i < 3.0):
            last = x + i          # write-only w.r.t. the body
            i = i + 1.0
        return last

    x = paddle.to_tensor(np.float32([10.0]))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        np.testing.assert_allclose(np.asarray(f(x).value), [12.0])


def test_branch_structure_mismatch_graph_breaks():
    """Branches with different pytree structures are unconvertible:
    first call must graph-break to eager, not crash."""
    @to_static
    def f(x):
        if (x.sum() > 0):
            y = (x, x * 2.0)
        else:
            y = x
        return y

    x = paddle.to_tensor(np.float32([1.0]))
    with pytest.warns(RuntimeWarning, match="graph break"):
        out = f(x)
    np.testing.assert_allclose(np.asarray(out[1].value), [2.0])


def test_branch_read_then_write_prebound_compiles():
    """A branch that READS a pre-bound name and REBINDS it must compile
    (regression: closure capture made the name local → UnboundLocal,
    silently graph-breaking every vision-zoo forward)."""
    @to_static
    def f(x):
        h = x * 2.0
        if (h.sum() > 0):
            h = h + 1.0
        else:
            h = h - 1.0
        return h

    pos = paddle.to_tensor(np.float32([1.0]))
    with warnings.catch_warnings():
        warnings.simplefilter("error")     # graph break would raise
        np.testing.assert_allclose(np.asarray(f(pos).value), [3.0])


def test_zoo_model_compiles_without_graph_break():
    """mobilenet-style forward (loops + one-sided prebound ifs) must
    jit cleanly under to_static."""
    from paddle_tpu.vision.models import mobilenet_v3_small
    paddle.seed(0)
    m = mobilenet_v3_small(num_classes=4, scale=0.35)
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(1, 3, 32, 32).astype(np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        out = to_static(m)(x)
    assert tuple(out.shape) == (1, 4)


def test_nested_tensor_if_converts():
    """A tensor-if nested inside a tensor-if must convert fully — the
    inner conversion's synthesized Returns must not make the outer
    statement look escaping (review-found regression)."""
    @to_static
    def f(x):
        y = x * 1.0
        if (y.sum() > 0):
            if (y.max() > 2.0):
                y = y * 10.0
            else:
                y = y + 1.0
        else:
            y = y - 1.0
        return y

    big = paddle.to_tensor(np.float32([3.0]))
    small = paddle.to_tensor(np.float32([1.0]))
    neg = paddle.to_tensor(np.float32([-1.0]))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        np.testing.assert_allclose(np.asarray(f(big).value), [30.0])
        np.testing.assert_allclose(np.asarray(f(small).value), [2.0])
        np.testing.assert_allclose(np.asarray(f(neg).value), [-2.0])
