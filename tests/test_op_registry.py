"""Generated OpTest coverage for every registry op.

Single-source principle (SURVEY §1): each OpSpec carries its numpy
reference and sample inputs, so this file is ONE parametrized test that
grows automatically with the registry — the TPU analog of the reference's
ops.yaml-driven OpTest matrix.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.registry import REGISTRY
from op_test import check_output, check_grad, DTYPE_ATOL


_SPECS = {s.name: s for s in REGISTRY}


def _flat_inputs(spec, arrays):
    """Variadic specs carry their tensor list as arrays[0]; flatten for
    the harness and rebuild the list inside the called fns."""
    return list(arrays[0]) if spec.n_tensors == -1 else arrays


@pytest.mark.parametrize("name", sorted(_SPECS))
def test_registry_op_output(name):
    spec = _SPECS[name]
    fn = getattr(paddle, name)
    arrays, attrs = spec.samples()

    def paddle_fn(*ts):
        if spec.n_tensors == -1:
            return fn(list(ts), **attrs)
        return fn(*ts, **attrs)

    def numpy_fn(*arrs):
        if spec.n_tensors == -1:
            return spec.np_ref(list(arrs), **attrs)
        return spec.np_ref(*arrs, **attrs)

    atol = spec.atol if spec.atol is not None else DTYPE_ATOL["float32"]
    check_output(paddle_fn, numpy_fn, _flat_inputs(spec, arrays),
                 atol=atol)


@pytest.mark.parametrize(
    "name", sorted(n for n, s in _SPECS.items() if s.grad))
def test_registry_op_grad(name):
    spec = _SPECS[name]
    fn = getattr(paddle, name)
    arrays, attrs = spec.samples()

    def paddle_fn(*ts):
        if spec.n_tensors == -1:
            out = fn(list(ts), **attrs)
        else:
            out = fn(*ts, **attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    atol = spec.grad_atol if spec.grad_atol is not None else 5e-3
    check_grad(paddle_fn, _flat_inputs(spec, arrays), atol=atol,
               rtol=atol)


def test_c_ops_namespace():
    """_C_ops resolves registry ops, hand-written ops, and functional."""
    from paddle_tpu import _C_ops
    assert _C_ops.erf is not None
    assert _C_ops.matmul is not None
    assert _C_ops.relu is not None
    with pytest.raises(AttributeError):
        _C_ops.definitely_not_an_op


def test_c_ops_inplace_alias_mutates():
    from paddle_tpu import _C_ops
    x = paddle.to_tensor(np.array([0.5, -0.5], np.float32))
    out = _C_ops.erf_(x)
    assert out is x
    np.testing.assert_allclose(np.asarray(x.value),
                               [0.5204999, -0.5204999], rtol=1e-5)


def test_bitwise_invert_int64_and_bool():
    x = paddle.to_tensor(np.array([2 ** 40], np.int64))
    np.testing.assert_array_equal(
        np.asarray(paddle.bitwise_invert(x).value), [-(2 ** 40) - 1])
    b = paddle.to_tensor(np.array([True, False]))
    np.testing.assert_array_equal(
        np.asarray(paddle.bitwise_invert(b).value), [False, True])


def test_tensor_methods_from_registry():
    x = paddle.to_tensor(np.array([0.1, 0.5], np.float32))
    np.testing.assert_allclose(np.asarray(x.erf().value),
                               [0.1124629, 0.5204999], rtol=1e-5)
    assert hasattr(x, "lgamma") and hasattr(x, "hypot")


def test_registry_size():
    """The registry must OWN (generate, not merely re-test) ≥50 ops that
    had no hand-written implementation (VERDICT round-1 item 7)."""
    owned = [s.name for s in REGISTRY
             if "op registry" in (getattr(paddle, s.name).__doc__ or "")]
    assert len(owned) >= 50, (len(owned), sorted(owned))


def test_cdist_inf_norm():
    x = paddle.to_tensor(np.array([[0., 0.], [1., 3.]], np.float32))
    y = paddle.to_tensor(np.array([[2., 1.]], np.float32))
    out = paddle.cdist(x, y, p=float("inf"))
    np.testing.assert_allclose(np.asarray(out.value), [[2.], [2.]])


def test_index_fill_negative_axis():
    x = paddle.to_tensor(np.zeros((2, 3), np.float32))
    out = paddle.index_fill(x, paddle.to_tensor(np.array([1])), axis=-1,
                            value=7.0)
    expect = np.zeros((2, 3), np.float32)
    expect[:, 1] = 7.0
    np.testing.assert_array_equal(np.asarray(out.value), expect)
