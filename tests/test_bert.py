"""BERT family + nn.set_compute_dtype (flax-idiom mixed precision).

Reference: PaddleNLP BertModel surface; the mixed-precision contract is
the TPU design's own (fp32 params are the masters, compute in bf16).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models.bert import (BertForMaskedLM, BertModel,
                                    bert_tiny_config)


def test_bert_forward_shapes():
    cfg = bert_tiny_config()
    m = BertModel(cfg)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 16)).astype(np.int32))
    seq, pooled = m(ids)
    assert tuple(seq.shape) == (2, 16, cfg.hidden_size)
    assert tuple(pooled.shape) == (2, cfg.hidden_size)


def test_bert_mlm_trains():
    paddle.seed(0)
    cfg = bert_tiny_config()
    m = BertForMaskedLM(cfg)
    opt = paddle.optimizer.AdamW(5e-3, parameters=m.parameters())
    from paddle_tpu.jit import TrainStep
    step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    x = paddle.to_tensor(ids)
    losses = [float(np.asarray(step(x, x).value)) for _ in range(12)]
    assert losses[-1] < losses[0] * 0.8, losses[::4]


def test_bert_compute_dtype_bf16():
    """cfg.dtype='bfloat16' → fp32 params (masters), bf16 activations."""
    cfg = bert_tiny_config(dtype="bfloat16")
    m = BertForMaskedLM(cfg)
    for n, p in m.state_dict().items():
        assert str(p.value.dtype) == "float32", (n, p.value.dtype)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 16)).astype(np.int32))
    logits = m(ids)
    assert str(logits.value.dtype) == "bfloat16"
    # loss is fp32 and close to the fp32 model's
    loss = m.compute_loss(logits, ids)
    assert str(loss.value.dtype) == "float32"
    assert np.isfinite(float(np.asarray(loss.value)))


def test_set_compute_dtype_counts_and_grad():
    """set_compute_dtype flips Linear/LayerNorm/Embedding; grads stay
    fp32 (cast is inside the recorded op, so the vjp casts back)."""
    m = nn.Sequential(nn.Linear(8, 8), nn.LayerNorm(8), nn.Linear(8, 2))
    n = nn.set_compute_dtype(m, "bfloat16")
    assert n == 3
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32))
    out = m(x)
    assert str(out.value.dtype) == "bfloat16"
    loss = (out.astype("float32") ** 2).sum()
    loss.backward()
    g = m[0].weight.grad
    assert g is not None and str(g.value.dtype) == "float32"


# -- ERNIE family (round 4) -------------------------------------------------
def test_ernie_forward_and_task_embeddings():
    from paddle_tpu.models.ernie import ErnieModel, ernie_tiny_config
    paddle.seed(0)
    cfg = ernie_tiny_config()
    m = ErnieModel(cfg)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (2, 12)).astype(np.int32))
    seq, pooled = m(ids)
    assert tuple(seq.shape) == (2, 12, cfg.hidden_size)
    # task-type ids change the representation (the ERNIE-specific table)
    task = paddle.to_tensor(np.ones((2, 12), np.int32))
    seq2, _ = m(ids, task_type_ids=task)
    assert not np.allclose(np.asarray(seq.value),
                           np.asarray(seq2.value))


def test_ernie_heads_thread_attention_mask_correctly():
    """Regression (r4 advisor, high): the task heads passed backbone
    args positionally, so attention_mask landed in position_ids.  An
    all-ones mask must be a no-op; a real padding mask must change the
    logits and task_type_ids must still reach the task table."""
    from paddle_tpu.models.ernie import (ErnieForSequenceClassification,
                                         ernie_tiny_config)
    paddle.seed(0)
    cfg = ernie_tiny_config()
    m = ErnieForSequenceClassification(cfg, num_classes=2)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (2, 12)).astype(np.int32))
    base = np.asarray(m(ids).value)
    ones = paddle.to_tensor(np.ones((2, 12), np.float32))
    np.testing.assert_allclose(
        np.asarray(m(ids, attention_mask=ones).value), base,
        rtol=2e-5, atol=2e-5)
    pad = np.ones((2, 12), np.float32)
    pad[:, 6:] = 0.0
    masked = np.asarray(
        m(ids, attention_mask=paddle.to_tensor(pad)).value)
    assert not np.allclose(masked, base)
    task = paddle.to_tensor(np.ones((2, 12), np.int32))
    assert not np.allclose(
        np.asarray(m(ids, task_type_ids=task).value), base)


def test_ernie_classifier_trains():
    from paddle_tpu.models.ernie import (ErnieForSequenceClassification,
                                         ernie_tiny_config)
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    m = ErnieForSequenceClassification(ernie_tiny_config(),
                                       num_classes=2)
    opt = paddle.optimizer.AdamW(5e-3, parameters=m.parameters())
    step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
    rng = np.random.RandomState(1)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 12)).astype(np.int32))
    y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype(np.int64))
    losses = [float(np.asarray(step(ids, y).value)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_ernie_mlm_bf16_compute():
    from paddle_tpu.models.ernie import ErnieForMaskedLM, ernie_tiny_config
    paddle.seed(0)
    cfg = ernie_tiny_config(dtype="bfloat16")
    m = ErnieForMaskedLM(cfg)
    for n, p in m.state_dict().items():
        assert str(p.value.dtype) == "float32", n   # fp32 masters
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 12)).astype(np.int32))
    logits = m(ids)
    assert str(logits.value.dtype) == "bfloat16"
    loss = m.compute_loss(logits, ids)
    assert np.isfinite(float(np.asarray(loss.value)))
