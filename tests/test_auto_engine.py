"""Full-auto parallel engine (analyze → plan → complete → emit).

Reference: auto_parallel/static/engine.py + planner_v2.py +
completion.py — here validated end-to-end on the virtual 8-device CPU
mesh: the planner picks a feasible strategy for an unannotated model,
the completion produces the megatron layout from shape+name seeds, and
the emitted trainer's loss matches an unsharded baseline.
"""
import numpy as np
import jax
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.auto_parallel import (
    AutoParallelEngine, analyze_model, complete_shardings)
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config


def _tiny_llama():
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128, num_attention_heads=4,
                            num_key_value_heads=4, vocab_size=256,
                            max_position_embeddings=64)
    return LlamaForCausalLM(cfg), cfg


def test_analyze_model_extracts_structure():
    model, cfg = _tiny_llama()
    info = analyze_model(model, seq_len=32)
    assert info["hidden_size"] == 64
    assert info["intermediate_size"] == 128
    assert info["num_hidden_layers"] == 2
    assert info["vocab_size"] == 256
    assert info["block_prefix"] and "layers" in info["block_prefix"]


def test_completion_megatron_layout_and_seed_respected():
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.topology import build_mesh
    model, cfg = _tiny_llama()
    mesh = build_mesh(mp=2, dp=4)
    # seed one param by hand: completion must not overwrite it
    q = model.llama.layers[0].self_attn.q_proj
    q._value = jax.device_put(q.value, NamedSharding(mesh, P("mp", None)))
    n = complete_shardings(model, mesh)
    assert n > 0
    spec = lambda p: tuple(p.value.sharding.spec)
    # seed kept (engine would have chosen column = (None, 'mp'))
    assert spec(q) == ("mp", None)
    l1 = model.llama.layers[1].self_attn
    assert spec(l1.q_proj) == (None, "mp")            # column
    assert spec(l1.o_proj) == ("mp", None)            # row (name hint)
    assert spec(model.llama.layers[1].mlp.down_proj) == ("mp", None)
    assert spec(model.llama.embed_tokens) == ("mp", None)  # vocab
    # 1-D norms stay replicated (GSPMD leak avoidance)
    norm = model.llama.layers[1].input_layernorm.weight
    assert not any(s is not None
                   for s in getattr(norm.value.sharding, "spec", ()))


def _engine(hbm, model=None, opt=None, **kw):
    if model is None:
        model, _ = _tiny_llama()
        opt = paddle.optimizer.AdamW(1e-3,
                                     parameters=model.parameters())
    return AutoParallelEngine(model, opt, global_batch_size=8,
                              seq_len=32, hbm_bytes=hbm, chip="v5e",
                              **kw)


def test_planner_finds_feasible_strategy_and_runs():
    eng = _engine(hbm=16e9)
    s = eng.plan()
    assert s["dp"] * s["mp"] * s["pp"] * s["sharding"] == 8
    eng.build()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (8, 32)).astype(np.int32)
    loss = eng.step(paddle.to_tensor(ids), paddle.to_tensor(ids))
    auto_loss = float(np.asarray(loss.value))
    assert np.isfinite(auto_loss)

    # strategy invariance: same loss as an unsharded single-device step
    paddle.seed(0)
    model2, _ = _tiny_llama()
    opt2 = paddle.optimizer.AdamW(1e-3, parameters=model2.parameters())
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh
    st = ShardedTrainStep(model2, opt2,
                          build_mesh(devices=jax.devices()[:1]),
                          sharding_stage=0)
    base = float(np.asarray(st(paddle.to_tensor(ids),
                               paddle.to_tensor(ids)).value))
    np.testing.assert_allclose(auto_loss, base, rtol=2e-4, atol=2e-5)


def test_planner_adapts_to_memory_budget():
    """Shrinking the budget must change the plan toward state sharding
    / recompute (reference planner_v2 cost-vs-memory tradeoff).  Uses
    what-if planning on a 7B-class config — the cost/memory models, not
    the in-hand tiny model, drive the choice."""
    llama7b = dict(hidden_size=4096, intermediate_size=11008,
                   num_hidden_layers=32, num_attention_heads=32,
                   vocab_size=32000, seq_len=2048)
    model, _ = _tiny_llama()
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())

    def plan_at(hbm):
        return AutoParallelEngine(model, opt, global_batch_size=8,
                                  seq_len=2048, hbm_bytes=hbm,
                                  chip="v5p", model_cfg=llama7b).plan()

    big = plan_at(95e9 * 8)      # practically unconstrained
    small = plan_at(24e9)        # tight: must shard state / recompute
    assert (small["sharding_stage"], small["sharding"],
            small["recompute"]) != (big["sharding_stage"],
                                    big["sharding"], big["recompute"]), \
        (big, small)
    assert small["sharding_stage"] >= 1 or small["recompute"] != "none"
    assert small["est_memory_gb"] <= 24.0


def test_planner_raises_when_infeasible():
    eng = _engine(hbm=0.001e9)
    with pytest.raises(RuntimeError, match="no feasible strategy"):
        eng.plan()


def test_auto_pp_segments_plain_sequential():
    """Round-5 verdict item 4: pp>1 on a plain Layer — the engine
    builds a PipelineLayer from the sequential children (shared param
    objects) and matches the manual pipeline loss."""
    import jax
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
    from paddle_tpu.parallel.pipeline import PipelineEngine
    from paddle_tpu.distributed.topology import build_mesh

    def build_model():
        paddle.seed(53)
        return nn.Sequential(*[
            nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                          nn.Linear(32, 16)) for _ in range(4)])

    def mse(o, y):
        return ((o - y) ** 2).mean()

    x = np.random.RandomState(2).randn(4, 16).astype(np.float32)
    pl = PipelineLayer(list(build_model()), loss_fn=mse)
    eng = PipelineEngine(pl, build_mesh(pp=2, dp=2,
                                        devices=jax.devices()[:4]),
                         num_virtual_stages=1)
    manual = float(np.asarray(eng.train_batch(
        [paddle.to_tensor(x), paddle.to_tensor(x)], 2).value))

    m2 = build_model()
    opt = paddle.optimizer.AdamW(1e-3, parameters=m2.parameters())
    cands = {"dp": [2], "mp": [1], "pp": [2], "vpp": [1],
             "sharding": [1], "sharding_stage": [0],
             "micro_batch_size": [1], "recompute": ["none"]}
    e = AutoParallelEngine(m2, opt, loss_fn=mse,
                           devices=jax.devices()[:4],
                           global_batch_size=4, seq_len=16,
                           candidates=cands)
    assert e.plan()["pp"] == 2
    auto = float(np.asarray(
        e.step(paddle.to_tensor(x), paddle.to_tensor(x)).value))
    np.testing.assert_allclose(auto, manual, rtol=1e-5, atol=1e-6)
    # shared params: stepping the engine moved the ORIGINAL model's
    # weights (the caller's optimizer owns the same tensors)
    assert e._auto_pl is not None


def test_auto_pp_refuses_non_sequential():
    """Arbitrary forward graphs are refused, not guessed."""
    class Odd(nn.Layer):
        """Has a repeated indexed block (so the planner sees 2 layers)
        but a NON-sequential forward — segmentation must refuse."""

        def __init__(self):
            super().__init__()
            self.branches = nn.LayerList([nn.Linear(8, 8),
                                          nn.Linear(8, 8)])

        def forward(self, x):
            return self.branches[0](x) + self.branches[1](x)

    import jax
    m = Odd()
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    cands = {"dp": [1], "mp": [1], "pp": [2], "vpp": [1],
             "sharding": [1], "sharding_stage": [0],
             "micro_batch_size": [1], "recompute": ["none"]}
    e = AutoParallelEngine(m, opt, loss_fn=lambda o, y: (o - y).mean(),
                           devices=jax.devices()[:2],
                           global_batch_size=2, seq_len=8,
                           allow_pp=True, candidates=cands)
    e.plan()
    with pytest.raises(RuntimeError, match="neither a PipelineLayer"):
        e.build()
