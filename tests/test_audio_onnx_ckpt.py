"""paddle.audio + onnx-equivalent export + async distributed checkpoint.

Reference: python/paddle/audio/ (features/functional/backends),
python/paddle/onnx/export.py, distributed/checkpoint/save_state_dict.py
(:46 async save queue).
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle


class TestAudioFunctional:
    def test_mel_scale_roundtrip(self):
        from paddle_tpu.audio.functional import hz_to_mel, mel_to_hz
        f = np.array([55.0, 440.0, 4000.0], np.float32)
        back = np.asarray(mel_to_hz(hz_to_mel(f)))
        np.testing.assert_allclose(back, f, rtol=1e-4)
        back_htk = np.asarray(mel_to_hz(hz_to_mel(f, htk=True), htk=True))
        np.testing.assert_allclose(back_htk, f, rtol=1e-4)

    def test_fbank_shape_and_coverage(self):
        from paddle_tpu.audio.functional import compute_fbank_matrix
        fb = np.asarray(compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257)
        assert (fb >= 0).all() and fb.sum() > 0
        # every mel filter covers some bins
        assert (fb.sum(axis=1) > 0).all()

    def test_power_to_db(self):
        from paddle_tpu.audio.functional import power_to_db
        db = np.asarray(power_to_db(np.array([1.0, 10.0, 100.0])))
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-4)

    def test_dct_orthonormal(self):
        from paddle_tpu.audio.functional import create_dct
        d = np.asarray(create_dct(13, 40))
        gram = d.T @ d
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-4)


class TestAudioFeatures:
    def test_spectrogram_tone_peak(self):
        """A pure tone's spectrogram peaks at the right FFT bin."""
        from paddle_tpu.audio.features import Spectrogram
        sr, n_fft = 16000, 512
        t = np.arange(sr // 4) / sr
        tone = np.sin(2 * np.pi * 1000.0 * t).astype(np.float32)
        spec = Spectrogram(n_fft=n_fft)(paddle.to_tensor(tone[None]))
        s = np.asarray(spec.value)[0]          # [bins, frames]
        peak_bin = int(s.mean(axis=1).argmax())
        expect = round(1000.0 * n_fft / sr)
        assert abs(peak_bin - expect) <= 1

    def test_mfcc_pipeline_shapes(self):
        from paddle_tpu.audio.features import (MelSpectrogram,
                                               LogMelSpectrogram, MFCC)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 8000).astype(np.float32))
        mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
        assert mel.shape[0] == 2 and mel.shape[1] == 40
        lm = LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
        assert lm.shape == mel.shape
        mf = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
        assert mf.shape[0] == 2 and mf.shape[1] == 13


class TestAudioBackends:
    def test_wav_roundtrip(self, tmp_path):
        from paddle_tpu import audio
        sr = 16000
        wav = np.sin(np.linspace(0, 100, 4000)).astype(np.float32)[None]
        p = str(tmp_path / "t.wav")
        audio.save(p, wav, sr)
        meta = audio.info(p)
        assert meta.sample_rate == sr and meta.num_channels == 1
        back, sr2 = audio.load(p)
        assert sr2 == sr
        np.testing.assert_allclose(back, wav, atol=1e-3)

    def test_datasets_learnable_labels(self):
        from paddle_tpu.audio.datasets import TESS
        ds = TESS(mode="train", n_synthetic=16)
        x, y = ds[0]
        assert x.ndim == 1 and 0 <= y < 7
        mf, _ = TESS(mode="train", n_synthetic=4, feat_type="mfcc",
                     n_mfcc=13)[0]
        assert mf.shape[0] == 13


class TestOnnxExport:
    def test_export_load_roundtrip(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.static import InputSpec
        paddle.seed(0)
        layer = nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                              nn.Linear(8, 2))
        p = str(tmp_path / "model")
        out_path = paddle.onnx.export(
            layer, p, input_spec=[InputSpec([-1, 4], "float32")])
        assert os.path.exists(out_path)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(3, 4).astype(np.float32))
        want = np.asarray(layer(x).value)
        loaded = paddle.onnx.load(out_path)
        got = np.asarray(loaded(x).value)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestAsyncCheckpoint:
    def test_async_save_matches_sync(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import (
            save_state_dict, load_state_dict, synchronize_async_saves)
        import paddle_tpu.nn as nn
        paddle.seed(1)
        m = nn.Linear(4, 4)
        sd = m.state_dict()
        fut = save_state_dict(sd, str(tmp_path / "async"),
                              async_save=True)
        synchronize_async_saves()
        assert fut.done()
        paddle.seed(2)
        m2 = nn.Linear(4, 4)
        load_state_dict(m2.state_dict(), str(tmp_path / "async"))
        np.testing.assert_allclose(np.asarray(m2.weight.value),
                                   np.asarray(m.weight.value))

    def test_async_save_snapshot_isolated_from_updates(self, tmp_path):
        """The checkpoint must hold the values AT CALL TIME even if the
        params are mutated right after (the donation hazard the sync
        snapshot protects against)."""
        from paddle_tpu.distributed.checkpoint import (
            save_state_dict, load_state_dict, synchronize_async_saves)
        import jax.numpy as jnp
        from paddle_tpu.framework.tensor import Tensor
        t = Tensor(jnp.ones((8,), jnp.float32))
        save_state_dict({"w": t}, str(tmp_path / "snap"),
                        async_save=True)
        t._value = jnp.zeros((8,), jnp.float32)  # mutate immediately
        synchronize_async_saves()
        probe = Tensor(jnp.full((8,), 7.0))
        load_state_dict({"w": probe}, str(tmp_path / "snap"))
        np.testing.assert_allclose(np.asarray(probe.value), np.ones(8))
