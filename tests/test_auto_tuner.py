"""Auto-tuner: prune rules, memory model, ranked search.

Reference: python/paddle/distributed/auto_tuner/ (tuner.py, prune.py,
memory_cost_model.py interface).
"""
import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import (AutoTuner, tune,
                                               estimate_memory_bytes,
                                               estimate_step_time)
from paddle_tpu.distributed.auto_tuner.prune import prune_candidate

LLAMA_1B = dict(hidden_size=2560, intermediate_size=6912,
                num_hidden_layers=14, num_attention_heads=20,
                num_key_value_heads=4, vocab_size=8192, seq_len=2048)


def _cand(**kw):
    c = dict(dp=1, mp=1, pp=1, vpp=1, sharding=8, sharding_stage=3,
             micro_batch_size=4, recompute="selective")
    c.update(kw)
    return c


class TestPrune:
    CFG = {"model_cfg": LLAMA_1B, "n_devices": 8,
           "global_batch_size": 64, "hbm_bytes": 95e9}

    def test_device_product(self):
        assert prune_candidate(self.CFG, _cand(dp=2)) is not None
        assert prune_candidate(self.CFG, _cand()) is None

    def test_mp_divisibility(self):
        # 20 heads: mp=8 does not divide
        bad = _cand(mp=8, sharding=1, sharding_stage=0)
        assert "mp" in prune_candidate(self.CFG, bad)

    def test_pp_layers(self):
        bad = _cand(pp=4, sharding=2)  # 14 % 4 != 0
        assert "layers" in prune_candidate(self.CFG, bad)

    def test_micro_divisibility(self):
        bad = _cand(micro_batch_size=16, sharding=8)  # 64/8=8 % 16
        assert "micro" in prune_candidate(self.CFG, bad)

    def test_sharding_stage_consistency(self):
        bad = _cand(sharding=1, dp=8, sharding_stage=3)
        assert "sharding" in prune_candidate(self.CFG, bad)

    def test_memory_prune(self):
        # 1B params fp32+moments replicated on a 16G chip, no recompute:
        # must be pruned by memory
        cfg = dict(self.CFG, hbm_bytes=16e9)
        bad = _cand(sharding=1, dp=8, sharding_stage=0,
                    recompute="none", micro_batch_size=8)
        assert "HBM" in prune_candidate(cfg, bad)


class TestMemoryModel:
    def test_bench_config_fits_v5e(self):
        """The actual round-3 bench point (1 chip, stage 3 no-op,
        selective recompute, b=8) must be estimated under 16G."""
        est = estimate_memory_bytes(
            LLAMA_1B, _cand(sharding=1, sharding_stage=0,
                            micro_batch_size=8),
            dtype_bytes=4.0, moment_bytes=2.0)
        assert 8e9 < est.total < 16e9, est

    def test_zero3_shards_params(self):
        full = estimate_memory_bytes(LLAMA_1B,
                                     _cand(sharding=1, dp=8,
                                           sharding_stage=0))
        sharded = estimate_memory_bytes(LLAMA_1B, _cand())
        assert sharded.params < full.params / 4
        assert sharded.optimizer < full.optimizer / 4

    def test_recompute_cuts_activations(self):
        none = estimate_memory_bytes(LLAMA_1B, _cand(recompute="none"))
        sel = estimate_memory_bytes(LLAMA_1B,
                                    _cand(recompute="selective"))
        full = estimate_memory_bytes(LLAMA_1B, _cand(recompute="full"))
        assert full.activations < sel.activations < none.activations


class TestTune:
    def test_ranked_output(self):
        ranked = tune(LLAMA_1B, n_devices=8, global_batch_size=64,
                      chip="v5p")
        assert len(ranked) > 10
        times = [c["est_step_time"] for c in ranked]
        assert times == sorted(times)
        for c in ranked[:3]:
            assert c["dp"] * c["mp"] * c["pp"] * c["sharding"] == 8
            assert c["est_memory_gb"] < 95

    def test_8dev_choice_for_1b_llama(self):
        """Pin the 8-device strategy for the 1B llama on v5p: plenty of
        HBM -> the tuner should avoid pp (bubble) and avoid recompute
        (replay flops), using pure data-parallel ZeRO or DP."""
        best = tune(LLAMA_1B, n_devices=8, global_batch_size=64,
                    chip="v5p")[0]
        assert best["pp"] == 1
        assert best["recompute"] == "none"
        assert best["dp"] * best["sharding"] == 8
        assert best["mp"] == 1

    def test_memory_constrained_prefers_zero3(self):
        """On 16G chips with the reference O2 scheme (bf16 params + fp32
        master + fp32 moments = 14 bytes/param) replicated state cannot
        fit: every surviving candidate shards state or the model."""
        ranked = tune(LLAMA_1B, n_devices=8, global_batch_size=64,
                      chip="v5e", hbm_bytes=16e9,
                      param_bytes=6.0, moment_bytes=4.0)
        assert ranked, "no feasible candidate found"
        assert all(c["sharding_stage"] >= 1 or c["pp"] > 1 or
                   c["mp"] > 1 for c in ranked)

    def test_compile_check_top_candidate(self):
        """The top candidate compiles through the real ShardedTrainStep
        on the 8-device virtual mesh."""
        ranked = tune(LLAMA_1B, n_devices=8, global_batch_size=64,
                      chip="v5p", compile_check=True, top_k=1)
        assert ranked
