"""Elastic scale-in/out + fault injection.

Reference: fleet/elastic/manager.py:125 (ElasticManager) — TTL
heartbeats (:40), scale events rewrite the endpoint list and relaunch,
ELASTIC_EXIT_CODE=101 (:33) asks for a re-form.

Pattern per SURVEY §4: fake cluster = launcher processes on localhost,
fault injection = killing one of them.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.launch.controller import ELASTIC_EXIT_CODE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _launcher_cmd(master_port, tmp_path, job, script, nnodes="1:2"):
    return [sys.executable, "-m", "paddle_tpu.distributed.launch",
            f"--master=127.0.0.1:{master_port}", f"--nnodes={nnodes}",
            f"--log_dir={tmp_path}/log", f"--job_id={job}",
            "--elastic_timeout=60", str(script)]


def _env(tmp_path):
    return dict(os.environ, DUMP_DIR=str(tmp_path),
                PYTHONPATH=REPO + os.pathsep
                + os.environ.get("PYTHONPATH", ""))


def _start_master(port):
    """Host the KV master in the TEST process: either launcher may die
    in these scenarios, and the store must survive it (in production a
    dedicated master/etcd plays this role)."""
    from paddle_tpu.distributed.launch.master import KVServer
    return KVServer(port).start()


def test_scale_in_on_pod_death(tmp_path):
    """Kill one of two pods mid-run: the survivor re-forms at world
    size 1 and finishes (reference: scale-in on lease expiry)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import json, os, time
        world = int(os.environ["PADDLE_TRAINERS_NUM"])
        epoch = int(os.environ["PADDLE_ELASTIC_EPOCH"])
        path = os.path.join(os.environ["DUMP_DIR"],
                            "run.%d.%s.json" % (epoch,
                                                os.environ["PADDLE_TRAINER_ID"]))
        with open(path, "w") as f:
            json.dump({"world": world, "epoch": epoch}, f)
        if world > 1:
            time.sleep(120)   # wait to be killed by the scale event
        # world 1 (post scale-in): finish cleanly
    """))
    port = _free_port()
    srv = _start_master(port)
    env = _env(tmp_path)
    procs = [subprocess.Popen(
        _launcher_cmd(port, tmp_path, "ei", script), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for _ in range(2)]
    # let the gang form and children start
    deadline = time.time() + 120
    while time.time() < deadline and not (
            (tmp_path / "run.0.0.json").exists()
            and (tmp_path / "run.0.1.json").exists()):
        time.sleep(0.5)
    assert (tmp_path / "run.0.0.json").exists(), "gang never formed"
    # fault injection: SIGKILL the second launcher (heartbeat stops)
    procs[1].kill()
    procs[1].wait()
    try:
        out, _ = procs[0].communicate(timeout=300)
    finally:
        srv.stop()
    assert procs[0].returncode == 0, out.decode()[-2000:]
    assert b"elastic re-form" in out
    # the survivor relaunched at world size 1, epoch 1
    done = [p for p in tmp_path.glob("run.1.*.json")]
    assert done, "no epoch-1 run recorded"
    rec = json.loads(done[0].read_text())
    assert rec["world"] == 1 and rec["epoch"] == 1


def test_scale_out_admits_new_pod(tmp_path):
    """Start one pod of an elastic 1:2 job, then add a second: the
    running pod re-forms at world size 2 (reference: scale-out on new
    registration)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import json, os, time
        world = int(os.environ["PADDLE_TRAINERS_NUM"])
        epoch = int(os.environ["PADDLE_ELASTIC_EPOCH"])
        path = os.path.join(os.environ["DUMP_DIR"],
                            "run.%d.%s.json" % (epoch,
                                                os.environ["PADDLE_TRAINER_ID"]))
        with open(path, "w") as f:
            json.dump({"world": world, "epoch": epoch}, f)
        if world < 2:
            time.sleep(120)   # hold until the scale-out re-form kills us
    """))
    port = _free_port()
    srv = _start_master(port)
    env = _env(tmp_path)
    first = subprocess.Popen(
        _launcher_cmd(port, tmp_path, "eo", script), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 120
    while time.time() < deadline and not (
            tmp_path / "run.0.0.json").exists():
        time.sleep(0.5)
    assert (tmp_path / "run.0.0.json").exists(), "solo gang never formed"
    second = subprocess.Popen(
        _launcher_cmd(port, tmp_path, "eo", script), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        out1, _ = first.communicate(timeout=300)
        out2, _ = second.communicate(timeout=300)
    finally:
        srv.stop()
    assert first.returncode == 0, out1.decode()[-2000:]
    assert second.returncode == 0, out2.decode()[-2000:]
    # both ranks ran at world 2 in a later epoch
    sized = []
    for p in tmp_path.glob("run.*.json"):
        rec = json.loads(p.read_text())
        if rec["world"] == 2:
            sized.append(rec)
    assert len(sized) >= 2, list(tmp_path.glob("run.*"))


def test_elastic_exit_code_triggers_reform(tmp_path):
    """A child exiting ELASTIC_EXIT_CODE=101 is relaunched via a
    re-form (epoch bump), not counted as a failure."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        epoch = int(os.environ["PADDLE_ELASTIC_EPOCH"])
        sys.exit({ELASTIC_EXIT_CODE} if epoch == 0 else 0)
    """))
    port = _free_port()
    env = _env(tmp_path)
    proc = subprocess.Popen(
        _launcher_cmd(port, tmp_path, "ec", script, nnodes="1"),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 0, out.decode()[-2000:]
    assert b"scale event" in out
