"""Incident flight recorder + in-step numerics telemetry — ISSUE 14.

The contracts under test:

  * the FlightRecorder ring is bounded; a trigger event dumps a
    complete incident bundle (trigger/events/trace/memory/cost/
    fingerprint/manifest) via tmp+rename (no half bundle ever has a
    final name); bundles are rate-limited PER TRIGGER KIND and
    retention-bounded (keep=N); a dump failure never detaches the
    recorder;
  * EVERY trigger kind produces exactly one rate-limited bundle when
    planted for real: perf.drift (configure_peaks + FLAGS_mfu_floor),
    fleet.straggler / fleet.desync (the r14 2-rank KV harness),
    train.anomaly (FLAGS_fault_injection step.data:mode=nan under the
    numerics plane), serve.hung (delay-injected chunk under the serve
    watchdog), watchdog.timeout — with the trigger event inside the
    bundle's JSONL;
  * FLAGS_numerics_stats: the compiled step returns per-layer-bundle
    grad/param/update norms + a first-nonfinite index; train.numerics
    events carry them; a nan step names the first bad layer and the
    StepAnomalyGuard abort report repeats it;
  * JsonlSink size-capped rotation (FLAGS_telemetry_max_log_mb):
    events.jsonl -> .1 -> .2 shifting, drain-flush preserved,
    merge_jsonl_traces reads segments oldest-first;
  * telemetry.span() marks a raising body with error=<type> and
    re-raises (clean spans are unmarked);
  * summary_of is the one shared window derivation (true min/max
    beside the percentiles) and the report CLIs pick it up;
  * tools/incident_report.py renders bundles; --selftest passes
    (tier-1 wiring, like telemetry_report --selftest).
"""
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import telemetry
from paddle_tpu.telemetry import flightrec
from paddle_tpu.telemetry.flightrec import FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    telemetry.reset()
    yield
    from paddle_tpu.framework.flags import set_flags
    telemetry.reset()
    set_flags({"FLAGS_mfu_floor": 0.0, "FLAGS_numerics_stats": False,
               "FLAGS_telemetry_max_log_mb": 0.0,
               "FLAGS_skip_nonfinite_steps": False,
               "FLAGS_stop_check_timeout": 0,
               "FLAGS_max_consecutive_bad_steps": 8})


def _mlp_step():
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    m = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                             paddle.nn.Linear(16, 8))
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    step = TrainStep(m, lambda o, y: paddle.nn.functional.mse_loss(o, y),
                     opt)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    return step, x


def _bundle_events(bundle):
    out = []
    with open(os.path.join(bundle, "events.jsonl")) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------------------
# recorder mechanics

class TestRecorder:
    def test_ring_bounded_and_no_dump_without_trigger(self, tmp_path):
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), ring=16))
        for i in range(100):
            telemetry.emit("train.step", step=i)
        assert len(rec._ring) == 16
        assert rec.bundles() == []

    def test_trigger_dumps_complete_bundle(self, tmp_path):
        rec = telemetry.add_sink(FlightRecorder(str(tmp_path / "inc")))
        for i in range(5):
            telemetry.emit("train.step", step=i, wall_ms=1.0)
        telemetry.emit("perf.drift", label="prog", attained=0.1,
                       floor=0.5)
        bundles = rec.bundles()
        assert len(bundles) == 1
        b = bundles[0]
        assert os.path.basename(b).endswith("perf-drift")
        for f in ("manifest.json", "trigger.json", "events.jsonl",
                  "trace.json", "memory.json", "cost.json",
                  "fingerprint.json"):
            assert os.path.isfile(os.path.join(b, f)), f
        trig = json.load(open(os.path.join(b, "trigger.json")))
        assert trig["event"] == "perf.drift" and trig["label"] == "prog"
        evs = _bundle_events(b)
        assert any(e["event"] == "perf.drift" for e in evs)
        assert sum(1 for e in evs if e["event"] == "train.step") == 5
        trace = json.load(open(os.path.join(b, "trace.json")))
        assert len(trace["traceEvents"]) == len(evs)
        man = json.load(open(os.path.join(b, "manifest.json")))
        assert man["kind"] == "perf.drift" and man["events"] == len(evs)
        fp = json.load(open(os.path.join(b, "fingerprint.json")))
        # resolved FLAGS + the r16 capture-id fingerprint ride along
        assert "FLAGS_numerics_stats" in fp["flags"]
        assert fp["capture_id"]
        # tmp+rename: no half-written directory left behind
        assert not [n for n in os.listdir(tmp_path / "inc")
                    if n.startswith(".tmp-")]

    def test_rate_limit_per_kind_and_distinct_kinds(self, tmp_path):
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=60.0))
        telemetry.emit("perf.drift", label="a")
        telemetry.emit("perf.drift", label="b")   # same kind: limited
        telemetry.emit("serve.hung", kind="decode")  # new kind: dumps
        names = [os.path.basename(b) for b in rec.bundles()]
        assert len(names) == 2, names
        assert sum("perf-drift" in n for n in names) == 1
        assert sum("serve-hung" in n for n in names) == 1
        assert rec.suppressed == {"perf.drift": 1}
        assert telemetry.registry().dump()["counters"][
            "flightrec.suppressed"] == 1

    def test_interval_zero_dumps_every_trigger(self, tmp_path):
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=0.0))
        telemetry.emit("perf.drift", label="a")
        telemetry.emit("perf.drift", label="b")
        assert len(rec.bundles()) == 2

    def test_retention_keeps_newest(self, tmp_path):
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=0.0,
                           keep=2))
        for i in range(5):
            telemetry.emit("perf.drift", label=f"p{i}")
        bundles = rec.bundles()
        assert len(bundles) == 2
        # newest survive: seq 4 and 5
        assert [os.path.basename(b)[:15] for b in bundles] == \
            ["incident-000004", "incident-000005"]
        trig = json.load(open(os.path.join(bundles[-1], "trigger.json")))
        assert trig["label"] == "p4"

    def test_seq_resumes_past_existing_bundles(self, tmp_path):
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=0.0))
        telemetry.emit("perf.drift", label="first")
        telemetry.remove_sink(rec)
        rec2 = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=0.0))
        telemetry.emit("perf.drift", label="second")
        names = [os.path.basename(b) for b in rec2.bundles()]
        assert names[0].startswith("incident-000001")
        assert names[1].startswith("incident-000002")

    def test_dump_failure_never_detaches_recorder(self, tmp_path):
        target = tmp_path / "inc"
        rec = telemetry.add_sink(FlightRecorder(str(target),
                                                interval_s=0.0))
        # make the incidents dir an unwritable FILE: every dump fails
        with open(target, "w") as f:
            f.write("not a dir")
        telemetry.emit("perf.drift", label="x")
        assert rec.errors == 1
        assert rec in telemetry.sinks()     # still attached
        # and the bus keeps delivering to it
        telemetry.emit("train.step", step=1)
        assert rec._ring[-1]["event"] == "train.step"

    def test_bundle_names_carry_rank_and_collision_falls_back(
            self, tmp_path):
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=0.0))
        # a same-named NON-EMPTY bundle already on disk (another
        # same-rank process won the rename; empty dirs are replaced by
        # rename): the dump falls back to a pid-suffixed name instead
        # of silently dropping the incident
        decoy = tmp_path / "inc" / "incident-000001-r0-perf-drift"
        os.makedirs(decoy)
        (decoy / "manifest.json").write_text("{}")
        telemetry.emit("perf.drift", label="x")
        assert rec.errors == 0
        names = sorted(os.path.basename(b) for b in rec.bundles())
        assert names[0] == "incident-000001-r0-perf-drift"
        assert names[1] == \
            f"incident-000001-r0-perf-drift-p{os.getpid()}"
        # the fleet identity rides the NAME once announced
        telemetry.set_rank(3, 4)
        telemetry.emit("perf.drift", label="y")
        assert any("-r3-" in os.path.basename(b)
                   for b in rec.bundles())

    def test_detach_returns_recorder_and_restore_reattaches(
            self, tmp_path):
        rec = flightrec.attach(str(tmp_path / "inc"))
        assert flightrec.detach() is rec
        assert flightrec.attached() is None and rec not in \
            telemetry.sinks()
        assert flightrec.restore(rec) is rec
        assert flightrec.attached() is rec and rec in telemetry.sinks()
        assert flightrec.restore(None) is None    # no-op
        flightrec.detach()

    def test_post_trigger_profile_window(self, tmp_path):
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=0.0,
                           profile_steps=2))
        telemetry.emit("perf.drift", label="x")
        if not rec._profile_ok:     # capability-gated: no-op backend
            pytest.skip("jax.profiler unsupported on this backend")
        assert rec._profile_active and rec._profile_left == 2
        telemetry.emit("train.step", step=1)
        telemetry.emit("train.step", step=2)
        # window closed after K step events; the trace landed in the
        # bundle's profile/ dir
        assert not rec._profile_active
        (b,) = rec.bundles()
        assert os.path.isdir(os.path.join(b, "profile"))

    def test_attach_idempotent_and_flag_armed(self, tmp_path):
        from paddle_tpu.framework.flags import set_flags
        r1 = flightrec.attach(str(tmp_path / "a"))
        assert flightrec.attach(str(tmp_path / "b")) is r1
        flightrec.detach()
        assert flightrec.attached() is None
        set_flags({"FLAGS_flightrec_dir": str(tmp_path / "auto")})
        try:
            r2 = flightrec.maybe_attach()
            assert r2 is not None and r2.dir == str(tmp_path / "auto")
        finally:
            set_flags({"FLAGS_flightrec_dir": ""})
            flightrec.detach()
        assert flightrec.maybe_attach() is None


# ---------------------------------------------------------------------------
# every trigger kind, planted for real (the ISSUE 14 coverage matrix).
# Each plant returns the expected bundle kind; the shared assertion is
# "exactly ONE rate-limited bundle of that kind, trigger event inside".

def _plant_drift():
    """perf.drift via configure_peaks + FLAGS_mfu_floor against a real
    compiled program with an absurd measured wall."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.telemetry import costledger
    compiled = jax.jit(lambda x: x @ x).lower(
        jnp.ones((32, 32), jnp.float32)).compile()
    costledger.ingest("flightrec.test", compiled)
    costledger.observe("flightrec.test", 250.0)
    costledger.configure_peaks(flops_per_sec=1e15,
                               hbm_bytes_per_sec=1e15)
    set_flags({"FLAGS_mfu_floor": 0.5})
    telemetry.cost_report()
    telemetry.cost_report()         # drift persists: edge, no re-fire
    return "perf.drift"


def _plant_straggler(kv):
    """fleet.straggler via the r14 2-rank harness: rank 1's step-3
    wall 10x the fleet's."""
    from paddle_tpu.telemetry.fleet import FleetAggregator, FleetSink
    for step in (1, 2, 3):
        for rank in (0, 1):
            wall = 100.0 if (rank == 1 and step == 3) else 10.0
            s = FleetSink(kv, job_id="fr", rank=rank, world=2, every=1)
            s.record({"event": "train.step", "step": step,
                      "ts": float(step), "wall_ms": wall,
                      "step_ms": wall, "k": 1})
            s.close()
    FleetAggregator(kv, job_id="fr", world=2, skew_ms=50.0).poll()
    return "fleet.straggler"


def _plant_desync(kv):
    """fleet.desync via rank step-counter spread past the threshold."""
    from paddle_tpu.telemetry.fleet import FleetAggregator, FleetSink
    for rank, step in ((0, 1), (1, 40)):
        s = FleetSink(kv, job_id="fr2", rank=rank, world=2, every=1)
        s.record({"event": "train.step", "step": step,
                  "ts": float(step), "wall_ms": 10.0, "step_ms": 10.0,
                  "k": 1})
        s.close()
    agg = FleetAggregator(kv, job_id="fr2", world=2, desync_steps=8)
    agg.poll()
    agg.poll()                      # edge-triggered: no second event
    return "fleet.desync"


def _plant_nan():
    """train.anomaly via FLAGS_fault_injection step.data:mode=nan under
    the numerics plane."""
    from paddle_tpu.distributed import fault
    from paddle_tpu.framework.flags import set_flags
    set_flags({"FLAGS_numerics_stats": True})
    step, x = _mlp_step()
    step(x, x)                      # clean step: ring has history
    with fault.scope("step.data:mode=nan"):
        step(x, x)
    return "train.anomaly"


def _plant_hung_chunk():
    """serve.hung via a delay-injected chunk aging past the serve
    watchdog deadline."""
    from paddle_tpu.distributed import fault
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.inference import ContinuousBatcher
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            intermediate_size=64,
                            num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=64)
    model = LlamaForCausalLM(cfg)
    set_flags({"FLAGS_stop_check_timeout": 0.05})
    try:
        with fault.scope("serve.chunk:step=1:mode=delay:secs=0.6"):
            bat = ContinuousBatcher(model, max_batch_size=1, max_len=32,
                                    chunk=4, prefill_chunk=4)
            bat.submit(np.arange(1, 5, dtype=np.int32), 4)
            bat.run()
    finally:
        set_flags({"FLAGS_stop_check_timeout": 0})
    return "serve.hung"


def _plant_watchdog():
    """watchdog.timeout via a watched block aging past its deadline."""
    from paddle_tpu.distributed.watchdog import watched
    with watched("flightrec probe", timeout=0.05):
        time.sleep(0.6)             # monitor polls at 0.25s
    return "watchdog.timeout"


_PLANTS = {
    "drift": (_plant_drift, False),
    "straggler": (_plant_straggler, True),
    "desync": (_plant_desync, True),
    "nan": (_plant_nan, False),
    "hung_chunk": (_plant_hung_chunk, False),
    "watchdog": (_plant_watchdog, False),
}


class TestTriggerKinds:
    @pytest.mark.parametrize("name", sorted(_PLANTS))
    def test_planted_trigger_lands_one_bundle(self, name, tmp_path):
        plant, needs_kv = _PLANTS[name]
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=60.0))
        if needs_kv:
            from paddle_tpu.distributed.launch.master import (KVClient,
                                                              KVServer)
            server = KVServer(0, host="127.0.0.1").start()
            try:
                kind = plant(KVClient(f"127.0.0.1:{server.port}"))
            finally:
                server.stop()
        else:
            kind = plant()
        # async emitters (watchdog monitor thread): wait for the dump
        deadline = time.monotonic() + 3.0
        want = kind.replace(".", "-")
        while time.monotonic() < deadline:
            if any(want in b for b in rec.bundles()):
                break
            time.sleep(0.05)
        matching = [b for b in rec.bundles() if want in b]
        assert len(matching) == 1, (kind, rec.bundles())
        evs = _bundle_events(matching[0])
        assert any(e.get("event") == kind for e in evs), kind
        if name == "nan":
            # the numerics plane named the first bad layer, inside the
            # SAME bundle (the acceptance criterion's nan case)
            nums = [e for e in evs if e.get("event") == "train.numerics"
                    and e.get("first_nonfinite", -1) >= 0]
            assert nums and nums[0]["first_nonfinite_layer"]


class TestPlantedAnomalyE2E:
    def test_step_begin_nan_spec_produces_named_bundle(self, tmp_path):
        """The acceptance wording verbatim: a run under
        FLAGS_fault_injection=step.begin:mode=nan produces exactly one
        rate-limited bundle per fired trigger kind, each with the
        trigger event and a non-empty ring inside, and the nonfinite
        bundle carries a train.numerics event naming the first bad
        layer."""
        from paddle_tpu.distributed import fault
        from paddle_tpu.framework.flags import set_flags
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=60.0))
        set_flags({"FLAGS_numerics_stats": True})
        step, x = _mlp_step()
        step(x, x)
        with fault.scope("step.begin:mode=nan"):
            loss = step(x, x)
        assert np.isnan(float(loss))    # begin-point nan really plants
        for kind in ("fault.hit", "train.anomaly"):
            matching = [b for b in rec.bundles()
                        if kind.replace(".", "-") in b]
            assert len(matching) == 1, (kind, rec.bundles())
            evs = _bundle_events(matching[0])
            assert evs                  # non-empty ring window
            assert any(e.get("event") == kind for e in evs)
        (anom,) = [b for b in rec.bundles() if "train-anomaly" in b]
        nums = [e for e in _bundle_events(anom)
                if e.get("event") == "train.numerics"
                and e.get("first_nonfinite", -1) >= 0]
        assert nums and nums[0]["first_nonfinite_layer"] == "0"


# ---------------------------------------------------------------------------
# numerics plane

class TestNumerics:
    def test_bundles_of_grouping(self):
        from paddle_tpu.telemetry.numerics import bundles_of
        labels, assign = bundles_of(
            ["layers.0.attn.q.weight", "layers.0.mlp.w", "layers.1.w",
             "embed.weight", "weight"])
        assert labels == ["layers.0", "layers.1", "embed", "weight"]
        assert assign == [0, 0, 1, 2, 3]

    def test_graph_stats_values(self):
        import jax.numpy as jnp
        from paddle_tpu.telemetry.numerics import graph_stats
        params = [jnp.ones((2,)), jnp.ones((2,))]
        grads = [jnp.asarray([3.0, 4.0]), jnp.asarray([0.0, 0.0])]
        new = [jnp.asarray([1.1, 1.0]), jnp.ones((2,))]
        st = graph_stats([0, 1], 2, params, grads, new)
        assert np.allclose(np.asarray(st["grad_norm"]), [5.0, 0.0])
        assert np.allclose(np.asarray(st["param_norm"]),
                           [np.sqrt(2)] * 2)
        assert int(st["first_nonfinite"]) == -1
        grads[1] = jnp.asarray([np.nan, 0.0])
        st = graph_stats([0, 1], 2, params, grads, new)
        assert int(st["first_nonfinite"]) == 1

    def test_trainstep_emits_numerics_events(self):
        from paddle_tpu.framework.flags import set_flags
        set_flags({"FLAGS_numerics_stats": True})
        step, x = _mlp_step()
        probe = telemetry.add_sink(telemetry.MemorySink())
        step(x, x)
        xs = paddle.to_tensor(np.ones((3, 4, 8), np.float32))
        step.run_steps(xs, xs)
        evs = [r for r in probe.records
               if r["event"] == "train.numerics"]
        # one per compiled call (the window's trend sample)
        assert len(evs) == 2
        # the positional bundle labels ride the FIRST event per
        # trainer only (they are identical every step)
        assert len(evs[0]["bundles"]) == len(evs[0]["grad_norm"])
        e = evs[-1]
        assert "bundles" not in e
        assert e["trainer"] == "jit" and e["step"] == 4
        assert len(e["grad_norm"]) == len(evs[0]["bundles"]) \
            == len(e["param_norm"]) == len(e["update_ratio"])
        assert e["first_nonfinite"] == -1
        assert all(v >= 0 for v in e["update_ratio"])
        # registry histograms accumulate sink or not
        d = telemetry.registry().dump()
        assert d["histograms"]["numerics.grad_norm"]["count"] >= 2

    def test_record_window_emits_first_bad_and_last(self):
        # fused window where steps 0 AND 2 go nonfinite: the first bad
        # step is emitted for attribution and the LAST step is still
        # emitted as the trend sample (regression: the last-step emit
        # used to be skipped whenever the last step was bad at all)
        from paddle_tpu.telemetry import numerics
        probe = telemetry.add_sink(telemetry.MemorySink())
        stats = {"grad_norm": np.array([[1.0], [2.0], [3.0]]),
                 "param_norm": np.ones((3, 1)),
                 "update_ratio": np.ones((3, 1)),
                 "first_nonfinite": np.array([0, -1, 0])}
        bad = numerics.record("jit", 3, 3, ["fc"], stats)
        assert bad == "fc"
        nums = [r for r in probe.records
                if r["event"] == "train.numerics"]
        assert [e["step"] for e in nums] == [1, 3]
        assert all(e["first_nonfinite_layer"] == "fc" for e in nums)
        anoms = [r for r in probe.records
                 if r["event"] == "train.anomaly"]
        assert len(anoms) == 1 and anoms[0]["step"] == 1

    def test_flags_off_step_returns_plain_tuple(self):
        # numerics off: the compiled call keeps its historic 4-tuple
        # (the bench byte-identical assert covers the HLO half)
        step, x = _mlp_step()
        step(x, x)
        assert not getattr(step, "_numerics", True)

    def test_sharded_guard_abort_names_layer(self):
        import jax
        from paddle_tpu.distributed import fault, guard
        from paddle_tpu.distributed.topology import build_mesh
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.parallel import ShardedTrainStep
        set_flags({"FLAGS_numerics_stats": True,
                   "FLAGS_skip_nonfinite_steps": True,
                   "FLAGS_max_consecutive_bad_steps": 1})
        paddle.seed(0)
        m = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                 paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 8))
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = ShardedTrainStep(
            m, opt, build_mesh(devices=jax.devices()[:1]),
            loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        with pytest.raises(guard.BadStepBudgetExceeded) as ei:
            with fault.scope("step.data:mode=nan:times=*"):
                step(x, x)
        assert "first nonfinite layer: 0" in str(ei.value)

    def test_offload_pipeline_per_layer_bundles(self):
        import jax
        from paddle_tpu.distributed.topology import build_mesh
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
        from paddle_tpu.parallel import OffloadPipelineStep
        set_flags({"FLAGS_numerics_stats": True})
        paddle.seed(7)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=3,
                          num_attention_heads=2, num_key_value_heads=2,
                          max_position_embeddings=32, dtype="float32")
        m = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        st = OffloadPipelineStep(m, opt,
                                 build_mesh(devices=jax.devices()[:1]))
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (2, 16))
            .astype(np.int32))
        probe = telemetry.add_sink(telemetry.MemorySink())
        st(ids, ids)
        evs = [r for r in probe.records
               if r["event"] == "train.numerics"]
        assert len(evs) == 1
        e = evs[0]
        # one bundle per scanned layer + the pre/post tail
        assert e["bundles"] == ["layer0", "layer1", "layer2", "tail"]
        assert e["first_nonfinite"] == -1
        assert all(v > 0 for v in e["grad_norm"])


# ---------------------------------------------------------------------------
# satellites: rotation, span error, summary_of

class TestJsonlRotation:
    def test_rotation_shifts_segments_and_merge_reads_in_order(
            self, tmp_path):
        from paddle_tpu.telemetry import JsonlSink
        from paddle_tpu.telemetry.fleet import (load_jsonl, log_segments,
                                                merge_jsonl_traces)
        path = str(tmp_path / "events.jsonl")
        sink = JsonlSink(path, max_mb=0.0003)   # ~300 bytes per segment
        n = 40
        for i in range(n):
            sink.record({"ts": float(i), "event": "train.step", "i": i})
        sink.close()
        assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
        # oldest-first segment order, every record present exactly once,
        # in emit order across the rotation boundaries
        segs = log_segments(path)
        assert segs[-1] == path
        recs = [r for s in segs for r in load_jsonl(s)]
        assert [r["i"] for r in recs] == list(range(n))
        doc = merge_jsonl_traces([path])
        data = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
        assert len(data) == n

    def test_flag_drives_rotation_and_default_off(self, tmp_path):
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.telemetry import JsonlSink
        p1 = str(tmp_path / "a.jsonl")
        sink = JsonlSink(p1)                    # flag at default: off
        for i in range(50):
            sink.record({"event": "x", "pad": "y" * 64})
        sink.close()
        assert not os.path.exists(p1 + ".1")
        set_flags({"FLAGS_telemetry_max_log_mb": 0.0003})
        try:
            p2 = str(tmp_path / "b.jsonl")
            sink = JsonlSink(p2)
            for i in range(50):
                sink.record({"event": "x", "pad": "y" * 64})
            sink.close()
            assert os.path.exists(p2 + ".1")
        finally:
            set_flags({"FLAGS_telemetry_max_log_mb": 0.0})

    def test_file_object_sink_never_rotates(self, tmp_path):
        import io
        from paddle_tpu.telemetry import JsonlSink
        buf = io.StringIO()
        sink = JsonlSink(buf, max_mb=0.0001)    # not owned: cap ignored
        for i in range(50):
            sink.record({"event": "x", "pad": "y" * 64})
        assert len(buf.getvalue().splitlines()) == 50


class TestSpanError:
    def test_raising_span_marked_and_reraises(self):
        probe = telemetry.add_sink(telemetry.MemorySink())
        with pytest.raises(ValueError):
            with telemetry.span("phase.x", step=1):
                raise ValueError("boom")
        with telemetry.span("phase.x", step=2):
            pass
        bad, clean = probe.records
        assert bad["error"] == "ValueError" and bad["step"] == 1
        assert "dur_ms" in bad
        assert "error" not in clean and clean["step"] == 2


class TestSummaryOf:
    def test_true_min_max_beside_percentiles(self):
        s = telemetry.summary_of([5.0, 1.0, 3.0, 100.0])
        assert s["count"] == 4
        assert s["min"] == 1.0 and s["max"] == 100.0
        assert s["p50"] == 3.0 or s["p50"] == 5.0
        assert telemetry.summary_of([]) == {
            "count": 0, "min": 0.0, "max": 0.0, "p50": 0.0,
            "p90": 0.0, "p99": 0.0}

    def test_histogram_summary_has_true_min_max(self):
        h = telemetry.histogram("fr.test", window=4)
        for v in (50.0, 1.0, 2.0, 3.0, 4.0, 5.0):
            h.observe(v)            # 50.0 rotated out of the window
        s = h.summary()
        assert s["min"] == 1.0 and s["max"] == 50.0   # lifetime-true

    def test_report_cli_step_ms_min_max(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import telemetry_report as cli
        finally:
            sys.path.pop(0)
        events = [{"ts": float(i), "event": "train.step", "step": i,
                   "wall_ms": w, "step_ms": w, "k": 1}
                  for i, w in enumerate((9.0, 1.0, 2.0, 2.5))]
        events[0]["cold"] = True    # excluded from the summary
        rep = cli.analyze(events)
        assert rep["step_ms"]["min"] == 1.0
        assert rep["step_ms"]["max"] == 2.5
        assert cli.render(rep)

    def test_serving_latency_block_carries_min_max(self):
        # the stats() block reads the shared derivation — synthesize
        # the window rather than running a server
        from paddle_tpu.telemetry import summary_of
        s = summary_of([2.0, 40.0, 3.0])
        assert set(s) >= {"count", "min", "max", "p50", "p90", "p99"}
        assert s["max"] == 40.0


# ---------------------------------------------------------------------------
# incident report CLI

class TestIncidentReportCLI:
    def _cli(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import incident_report as cli
        finally:
            sys.path.pop(0)
        return cli

    def test_selftest(self):
        # tier-1 wiring (acceptance): plants a drift AND a nan fault,
        # asserts one bundle each with the right trigger, renders both
        assert self._cli().main(["--selftest"]) == 0

    def test_render_bundle_and_directory(self, tmp_path, capsys):
        cli = self._cli()
        rec = telemetry.add_sink(
            FlightRecorder(str(tmp_path / "inc"), interval_s=0.0))
        telemetry.emit("train.step", step=1, wall_ms=2.0)
        telemetry.emit("train.numerics", trainer="jit", step=1,
                       bundles=["fc"], grad_norm=[1.5],
                       param_norm=[2.0], update_ratio=[0.001],
                       first_nonfinite=-1)
        telemetry.emit("perf.drift", label="prog", attained=0.1)
        (b,) = rec.bundles()
        rep = cli.analyze(b)
        assert rep["kind"] == "perf.drift"
        assert rep["numerics"]["samples"] == 1
        assert rep["timeline"][-1]["event"] == "perf.drift"
        out = cli.render(rep)
        assert "perf.drift" in out and "numerics" in out
        # directory mode renders every bundle; missing path errors
        assert cli.main([str(tmp_path / "inc")]) == 0
        assert "incident:" in capsys.readouterr().out
        assert cli.main([str(tmp_path / "nothing")]) == 1
