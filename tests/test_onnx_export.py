"""ONNX export validation (paddle_tpu/onnx.py).

No onnx package ships in this environment, so the test carries a
minimal protobuf wire-format DECODER plus a numpy interpreter for the
emitted op set: the exported ModelProto is parsed back and EXECUTED,
and its outputs must match the live model — end-to-end evidence the
bytes constitute a correct ONNX graph.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


# -- minimal proto reader ---------------------------------------------------
def _read_varint(buf, i):
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _fields(buf):
    i, out = 0, []
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        else:
            raise ValueError(f"wire type {wt}")
        out.append((field, v))
    return out


_DT_NP = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
          11: np.float64}


def _tensor(buf):
    dims, dt, name, raw = [], 1, "", b""
    for f, v in _fields(buf):
        if f == 1:
            dims.append(v)
        elif f == 2:
            dt = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
    return name, np.frombuffer(raw, _DT_NP[dt]).reshape(dims)


def _parse_model(raw):
    graph = None
    for f, v in _fields(raw):
        if f == 7:
            graph = v
    assert graph is not None, "no GraphProto"
    nodes, inits, inputs, outputs = [], {}, [], []
    for f, v in _fields(graph):
        if f == 1:
            ins, outs, op, attrs = [], [], "", {}
            for nf, nv in _fields(v):
                if nf == 1:
                    ins.append(nv.decode())
                elif nf == 2:
                    outs.append(nv.decode())
                elif nf == 4:
                    op = nv.decode()
                elif nf == 5:
                    aname, ints, i_val, t_val = "", [], None, None
                    for af, av in _fields(nv):
                        if af == 1:
                            aname = av.decode()
                        elif af in (2, 3):
                            i_val = av
                        elif af == 8:
                            ints.append(av)
                        elif af == 5:
                            t_val = _tensor(av)[1]
                    attrs[aname] = (t_val if t_val is not None else
                                    (ints if ints else i_val))
            nodes.append((op, ins, outs, attrs))
        elif f == 5:
            n, t = _tensor(v)
            inits[n] = t
        elif f == 11:
            inputs.append(v)
        elif f == 12:
            outputs.append(v)

    def vi_name(buf):
        for f2, v2 in _fields(buf):
            if f2 == 1:
                return v2.decode()
    return nodes, inits, [vi_name(b) for b in inputs], \
        [vi_name(b) for b in outputs]


def _run_graph(nodes, env):
    for op, ins, outs, attrs in nodes:
        a = [env[i] for i in ins]
        if op == "MatMul":
            r = a[0] @ a[1]
        elif op == "Add":
            r = a[0] + a[1]
        elif op == "Sub":
            r = a[0] - a[1]
        elif op == "Mul":
            r = a[0] * a[1]
        elif op == "Div":
            r = a[0] / a[1]
        elif op == "Tanh":
            r = np.tanh(a[0])
        elif op == "Sigmoid":
            r = 1 / (1 + np.exp(-a[0]))
        elif op == "Max":
            r = np.maximum(a[0], a[1])
        elif op == "Exp":
            r = np.exp(a[0])
        elif op == "Reshape":
            r = a[0].reshape([int(d) for d in a[1]])
        elif op == "Transpose":
            r = np.transpose(a[0], attrs["perm"])
        elif op == "Expand":
            r = np.broadcast_to(a[0], [int(d) for d in a[1]]).copy()
        elif op == "Cast":
            r = a[0].astype(_DT_NP[int(attrs["to"])])
        elif op in ("Identity",):
            r = a[0]
        elif op == "ReduceSum":
            r = a[0].sum(tuple(int(d) for d in a[1]))
        elif op == "ReduceMax":
            if len(a) > 1:                    # opset>=18: axes input
                r = a[0].max(tuple(int(d) for d in a[1]))
            else:
                r = a[0].max(tuple(int(d) for d in attrs["axes"]))
        elif op == "Pow":
            r = a[0] ** a[1]
        elif op == "Reciprocal":
            r = 1.0 / a[0]
        elif op == "Sqrt":
            r = np.sqrt(a[0])
        elif op == "Neg":
            r = -a[0]
        elif op == "Abs":
            r = np.abs(a[0])
        elif op == "Erf":
            from scipy import special as sps
            r = sps.erf(a[0])
        elif op == "Min":
            r = np.minimum(a[0], a[1])
        elif op == "Conv":
            import torch
            pads = attrs.get("pads", [0, 0, 0, 0])
            nd = len(pads) // 2
            assert pads[:nd] == pads[nd:], "asymmetric pads"
            fn = {1: torch.nn.functional.conv1d,
                  2: torch.nn.functional.conv2d,
                  3: torch.nn.functional.conv3d}[nd]
            r = fn(torch.from_numpy(a[0]), torch.from_numpy(a[1]),
                   None if len(a) < 3 else torch.from_numpy(a[2]),
                   stride=[int(s) for s in attrs["strides"]],
                   padding=[int(x) for x in pads[:nd]],
                   dilation=[int(d) for d in attrs["dilations"]],
                   groups=int(attrs.get("group", 1))).numpy()
        elif op == "MaxPool":
            import torch
            pads = attrs.get("pads", [0, 0, 0, 0])
            nd = len(pads) // 2
            r = torch.nn.functional.max_pool2d(
                torch.from_numpy(a[0]),
                [int(k) for k in attrs["kernel_shape"]],
                stride=[int(s) for s in attrs["strides"]],
                padding=[int(x) for x in pads[:nd]]).numpy()
        elif op == "Concat":
            r = np.concatenate(a, axis=int(attrs["axis"]))
        elif op == "Slice":
            starts, ends = a[1], a[2]
            axes = a[3] if len(a) > 3 else np.arange(len(starts))
            steps = a[4] if len(a) > 4 else np.ones(len(starts),
                                                    np.int64)
            sl = [slice(None)] * a[0].ndim
            for s_, e_, ax, st in zip(starts, ends, axes, steps):
                sl[int(ax)] = slice(int(s_), int(e_), int(st))
            r = a[0][tuple(sl)]
        elif op == "Pad":
            pads = a[1]
            nd = len(pads) // 2
            width = [(int(pads[i]), int(pads[i + nd]))
                     for i in range(nd)]
            val = float(a[2]) if len(a) > 2 else 0.0
            r = np.pad(a[0], width, constant_values=val)
        elif op == "Gather":
            r = np.take(a[0], a[1].astype(np.int64),
                        axis=int(attrs.get("axis", 0)))
        elif op == "Unsqueeze":
            r = np.expand_dims(a[0], int(a[1][0]))
        elif op == "ArgMax":
            r = np.argmax(a[0], axis=int(attrs["axis"]))
        elif op == "Where":
            r = np.where(a[0], a[1], a[2])
        elif op == "Less":
            r = a[0] < a[1]
        elif op == "LessOrEqual":
            r = a[0] <= a[1]
        elif op == "Greater":
            r = a[0] > a[1]
        elif op == "GreaterOrEqual":
            r = a[0] >= a[1]
        elif op == "Equal":
            r = a[0] == a[1]
        else:
            raise NotImplementedError(op)
        env[outs[0]] = r
    return env


def test_onnx_export_mlp_roundtrip(tmp_path):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    m.eval()
    from paddle_tpu.static import InputSpec
    path = paddle.onnx.export(
        m, str(tmp_path / "mlp"),
        input_spec=[InputSpec([3, 4], "float32")], format="onnx")
    raw = open(path, "rb").read()
    nodes, inits, inputs, outputs = _parse_model(raw)
    assert inputs == ["x0"] and len(outputs) == 1
    assert any(op == "MatMul" for op, *_ in nodes)

    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    env = dict(inits)
    env["x0"] = x
    env = _run_graph(nodes, env)
    got = env[outputs[0]]
    want = np.asarray(m(paddle.to_tensor(x)).value)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_onnx_export_unsupported_raises(tmp_path):
    class WithSort(nn.Layer):
        def forward(self, x):
            return paddle.sort(x)

    from paddle_tpu.static import InputSpec
    with pytest.raises(NotImplementedError, match="primitive"):
        paddle.onnx.export(WithSort(), str(tmp_path / "bad"),
                           input_spec=[InputSpec([4], "float32")],
                           format="onnx")


def test_onnx_stablehlo_format_still_works(tmp_path):
    paddle.seed(0)
    m = nn.Linear(4, 2)
    from paddle_tpu.static import InputSpec
    p = paddle.onnx.export(m, str(tmp_path / "lin"),
                           input_spec=[InputSpec([2, 4], "float32")])
    loaded = paddle.onnx.load(p)
    x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(loaded(paddle.to_tensor(x)).value),
        np.asarray(m(paddle.to_tensor(x)).value), rtol=1e-5)


def test_onnx_export_resnet18_roundtrip(tmp_path):
    """Round-5 verdict item 7: a CNN (conv / maxpool / bn / residual
    adds / pooling / fc) exports to ONNX, and decoding+executing the
    bytes reproduces the eager forward."""
    from paddle_tpu.vision.models import resnet18
    from paddle_tpu.onnx import export_onnx
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    m = resnet18(num_classes=10)
    m.eval()
    path = export_onnx(m, str(tmp_path / "rn18"),
                       input_spec=[InputSpec([1, 3, 32, 32])])
    raw = open(path, "rb").read()
    nodes, inits, in_names, out_names = _parse_model(raw)
    x = np.random.RandomState(0).randn(1, 3, 32, 32).astype(np.float32)
    env = dict(inits)
    env[in_names[0]] = x
    env = _run_graph(nodes, env)
    got = env[out_names[0]]
    want = np.asarray(m(paddle.to_tensor(x)).value)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_onnx_export_pad_slice_concat_gather_roundtrip(tmp_path):
    """The round-5 primitive additions in one graph: pad, slice,
    concat, gather, interpolate-free manipulation ops."""
    import jax.numpy as jnp
    from paddle_tpu.onnx import export_onnx
    from paddle_tpu.static import InputSpec

    class Manip(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(16, 8)

        def forward(self, x, idx):
            p = paddle.nn.functional.pad(
                x.reshape([1, 1, 4, 8]), [1, 1, 0, 0],
                value=0.5).reshape([4, 10])
            s = p[:, 1:9]
            c = paddle.concat([s, x], axis=1)
            e = self.emb(idx)
            return paddle.matmul(c, paddle.ones((16, 8))) + e

    paddle.seed(3)
    m = Manip()
    m.eval()
    x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    idx = np.array([[1], [3], [0], [7]], np.int64)
    want = np.asarray(m(paddle.to_tensor(x),
                        paddle.to_tensor(idx)).value)
    path = export_onnx(m, str(tmp_path / "manip"),
                       input_spec=[InputSpec([4, 8]),
                                   InputSpec([4, 1], dtype="int64")])
    nodes, inits, in_names, out_names = _parse_model(
        open(path, "rb").read())
    env = dict(inits)
    env[in_names[0]] = x
    env[in_names[1]] = idx
    env = _run_graph(nodes, env)
    np.testing.assert_allclose(env[out_names[0]], want, rtol=1e-4,
                               atol=1e-4)


def test_onnx_opset_version_honored(tmp_path):
    """opset_version is validated and changes the emitted encodings."""
    from paddle_tpu.onnx import export_onnx
    from paddle_tpu.static import InputSpec

    class MaxNet(nn.Layer):
        def forward(self, x):
            return paddle.max(x, axis=1)

    m = MaxNet()
    x = np.random.RandomState(0).randn(3, 5).astype(np.float32)
    with pytest.raises(ValueError, match="opset_version 11"):
        export_onnx(m, str(tmp_path / "bad"),
                    input_spec=[InputSpec([3, 5])],
                    opset_version=11)
    for opset in (13, 18):
        path = export_onnx(m, str(tmp_path / f"m{opset}"),
                           input_spec=[InputSpec([3, 5])],
                           opset_version=opset)
        nodes, inits, in_names, out_names = _parse_model(
            open(path, "rb").read())
        rm = [n for n in nodes if n[0] == "ReduceMax"]
        assert rm, nodes
        # opset>=18: axes ride as a second INPUT; before: attribute
        assert (len(rm[0][1]) == 2) == (opset >= 18)
        env = dict(inits)
        env[in_names[0]] = x
        env = _run_graph(nodes, env)
        np.testing.assert_allclose(env[out_names[0]], x.max(1),
                                   rtol=1e-6)
