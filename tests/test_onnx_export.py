"""ONNX export validation (paddle_tpu/onnx.py).

No onnx package ships in this environment, so the test carries a
minimal protobuf wire-format DECODER plus a numpy interpreter for the
emitted op set: the exported ModelProto is parsed back and EXECUTED,
and its outputs must match the live model — end-to-end evidence the
bytes constitute a correct ONNX graph.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


# -- minimal proto reader ---------------------------------------------------
def _read_varint(buf, i):
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _fields(buf):
    i, out = 0, []
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        else:
            raise ValueError(f"wire type {wt}")
        out.append((field, v))
    return out


_DT_NP = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
          11: np.float64}


def _tensor(buf):
    dims, dt, name, raw = [], 1, "", b""
    for f, v in _fields(buf):
        if f == 1:
            dims.append(v)
        elif f == 2:
            dt = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
    return name, np.frombuffer(raw, _DT_NP[dt]).reshape(dims)


def _parse_model(raw):
    graph = None
    for f, v in _fields(raw):
        if f == 7:
            graph = v
    assert graph is not None, "no GraphProto"
    nodes, inits, inputs, outputs = [], {}, [], []
    for f, v in _fields(graph):
        if f == 1:
            ins, outs, op, attrs = [], [], "", {}
            for nf, nv in _fields(v):
                if nf == 1:
                    ins.append(nv.decode())
                elif nf == 2:
                    outs.append(nv.decode())
                elif nf == 4:
                    op = nv.decode()
                elif nf == 5:
                    aname, ints, i_val, t_val = "", [], None, None
                    for af, av in _fields(nv):
                        if af == 1:
                            aname = av.decode()
                        elif af == 2:
                            i_val = av
                        elif af == 8:
                            ints.append(av)
                        elif af == 5:
                            t_val = _tensor(av)[1]
                    attrs[aname] = (t_val if t_val is not None else
                                    (ints if ints else i_val))
            nodes.append((op, ins, outs, attrs))
        elif f == 5:
            n, t = _tensor(v)
            inits[n] = t
        elif f == 11:
            inputs.append(v)
        elif f == 12:
            outputs.append(v)

    def vi_name(buf):
        for f2, v2 in _fields(buf):
            if f2 == 1:
                return v2.decode()
    return nodes, inits, [vi_name(b) for b in inputs], \
        [vi_name(b) for b in outputs]


def _run_graph(nodes, env):
    for op, ins, outs, attrs in nodes:
        a = [env[i] for i in ins]
        if op == "MatMul":
            r = a[0] @ a[1]
        elif op == "Add":
            r = a[0] + a[1]
        elif op == "Sub":
            r = a[0] - a[1]
        elif op == "Mul":
            r = a[0] * a[1]
        elif op == "Div":
            r = a[0] / a[1]
        elif op == "Tanh":
            r = np.tanh(a[0])
        elif op == "Sigmoid":
            r = 1 / (1 + np.exp(-a[0]))
        elif op == "Max":
            r = np.maximum(a[0], a[1])
        elif op == "Exp":
            r = np.exp(a[0])
        elif op == "Reshape":
            r = a[0].reshape([int(d) for d in a[1]])
        elif op == "Transpose":
            r = np.transpose(a[0], attrs["perm"])
        elif op == "Expand":
            r = np.broadcast_to(a[0], [int(d) for d in a[1]]).copy()
        elif op == "Cast":
            r = a[0].astype(_DT_NP[int(attrs["to"])])
        elif op in ("Identity",):
            r = a[0]
        elif op == "ReduceSum":
            r = a[0].sum(tuple(int(d) for d in a[1]))
        elif op == "Pow":
            r = a[0] ** a[1]
        else:
            raise NotImplementedError(op)
        env[outs[0]] = r
    return env


def test_onnx_export_mlp_roundtrip(tmp_path):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    m.eval()
    from paddle_tpu.static import InputSpec
    path = paddle.onnx.export(
        m, str(tmp_path / "mlp"),
        input_spec=[InputSpec([3, 4], "float32")], format="onnx")
    raw = open(path, "rb").read()
    nodes, inits, inputs, outputs = _parse_model(raw)
    assert inputs == ["x0"] and len(outputs) == 1
    assert any(op == "MatMul" for op, *_ in nodes)

    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    env = dict(inits)
    env["x0"] = x
    env = _run_graph(nodes, env)
    got = env[outputs[0]]
    want = np.asarray(m(paddle.to_tensor(x)).value)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_onnx_export_unsupported_raises(tmp_path):
    class WithSort(nn.Layer):
        def forward(self, x):
            return paddle.sort(x)

    from paddle_tpu.static import InputSpec
    with pytest.raises(NotImplementedError, match="primitive"):
        paddle.onnx.export(WithSort(), str(tmp_path / "bad"),
                           input_spec=[InputSpec([4], "float32")],
                           format="onnx")


def test_onnx_stablehlo_format_still_works(tmp_path):
    paddle.seed(0)
    m = nn.Linear(4, 2)
    from paddle_tpu.static import InputSpec
    p = paddle.onnx.export(m, str(tmp_path / "lin"),
                           input_spec=[InputSpec([2, 4], "float32")])
    loaded = paddle.onnx.load(p)
    x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(loaded(paddle.to_tensor(x)).value),
        np.asarray(m(paddle.to_tensor(x)).value), rtol=1e-5)
