"""Parameter server: sharded sparse tables, pull/push, fleet lifecycle.

Reference tests being matched: `test/legacy_test/test_dist_fleet_ps*.py`
(PS training via fleet role env) and the sparse-table semantics of
`paddle/fluid/distributed/ps/table/memory_sparse_table.cc` (lazy init,
server-side optimizer, duplicate-id grad merge).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (SparseTable, DenseTable, PSServer,
                                       PSClient, DistributedEmbedding)
from paddle_tpu.distributed import fleet


@pytest.fixture
def two_servers():
    servers = []
    for _ in range(2):
        s = PSServer(port=0)
        for t in (SparseTable("emb", dim=4, lr=0.5),
                  SparseTable("emb_ada", dim=4, optimizer="adagrad",
                              lr=0.5)):
            s.register_table(t)
        s.start()
        servers.append(s)
    yield servers
    for s in servers:
        s.stop()


class TestTables:
    def test_deterministic_lazy_init(self):
        a = SparseTable("t", dim=8)
        b = SparseTable("t", dim=8)
        np.testing.assert_array_equal(a.pull([3, 7]), b.pull([3, 7]))
        c = SparseTable("other", dim=8)
        assert not np.allclose(a.pull([3]), c.pull([3]))

    def test_push_sgd_and_duplicate_merge(self):
        t = SparseTable("t", dim=2, lr=1.0)
        before = t.pull([5])[0].copy()
        # duplicate id in one push must ACCUMULATE, not last-write-win
        t.push([5, 5], np.array([[1., 0.], [2., 0.]], np.float32))
        after = t.pull([5])[0]
        np.testing.assert_allclose(after, before - [3., 0.], rtol=1e-6)

    def test_adagrad_scales_update(self):
        t = SparseTable("t", dim=1, optimizer="adagrad", lr=1.0)
        before = t.pull([1])[0].copy()
        t.push([1], np.array([[2.0]], np.float32))
        # first adagrad step: -lr * g / sqrt(g^2) = -1.0
        np.testing.assert_allclose(t.pull([1])[0], before - 1.0,
                                   rtol=1e-5)

    def test_dense_table_roundtrip(self):
        t = DenseTable("d", (3, 2), lr=0.1)
        t.set(np.ones((3, 2), np.float32))
        t.push(np.full((3, 2), 2.0, np.float32))
        np.testing.assert_allclose(t.pull(), 0.8 * np.ones((3, 2)),
                                   rtol=1e-6)


class TestClientServer:
    def test_sharded_pull_matches_local_tables(self, two_servers):
        client = PSClient([s.endpoint for s in two_servers])
        ids = np.array([0, 1, 2, 3, 9, 2], np.int64)  # mixed shards + dup
        rows = client.pull_sparse("emb", ids)
        assert rows.shape == (6, 4)
        # shard routing: id % 2 selects the server
        for i, rid in enumerate(ids):
            local = two_servers[rid % 2].table("emb").pull([rid])[0]
            np.testing.assert_allclose(rows[i], local)
        np.testing.assert_allclose(rows[2], rows[5])  # duplicate id

    def test_push_routes_to_owning_shard(self, two_servers):
        client = PSClient([s.endpoint for s in two_servers])
        ids = np.array([4, 7], np.int64)
        before = client.pull_sparse("emb", ids)
        client.push_sparse("emb", ids,
                           np.ones((2, 4), np.float32))
        after = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(after, before - 0.5, rtol=1e-5)
        # rows landed on their owning servers only
        assert len(two_servers[0].table("emb")) == 1  # id 4
        assert len(two_servers[1].table("emb")) == 1  # id 7

    def test_dense_single_home_by_name_hash(self, two_servers):
        """Dense tables are single-homed on crc32(name) % n_servers:
        pushes land only on the home server's copy, pulls read it back,
        and distinct names spread across the fleet (advisor r5 item 5 —
        previously every dense call hit endpoint 0)."""
        import zlib
        names = ["w_a", "w_b", "w_c", "w_d"]
        for s in two_servers:          # register everywhere (harmless)
            for n in names:
                s.register_table(DenseTable(n, (2, 2), lr=1.0))
        client = PSClient([s.endpoint for s in two_servers])
        homes = {n: zlib.crc32(n.encode()) % 2 for n in names}
        assert set(homes.values()) == {0, 1}  # names actually spread
        for n in names:
            client.push_dense(n, np.ones((2, 2), np.float32))
            home, other = homes[n], 1 - homes[n]
            np.testing.assert_allclose(
                two_servers[home].table(n).pull(),
                -np.ones((2, 2)), rtol=1e-6)
            # the non-home replica is cold — documented single-home
            np.testing.assert_allclose(
                two_servers[other].table(n).pull(), 0.0)
            np.testing.assert_allclose(client.pull_dense(n),
                                       -np.ones((2, 2)), rtol=1e-6)

    def test_unknown_table_is_client_error(self, two_servers):
        client = PSClient([s.endpoint for s in two_servers])
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            client.pull_sparse("nope", np.array([0], np.int64))


class TestDistributedEmbedding:
    def test_training_converges_to_targets(self, two_servers):
        """End-to-end PS training: embedding rows move to fixed targets
        under pulled-block gather + grad push (matching an all-local
        embedding trained the same way)."""
        client = PSClient([s.endpoint for s in two_servers])
        emb = DistributedEmbedding(client, "emb", dim=4)
        rng = np.random.RandomState(0)
        n_vocab = 10
        targets = rng.randn(n_vocab, 4).astype(np.float32)
        for step in range(250):
            ids = rng.randint(0, n_vocab, size=(8,))
            out = emb(paddle.to_tensor(ids.astype(np.int64)))
            tgt = paddle.to_tensor(targets[ids])
            loss = ((out - tgt) ** 2).mean()
            loss.backward()
            emb.push_grad()
        final = client.pull_sparse("emb", np.arange(n_vocab))
        np.testing.assert_allclose(final, targets, atol=0.1)

    def test_push_grad_requires_backward(self, two_servers):
        client = PSClient([s.endpoint for s in two_servers])
        emb = DistributedEmbedding(client, "emb", dim=4)
        emb(paddle.to_tensor(np.array([1, 2], np.int64)))
        with pytest.raises(RuntimeError, match="backward"):
            emb.push_grad()


class TestFleetLifecycle:
    def test_server_and_worker_roles(self, monkeypatch):
        # server process view
        srv = fleet.init_server(SparseTable("emb", dim=4), port=0)
        fleet.run_server(block=False)
        try:
            monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                               srv.endpoint)
            monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
            fleet.init(is_collective=False)
            assert fleet.is_worker() and not fleet.is_server()
            client = fleet.init_worker()
            rows = client.pull_sparse("emb", np.array([0, 1], np.int64))
            assert rows.shape == (2, 4)
            assert fleet.ps_client() is client
            fleet.stop_worker()
            assert fleet.ps_client() is None
        finally:
            fleet.stop_server()

    def test_pserver_role_detected(self, monkeypatch):
        monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                           "127.0.0.1:1,127.0.0.1:2")
        monkeypatch.setenv("PADDLE_PORT", "2")
        fleet.init(is_collective=False)
        assert fleet.is_server() and not fleet.is_worker()
        rm = fleet._fleet_state["role_maker"]
        assert rm.server_index() == 1
        assert rm.server_endpoints() == ["127.0.0.1:1", "127.0.0.1:2"]
