"""Perf-regression sentry tests (ISSUE 12): tools/perf_report.py
wired into tier-1 like the chaos_check/fleet_report selftests, plus
unit coverage of the comparison rules (spread-aware thresholds,
cross-environment refusal, comparable=false skip) and the bench.py
env-fingerprint satellite."""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cli():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import perf_report
    finally:
        sys.path.pop(0)
    return perf_report


def _rec(metric, value, spread=0.02, reps=3, capture_id="envA", **kw):
    rec = {"metric": metric, "value": value, "unit": "u",
           "vs_baseline": 1.0, "reps": reps, "spread": spread,
           "capture_id": capture_id}
    rec.update(kw)
    return rec


BASE = [("BENCH_r90.json", [_rec("tok_s", 1000.0)])]


class TestCompare:
    def test_regression_caught_with_named_finding(self, cli):
        rep = cli.compare([_rec("tok_s", 800.0)], BASE)
        assert len(rep["findings"]) == 1
        f = rep["findings"][0]
        assert f["code"] == "perf-regression" and f["metric"] == "tok_s"
        assert f["baseline_capture"] == "BENCH_r90.json"
        assert "20.0%" in f["message"]

    def test_drop_inside_spread_band_passes(self, cli):
        # allowed = max(3 * 0.02, 0.05) = 6%; a 4% drop is noise
        rep = cli.compare([_rec("tok_s", 960.0)], BASE)
        assert rep["findings"] == [] and rep["compared"] == 1

    def test_noisier_side_widens_the_band(self, cli):
        noisy_base = [("b.json", [_rec("tok_s", 1000.0, spread=0.10)])]
        assert cli.compare([_rec("tok_s", 750.0)],
                           noisy_base)["findings"] == []
        assert cli.compare([_rec("tok_s", 1000.0, spread=0.10)],
                           BASE)["findings"] == []

    def test_improvement_never_fires(self, cli):
        rep = cli.compare([_rec("tok_s", 2000.0)], BASE)
        assert rep["findings"] == []

    def test_cross_env_capture_refused(self, cli):
        rep = cli.compare([_rec("tok_s", 10.0, capture_id="envB")],
                          BASE)
        assert rep["findings"] == [] and rep["compared"] == 0
        assert any("env mismatch" in r["verdict"] for r in rep["rows"])

    def test_unfingerprinted_records_refused(self, cli):
        legacy_base = [("b.json", [{"metric": "tok_s", "value": 1000.0,
                                    "reps": 3, "spread": 0.01}])]
        rep = cli.compare([_rec("tok_s", 10.0)], legacy_base)
        assert rep["findings"] == [] and rep["compared"] == 0
        assert any("no env fingerprint" in r["verdict"]
                   for r in rep["rows"])

    def test_one_shot_comparable_false_skipped(self, cli):
        base = [("b.json", [_rec("serve", 50.0, reps=1, spread=0.0,
                                 comparable=False)])]
        rep = cli.compare([_rec("serve", 1.0)], base)
        assert rep["findings"] == [] and rep["compared"] == 0

    def test_stray_cross_env_capture_cannot_shadow_baseline(self, cli):
        """A legacy/cross-env capture appended to the trajectory must
        not disable the gate: the judge walks back to the newest
        MATCHING-fingerprint baseline."""
        traj = BASE + [("BENCH_r91.json",
                        [_rec("tok_s", 1000.0, capture_id="envB")]),
                       ("BENCH_r92.json",
                        [{"metric": "tok_s", "value": 1000.0,
                          "reps": 3, "spread": 0.01}])]
        rep = cli.compare([_rec("tok_s", 700.0)], traj)
        assert len(rep["findings"]) == 1
        assert rep["findings"][0]["baseline_capture"] \
            == "BENCH_r90.json"
        # and a clean matching capture still passes
        assert cli.compare([_rec("tok_s", 990.0)],
                           traj)["findings"] == []

    def test_newest_baseline_wins(self, cli):
        traj = [("BENCH_r1.json", [_rec("tok_s", 500.0)]),
                ("BENCH_r2.json", [_rec("tok_s", 1000.0)])]
        rep = cli.compare([_rec("tok_s", 940.0)], traj)
        assert rep["findings"] == []
        assert rep["rows"][0]["baseline"] == 1000.0
        rep = cli.compare([_rec("tok_s", 700.0)], traj)
        assert rep["findings"]          # vs r2, not the older r1

    def test_bench_error_line_fails_the_gate(self, cli):
        """A crashed leg emits only <config>_bench_error — its real
        metrics vanish, and vanishing must not read as clean."""
        rep = cli.compare(
            [{"metric": "llama_bench_error", "value": 0,
              "unit": "rc=1"}], BASE)
        assert len(rep["findings"]) == 1
        assert rep["findings"][0]["code"] == "bench-error"

    def test_vanished_metric_surfaced_not_failed(self, cli):
        rep = cli.compare([_rec("other", 1.0)], BASE)
        assert rep["findings"] == []
        missing = [r for r in rep["rows"]
                   if r["verdict"].startswith("missing")]
        assert [r["metric"] for r in missing] == ["tok_s"]
        assert missing[0]["baseline"] == 1000.0
        assert "missing" in cli.render(rep)

    def test_render_names_verdicts(self, cli):
        rep = cli.compare([_rec("tok_s", 800.0)], BASE)
        out = cli.render(rep)
        assert "REGRESSION" in out and "perf-regression" in out


class TestLoading:
    def test_parse_driver_capture_and_jsonl(self, cli, tmp_path):
        drv = tmp_path / "BENCH_r1.json"
        lines = [json.dumps(_rec("a", 1.0)), "WARNING: noise",
                 json.dumps(_rec("b", 2.0))]
        drv.write_text(json.dumps(
            {"n": 1, "rc": 0, "tail": "\n".join(lines)}))
        recs = cli.parse_capture(str(drv))
        assert [r["metric"] for r in recs] == ["a", "b"]
        raw = tmp_path / "run.jsonl"
        raw.write_text("\n".join(lines))
        recs = cli.parse_capture(str(raw))
        assert [r["metric"] for r in recs] == ["a", "b"]

    def test_load_trajectory_orders_by_round(self, cli, tmp_path):
        for n, v in ((2, 20.0), (10, 100.0), (1, 10.0)):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(
                {"tail": json.dumps(_rec("m", v))}))
        traj = cli.load_trajectory(str(tmp_path))
        assert [name for name, _ in traj] == [
            "BENCH_r01.json", "BENCH_r02.json", "BENCH_r10.json"]

    def test_real_trajectory_parses(self, cli):
        traj = cli.load_trajectory(REPO)
        assert len(traj) >= 5
        latest = traj[-1][1]
        assert any(r["metric"] == "llama_train_tokens_per_sec_per_chip"
                   for r in latest)


class TestCLI:
    def test_selftest(self, cli):
        assert cli.main(["--selftest"]) == 0

    def test_cli_detects_planted_regression(self, cli, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(
            {"tail": json.dumps(_rec("tok_s", 1000.0))}))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(
            {"tail": json.dumps(_rec("tok_s", 500.0))}))
        assert cli.main(["--trajectory", str(tmp_path)]) == 1
        # and a clean follow-up passes
        (tmp_path / "BENCH_r03.json").write_text(json.dumps(
            {"tail": json.dumps(_rec("tok_s", 995.0))}))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(
            {"tail": json.dumps(_rec("tok_s", 1000.0))}))
        assert cli.main(["--trajectory", str(tmp_path)]) == 0

    def test_cli_current_file(self, cli, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(
            {"tail": json.dumps(_rec("tok_s", 1000.0))}))
        cur = tmp_path / "run.jsonl"
        cur.write_text(json.dumps(_rec("tok_s", 100.0)))
        assert cli.main(["--trajectory", str(tmp_path),
                         "--current", str(cur)]) == 1


class TestBenchFingerprint:
    """Satellite 2: bench.py JSON lines carry the env fingerprint +
    capture id, and one-shot lines are marked comparable=false."""

    @pytest.fixture()
    def bench(self):
        sys.path.insert(0, REPO)
        try:
            import bench
        finally:
            sys.path.pop(0)
        return bench

    def test_emit_carries_fingerprint_and_capture_id(self, bench,
                                                     capsys):
        bench._emit("m", 123.0, "u", 1.0, 0.01, [1.0, 2.0, 3.0])
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[0])
        assert rec["capture_id"] == bench._capture_id()
        assert rec["env"]["jax"] and rec["env"]["backend"]
        assert "FLAGS_weight_only_dtype" in rec["env"]["flags"]
        assert "comparable" not in rec        # 3 reps: comparable
        bench._emit("m1", 5.0, "u", 1.0, 0.0, [5.0])
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[0])
        assert rec["comparable"] is False     # one-shot line

    def test_capture_id_is_fingerprint_stable(self, bench,
                                              monkeypatch):
        a = bench._capture_id()
        assert a == bench._capture_id()       # cached + deterministic
        monkeypatch.setenv("BENCH_CAPTURE_ID", "forced")
        assert bench._capture_id() == "forced"
