"""Vision model zoo forward-shape checks.

Reference test model: test/legacy_test/test_vision_models.py (construct
each zoo model, forward a batch, check the logits shape).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _x(hw):
    return paddle.to_tensor(
        np.random.RandomState(0).randn(1, 3, hw, hw).astype(np.float32))


CASES = [
    ("mobilenet_v2", lambda: models.mobilenet_v2(scale=0.25,
                                                 num_classes=10), 64),
    ("mobilenet_v1", lambda: models.mobilenet_v1(scale=0.25,
                                                 num_classes=10), 64),
    ("squeezenet1_1", lambda: models.squeezenet1_1(num_classes=10), 64),
    ("squeezenet1_0", lambda: models.squeezenet1_0(num_classes=10), 96),
    ("alexnet", lambda: models.alexnet(num_classes=10), 224),
    ("vgg11", lambda: models.vgg11(num_classes=10), 224),
    ("vgg11_bn", lambda: models.vgg11(batch_norm=True,
                                      num_classes=10), 224),
]


@pytest.mark.parametrize("name,mk,hw", CASES, ids=[c[0] for c in CASES])
def test_forward_shape(name, mk, hw):
    paddle.seed(0)
    m = mk()
    out = m(_x(hw))
    assert out.shape == [1, 10]


def test_backward_through_mobilenet():
    paddle.seed(0)
    m = models.mobilenet_v2(scale=0.25, num_classes=4)
    out = m(_x(64))
    loss = (out ** 2).mean()
    loss.backward()
    grads = [p.grad for p in m.parameters() if not p.stop_gradient]
    assert any(g is not None for g in grads)
