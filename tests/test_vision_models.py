"""Vision model zoo forward-shape checks.

Reference test model: test/legacy_test/test_vision_models.py (construct
each zoo model, forward a batch, check the logits shape).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _x(hw):
    return paddle.to_tensor(
        np.random.RandomState(0).randn(1, 3, hw, hw).astype(np.float32))


CASES = [
    ("mobilenet_v2", lambda: models.mobilenet_v2(scale=0.25,
                                                 num_classes=10), 64),
    ("mobilenet_v1", lambda: models.mobilenet_v1(scale=0.25,
                                                 num_classes=10), 64),
    ("squeezenet1_1", lambda: models.squeezenet1_1(num_classes=10), 64),
    ("squeezenet1_0", lambda: models.squeezenet1_0(num_classes=10), 96),
    ("alexnet", lambda: models.alexnet(num_classes=10), 224),
    ("vgg11", lambda: models.vgg11(num_classes=10), 224),
    ("vgg11_bn", lambda: models.vgg11(batch_norm=True,
                                      num_classes=10), 224),
]


@pytest.mark.parametrize("name,mk,hw", CASES, ids=[c[0] for c in CASES])
def test_forward_shape(name, mk, hw):
    paddle.seed(0)
    m = mk()
    out = m(_x(hw))
    assert out.shape == [1, 10]


def test_backward_through_mobilenet():
    paddle.seed(0)
    m = models.mobilenet_v2(scale=0.25, num_classes=4)
    out = m(_x(64))
    loss = (out ** 2).mean()
    loss.backward()
    grads = [p.grad for p in m.parameters() if not p.stop_gradient]
    assert any(g is not None for g in grads)


class TestRound3Zoo:
    """The five families added in round 3 (VERDICT #10): densenet,
    googlenet, inceptionv3, mobilenetv3, shufflenetv2."""

    @pytest.mark.parametrize("ctor,size", [
        # one representative per block family — mobilenet_v3_large
        # shares mobilenet_v3_small's block code and only adds ~30s of
        # XLA CPU compile to the suite
        ("mobilenet_v3_small", 64),
        ("shufflenet_v2_x0_25", 64), ("densenet121", 64),
        ("googlenet", 64),
    ])
    def test_forward_shapes(self, ctor, size):
        from paddle_tpu.vision import models
        from paddle_tpu.jit import to_static
        paddle.seed(0)
        m = getattr(models, ctor)(num_classes=7)
        m.eval()
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 3, size, size).astype(np.float32))
        # jitted forward: ONE XLA compile per model instead of hundreds
        # of per-op eager compiles (the r3 version took up to 57s/model)
        out = to_static(m)(x)
        if isinstance(out, tuple):   # googlenet mirrors (main, aux1, aux2)
            out = out[0]
        assert tuple(out.shape) == (2, 7)

    def test_inception_v3_forward(self):
        from paddle_tpu.vision.models import inception_v3
        from paddle_tpu.jit import to_static
        paddle.seed(0)
        m = inception_v3(num_classes=5)
        m.eval()
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(1, 3, 299, 299).astype(np.float32))
        out = to_static(m)(x)
        assert tuple(out.shape) == (1, 5)

    def test_mobilenetv3_trains(self):
        from paddle_tpu.vision.models import mobilenet_v3_small
        from paddle_tpu.jit import TrainStep
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = mobilenet_v3_small(num_classes=4, scale=0.5)
        opt = paddle.optimizer.Momentum(0.05, parameters=m.parameters())
        step = TrainStep(m, lambda o, y:
                         nn.functional.cross_entropy(o, y), opt)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 3, 64, 64).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
        losses = [float(np.asarray(step(x, y).value)) for _ in range(4)]
        assert losses[-1] < losses[0]


class TestViT:
    """Round-4 addition: Vision Transformer (patchify conv + pre-LN
    encoder over ops.attention)."""

    def test_forward_shape(self):
        from paddle_tpu.vision.models import vit_tiny_patch4
        paddle.seed(0)
        m = vit_tiny_patch4()
        m.eval()
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 3, 32, 32).astype(np.float32))
        out = m(x)
        assert tuple(out.shape) == (2, 10)

    def test_trains(self):
        from paddle_tpu.vision.models import vit_tiny_patch4
        from paddle_tpu.jit import TrainStep
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = vit_tiny_patch4(num_classes=4)
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda o, y:
                         nn.functional.cross_entropy(o, y), opt)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 3, 32, 32).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
        losses = [float(np.asarray(step(x, y).value)) for _ in range(6)]
        assert losses[-1] < losses[0]
