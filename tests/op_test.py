"""OpTest harness — numpy-reference op checks.

Reference: `test/legacy_test/op_test.py:418` — check_output (:2925)
compares against a numpy reference per place/dtype, check_grad (:3129)
compares analytic vs numeric gradients with per-dtype tolerances.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor

# float32 tolerances account for XLA:CPU's vectorized transcendental
# approximations (same spirit as the reference's per-op white lists in
# test/white_list/op_accuracy_white_list.py)
DTYPE_ATOL = {"float64": 1e-10, "float32": 1e-4, "float16": 1e-2,
              "bfloat16": 2e-2}
DTYPE_RTOL = {"float64": 1e-7, "float32": 1e-4, "float16": 1e-2,
              "bfloat16": 2e-2}


def check_output(paddle_fn, numpy_fn, inputs, atol=None, rtol=None,
                 dtype="float32"):
    """Run op on Tensors and compare with numpy_fn on ndarrays."""
    t_inputs = [paddle.to_tensor(np.asarray(a, dtype)) for a in inputs]
    out = paddle_fn(*t_inputs)
    ref = numpy_fn(*[np.asarray(a, dtype) for a in inputs])
    atol = atol if atol is not None else DTYPE_ATOL[dtype]
    rtol = rtol if rtol is not None else DTYPE_RTOL[dtype]
    outs = out if isinstance(out, (list, tuple)) else [out]
    refs = ref if isinstance(ref, (list, tuple)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(np.asarray(o.value, np.float64),
                                   np.asarray(r, np.float64),
                                   atol=atol, rtol=rtol)


def check_grad(paddle_fn, inputs, dtype="float32", eps=1e-3, atol=5e-3,
               rtol=5e-3, seed_output_index=0):
    """Numeric vs analytic gradient (central differences), matching the
    reference's get_numeric_gradient strategy."""
    arrays = [np.asarray(a, dtype) for a in inputs]

    def scalar_loss(arrs):
        ts = [paddle.to_tensor(a) for a in arrs]
        for t in ts:
            t.stop_gradient = False
        out = paddle_fn(*ts)
        if isinstance(out, (list, tuple)):
            out = out[seed_output_index]
        return ts, paddle.sum(out * out)  # smooth scalarization

    ts, loss = scalar_loss(arrays)
    loss.backward()
    analytic = [np.asarray(t.grad.value) if t.grad is not None else
                np.zeros_like(a) for t, a in zip(ts, arrays)]

    for idx, base in enumerate(arrays):
        numeric = np.zeros_like(base, np.float64)
        flat = base.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            _, lp = scalar_loss(arrays)
            flat[i] = orig - eps
            _, lm = scalar_loss(arrays)
            flat[i] = orig
            numeric.reshape(-1)[i] = (float(lp) - float(lm)) / (2 * eps)
        np.testing.assert_allclose(analytic[idx].astype(np.float64),
                                   numeric, atol=atol, rtol=rtol,
                                   err_msg=f"grad mismatch for input {idx}")
