"""Op correctness vs numpy (reference: test/legacy_test OpTest pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_output, check_grad


def r(*shape):
    return np.random.rand(*shape).astype(np.float32)


class TestElementwise:
    def test_add(self):
        check_output(paddle.add, np.add, [r(3, 4), r(3, 4)])
        check_grad(paddle.add, [r(2, 3), r(2, 3)])

    def test_broadcast_add(self):
        check_output(paddle.add, np.add, [r(3, 4), r(4)])

    def test_subtract(self):
        check_output(paddle.subtract, np.subtract, [r(3, 4), r(3, 4)])

    def test_multiply(self):
        check_output(paddle.multiply, np.multiply, [r(3, 4), r(3, 4)])
        check_grad(paddle.multiply, [r(2, 3), r(2, 3)])

    def test_divide(self):
        check_output(paddle.divide, np.divide,
                     [r(3, 4), r(3, 4) + 0.5])

    def test_pow(self):
        check_output(lambda x: paddle.pow(x, 2.0), lambda x: x ** 2,
                     [r(3, 4)])

    def test_maximum_minimum(self):
        check_output(paddle.maximum, np.maximum, [r(3), r(3)])
        check_output(paddle.minimum, np.minimum, [r(3), r(3)])

    def test_exp_log(self):
        check_output(paddle.exp, np.exp, [r(5)])
        check_output(paddle.log, np.log, [r(5) + 0.1])
        check_grad(paddle.exp, [r(4)])

    def test_sqrt_rsqrt(self):
        check_output(paddle.sqrt, np.sqrt, [r(5) + 0.1])
        check_output(paddle.rsqrt, lambda x: 1 / np.sqrt(x), [r(5) + 0.1])

    def test_trig(self):
        check_output(paddle.sin, np.sin, [r(5)])
        check_output(paddle.cos, np.cos, [r(5)])
        check_output(paddle.tanh, np.tanh, [r(5)])

    def test_clip(self):
        check_output(lambda x: paddle.clip(x, 0.2, 0.8),
                     lambda x: np.clip(x, 0.2, 0.8), [r(10)])

    def test_scale(self):
        check_output(lambda x: paddle.scale(x, 2.0, 1.0),
                     lambda x: 2.0 * x + 1.0, [r(4)])


class TestMatmul:
    def test_matmul(self):
        check_output(paddle.matmul, np.matmul, [r(3, 4), r(4, 5)])
        check_grad(paddle.matmul, [r(2, 3), r(3, 2)])

    def test_matmul_transpose(self):
        check_output(lambda a, b: paddle.matmul(a, b, transpose_y=True),
                     lambda a, b: a @ b.T, [r(3, 4), r(5, 4)])

    def test_batched(self):
        check_output(paddle.matmul, np.matmul, [r(2, 3, 4), r(2, 4, 5)])

    def test_dot(self):
        check_output(paddle.dot, lambda a, b: np.sum(a * b, -1),
                     [r(4), r(4)])


class TestReductions:
    def test_sum(self):
        check_output(paddle.sum, np.sum, [r(3, 4)])
        check_output(lambda x: paddle.sum(x, axis=1),
                     lambda x: np.sum(x, 1), [r(3, 4)])
        check_output(lambda x: paddle.sum(x, axis=1, keepdim=True),
                     lambda x: np.sum(x, 1, keepdims=True), [r(3, 4)])
        check_grad(paddle.sum, [r(3, 3)])

    def test_mean_max_min_prod(self):
        check_output(paddle.mean, np.mean, [r(3, 4)])
        check_output(paddle.max, np.max, [r(3, 4)])
        check_output(paddle.min, np.min, [r(3, 4)])
        check_output(paddle.prod, np.prod, [r(6)])

    def test_cumsum(self):
        check_output(lambda x: paddle.cumsum(x, axis=1),
                     lambda x: np.cumsum(x, 1), [r(3, 4)])

    def test_logsumexp(self):
        from scipy.special import logsumexp
        check_output(paddle.logsumexp, logsumexp, [r(3, 4)])

    def test_std_var(self):
        check_output(lambda x: paddle.std(x),
                     lambda x: np.std(x, ddof=1), [r(10)])
        check_output(lambda x: paddle.var(x, unbiased=False),
                     lambda x: np.var(x), [r(10)])


class TestManipulation:
    def test_reshape(self):
        check_output(lambda x: paddle.reshape(x, [4, 3]),
                     lambda x: x.reshape(4, 3), [r(3, 4)])
        check_grad(lambda x: paddle.reshape(x, [-1]), [r(2, 3)])

    def test_transpose(self):
        check_output(lambda x: paddle.transpose(x, [1, 0]),
                     lambda x: x.T, [r(3, 4)])

    def test_concat_stack_split(self):
        check_output(lambda a, b: paddle.concat([a, b], axis=0),
                     lambda a, b: np.concatenate([a, b], 0),
                     [r(2, 3), r(4, 3)])
        check_output(lambda a, b: paddle.stack([a, b], axis=1),
                     lambda a, b: np.stack([a, b], 1), [r(2, 3), r(2, 3)])
        x = paddle.to_tensor(r(6, 4))
        parts = paddle.split(x, 3, axis=0)
        assert len(parts) == 3 and parts[0].shape == [2, 4]
        parts = paddle.split(x, [1, 2, -1], axis=0)
        assert [p.shape[0] for p in parts] == [1, 2, 3]

    def test_squeeze_unsqueeze(self):
        check_output(lambda x: paddle.unsqueeze(x, 0),
                     lambda x: x[None], [r(3)])
        check_output(lambda x: paddle.squeeze(x, 0),
                     lambda x: x.squeeze(0), [r(1, 3)])

    def test_tile_expand(self):
        check_output(lambda x: paddle.tile(x, [2, 3]),
                     lambda x: np.tile(x, (2, 3)), [r(2, 2)])
        check_output(lambda x: paddle.expand(x, [3, 4]),
                     lambda x: np.broadcast_to(x, (3, 4)), [r(1, 4)])

    def test_gather(self):
        x = paddle.to_tensor(r(5, 3))
        idx = paddle.to_tensor(np.array([0, 2, 4]))
        out = paddle.gather(x, idx)
        np.testing.assert_allclose(out.numpy(),
                                   x.numpy()[[0, 2, 4]], rtol=1e-6)

    def test_getitem_setitem(self):
        x = paddle.to_tensor(r(4, 5))
        np.testing.assert_allclose(x[1:3, ::2].numpy(),
                                   x.numpy()[1:3, ::2])
        y = paddle.to_tensor(r(4, 5))
        y[0] = 1.0
        assert np.allclose(y.numpy()[0], 1.0)

    def test_getitem_grad(self):
        check_grad(lambda x: x[1:, :2], [r(3, 3)])

    def test_flip_roll(self):
        check_output(lambda x: paddle.flip(x, [0]),
                     lambda x: np.flip(x, 0), [r(3, 4)])
        check_output(lambda x: paddle.roll(x, 2, 0),
                     lambda x: np.roll(x, 2, 0), [r(5, 2)])

    def test_pad(self):
        check_output(lambda x: paddle.nn.functional.pad(
            x, [1, 2], value=0.5),
            lambda x: np.pad(x, ((0, 0), (1, 2)),
                             constant_values=0.5), [r(2, 3)])

    def test_cast(self):
        x = paddle.to_tensor(r(3))
        assert paddle.cast(x, "float16").dtype == paddle.float16
        assert x.astype("int32").dtype == paddle.int32

    def test_scatter_ops(self):
        x = paddle.zeros([4, 3])
        idx = paddle.to_tensor(np.array([1, 3]))
        upd = paddle.to_tensor(np.ones((2, 3), np.float32))
        out = paddle.scatter(x, idx, upd)
        expect = np.zeros((4, 3), np.float32)
        expect[[1, 3]] = 1
        np.testing.assert_allclose(out.numpy(), expect)


class TestSearchSort:
    def test_argmax_argmin(self):
        a = r(4, 5)
        x = paddle.to_tensor(a)
        assert int(paddle.argmax(x)) == int(np.argmax(a))
        np.testing.assert_array_equal(paddle.argmax(x, axis=1).numpy(),
                                      np.argmax(a, 1))

    def test_sort_argsort(self):
        a = r(4, 5)
        np.testing.assert_allclose(paddle.sort(paddle.to_tensor(a)).numpy(),
                                   np.sort(a), rtol=1e-6)
        np.testing.assert_array_equal(
            paddle.argsort(paddle.to_tensor(a)).numpy(), np.argsort(a))

    def test_topk(self):
        a = r(3, 10)
        vals, idx = paddle.topk(paddle.to_tensor(a), 3)
        ref = np.sort(a, axis=-1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)

    def test_where(self):
        a, b = r(3, 4), r(3, 4)
        cond = a > b
        out = paddle.where(paddle.to_tensor(cond), paddle.to_tensor(a),
                           paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.where(cond, a, b))

    def test_nonzero(self):
        a = (r(4, 4) > 0.5).astype(np.float32)
        out = paddle.nonzero(paddle.to_tensor(a))
        ref = np.stack(np.nonzero(a), 1)
        np.testing.assert_array_equal(out.numpy(), ref)

    def test_unique(self):
        a = np.array([1, 3, 1, 2, 3], np.int64)
        out = paddle.unique(paddle.to_tensor(a))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])


class TestCreation:
    def test_basic(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], "int64").dtype == paddle.int64
        assert paddle.full([2, 2], 7).numpy()[0, 0] == 7
        np.testing.assert_array_equal(paddle.arange(5).numpy(),
                                      np.arange(5))
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5),
            rtol=1e-6)
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))

    def test_tril_triu(self):
        check_output(paddle.tril, np.tril, [r(4, 4)])
        check_output(paddle.triu, np.triu, [r(4, 4)])

    def test_like(self):
        x = paddle.to_tensor(r(2, 3))
        assert paddle.zeros_like(x).shape == [2, 3]
        assert paddle.full_like(x, 3.0).numpy()[0, 0] == 3.0

    def test_default_dtypes(self):
        assert paddle.to_tensor(1.5).dtype == paddle.float32
        assert paddle.to_tensor(2).dtype == paddle.int64
        assert paddle.to_tensor([True]).dtype == paddle.bool_


class TestLinalg:
    def test_inverse_solve(self):
        a = r(3, 3) + 3 * np.eye(3, dtype=np.float32)
        check_output(paddle.linalg.inv, np.linalg.inv, [a], atol=1e-4)
        b = r(3, 2)
        out = paddle.linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.linalg.solve(a, b),
                                   atol=1e-4)

    def test_norm(self):
        a = r(3, 4)
        assert np.isclose(float(paddle.linalg.norm(paddle.to_tensor(a))),
                          np.linalg.norm(a), rtol=1e-5)

    def test_svd_qr_cholesky(self):
        a = r(4, 3)
        u, s, vh = paddle.linalg.svd(paddle.to_tensor(a))
        rec = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, a, atol=1e-4)
        spd = a.T @ a + np.eye(3, dtype=np.float32)
        l = paddle.linalg.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(l.numpy() @ l.numpy().T, spd, atol=1e-4)

    def test_einsum(self):
        a, b = r(3, 4), r(4, 5)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


class TestLogic:
    def test_compare(self):
        a, b = r(3), r(3)
        x, y = paddle.to_tensor(a), paddle.to_tensor(b)
        np.testing.assert_array_equal((x < y).numpy(), a < b)
        np.testing.assert_array_equal((x >= y).numpy(), a >= b)
        assert bool(paddle.allclose(x, x))

    def test_isnan_isinf(self):
        a = np.array([1.0, np.nan, np.inf], np.float32)
        x = paddle.to_tensor(a)
        np.testing.assert_array_equal(paddle.isnan(x).numpy(),
                                      np.isnan(a))
        np.testing.assert_array_equal(paddle.isinf(x).numpy(),
                                      np.isinf(a))


class TestRandom:
    def test_shapes_dtypes(self):
        assert paddle.rand([3, 4]).shape == [3, 4]
        assert paddle.randn([2]).dtype == paddle.float32
        ri = paddle.randint(0, 10, [100])
        assert int(ri.numpy().min()) >= 0 and int(ri.numpy().max()) < 10
        p = paddle.randperm(10).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(10))

    def test_seed_determinism(self):
        paddle.seed(7)
        a = paddle.randn([4]).numpy()
        paddle.seed(7)
        b = paddle.randn([4]).numpy()
        np.testing.assert_array_equal(a, b)

    def test_uniform_range(self):
        u = paddle.uniform([1000], min=2.0, max=3.0).numpy()
        assert u.min() >= 2.0 and u.max() <= 3.0
