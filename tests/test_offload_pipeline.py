"""Double-buffered ZeRO-3 host-offload streaming pipeline.

What is being validated (parallel/offload_pipeline.py):
  * CPU-mode parity: the streamed pipeline's 3-step losses and final
    weights match the in-HBM ShardedTrainStep (exact wire dtype → fp32
    tolerance; bf16 wire-cast → bf16-level tolerance);
  * ONE compiled program regardless of layer count: both the layer
    loop and its backward are `lax.scan`s, so the op count (e.g.
    `dot_general`s) must not scale with L and exactly two while loops
    appear;
  * the window invariant: HBM holds at most (prefetch_depth+1) layers'
    parameters;
  * `offload="stream"` / DistributedStrategy plumbing through
    ShardedTrainStep;
  * the param_stream_scope unvisited-parameter guard (previously a
    silent no-op).

These run on the CPU backend: placement annotations degrade to plain
device memory there (no pinned_host memory kind) but the program
structure and the math are identical — that is exactly the CPU
fallback the pipeline documents.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig
from paddle_tpu.parallel import ShardedTrainStep, OffloadPipelineStep
from paddle_tpu.distributed.topology import build_mesh


def _cfg(L=3, hidden=32):
    return LlamaConfig(vocab_size=64, hidden_size=hidden,
                       intermediate_size=2 * hidden,
                       num_hidden_layers=L, num_attention_heads=2,
                       num_key_value_heads=2, max_position_embeddings=32,
                       dtype="float32")


def _make(kind, L=3, seed=7, **kw):
    paddle.seed(seed)
    m = LlamaForCausalLM(_cfg(L))
    opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters(),
                                 weight_decay=0.1)
    mesh = build_mesh(devices=jax.devices()[:1])
    if kind == "base":
        st = ShardedTrainStep(m, opt, mesh, sharding_stage=3)
    elif kind == "pipe":
        st = OffloadPipelineStep(m, opt, mesh, **kw)
    else:  # via the trainer front door
        st = ShardedTrainStep(m, opt, mesh, sharding_stage=3,
                              offload="stream", **kw)
    return m, st


def _batch(n=2, s=16):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(
        rng.randint(0, 64, (n, s)).astype(np.int32))


class TestParity:
    def test_three_step_losses_match_in_hbm_trainer(self):
        """Same wire dtype as storage → the satellite's parity bar:
        3-step losses and final weights match the non-streamed trainer
        to fp32 tolerance (the programs differ, so reductions may
        reassociate at the last bit)."""
        x = _batch()
        m1, s1 = _make("base")
        base = [float(np.asarray(s1(x, x).value)) for _ in range(3)]
        m2, s2 = _make("pipe", cast_dtype=None)
        pipe = [float(np.asarray(s2(x, x).value)) for _ in range(3)]
        np.testing.assert_allclose(pipe, base, rtol=2e-6, atol=1e-7)
        s2.sync_to_model()
        sd1, sd2 = m1.state_dict(), m2.state_dict()
        for n in sd1:
            np.testing.assert_allclose(
                np.asarray(sd2[n].value), np.asarray(sd1[n].value),
                rtol=1e-5, atol=1e-6, err_msg=n)

    def test_bf16_wire_cast_stays_close(self):
        """bf16 wire: params cross host→HBM as bf16 (half the DMA
        bytes), fp32 masters stay parked — losses track the exact run
        to bf16-level tolerance."""
        x = _batch()
        _, s1 = _make("pipe", cast_dtype=None)
        _, s2 = _make("pipe", cast_dtype="bfloat16")
        a = [float(np.asarray(s1(x, x).value)) for _ in range(3)]
        b = [float(np.asarray(s2(x, x).value)) for _ in range(3)]
        np.testing.assert_allclose(b, a, rtol=0.05, atol=0.05)

    def test_run_steps_matches_per_step_calls(self):
        x = np.random.RandomState(3).randint(
            0, 64, (2, 2, 16)).astype(np.int32)
        _, s1 = _make("pipe", cast_dtype=None)
        losses = s1.run_steps(paddle.to_tensor(x), paddle.to_tensor(x))
        _, s2 = _make("pipe", cast_dtype=None)
        singles = [float(np.asarray(
            s2(paddle.to_tensor(x[i]), paddle.to_tensor(x[i])).value))
            for i in range(2)]
        np.testing.assert_allclose(np.asarray(losses.value), singles,
                                   rtol=1e-6)

    def test_run_steps_advances_per_step_scheduler(self):
        """run_steps keeps ShardedTrainStep's per-step LRScheduler
        contract (jit.per_step_lrs): the scheduler ends K steps ahead
        and the window trained on the per-step values, not a frozen
        pre-window LR."""
        from paddle_tpu.optimizer.lr import PiecewiseDecay
        paddle.seed(7)
        m = LlamaForCausalLM(_cfg(2))
        sched = PiecewiseDecay(boundaries=[1], values=[1e-2, 1e-3])
        opt = paddle.optimizer.AdamW(sched, parameters=m.parameters())
        mesh = build_mesh(devices=jax.devices()[:1])
        st = OffloadPipelineStep(m, opt, mesh, cast_dtype=None)
        x = np.random.RandomState(3).randint(
            0, 64, (2, 2, 16)).astype(np.int32)
        st.run_steps(paddle.to_tensor(x), paddle.to_tensor(x))
        assert sched.last_epoch == 2
        assert float(sched()) == pytest.approx(1e-3)


class TestOneProgram:
    def test_program_independent_of_layer_count(self):
        """The scanned step compiles exactly one program whose size
        does not scale with L: identical dot_general count for L=2 and
        L=4, and exactly two scan loops (forward + reverse/backward) —
        i.e. the backward does NOT re-stream via per-layer remat
        replay regions."""
        x = _batch()
        _, p2 = _make("pipe", L=2, cast_dtype=None)
        _, p4 = _make("pipe", L=4, cast_dtype=None)
        h2 = p2.compiled_hlo(x, x)
        h4 = p4.compiled_hlo(x, x)
        assert h2.count("dot_general") == h4.count("dot_general")
        assert h2.count("stablehlo.while") == 2
        assert h4.count("stablehlo.while") == 2
        # program TEXT size is near-constant in L too (no unrolling)
        assert len(h4) < 1.1 * len(h2)

    def test_window_invariant(self):
        """≤ (prefetch_depth+1) layers' params resident: the window is
        depth+1 deep and per-layer fetches are single-layer dynamic
        slices of the host stack (no full-stack device copy)."""
        x = _batch()
        _, p = _make("pipe", L=4, cast_dtype=None, prefetch_depth=2)
        assert p.window_size == 3
        assert p.hbm_param_bytes() == 3 * p.layer_param_bytes()
        hlo = p.compiled_hlo(x, x)
        # the stacked q_proj is [4, 32, 32] f32; its windowed fetch is
        # a [1, 32, 32] dynamic_slice inside the loops
        assert "tensor<1x32x32xf32>" in hlo
        sb = p.stream_bytes_per_step()
        assert sb["prefetch_depth"] == 2
        # fwd streams L wire layers; bwd streams L (param+state) bundles
        assert sb["h2d_bytes"] > sb["d2h_bytes"] > 0
        assert p.dma_probe(reps=1) > 0.0

    def test_depth_must_be_positive(self):
        with pytest.raises(ValueError, match="prefetch_depth"):
            _make("pipe", prefetch_depth=0)


class TestPlumbing:
    def test_sharded_trainer_stream_delegation(self):
        """ShardedTrainStep(offload="stream") rides the pipeline and
        matches the in-HBM trainer like the direct construction."""
        x = _batch()
        _, s1 = _make("base")
        base = [float(np.asarray(s1(x, x).value)) for _ in range(2)]
        _, s2 = _make("stream", offload_cast_dtype=None)
        assert s2._pipeline is not None
        got = [float(np.asarray(s2(x, x).value)) for _ in range(2)]
        np.testing.assert_allclose(got, base, rtol=2e-6, atol=1e-7)

    def test_from_strategy_plumbs_offload_knobs(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        strat = DistributedStrategy()
        assert strat.sharding_configs["offload_prefetch_depth"] == 1
        assert strat.sharding_configs["offload_cast_dtype"] == "bfloat16"
        strat.sharding_configs.update(
            stage=3, offload="stream", offload_prefetch_depth=2,
            offload_cast_dtype=None)
        paddle.seed(7)
        m = LlamaForCausalLM(_cfg(2))
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        mesh = build_mesh(devices=jax.devices()[:1])
        # sharding_configs only apply under the strategy.sharding
        # master switch (reference semantics)
        off = ShardedTrainStep.from_strategy(m, opt, mesh, strat)
        assert off._pipeline is None and off.stage == 0
        strat.sharding = True
        st = ShardedTrainStep.from_strategy(m, opt, mesh, strat)
        assert st._pipeline is not None
        assert st._pipeline.prefetch_depth == 2
        x = _batch()
        assert np.isfinite(float(np.asarray(st(x, x).value)))

    def test_non_block_model_raises(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 4))
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        mesh = build_mesh(devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="block stack"):
            OffloadPipelineStep(m, opt, mesh)


class TestBlockSemantics:
    def test_backward_recompute_shares_forward_dropout_masks(self):
        """Each block call runs under a per-(step, layer) key scope, so
        the backward scan's recompute draws the SAME dropout masks the
        forward used.  The net is linear in each block scale w_i given
        the masks, so loss == dloss/dw_i exactly (at w=1) — a backward
        that recomputed with different masks produces a gradient of a
        different function and the equality breaks."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.framework.tensor import Parameter

        class DropBlock(nn.Layer):
            def __init__(self):
                super().__init__()
                self.scale = Parameter(jnp.ones([1], jnp.float32))

            def forward(self, x):
                return F.dropout(x * self.scale, p=0.5, training=True)

        class DropNet(nn.Layer):
            def __init__(self, L):
                super().__init__()
                self.layers = nn.LayerList(
                    [DropBlock() for _ in range(L)])
                self.head = Parameter(jnp.ones([1], jnp.float32))

            def forward(self, x):
                h = x
                for b in self.layers:
                    h = b(h)
                return h * self.head

        paddle.seed(11)
        m = DropNet(2)
        opt = paddle.optimizer.SGD(1.0, parameters=m.parameters())
        mesh = build_mesh(devices=jax.devices()[:1])
        st = OffloadPipelineStep(m, opt, mesh, cast_dtype=None,
                                 loss_fn=lambda o, y: o.mean())
        x = paddle.to_tensor(
            np.random.RandomState(0).rand(4, 8).astype(np.float32)
            + 0.5)
        loss0 = float(np.asarray(st(x, x).value))
        assert loss0 > 0
        st.sync_to_model()
        sd = m.state_dict()
        for i in range(2):
            w_after = float(np.asarray(sd[f"layers.{i}.scale"].value)[0])
            g = 1.0 - w_after  # SGD, lr=1, wd=0
            assert g == pytest.approx(loss0, rel=1e-5), (i, g, loss0)

    def test_block_keyword_args_are_replayed(self):
        """Blocks called with keyword arguments (array AND python
        valued) get them captured and replayed in both scans — a
        capture that dropped kwargs would run the blocks on their
        defaults (here: the identity path) and diverge from the
        trainer."""
        import paddle_tpu.nn as nn
        from paddle_tpu.framework.tensor import Parameter

        class KwBlock(nn.Layer):
            def __init__(self):
                super().__init__()
                self.w = Parameter(
                    jnp.full([1], 2.0, jnp.float32))

            def forward(self, x, gate=None, off=True):
                if off or gate is None:
                    return x
                return x * self.w * gate

        class KwNet(nn.Layer):
            def __init__(self, L):
                super().__init__()
                self.layers = nn.LayerList(
                    [KwBlock() for _ in range(L)])
                self.head = Parameter(jnp.ones([1], jnp.float32))

            def forward(self, x):
                gate = x * 0 + 0.3
                h = x
                for b in self.layers:
                    h = b(h, gate=gate, off=False)
                return h * self.head

        def build():
            paddle.seed(3)
            m = KwNet(2)
            opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
            return m, opt

        mesh = build_mesh(devices=jax.devices()[:1])
        x = paddle.to_tensor(
            np.random.RandomState(1).rand(2, 4).astype(np.float32))
        loss_fn = lambda o, y: o.mean()
        m1, o1 = build()
        base = float(np.asarray(ShardedTrainStep(
            m1, o1, mesh, sharding_stage=0,
            loss_fn=loss_fn)(x, x).value))
        m2, o2 = build()
        pipe = float(np.asarray(OffloadPipelineStep(
            m2, o2, mesh, cast_dtype=None,
            loss_fn=loss_fn)(x, x).value))
        assert pipe == pytest.approx(base, rel=1e-6)
        # the kwargs actually mattered: dropped kwargs would take the
        # identity path and land exactly on mean(x)
        ident = float(np.asarray(x.value).mean())
        assert abs(pipe - ident) > 1e-3


class TestExtrasSemantics:
    def _kw_block(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.framework.tensor import Parameter

        class KwBlock(nn.Layer):
            def __init__(self):
                super().__init__()
                self.w = Parameter(jnp.full([1], 2.0, jnp.float32))

            def forward(self, x, gate=None, off=True):
                if off or gate is None:
                    return x
                return x * self.w * gate

        return KwBlock

    def test_learned_pre_stack_extra_gets_gradient(self):
        """A block input computed from a trainable pre-stack parameter
        is a DIFFERENTIATED extra: its per-layer cotangents accumulate
        through the backward scan into the producing parameter (a
        stop-gradient capture would leave it frozen forever)."""
        import paddle_tpu.nn as nn
        from paddle_tpu.framework.tensor import Parameter
        KwBlock = self._kw_block()

        class GateNet(nn.Layer):
            def __init__(self, L):
                super().__init__()
                self.gate = Parameter(jnp.full([1], 0.5, jnp.float32))
                self.layers = nn.LayerList(
                    [KwBlock() for _ in range(L)])

            def forward(self, x):
                g = x * 0 + self.gate
                h = x
                for b in self.layers:
                    h = b(h, gate=g, off=False)
                return h

        paddle.seed(5)
        m = GateNet(2)
        opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
        mesh = build_mesh(devices=jax.devices()[:1])
        st = OffloadPipelineStep(m, opt, mesh, cast_dtype=None,
                                 loss_fn=lambda o, y: o.mean())
        x = paddle.to_tensor(
            np.random.RandomState(2).rand(2, 4).astype(np.float32)
            + 0.5)
        st(x, x)
        gate_after = float(np.asarray(
            m.state_dict()["gate"].value)[0])
        assert gate_after != pytest.approx(0.5), \
            "learned extra's gradient was dropped"

    def test_layer_varying_block_args_rejected(self):
        """Per-layer block arguments cannot be expressed by the scanned
        step — the trace-time capture detects and rejects them instead
        of silently replaying layer 0's values everywhere."""
        import paddle_tpu.nn as nn
        KwBlock = self._kw_block()

        class VaryNet(nn.Layer):
            def __init__(self, L):
                super().__init__()
                self.layers = nn.LayerList(
                    [KwBlock() for _ in range(L)])

            def forward(self, x):
                h = x
                for i, b in enumerate(self.layers):
                    h = b(h, gate=x * 0 + 0.1 * (i + 1), off=False)
                return h

        paddle.seed(5)
        m = VaryNet(2)
        opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
        mesh = build_mesh(devices=jax.devices()[:1])
        st = OffloadPipelineStep(m, opt, mesh, cast_dtype=None,
                                 loss_fn=lambda o, y: o.mean())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with pytest.raises(Exception, match="different non-hidden"):
            st(x, x)

    def test_adagrad_initial_accumulator_parity(self):
        """Per-layer optimizer-state init goes through the optimizer's
        own _init_state: a nonzero Adagrad initial accumulator matches
        the in-HBM trainer (zero-initialized stacks would diverge on
        step 1)."""
        x = _batch()

        def build():
            paddle.seed(7)
            m = LlamaForCausalLM(_cfg(2))
            opt = paddle.optimizer.Adagrad(
                1e-2, parameters=m.parameters(),
                initial_accumulator_value=0.1)
            return m, opt

        mesh = build_mesh(devices=jax.devices()[:1])
        m1, o1 = build()
        s1 = ShardedTrainStep(m1, o1, mesh, sharding_stage=3)
        base = [float(np.asarray(s1(x, x).value)) for _ in range(2)]
        m2, o2 = build()
        s2 = OffloadPipelineStep(m2, o2, mesh, cast_dtype=None)
        pipe = [float(np.asarray(s2(x, x).value)) for _ in range(2)]
        np.testing.assert_allclose(pipe, base, rtol=2e-6, atol=1e-7)


class TestHostsideTwin:
    def test_adamw_hostside_matches_pure_rule(self):
        """The jnp twin of the fused kernel (what the pipeline's
        backward scan applies off-TPU) is bit-identical to the
        optimizer's pure `_update` rule — the in-backward update cannot
        drift from the trainer's."""
        from paddle_tpu.ops.pallas.fused_adamw import adamw_hostside
        from paddle_tpu.optimizer.optimizer import Adam
        rng = np.random.RandomState(0)
        p = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        g = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        m = jnp.asarray(rng.randn(16, 8).astype(np.float32)) * 0.1
        v = jnp.abs(jnp.asarray(rng.randn(16, 8).astype(np.float32)))
        for wd, dec in ((0.0, True), (0.1, True), (0.1, False)):
            ref_p, ref_st = Adam._update(
                p, g, {"moment1": m, "moment2": v}, 1e-3, wd, 3,
                b1=0.9, b2=0.999, eps=1e-8, decoupled=dec)
            new_p, nm, nv, mst = adamw_hostside(
                g, m, v, p, 1e-3, 3, b1=0.9, b2=0.999, eps=1e-8,
                wd=wd, decoupled=dec, out_dtype=jnp.float32)
            np.testing.assert_array_equal(np.asarray(new_p),
                                          np.asarray(ref_p))
            np.testing.assert_array_equal(np.asarray(nm),
                                          np.asarray(ref_st["moment1"]))
            np.testing.assert_array_equal(np.asarray(nv),
                                          np.asarray(ref_st["moment2"]))
            np.testing.assert_array_equal(np.asarray(mst),
                                          np.asarray(new_p))

    def test_adamw_hostside_matches_kernel_interpret(self):
        """Twin vs the Pallas kernel (interpret mode): same single-pass
        math to fp32 tolerance, bf16 param + fp32 master layout."""
        from paddle_tpu.ops.pallas.fused_adamw import (adamw_hostside,
                                                       fused_adamw)
        rng = np.random.RandomState(1)
        mst = jnp.asarray(rng.randn(2048).astype(np.float32))
        g = mst.astype(jnp.bfloat16) * 0 + jnp.asarray(
            rng.randn(2048).astype(np.float32)).astype(jnp.bfloat16)
        m = jnp.zeros(2048, jnp.float32)
        v = jnp.zeros(2048, jnp.float32)
        try:
            kp, km, kv, kmst = fused_adamw(g, m, v, mst, 1e-3, 1,
                                           wd=0.01)
        except AttributeError as e:  # pragma: no cover
            pytest.skip(f"pallas kernel unavailable on this jax: {e}")
        tp, tm, tv, tmst = adamw_hostside(g, m, v, mst, 1e-3, 1, wd=0.01)
        np.testing.assert_allclose(np.asarray(kmst), np.asarray(tmst),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(kp, dtype=np.float32),
            np.asarray(tp, dtype=np.float32), rtol=1e-2, atol=1e-2)


class TestParamStreamGuard:
    def test_unvisited_param_raises(self):
        """A stream-table entry the traced step never consults must
        raise (previously a silent no-op: the param simply never
        streamed)."""
        from paddle_tpu.parallel.param_stream import (
            param_stream_scope, stream_sharding_for)
        a, b = paddle.to_tensor([1.0]), paddle.to_tensor([2.0])
        table = {id(a): "sh_a", id(b): "sh_b"}
        names = {id(a): "layer.0.w", id(b): "layer.1.w"}
        with pytest.raises(RuntimeError, match="layer.1.w"):
            with param_stream_scope(table, names):
                assert stream_sharding_for(a) == "sh_a"  # b: never

    def test_all_visited_is_clean(self):
        from paddle_tpu.parallel.param_stream import (
            param_stream_scope, stream_sharding_for)
        a = paddle.to_tensor([1.0])
        with param_stream_scope({id(a): "sh"}, {id(a): "w"}):
            assert stream_sharding_for(a) == "sh"

    def test_body_exception_not_masked(self):
        from paddle_tpu.parallel.param_stream import param_stream_scope
        a = paddle.to_tensor([1.0])
        with pytest.raises(KeyError, match="boom"):
            with param_stream_scope({id(a): "sh"}, {id(a): "w"}):
                raise KeyError("boom")
