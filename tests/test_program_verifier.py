"""Tape verifier (paddle_tpu/analysis/verifier.py) + satellites.

Every check gets a planted-defect regression test: the defect is a tape
state a buggy pass / unbalanced guard / missing feed CAN produce, and
the assertion is that the verifier (or the hardened error path) flags
it — each of these fails against the pre-verifier code.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.analysis import (verify_program, check_program,
                                 ProgramVerifyError)
from paddle_tpu.analysis.verifier import VERIFY_CALLS as _  # noqa: F401
from paddle_tpu.static.program import (OpDesc, REGISTERED_PASSES,
                                       apply_pass, pop_program,
                                       push_program, replay)


@pytest.fixture(autouse=True)
def _static_mode():
    static.enable_static()
    yield
    static.disable_static()


def _mlp_program():
    """data -> matmul(w) -> relu -> matmul(v) -> mean, all on the tape."""
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [4, 8], "float32")
        rng = np.random.RandomState(0)
        w = paddle.to_tensor(rng.randn(8, 16).astype("float32"))
        v = paddle.to_tensor(rng.randn(16, 2).astype("float32"))
        h = paddle.nn.functional.relu(paddle.matmul(x, w))
        out = paddle.matmul(h, v)
        loss = (out * out).mean()
    return main, x, out, loss


def _codes(findings):
    return {f.code for f in findings}


class TestVerifier:
    def test_clean_program_both_levels(self):
        main, *_ = _mlp_program()
        assert verify_program(main, level="structural") == []
        assert verify_program(main, level="full") == []
        check_program(main, level="full")   # must not raise

    def test_reversed_tape_is_use_before_def(self):
        main, *_ = _mlp_program()
        main._no_autoverify = True
        main.ops = list(reversed(main.ops))
        assert "use-before-def" in _codes(verify_program(main))

    def test_double_definition_is_flagged(self):
        main, *_ = _mlp_program()
        main._no_autoverify = True
        dup = main.ops[-1]
        main.ops.append(OpDesc(dup.type, dup.fn, dup.in_vids,
                               dup.out_vids))
        assert "ssa-double-def" in _codes(verify_program(main))

    def test_leaf_overwrite_is_flagged(self):
        """A recorded mutation of a parameter vid that skipped the
        on_inplace_retag protocol (replay would apply it twice)."""
        main, *_ = _mlp_program()
        main._no_autoverify = True
        last = main.ops[-1]
        leaf_vid = next(v for v in main.leaves
                        if v not in last.in_vids)
        main.ops[-1] = OpDesc(last.type, last.fn, last.in_vids,
                              (leaf_vid,))
        assert "leaf-overwrite" in _codes(verify_program(main))

    def test_inplace_self_alias_is_flagged(self):
        # plant on op 0 writing its own WEIGHT input (a leaf — an input
        # that is an earlier op's output would fire ssa-double-def
        # first, a different hazard)
        main, *_ = _mlp_program()
        main._no_autoverify = True
        op = main.ops[0]
        main.ops[0] = OpDesc(op.type, op.fn, op.in_vids,
                             (op.in_vids[1],))
        assert "inplace-self-alias" in _codes(verify_program(main))

    def test_placeholder_overwrite_is_flagged(self):
        main, x, *_ = _mlp_program()
        main._no_autoverify = True
        op = main.ops[-1]
        main.ops[-1] = OpDesc(op.type, op.fn, op.in_vids,
                              (x._static_vid,))
        assert "placeholder-overwrite" in _codes(verify_program(main))

    def test_dangling_leaf_is_flagged(self):
        main, *_ = _mlp_program()
        main._no_autoverify = True
        main.leaves[next(iter(main.leaves))] = (None, None)
        assert "dangling-leaf" in _codes(verify_program(main))

    def test_unknown_named_var_is_flagged(self):
        main, *_ = _mlp_program()
        main._no_autoverify = True
        main.var_names["ghost"] = 10 ** 9
        assert "unknown-named-var" in _codes(verify_program(main))

    def test_arity_mismatch_is_flagged_at_full_level(self):
        """replay's zip silently drops surplus fn outputs / leaves
        surplus out_vids unbound — only the abstract-eval check sees
        it."""
        main, *_ = _mlp_program()
        main._no_autoverify = True
        op = main.ops[0]
        main.ops[0] = OpDesc(op.type, op.fn, op.in_vids,
                             tuple(op.out_vids) + (10 ** 9 + 1,))
        assert verify_program(main, level="structural") == []
        assert "arity-mismatch" in _codes(
            verify_program(main, level="full"))

    def test_error_message_names_op_and_vid(self):
        main, *_ = _mlp_program()
        main._no_autoverify = True
        main.ops = list(reversed(main.ops))
        with pytest.raises(ProgramVerifyError) as ei:
            check_program(main)
        assert "use-before-def" in str(ei.value)
        assert "mean" in str(ei.value) or "matmul" in str(ei.value)


class TestPassIntegration:
    def test_buggy_pass_fails_at_apply_pass(self):
        """The Operation::Verify contract: a pass that breaks
        topological order is rejected by apply_pass itself."""
        def evil(program, targets=None):
            program.ops = list(reversed(program.ops))
            return program
        REGISTERED_PASSES["_evil_reverse"] = evil
        try:
            main, *_ = _mlp_program()
            main._no_autoverify = True
            with pytest.raises(ProgramVerifyError) as ei:
                apply_pass(main, "_evil_reverse")
            assert "_evil_reverse" in str(ei.value)
        finally:
            del REGISTERED_PASSES["_evil_reverse"]

    @pytest.mark.parametrize("pass_name", sorted(REGISTERED_PASSES))
    def test_registered_passes_leave_random_tape_clean(self, pass_name):
        """Every shipped pass must leave a randomized tape
        verifier-clean (apply_pass now enforces it; the full-level
        re-verify below is the belt to that suspender)."""
        for seed in range(3):
            rng = np.random.RandomState(seed)
            main = static.Program()
            with static.program_guard(main, static.Program()):
                x = static.data("x", [3, 6], "float32")
                t = x
                consts = [paddle.to_tensor(
                    rng.randn(6, 6).astype("float32")) for _ in range(2)]
                live = [t]
                for _ in range(int(rng.randint(3, 8))):
                    choice = rng.randint(4)
                    if choice == 0:
                        t = paddle.matmul(t, consts[rng.randint(2)])
                    elif choice == 1:
                        t = paddle.nn.functional.relu(t)
                    elif choice == 2:
                        t = t + live[rng.randint(len(live))]
                    else:
                        t = t * 0.5
                    live.append(t)
                loss = t.mean()
            apply_pass(main, pass_name, targets=[loss])
            assert verify_program(main, level="full") == [], pass_name

    def test_executor_flag_gated_verification(self):
        """FLAGS_check_program off: the planted double-def replays
        (last write wins, silently).  On: Executor.run refuses it."""
        main, x, out, loss = _mlp_program()
        main._no_autoverify = True
        dup = main.ops[-1]
        main.ops.append(OpDesc(dup.type, dup.fn, dup.in_vids,
                               dup.out_vids))
        exe = static.Executor()
        xv = np.random.RandomState(1).randn(4, 8).astype("float32")
        exe.run(main, feed={"x": xv}, fetch_list=[loss])  # flag off: runs
        paddle.set_flags({"FLAGS_check_program": True})
        try:
            with pytest.raises(ProgramVerifyError):
                exe.run(main, feed={"x": xv}, fetch_list=[loss])
        finally:
            paddle.set_flags({"FLAGS_check_program": False})

    def test_hot_path_runs_zero_verifications_with_flag_off(self):
        from paddle_tpu.analysis import verifier
        main, x, out, loss = _mlp_program()
        exe = static.Executor()
        xv = np.random.RandomState(2).randn(4, 8).astype("float32")
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        before = verifier.VERIFY_CALLS
        keys_before = set(main._exec_cache)
        for _ in range(3):
            exe.run(main, feed={"x": xv}, fetch_list=[loss])
        assert verifier.VERIFY_CALLS == before
        # and verification (when invoked explicitly) perturbs neither
        # the replay cache nor the tape version
        ver = main._version
        verify_program(main, level="full")
        assert main._version == ver
        assert set(main._exec_cache) == keys_before


class TestSatellites:
    def test_pop_program_raises_on_unbalanced_pop(self):
        """Pre-fix: a mismatched pop silently no-oped, leaving the
        recording stack pointing at the wrong Program."""
        a, b = static.Program(), static.Program()
        push_program(a)
        with pytest.raises(RuntimeError, match="unbalanced"):
            pop_program(b)
        pop_program(a)                      # balanced pop still fine
        with pytest.raises(RuntimeError, match="unbalanced"):
            pop_program(a)                  # empty stack

    def test_program_guard_still_balanced(self):
        main = static.Program()
        with static.program_guard(main, static.Program()):
            x = static.data("xx", [2, 2], "float32")
            (x * x).mean()
        assert static.program.current_program() is None \
            if hasattr(static, "program") else True

    def test_replay_missing_var_names_target_and_chain(self):
        """Pre-fix: bare `KeyError: 7`.  Now: the fetch target and the
        consuming-op chain are spelled out."""
        import jax.numpy as jnp

        def mm(a, b):
            return a @ b

        def act(a):
            return jnp.maximum(a, 0)

        ops = [OpDesc("matmul", mm, (1, 2), (3,)),
               OpDesc("relu", act, (3,), (4,)),
               OpDesc("matmul", mm, (4, 5), (6,))]
        env = {2: jnp.ones((4, 4)), 5: jnp.ones((4, 4))}
        with pytest.raises(KeyError) as ei:
            replay(ops, env, [6], var_names={1: "x", 6: "out"})
        msg = str(ei.value)
        assert "var 1 ('x')" in msg
        assert "'matmul'" in msg
        assert "matmul -> relu -> matmul" in msg
        assert "fetch target var 6 ('out')" in msg

    def test_replay_missing_fetch_target_named(self):
        with pytest.raises(KeyError) as ei:
            replay([], {}, [9], var_names={9: "loss"})
        assert "IS fetch target var 9 ('loss')" in str(ei.value)

    def test_executor_missing_feed_mentions_fetch_chain(self):
        """End-to-end: fetching past an unfed placeholder chain keeps
        the old KeyError type but the message now navigates the tape."""
        main, x, out, loss = _mlp_program()
        exe = static.Executor()
        # drop the leaf snapshot for the weight so replay cannot fall
        # back to it (simulates a released constant)
        main._no_autoverify = True
        vid = next(iter(main.leaves))
        main.leaves[vid] = (None, None)
        with pytest.raises(KeyError):
            exe.run(main, feed={"x": np.zeros((4, 8), "float32")},
                    fetch_list=[loss])


class TestCLI:
    def test_selftest_all_checks_fire(self):
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        import verify_program as cli
        assert cli.main(["--selftest"]) == 0

    def test_target_mode_flags_defective_program(self, tmp_path,
                                                 monkeypatch, capsys):
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        import verify_program as cli
        (tmp_path / "progmod.py").write_text(
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "import paddle_tpu.static as static\n"
            "def make():\n"
            "    static.enable_static()\n"
            "    main = static.Program()\n"
            "    main._no_autoverify = True\n"
            "    with static.program_guard(main, static.Program()):\n"
            "        x = static.data('x', [2, 3], 'float32')\n"
            "        (x * x).mean()\n"
            "    static.disable_static()\n"
            "    main.ops = list(reversed(main.ops))\n"
            "    return main\n")
        monkeypatch.chdir(tmp_path)
        rc = cli.main(["progmod:make", "--json"])
        out = capsys.readouterr().out
        assert rc == 1
        import json
        data = json.loads(out)
        assert data["findings"] >= 1
        codes = [f["code"] for p in data["programs"]
                 for f in p["findings"]]
        assert "use-before-def" in codes
