"""Program Sentinel tests (r22): the pass manager, the HLO collective
census parser, census_diff / replication_audit, and the engine
preflights — including the planted-defect acceptance test (a dropped
sharding constraint MUST be caught by the census, naming the op, the
axis, and the byte count).
"""
import json
import os
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.flags import set_flags, get_flag
from paddle_tpu.analysis.base import Finding
from paddle_tpu.analysis.passes import (
    Pass, PassContext, PassManager, SentinelError, register_pass,
    registered_passes, sentinel_preflight)
from paddle_tpu.analysis.sharding_census import (
    HloCollective, parse_hlo_collectives, census_diff,
    replication_audit, modeled_budgets)
from paddle_tpu.analysis.collectives import CollectiveEvent
from paddle_tpu.distributed.topology import (
    build_mesh, set_hybrid_communicate_group)


def _need8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")


def _codes(findings):
    return {f.code for f in findings}


@pytest.fixture(autouse=True)
def _fresh_hcg():
    set_hybrid_communicate_group(None)
    yield
    set_hybrid_communicate_group(None)


# ---------------------------------------------------------------------------
# pass-manager mechanics (no compiles)

def _probe_pass(name, findings, **kw):
    return Pass(name, lambda ctx: list(findings), **kw)


def _fn_ctx(label="probe:prog", **kw):
    return PassContext("fn", label, fn=lambda x: x + 1,
                       args=(jnp.ones(()),), **kw)


class TestPassManager:
    def test_severity_ordering_and_pass_name_stamp(self):
        pm = PassManager(passes=[
            _probe_pass("warns", [Finding("w", "warn", severity="warning")]),
            _probe_pass("errs", [Finding("e", "err", severity="error")]),
        ], use_baseline=False)
        rep = pm.run(_fn_ctx(), level="build")
        assert [f.severity for f in rep.findings] == ["error", "warning"]
        assert rep.findings[0].pass_name == "errs"
        assert rep.findings[1].pass_name == "warns"
        assert rep.passes_run == ["warns", "errs"]

    def test_enable_disable_switches(self):
        p = _probe_pass("probe", [Finding("x", "m")])
        off = _probe_pass("off-by-default", [Finding("y", "m")],
                          default=False)
        rep = PassManager(passes=[p, off], disable=("probe",),
                          use_baseline=False).run(_fn_ctx())
        assert rep.passes_run == []          # default-off stays off
        rep = PassManager(passes=[p, off], enable=("off-by-default",),
                          use_baseline=False).run(_fn_ctx())
        assert set(rep.passes_run) == {"probe", "off-by-default"}

    def test_per_pass_flag_switch(self):
        p = _probe_pass("flagged-probe", [Finding("x", "m")])
        try:
            set_flags({"FLAGS_sentinel_pass_flagged_probe": False})
            rep = PassManager(passes=[p],
                              use_baseline=False).run(_fn_ctx())
            assert rep.passes_run == []
        finally:
            set_flags({"FLAGS_sentinel_pass_flagged_probe": None})
        rep = PassManager(passes=[p], use_baseline=False).run(_fn_ctx())
        assert rep.passes_run == ["flagged-probe"]

    def test_level_filtering(self):
        b = _probe_pass("b", [Finding("b", "m")], level="build")
        f = _probe_pass("f", [Finding("f", "m")], level="full")
        pm = PassManager(passes=[b, f], use_baseline=False)
        assert pm.run(_fn_ctx(), level="build").passes_run == ["b"]
        assert pm.run(_fn_ctx(), level="full").passes_run == ["b", "f"]

    def test_applies_predicate(self):
        p = _probe_pass("trainer-only", [Finding("x", "m")],
                        applies=lambda ctx: ctx.kind == "trainer")
        rep = PassManager(passes=[p], use_baseline=False).run(_fn_ctx())
        assert rep.passes_run == []

    def test_baseline_suppression_exact_and_wildcard(self):
        p = _probe_pass("probe", [Finding("boom", "m")])
        for base in ({("probe:prog", "probe", "boom")},
                     {("*", "probe", "*")},
                     {("probe:prog", "*", "boom")}):
            rep = PassManager(passes=[p], baseline=base).run(_fn_ctx())
            assert rep.findings == []
            assert [f.code for f in rep.suppressed] == ["boom"]
        # a non-matching triple does not suppress
        rep = PassManager(passes=[p], baseline={
            ("other:prog", "probe", "boom")}).run(_fn_ctx())
        assert [f.code for f in rep.findings] == ["boom"]

    def test_pass_crash_becomes_error_finding(self):
        def explode(ctx):
            raise RuntimeError("kaput")
        pm = PassManager(passes=[Pass("bad", explode)],
                         use_baseline=False)
        rep = pm.run(_fn_ctx())
        assert [f.code for f in rep.findings] == ["pass-crashed"]
        assert rep.findings[0].severity == "error"
        assert "kaput" in rep.findings[0].message
        with pytest.raises(RuntimeError, match="kaput"):
            pm.run(_fn_ctx(), collect_errors=False)

    def test_raise_on_error(self):
        pm = PassManager(passes=[
            _probe_pass("errs", [Finding("e", "bad", severity="error")]),
        ], use_baseline=False)
        rep = pm.run(_fn_ctx())
        with pytest.raises(SentinelError) as ei:
            rep.raise_on_error()
        assert ei.value.findings[0].code == "e"
        # warnings alone never raise
        pm = PassManager(passes=[
            _probe_pass("warns", [Finding("w", "m", severity="warning")]),
        ], use_baseline=False)
        pm.run(_fn_ctx()).raise_on_error()

    def test_register_pass_decorator_and_replacement(self):
        try:
            @register_pass("zz-test-probe", level="build", doc="probe")
            def _probe(ctx):
                return [Finding("zz", "m")]
            assert "zz-test-probe" in registered_passes()

            @register_pass("zz-test-probe", level="full")
            def _probe2(ctx):
                return []
            assert registered_passes()["zz-test-probe"].level == "full"
        finally:
            from paddle_tpu.analysis import passes as passes_mod
            passes_mod._REGISTRY.pop("zz-test-probe", None)

    def test_sentinel_preflight_flag_gate(self):
        calls = []

        def record(ctx):
            calls.append(ctx.label)
            return []
        pm = PassManager(passes=[Pass("rec", record)],
                         use_baseline=False)
        try:
            set_flags({"FLAGS_static_sentinel": False})
            assert sentinel_preflight(_fn_ctx(), manager=pm) is None
            assert calls == []
        finally:
            set_flags({"FLAGS_static_sentinel": True})
        rep = sentinel_preflight(_fn_ctx(), manager=pm)
        assert calls and rep is not None

    def test_report_to_dict_shape(self):
        pm = PassManager(passes=[
            _probe_pass("p", [Finding("c", "m", severity="warning")]),
        ], use_baseline=False)
        d = pm.run(_fn_ctx()).to_dict()
        assert d["program"] == "probe:prog"
        assert d["findings"][0]["code"] == "c"
        assert d["findings"][0]["pass"] == "p"
        assert d["suppressed"] == []

    def test_catalog_registered(self):
        cat = registered_passes()
        for name in ("collective-order", "overlap-plan", "donation",
                     "grad-comm-dtype", "collective-census",
                     "replication-audit"):
            assert name in cat, name
        assert cat["collective-census"].level == "full"
        assert cat["collective-order"].level == "build"
        assert cat["dtype-promotion"].default is False


# ---------------------------------------------------------------------------
# HLO census parser (pure text)

_AR = ('  %all-reduce.1 = f32[128,64]{1,0} all-reduce(%p0), '
       'replica_groups={{0,1,2,3},{4,5,6,7}}, '
       'use_global_device_ids=true, to_apply=%add, '
       'metadata={op_name="jit(step)/psum" source_file="x.py"}')
_AG_IOTA = ('  %all-gather.2 = f32[64,64]{1,0} all-gather(%p1), '
            'channel_id=1, replica_groups=[2,4]<=[4,2]T(1,0), '
            'dimensions={0}, use_global_device_ids=true')
_RS_TUPLE = ('  %reduce-scatter.3 = (f32[16]{0}, f32[16]{0}) '
             'reduce-scatter(%a, %b), replica_groups={{0,1,2,3}}, '
             'dimensions={0}, to_apply=%add')
_CP = ('  %collective-permute.4 = f32[32]{0} collective-permute(%x), '
       'source_target_pairs={{0,1},{1,2},{2,3},{3,0}}')
_AR_START = ('  %all-reduce-start.5 = (f32[64]{0}, f32[64]{0}) '
             'all-reduce-start(%p2), replica_groups={{0,1}}, '
             'to_apply=%add')
_AR_DONE = ('  %all-reduce-done.5 = f32[64]{0} '
            'all-reduce-done(%all-reduce-start.5)')


class TestHloParser:
    def test_all_reduce_explicit_groups(self):
        (c,) = parse_hlo_collectives(_AR)
        assert c.op == "all-reduce" and c.cls == "reduce"
        assert c.name == "all-reduce.1"
        assert c.result_bytes == 128 * 64 * 4
        assert (c.num_groups, c.group_size) == (2, 4)
        # all-reduce result carries the full tensor; x num_groups
        assert c.global_bytes == 128 * 64 * 4 * 2
        assert c.op_name == "jit(step)/psum"

    def test_all_gather_iota_groups_with_transpose(self):
        (c,) = parse_hlo_collectives(_AG_IOTA)
        # [2,4]<=[4,2]T(1,0): iota(8).reshape(4,2).T -> groups
        # {0,2,4,6},{1,3,5,7}
        assert (c.num_groups, c.group_size) == (2, 4)
        assert c.global_bytes == 64 * 64 * 4 * 2

    def test_reduce_scatter_tuple_type(self):
        (c,) = parse_hlo_collectives(_RS_TUPLE)
        assert c.cls == "reduce"
        assert c.result_bytes == 2 * 16 * 4          # tuple summed
        # result is the per-participant shard: x group_size x groups
        assert c.global_bytes == 2 * 16 * 4 * 4 * 1

    def test_collective_permute_pairs(self):
        (c,) = parse_hlo_collectives(_CP)
        assert c.cls == "permute"
        assert c.num_groups == 4                      # 4 pairs
        assert c.global_bytes == 32 * 4 * 4

    def test_async_start_done_counted_once_and_halved(self):
        out = parse_hlo_collectives(_AR_START + "\n" + _AR_DONE)
        assert len(out) == 1
        (c,) = out
        # -start's tuple result doubles the operand buffer; halved back
        assert c.result_bytes == 64 * 4
        assert c.global_bytes == 64 * 4

    def test_non_collective_text_ignored(self):
        text = ("  %add.1 = f32[4]{0} add(%a, %b)\n"
                "  %fusion = f32[4]{0} fusion(%c), kind=kLoop\n"
                "  ROOT %tuple = () tuple()\n")
        assert parse_hlo_collectives(text) == []

    def test_axes_inference_on_mesh(self):
        _need8()
        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("dp", "mp"))
        ids = {(r, c): int(devs[r, c].id) for r in range(2)
               for c in range(4)}
        # groups fixing dp, varying mp
        mp_groups = "{{%s},{%s}}" % (
            ",".join(str(ids[(0, c)]) for c in range(4)),
            ",".join(str(ids[(1, c)]) for c in range(4)))
        line = ("  %all-reduce.9 = f32[8]{0} all-reduce(%p), "
                "replica_groups=" + mp_groups + ", to_apply=%add")
        (c,) = parse_hlo_collectives(line, mesh)
        assert c.axes == ("mp",)
        # groups fixing mp, varying dp
        dp_groups = "{" + ",".join(
            "{%d,%d}" % (ids[(0, c)], ids[(1, c)]) for c in range(4)) + "}"
        line = ("  %all-reduce.10 = f32[8]{0} all-reduce(%p), "
                "replica_groups=" + dp_groups + ", to_apply=%add")
        (c,) = parse_hlo_collectives(line, mesh)
        assert c.axes == ("dp",)


# ---------------------------------------------------------------------------
# census_diff / modeled_budgets (pure)

def _hc(op, gbytes, axes=(), name="x", op_name=""):
    from paddle_tpu.analysis.sharding_census import COLLECTIVE_CLASS
    return HloCollective(op=op, name=name, cls=COLLECTIVE_CLASS[op],
                         result_bytes=gbytes, global_bytes=gbytes,
                         num_groups=1, group_size=2, axes=tuple(axes),
                         op_name=op_name)


class TestCensusDiff:
    def test_within_budget_is_clean(self):
        emitted = [_hc("all-reduce", 1 << 20)]
        modeled = [CollectiveEvent("psum", ("grads",), ("dp",),
                                   bytes=1 << 20)]
        assert census_diff(emitted, modeled, min_bytes=1024,
                           slack=2.0) == []

    def test_excess_traffic_is_error_naming_ops(self):
        emitted = [_hc("all-gather", 8 << 20, axes=("mp",),
                       name="all-gather.7",
                       op_name="jit(step)/dot_general")]
        f = census_diff(emitted, [], min_bytes=1024, slack=2.0,
                        label="prog")
        assert _codes(f) == {"census-unmodeled-collective"}
        (g,) = f
        assert g.severity == "error"
        assert "all-gather.7" in g.message      # instruction named
        assert "mp" in g.message                # axis named
        assert "8.000MB" in g.message           # byte count named
        assert "dot_general" in g.message       # source op named
        assert g.detail["class"] == "gather"
        assert g.detail["emitted_bytes"] == 8 << 20

    def test_missing_firm_budget_is_warning(self):
        modeled = [CollectiveEvent("psum", ("grads",), ("dp",),
                                   bytes=64 << 20)]
        f = census_diff([], modeled, min_bytes=1024, slack=2.0)
        assert _codes(f) == {"census-missing-collective"}
        assert f[0].severity == "warning"

    def test_allowance_never_missing_but_raises_ceiling(self):
        allowance = [CollectiveEvent(
            "all_gather", ("allowance", "params"), ("sharding",),
            bytes=16 << 20)]
        # nothing emitted against an allowance: no warning
        assert census_diff([], allowance, min_bytes=1024, slack=2.0) == []
        # emitted traffic up to the allowance: no error
        emitted = [_hc("all-gather", 16 << 20)]
        assert census_diff(emitted, allowance, min_bytes=1024,
                           slack=2.0) == []

    def test_min_bytes_floor_absorbs_noise(self):
        emitted = [_hc("all-reduce", 100)]
        assert census_diff(emitted, [], min_bytes=1024, slack=2.0) == []

    def test_modeled_budgets_firm_only_drops_allowances(self):
        events = [
            CollectiveEvent("psum", ("grads",), ("dp",), bytes=100),
            CollectiveEvent("all_gather", ("allowance", "p"),
                            ("sharding",), bytes=50),
            CollectiveEvent("ppermute", ("ring",), ("sep",), bytes=7),
        ]
        assert modeled_budgets(events) == {
            "reduce": 100, "gather": 50, "permute": 7}
        assert modeled_budgets(events, firm_only=True) == {
            "reduce": 100, "gather": 0, "permute": 7}


# ---------------------------------------------------------------------------
# replication audit (pure synthetic ENTRY text)

_HLO_TMPL = """HloModule m

%add (a: f32[], b: f32[]) {
  %scratch = f32[999,999]{1,0} parameter(0)
}

ENTRY %main (p0: f32[@W@]) -> f32[] {
  %p0 = f32[@W@]{1,0} parameter(0)
  %p1 = f32[256]{0} parameter(1)
  %c = f32[] constant(0)
}
"""


def _entry_hlo(w_shape):
    return _HLO_TMPL.replace(
        "@W@", ",".join(str(d) for d in w_shape))


class TestReplicationAudit:
    PARAMS = [("w", (64, 2048), "float32", (64, 256)),   # mp-sharded /8
              ("b", (256,), "float32", (256,))]          # replicated

    def test_sharded_param_at_local_shape_is_clean(self):
        text = _entry_hlo((64, 256))
        assert replication_audit(text, self.PARAMS,
                                 min_bytes=1024) == []

    def test_sharded_param_at_global_shape_flagged(self):
        text = _entry_hlo((64, 2048))
        f = replication_audit(text, self.PARAMS, min_bytes=1024,
                              label="prog")
        assert _codes(f) == {"replicated-large-tensor"}
        (g,) = f
        assert g.severity == "error"
        assert "'w'" in g.message
        assert "(64, 2048)" in g.message and "(64, 256)" in g.message

    def test_small_tensors_below_floor_ignored(self):
        text = _entry_hlo((64, 2048))
        assert replication_audit(text, self.PARAMS,
                                 min_bytes=1 << 30) == []

    def test_intentionally_replicated_never_flagged(self):
        # b has lshape == gshape: even absent from ENTRY, not a finding
        text = _entry_hlo((64, 256)).replace(
            "  %p1 = f32[256]{0} parameter(1)\n", "")
        assert replication_audit(text, self.PARAMS,
                                 min_bytes=1024) == []

    def test_called_computation_params_ignored(self):
        # the f32[999,999] parameter lives in %add, not ENTRY
        text = _entry_hlo((64, 256))
        params = [("s", (999, 999), "float32", (999, 333))]
        assert replication_audit(text, params, min_bytes=1024) == []


# ---------------------------------------------------------------------------
# engine preflights: the model matches the metal

class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(16, 32)
        self.l2 = nn.Linear(32, 16)
        self.l3 = nn.Linear(16, 4)

    def forward(self, x):
        h = nn.functional.relu(self.l1(x))
        return self.l3(nn.functional.relu(self.l2(h)))


def _mse(pred, y):
    return ((pred - y) ** 2).mean()


def _mlp_batch():
    rng = np.random.RandomState(0)
    return (rng.randn(8, 16).astype("float32"),
            rng.randn(8, 4).astype("float32"))


class TestTrainerPreflight:
    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_zero_stages_census_clean(self, stage):
        _need8()
        from paddle_tpu.parallel import ShardedTrainStep
        paddle.seed(0)
        m = _MLP()
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=1e-3)
        step = ShardedTrainStep(m, opt, build_mesh(sharding=8),
                                sharding_stage=stage, loss_fn=_mse)
        x, y = _mlp_batch()
        rep = step.preflight(x, y, census_min_bytes=64)
        assert rep is not None
        assert rep.findings == [], [f.message for f in rep.findings]
        assert "collective-census" in rep.passes_run
        assert "replication-audit" in rep.passes_run
        assert "donation" in rep.passes_run


class TestHybridPreflight:
    def test_composed_point_census_clean(self):
        _need8()
        from paddle_tpu.parallel import HybridParallelEngine
        paddle.seed(0)
        m = _MLP()
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=1e-3)
        eng = HybridParallelEngine(m, opt, loss_fn=_mse, dp_degree=2,
                                   mp_degree=2, sharding_degree=2,
                                   sharding_stage=1)
        x, y = _mlp_batch()
        rep = eng.preflight(x, y, census_min_bytes=64)
        assert rep is not None
        assert rep.findings == [], [f.message for f in rep.findings]


@pytest.fixture(scope="module")
def pipeline_engine():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc, PipelineLayer)
    from paddle_tpu.parallel.pipeline import PipelineEngine
    set_hybrid_communicate_group(None)
    d = 8
    paddle.seed(0)
    pl = PipelineLayer([LayerDesc(nn.Linear, d, d) for _ in range(4)],
                       loss_fn=_mse)
    eng = PipelineEngine(pl, mesh=build_mesh(pp=2, dp=4))
    rng = np.random.RandomState(7)
    data = (rng.randn(8, d).astype("float32"),
            rng.randn(8, d).astype("float32"))
    yield eng, data
    set_hybrid_communicate_group(None)


class TestPipelinePreflight:
    def test_chunk_programs_census_clean(self, pipeline_engine):
        eng, data = pipeline_engine
        reports = eng.preflight(data, census_min_bytes=64)
        assert len(reports) == 2 * len(eng.chunks)   # fwd + bwd each
        for rep in reports:
            assert rep.findings == [], (
                rep.label, [f.message for f in rep.findings])

    # satellite (c): lint_donation over PipelineEngine-built programs
    def test_chunk_programs_declare_no_donation(self, pipeline_engine):
        from paddle_tpu.analysis import lint_donation
        eng, data = pipeline_engine
        st = eng.chunks[0]
        st.begin_batch()
        a = st.place_activation(jnp.asarray(data[0]))
        lowered = st._fwd.lower(st.param_vals, st.buf_vals, a)
        assert lowered.donate_argnums == ()
        assert lint_donation(lowered) == []

    def test_chunk_bwd_activation_donation_aliases(self, pipeline_engine):
        # the activation donated into a backward IS aliasable: dx has
        # the same shape and the backward consumes x
        from paddle_tpu.analysis import lint_donation
        eng, data = pipeline_engine
        st = eng.chunks[0]
        st.begin_batch()
        x = jnp.ones((4, 8), jnp.float32)
        dy = jnp.ones((4, 8), jnp.float32)
        lowered = jax.jit(st._bwd_impl, donate_argnums=(2,)).lower(
            st.param_vals, st.buf_vals, x, dy)
        assert lint_donation(lowered) == []

    def test_chunk_bwd_dx_param_donation_flagged(self, pipeline_engine):
        # blanket-donating params into the zero-bubble dx-only half is
        # a real bug: dx = dy @ W^T never reads the biases, XLA drops
        # them, and the donation silently keeps both copies live — the
        # lint must name each dropped donated leaf
        from paddle_tpu.analysis import lint_donation
        eng, data = pipeline_engine
        st = eng.chunks[0]
        st.begin_batch()
        x = jnp.ones((4, 8), jnp.float32)
        dy = jnp.ones((4, 8), jnp.float32)
        lowered = jax.jit(st._bwd_dx_impl, donate_argnums=(0,)).lower(
            st.param_vals, st.buf_vals, x, dy)
        f = lint_donation(lowered)
        assert _codes(f) == {"donation-unaliased"}
        assert len(f) == 2                    # the two bias leaves
        assert all("float32[8]" in g.message for g in f)


# ---------------------------------------------------------------------------
# the planted-defect acceptance test: drop a sharding constraint from a
# dp x mp program and the census MUST name the implicit all-gather

class TestInjectedDefect:
    def _run(self, fn, modeled, min_bytes=256):
        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("dp", "mp"))
        rng = np.random.RandomState(0)
        w1 = jax.device_put(rng.randn(64, 256).astype("float32"),
                            NamedSharding(mesh, P(None, "mp")))
        w2 = jax.device_put(rng.randn(256, 64).astype("float32"),
                            NamedSharding(mesh, P("mp", None)))
        x = jax.device_put(rng.randn(32, 64).astype("float32"),
                           NamedSharding(mesh, P("dp", None)))
        ctx = PassContext("fn", "defect:prog", fn=fn(mesh),
                          args=(x, w1, w2), mesh=mesh,
                          modeled_events=lambda: modeled,
                          extra={"census_min_bytes": min_bytes,
                                 "census_slack": 2.0})
        return PassManager(use_baseline=False).run(ctx, level="full")

    MODELED = [CollectiveEvent("psum", ("y-partial",), ("mp",),
                               bytes=32 * 64 * 4)]

    def test_constrained_program_clean(self):
        _need8()

        def make(mesh):
            def constrained(x, w1, w2):
                h = jax.lax.with_sharding_constraint(
                    x @ w1, NamedSharding(mesh, P("dp", "mp")))
                return (h @ w2).sum()
            return constrained
        rep = self._run(make, self.MODELED)
        assert rep.findings == [], [f.message for f in rep.findings]

    def test_dropped_constraint_caught_with_op_axis_bytes(self):
        _need8()

        def make(mesh):
            def dropped(x, w1, w2):
                # the mp constraint removed: XLA must all-gather h
                h = jax.lax.with_sharding_constraint(
                    x @ w1, NamedSharding(mesh, P("dp", None)))
                return (h @ w2).sum()
            return dropped
        rep = self._run(make, self.MODELED)
        hits = [f for f in rep.findings
                if f.code == "census-unmodeled-collective"]
        assert hits, [f.message for f in rep.findings]
        (g,) = hits
        assert g.severity == "error"
        assert "all-gather" in g.message          # the op
        assert "mp" in str(g.detail)              # the axis
        assert "MB" in g.message                  # the byte count
        ops = g.detail["ops"]
        assert any("mp" in op["axes"] for op in ops)
        assert all(op["global_bytes"] > 0 for op in ops)


# ---------------------------------------------------------------------------
# satellite (e): the static_check.py --smoke tier-1 leg

class TestStaticCheckSmoke:
    def test_smoke_leg_green(self, capsys):
        _need8()
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        sys.path.insert(0, tools)
        try:
            import static_check
        finally:
            sys.path.remove(tools)
        rc = static_check.main(["--smoke", "--json", "--min-bytes",
                                "512"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0, doc
        names = {p["program"] for p in doc["programs"]}
        assert len(names) == len(static_check.SMOKE)
        for prog in doc["programs"]:
            assert prog.get("findings") == [], prog
        assert {c["check"] for c in doc["selftest"]} == {
            "constrained-program-clean", "dropped-constraint-caught"}
        assert all(c["ok"] for c in doc["selftest"]), doc["selftest"]

    def test_baseline_file_parses(self):
        from paddle_tpu.analysis.passes import load_baseline
        base = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "static_baseline.json")
        assert isinstance(load_baseline(base), set)
