"""Generated execution tests for the exec-spec table.

One parametrized test per ExecSpec (`paddle_tpu/ops/exec_specs.py`):
runs the op on sampled inputs and checks against the numpy/scipy
reference (or the spec's property check).  Together with the OpSpec
registry tests this is the executed-coverage evidence the op audit
reports — the TPU analog of the reference's OpTest matrix
(test/legacy_test/op_test.py check_output).
"""
import pytest

from paddle_tpu.ops.exec_specs import EXEC_SPECS, run_spec

_BY_ID = {}
for i, s in enumerate(EXEC_SPECS):
    _BY_ID[f"{s.op}#{i}" if s.op in {t.op for t in EXEC_SPECS[:i]}
           else s.op] = s


@pytest.mark.parametrize("name", sorted(_BY_ID))
def test_exec_spec(name):
    run_spec(_BY_ID[name])


def test_no_duplicate_full_specs():
    """Each yaml op gets counted once in the audit even if multiple
    specs exist; sanity-check the table is non-empty and well-formed."""
    assert len(EXEC_SPECS) >= 150
    for s in EXEC_SPECS:
        assert (s.ref is not None or s.check is not None
                or s.custom is not None), s.op
