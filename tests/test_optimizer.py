"""Optimizer + LR scheduler + AMP tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.tensor import Parameter


def make_param(val):
    p = Parameter(np.asarray(val, np.float32))
    return p


def set_grad(p, g):
    p.grad = paddle.to_tensor(np.asarray(g, np.float32))


class TestSGD:
    def test_step(self):
        p = make_param([1.0, 2.0])
        opt = paddle.optimizer.SGD(0.1, parameters=[p])
        set_grad(p, [1.0, 1.0])
        opt.step()
        np.testing.assert_allclose(p.numpy(), [0.9, 1.9], rtol=1e-6)

    def test_weight_decay(self):
        p = make_param([1.0])
        opt = paddle.optimizer.SGD(0.1, parameters=[p], weight_decay=0.5)
        set_grad(p, [0.0])
        opt.step()
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.5], rtol=1e-6)


class TestMomentum:
    def test_velocity(self):
        p = make_param([0.0])
        opt = paddle.optimizer.Momentum(0.1, 0.9, parameters=[p])
        set_grad(p, [1.0])
        opt.step()
        np.testing.assert_allclose(p.numpy(), [-0.1])
        set_grad(p, [1.0])
        opt.step()
        # v2 = 0.9*1 + 1 = 1.9 → p = -0.1 - 0.19
        np.testing.assert_allclose(p.numpy(), [-0.29], rtol=1e-5)


class TestAdam:
    def test_first_step_size(self):
        p = make_param([1.0])
        opt = paddle.optimizer.Adam(0.001, parameters=[p])
        set_grad(p, [10.0])
        opt.step()
        # adam first step ≈ lr regardless of grad scale
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.001], rtol=1e-4)

    def test_reference_sequence(self):
        # compare against a hand-rolled adam
        rng = np.random.RandomState(0)
        w = rng.rand(4).astype(np.float32)
        g_seq = [rng.rand(4).astype(np.float32) for _ in range(5)]
        p = make_param(w.copy())
        opt = paddle.optimizer.Adam(0.01, parameters=[p])
        m = np.zeros(4)
        v = np.zeros(4)
        ref = w.astype(np.float64).copy()
        for t, g in enumerate(g_seq, 1):
            set_grad(p, g)
            opt.step()
            m = 0.9 * m + 0.1 * g
            v = 0.999 * v + 0.001 * g * g
            mh = m / (1 - 0.9 ** t)
            vh = v / (1 - 0.999 ** t)
            ref -= 0.01 * mh / (np.sqrt(vh) + 1e-8)
        np.testing.assert_allclose(p.numpy(), ref, rtol=1e-4)


class TestAdamW:
    def test_decoupled_decay(self):
        p = make_param([1.0])
        opt = paddle.optimizer.AdamW(0.1, parameters=[p], weight_decay=0.1)
        set_grad(p, [0.0])
        opt.step()
        # zero grad → pure decay: p -= lr * wd * p
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.1 * 1.0],
                                   rtol=1e-5)

    def test_apply_decay_param_fun(self):
        p = make_param([1.0])
        p.name = "bias"
        opt = paddle.optimizer.AdamW(
            0.1, parameters=[p], weight_decay=0.5,
            apply_decay_param_fun=lambda n: "bias" not in n)
        set_grad(p, [0.0])
        opt.step()
        np.testing.assert_allclose(p.numpy(), [1.0])  # no decay applied


class TestMultiPrecision:
    def test_bf16_master_weights(self):
        p = Parameter(np.asarray([1.0], np.float32))
        p._value = p._value.astype("bfloat16")
        opt = paddle.optimizer.AdamW(1e-4, parameters=[p],
                                     multi_precision=True)
        for _ in range(10):
            set_grad(p, [0.01])
            opt.step()
        # master weights keep fp32 precision across tiny updates
        assert id(p) in opt._master_weights


class TestLRSchedulers:
    def test_scheduler_drives_optimizer(self):
        sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        p = make_param([1.0])
        opt = paddle.optimizer.SGD(sched, parameters=[p])
        assert opt.get_lr() == pytest.approx(0.1)
        sched.step()
        sched.step()
        assert opt.get_lr() == pytest.approx(0.05)

    def test_cosine(self):
        s = paddle.optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert s() == pytest.approx(1.0)
        s.step(10)
        assert s() == pytest.approx(0.0, abs=1e-9)

    def test_warmup(self):
        s = paddle.optimizer.lr.LinearWarmup(0.1, 10, 0.0, 0.1)
        s.step(5)
        assert s() == pytest.approx(0.05)
        s.step(20)
        assert s() == pytest.approx(0.1)

    def test_piecewise(self):
        s = paddle.optimizer.lr.PiecewiseDecay([3, 6], [0.1, 0.01, 0.001])
        s.step(0)
        assert s() == pytest.approx(0.1)
        s.step(4)
        assert s() == pytest.approx(0.01)
        s.step(100)
        assert s() == pytest.approx(0.001)

    def test_reduce_on_plateau(self):
        s = paddle.optimizer.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)
        assert s() == pytest.approx(0.05)


class TestGradClipIntegration:
    def test_clip_in_optimizer(self):
        p = make_param([0.0])
        clip = nn.ClipGradByGlobalNorm(0.5)
        opt = paddle.optimizer.SGD(1.0, parameters=[p], grad_clip=clip)
        set_grad(p, [10.0])
        opt.step()
        np.testing.assert_allclose(p.numpy(), [-0.5], rtol=1e-5)


class TestAMP:
    def test_auto_cast_matmul_bf16(self):
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            a = paddle.ones([2, 2])
            out = paddle.matmul(a, a)
        assert out.dtype == paddle.bfloat16

    def test_black_list_stays_fp32(self):
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            x = paddle.ones([4], "bfloat16")
            out = paddle.mean(x)
        assert out.dtype == paddle.float32

    def test_decorate_o2(self):
        net = nn.Linear(2, 2)
        net2 = paddle.amp.decorate(net, level="O2", dtype="bfloat16")
        assert net2.weight.dtype == paddle.bfloat16

    def test_grad_scaler_noop_path(self):
        p = make_param([1.0])
        opt = paddle.optimizer.SGD(0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(use_dynamic_loss_scaling=False)
        loss = paddle.to_tensor(1.0)
        scaled = scaler.scale(loss)
        assert float(scaled) == 1.0
        set_grad(p, [1.0])
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-6)


class TestStateDict:
    def test_optimizer_state_roundtrip(self):
        p = make_param([1.0, 2.0])
        p.name = "w0"
        opt = paddle.optimizer.Adam(0.01, parameters=[p])
        set_grad(p, [0.1, 0.1])
        opt.step()
        sd = opt.state_dict()
        p2 = make_param([1.0, 2.0])
        p2.name = "w0"
        opt2 = paddle.optimizer.Adam(0.01, parameters=[p2])
        opt2.set_state_dict(sd)
        assert opt2._step_count == 1
        np.testing.assert_allclose(
            opt2._accumulators[id(p2)]["moment1"],
            opt._accumulators[id(p)]["moment1"])


class TestFusedAdamW:
    """Pallas fused kernel vs the pure Adam update rule (interpret mode),
    and the master-weight path inside the jitted trainers."""

    @pytest.mark.parametrize("n", [1000, 512 * 1024 + 3])
    def test_kernel_matches_pure_rule(self, n):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.fused_adamw import fused_adamw
        from paddle_tpu.optimizer.optimizer import Adam

        rng = np.random.RandomState(0)
        g = jnp.asarray(rng.randn(n).astype(np.float32)).astype(jnp.bfloat16)
        m = jnp.asarray(rng.randn(n).astype(np.float32)) * 0.1
        v = jnp.abs(jnp.asarray(rng.randn(n).astype(np.float32))) * 0.01
        master = jnp.asarray(rng.randn(n).astype(np.float32))
        lr, step, wd = 1e-3, 3, 0.1

        p_f, m_f, v_f, mst_f = fused_adamw(
            g, m, v, master, lr, step, b1=0.9, b2=0.999, eps=1e-8,
            wd=wd, decoupled=True, out_dtype=jnp.bfloat16)
        ref_mst, ref_state = Adam._update(
            master, g.astype(jnp.float32),
            {"moment1": m, "moment2": v}, lr, wd, step,
            b1=0.9, b2=0.999, eps=1e-8, decoupled=True)
        np.testing.assert_allclose(np.asarray(mst_f), np.asarray(ref_mst),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(m_f),
                                   np.asarray(ref_state["moment1"]),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(v_f),
                                   np.asarray(ref_state["moment2"]),
                                   atol=1e-6, rtol=1e-6)
        # p is the bf16 cast of the (1e-6-tolerance) master: values near a
        # rounding boundary may flip one bf16 ulp
        np.testing.assert_allclose(
            np.asarray(p_f.astype(jnp.float32)),
            np.asarray(ref_mst.astype(jnp.bfloat16).astype(jnp.float32)),
            atol=1e-2, rtol=1e-2)

    def test_trainstep_master_weights(self):
        """bf16 model + multi_precision: the fp32 master accumulates
        updates a bf16-only parameter would lose."""
        import jax.numpy as jnp
        from paddle_tpu.jit import TrainStep

        paddle.seed(0)
        lin = nn.Linear(8, 8)
        lin.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(1e-5, parameters=lin.parameters(),
                                     multi_precision=True)

        def loss_fn(out, y):
            return ((out - y) ** 2).mean()

        step = TrainStep(lin, loss_fn, opt)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        x = paddle.cast(x, "bfloat16")
        losses = [float(np.asarray(step(x, x).value)) for _ in range(5)]
        # master state exists and is fp32
        assert all("master" in s for s in step._opt_states)
        assert all(s["master"].dtype == jnp.float32
                   for s in step._opt_states)
        # tiny lr: bf16-only updates would round away; the fp32 master
        # must still drift from its starting point
        drift = float(np.abs(np.asarray(
            step._opt_states[0]["master"]).astype(np.float64)
            - np.asarray(lin.weight.value.astype(jnp.float32))).max())
        assert drift > 0, "fp32 master must hold sub-bf16-ulp updates"
        assert losses[-1] <= losses[0]

    def test_sharded_trainer_master_sharded_stage1(self):
        """ZeRO-1: master shards land on the sharding axis with the
        moments."""
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh

        paddle.seed(0)
        lin = nn.Linear(16, 16)
        lin.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(1e-3, parameters=lin.parameters(),
                                     multi_precision=True)
        mesh = build_mesh(sharding=4,
                          devices=jax.devices()[:4])
        st = ShardedTrainStep(lin, opt, mesh, sharding_stage=1,
                              loss_fn=lambda o, y: ((o - y) ** 2).mean())
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 16).astype(np.float32))
        x = paddle.cast(x, "bfloat16")
        l0 = float(np.asarray(st(x, x).value))
        for s in st._opt_states:
            assert "master" in s
            spec = s["master"].sharding.spec
            assert any(ax == "sharding" for ax in spec if ax), spec
        l1 = float(np.asarray(st(x, x).value))
        assert np.isfinite(l0) and np.isfinite(l1)


class TestFusedAdamWFp32Params:
    """fp32-param ("param is the master", flax param_dtype idiom) fused
    kernel mode + bf16 moment storage + shard_map wrapping."""

    def test_fp32_mode_matches_pure_rule(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.fused_adamw import fused_adamw
        from paddle_tpu.optimizer.optimizer import Adam

        rng = np.random.RandomState(0)
        n = 4096
        g = jnp.asarray(rng.randn(n).astype(np.float32))
        m = jnp.asarray(rng.randn(n).astype(np.float32)) * 0.1
        v = jnp.abs(jnp.asarray(rng.randn(n).astype(np.float32))) * 0.01
        p = jnp.asarray(rng.randn(n).astype(np.float32))
        lr, step, wd = 1e-3, 3, 0.1

        p_f, m_f, v_f, mst_f = fused_adamw(
            g, m, v, p, lr, step, b1=0.9, b2=0.999, eps=1e-8,
            wd=wd, decoupled=True, out_dtype=jnp.float32)
        ref_p, ref_state = Adam._update(
            p, g, {"moment1": m, "moment2": v}, lr, wd, step,
            b1=0.9, b2=0.999, eps=1e-8, decoupled=True)
        np.testing.assert_allclose(np.asarray(p_f), np.asarray(ref_p),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(mst_f), np.asarray(p_f))
        np.testing.assert_allclose(np.asarray(m_f),
                                   np.asarray(ref_state["moment1"]),
                                   atol=1e-6, rtol=1e-6)

    def test_bf16_moments_match_pure_rule(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.fused_adamw import fused_adamw
        from paddle_tpu.optimizer.optimizer import Adam

        rng = np.random.RandomState(1)
        n = 2048
        g = jnp.asarray(rng.randn(n).astype(np.float32))
        m = (jnp.asarray(rng.randn(n).astype(np.float32)) * 0.1
             ).astype(jnp.bfloat16)
        v = (jnp.abs(jnp.asarray(rng.randn(n).astype(np.float32)))
             * 0.01).astype(jnp.bfloat16)
        p = jnp.asarray(rng.randn(n).astype(np.float32))

        p_f, m_f, v_f, _ = fused_adamw(
            g, m, v, p, 1e-3, 2, b1=0.9, b2=0.999, eps=1e-8,
            wd=0.0, decoupled=True, out_dtype=jnp.float32)
        assert m_f.dtype == jnp.bfloat16 and v_f.dtype == jnp.bfloat16
        ref_p, ref_state = Adam._update(
            p, g, {"moment1": m, "moment2": v}, 1e-3, 0.0, 2,
            b1=0.9, b2=0.999, eps=1e-8, decoupled=True)
        np.testing.assert_allclose(np.asarray(p_f), np.asarray(ref_p),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(m_f.astype(jnp.float32)),
            np.asarray(ref_state["moment1"].astype(jnp.float32)))

    def test_adam_moment_dtype_state(self):
        """moment_dtype plumbs into accumulator init + pure update."""
        import jax.numpy as jnp

        p = paddle.to_tensor(np.ones(8, np.float32))
        p.stop_gradient = False
        opt = paddle.optimizer.Adam(0.01, parameters=[p],
                                    moment_dtype="bfloat16")
        loss = (p ** 2).sum()
        loss.backward()
        opt.step()
        st = opt._accumulators[id(p)]
        assert st["moment1"].dtype == jnp.bfloat16
        assert st["moment2"].dtype == jnp.bfloat16

    def test_sharded_trainer_fused_shard_map(self):
        """The fused kernel runs shard_map-wrapped on a >1-device mesh
        (Pallas interpret mode on CPU) and matches the unfused path."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh

        def run_once(force_fused):
            paddle.set_flags({"FLAGS_fused_adamw_interpret": force_fused,
                              "FLAGS_use_fused_adamw": force_fused})
            try:
                paddle.seed(0)
                lin = nn.Linear(16, 16)
                # fp32 params + bf16 moments: the fp32-param kernel mode
                opt = paddle.optimizer.AdamW(
                    1e-2, parameters=lin.parameters(),
                    moment_dtype="bfloat16")
                mesh = build_mesh(sharding=4, devices=jax.devices()[:4])
                st = ShardedTrainStep(
                    lin, opt, mesh, sharding_stage=3,
                    loss_fn=lambda o, y: ((o - y) ** 2).mean())
                x = paddle.to_tensor(np.random.RandomState(0)
                                     .randn(8, 16).astype(np.float32))
                return [float(np.asarray(st(x, x).value))
                        for _ in range(3)]
            finally:
                paddle.set_flags({"FLAGS_fused_adamw_interpret": False,
                                  "FLAGS_use_fused_adamw": True})

        fused = run_once(True)
        plain = run_once(False)
        np.testing.assert_allclose(fused, plain, rtol=2e-2, atol=2e-2)
        assert fused[-1] < fused[0]


class TestMultiTensorAdamW:
    """Opt-in multi-tensor grouping (FLAGS_multi_tensor_adamw): small
    params flatten into ONE fused call; must match the per-param path
    bit-for-bit semantics-wise.  Default OFF by measurement (neutral on
    llama, -4.3% on bert — PROFILE_r05.md)."""

    def test_grouped_matches_per_param(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.jit import TrainStep

        def run(mt):
            set_flags({"fused_adamw_interpret": True,
                       "multi_tensor_adamw": mt})
            try:
                paddle.seed(7)
                m = nn.Sequential(nn.Linear(16, 32), nn.LayerNorm(32),
                                  nn.Linear(32, 4))
                opt = paddle.optimizer.AdamW(
                    1e-2, parameters=m.parameters(), weight_decay=0.01)
                step = TrainStep(
                    m, lambda o, t: ((o - t) ** 2).mean(), opt)
                x = np.random.RandomState(0).randn(8, 16).astype(
                    np.float32)
                y = np.random.RandomState(1).randn(8, 4).astype(
                    np.float32)
                for _ in range(3):
                    step(paddle.to_tensor(x), paddle.to_tensor(y))
                return [np.asarray(p.value) for p in m.parameters()]
            finally:
                set_flags({"fused_adamw_interpret": False,
                           "multi_tensor_adamw": False})

        for a, b in zip(run(False), run(True)):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

    def test_grouping_key_separates_weight_decay(self):
        """Params with different wd must not land in one flat group."""
        import jax.numpy as jnp
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.optimizer.jit_update import apply_updates
        from paddle_tpu.optimizer.optimizer import Adam

        rng = np.random.RandomState(3)
        params = [jnp.asarray(rng.randn(8).astype(np.float32))
                  for _ in range(4)]
        grads = [jnp.asarray(rng.randn(8).astype(np.float32))
                 for _ in range(4)]
        states = [{"moment1": jnp.zeros(8, jnp.float32),
                   "moment2": jnp.zeros(8, jnp.float32)}
                  for _ in range(4)]
        hp = dict(b1=0.9, b2=0.999, eps=1e-8, decoupled=True)
        wds = [0.1, 0.0, 0.1, 0.0]
        set_flags({"multi_tensor_adamw": True,
                   "fused_adamw_interpret": True})
        try:
            new_p, _ = apply_updates(Adam._update, params, grads,
                                     states, 1e-2, wds, 1, hp)
        finally:
            set_flags({"multi_tensor_adamw": False,
                       "fused_adamw_interpret": False})
        for i in range(4):
            ref_p, _ = Adam._update(
                params[i], grads[i],
                {"moment1": jnp.zeros(8, jnp.float32),
                 "moment2": jnp.zeros(8, jnp.float32)},
                1e-2, wds[i], 1, **hp)
            np.testing.assert_allclose(np.asarray(new_p[i]),
                                       np.asarray(ref_p),
                                       rtol=1e-5, atol=1e-6)
