"""Collective hang watchdog (reference comm_task_manager.h:37
CommTaskManager: age in-flight collectives, report on timeout)."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.watchdog import (CommTaskManager, watched,
                                             get_comm_task_manager)


class TestWatchdog:
    def test_disabled_by_default(self):
        mgr = get_comm_task_manager()
        assert mgr.start_task("noop") is None  # flag 0 -> no-op

    def test_times_out_and_reports(self):
        mgr = CommTaskManager(poll_interval=0.05)
        fired = []
        mgr.on_timeout = lambda task, report: fired.append(
            (task.name, report))
        task = mgr.start_task("hung allreduce", timeout=0.2)
        try:
            time.sleep(0.6)
        finally:
            task.done()
            mgr.shutdown()
        assert fired and fired[0][0] == "hung allreduce"
        report = fired[0][1]
        assert "thread" in report          # stack dump present
        assert "exceeded its deadline" in report
        # only reported once despite several poll cycles
        assert len(fired) == 1

    def test_completed_task_never_reports(self):
        mgr = CommTaskManager(poll_interval=0.05)
        fired = []
        mgr.on_timeout = lambda *a: fired.append(a)
        with mgr.start_task("quick", timeout=5.0):
            pass
        time.sleep(0.2)
        mgr.shutdown()
        assert not fired

    def test_flag_arms_watched(self):
        mgr = get_comm_task_manager()
        fired = []
        old = mgr.on_timeout
        mgr.on_timeout = lambda task, report: fired.append(task.name)
        paddle.set_flags({"FLAGS_stop_check_timeout": 1})
        try:
            # simulate a hung barrier: a watched region that outlives
            # the 1s deadline (poll interval 0.25s)
            with watched("hung barrier"):
                time.sleep(1.8)
        finally:
            paddle.set_flags({"FLAGS_stop_check_timeout": 0})
            mgr.on_timeout = old
        assert fired == ["hung barrier"]

    def test_hung_kv_barrier_reports(self):
        """A real barrier against a KV store whose peer never shows up
        is caught by the watchdog before its own timeout."""
        from paddle_tpu.distributed.launch.master import KVServer
        from paddle_tpu.distributed.host_collectives import KVCollectives
        srv = KVServer(0).start()
        mgr = get_comm_task_manager()
        fired = []
        old = mgr.on_timeout
        mgr.on_timeout = lambda task, report: fired.append(task.name)
        paddle.set_flags({"FLAGS_stop_check_timeout": 1})
        try:
            hc = KVCollectives(f"127.0.0.1:{srv.port}", rank=0, world=2,
                               timeout=2.5)
            with pytest.raises(TimeoutError):
                hc.barrier()  # peer 1 never arrives
        finally:
            paddle.set_flags({"FLAGS_stop_check_timeout": 0})
            mgr.on_timeout = old
            srv.stop()
        assert fired and "host collective" in fired[0]
