"""Diffusion UNet (baseline config 5 surface): conditional
epsilon-prediction shape + training convergence."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.unet import UNet2DConditionModel, unet_tiny_config


def _batch(rng, cfg, b=2, hw=16, ctx_len=8):
    x = rng.randn(b, cfg.in_channels, hw, hw).astype(np.float32)
    t = rng.randint(0, 1000, (b,)).astype(np.int32)
    ctx = rng.randn(b, ctx_len, cfg.cross_attention_dim).astype(
        np.float32)
    eps = rng.randn(b, cfg.out_channels, hw, hw).astype(np.float32)
    return (paddle.to_tensor(x), paddle.to_tensor(t),
            paddle.to_tensor(ctx), paddle.to_tensor(eps))


def test_unet_forward_shape():
    paddle.seed(0)
    cfg = unet_tiny_config()
    m = UNet2DConditionModel(cfg)
    x, t, ctx, _ = _batch(np.random.RandomState(0), cfg)
    out = m(x, t, ctx)
    assert tuple(out.shape) == (2, cfg.out_channels, 16, 16)


def test_unet_trains():
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    cfg = unet_tiny_config()
    m = UNet2DConditionModel(cfg)
    opt = paddle.optimizer.AdamW(2e-3, parameters=m.parameters())
    rng = np.random.RandomState(0)
    x, t, ctx, eps = _batch(rng, cfg)

    step = TrainStep(m, lambda o, y: m.compute_loss(o, y), opt)
    losses = [float(np.asarray(step(x, t, ctx, eps).value))
              for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_unet_bf16_compute_dtype():
    """cfg.dtype='bfloat16' → fp32 master params, bf16 conv/linear
    compute (nn.set_compute_dtype now covers _ConvNd/GroupNorm)."""
    from paddle_tpu.models.unet import UNet2DConditionModel, unet_tiny_config
    paddle.seed(0)
    cfg = unet_tiny_config()
    cfg.dtype = "bfloat16"
    m = UNet2DConditionModel(cfg)
    for n, p in m.state_dict().items():
        assert str(p.value.dtype) == "float32", n
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(1, cfg.in_channels, 16, 16)
                         .astype(np.float32))
    t = paddle.to_tensor(np.array([3], np.int32))
    ctx = paddle.to_tensor(rng.randn(1, 4, cfg.cross_attention_dim)
                           .astype(np.float32))
    out = m(x, t, ctx)
    assert str(out.value.dtype) == "bfloat16"
    eps = paddle.to_tensor(rng.randn(*out.shape).astype(np.float32))
    loss = m.compute_loss(out, eps)
    assert np.isfinite(float(np.asarray(loss.value)))
