"""dist.to_static / DistModel / Engine + shard_optimizer/shard_dataloader.

Reference test model: test_to_static_api.py, test_engine_api.py —
DistModel train loss must match the dygraph trainer; Engine.fit learns.
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.io import Dataset, DataLoader


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 1)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


class RegData(Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 1).astype(np.float32)
        self.y = (self.x @ w).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def mse(out, label):
    return ((out - label) ** 2).mean()


class TestDistModel:
    def test_train_matches_dygraph_step(self):
        def run(static):
            paddle.seed(5)
            net = MLP()
            opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
            x = np.random.RandomState(1).randn(8, 8).astype(np.float32)
            y = np.random.RandomState(2).randn(8, 1).astype(np.float32)
            losses = []
            if static:
                dm = dist.to_static(net, loss=mse, optimizer=opt)
                for _ in range(4):
                    losses.append(float(np.asarray(
                        dm(paddle.to_tensor(x), paddle.to_tensor(y)).value)))
            else:
                for _ in range(4):
                    out = net(paddle.to_tensor(x))
                    loss = mse(out, paddle.to_tensor(y))
                    loss.backward()
                    opt.step()
                    opt.clear_grad()
                    losses.append(float(np.asarray(loss.value)))
            return losses

        np.testing.assert_allclose(run(True), run(False),
                                   rtol=1e-4, atol=1e-5)

    def test_eval_and_predict_modes(self):
        paddle.seed(0)
        net = MLP()
        opt = paddle.optimizer.SGD(0.01, parameters=net.parameters())
        dm = dist.to_static(net, loss=mse, optimizer=opt)
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        y = paddle.to_tensor(np.ones((4, 1), np.float32))
        train_loss = dm(x, y)
        dm.eval()
        ev = dm(x, y)
        assert np.isfinite(float(np.asarray(ev.value)))
        dm.predict()
        out = dm(x)
        assert out.shape == [4, 1]
        dm.train()
        l2 = dm(x, y)
        assert float(np.asarray(l2.value)) <= float(
            np.asarray(train_loss.value)) + 1e-6

    def test_sharding_strategy_stage(self):
        paddle.seed(0)
        net = MLP()
        opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
        strategy = dist.Strategy({"sharding": {"enable": True, "stage": 3,
                                               "degree": 4}})
        dm = dist.to_static(net, loss=mse, optimizer=opt,
                            strategy=strategy)
        x = paddle.to_tensor(np.ones((8, 8), np.float32))
        y = paddle.to_tensor(np.ones((8, 1), np.float32))
        loss = dm(x, y)
        assert np.isfinite(float(np.asarray(loss.value)))
        # stage-3: fc1 weight sharded over the sharding axis
        spec = net.fc1.weight.value.sharding.spec
        assert any(s == "sharding" for s in spec if s)


class TestEngine:
    def test_fit_evaluate_predict(self, tmp_path):
        paddle.seed(0)
        net = MLP()
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        engine = dist.Engine(net, loss=mse, optimizer=opt)
        data = RegData()
        hist = engine.fit(data, epochs=3, batch_size=16, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]
        ev = engine.evaluate(data, batch_size=16)
        assert ev["loss"] < hist["loss"][0]
        outs = engine.predict(data, batch_size=16, steps=1)
        assert len(outs) == 1
        engine.save(str(tmp_path / "ckpt"))
        engine.load(str(tmp_path / "ckpt"))


class TestShardOptimizer:
    def test_states_sharded(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(8),
                                dim_names=["dp"])
        dist.auto_parallel.set_mesh(mesh)
        try:
            paddle.seed(0)
            net = MLP()
            opt = paddle.optimizer.AdamW(1e-3,
                                         parameters=net.parameters())
            opt = dist.shard_optimizer(opt, dist.ShardingStage1())
            st = opt._init_state(net.fc1.weight)
            spec = st["moment1"].sharding.spec
            assert any(s == "dp" for s in spec if s)
        finally:
            dist.auto_parallel.set_mesh(None)

    def test_eager_masters_sharded(self):
        """multi_precision masters are created by assignment in
        Optimizer.step (not _init_state) and must still shard."""
        mesh = dist.ProcessMesh(np.arange(8).reshape(8),
                                dim_names=["dp"])
        dist.auto_parallel.set_mesh(mesh)
        try:
            paddle.seed(0)
            net = MLP()
            import paddle_tpu.amp as amp
            net = amp.decorate(net, level="O2", dtype="bfloat16")
            opt = paddle.optimizer.AdamW(
                1e-3, parameters=net.parameters(), multi_precision=True)
            opt = dist.shard_optimizer(opt, dist.ShardingStage1())
            x = paddle.to_tensor(np.ones((8, 8), np.float32))
            out = net(paddle.cast(x, "bfloat16"))
            loss = out.astype("float32").mean()
            loss.backward()
            opt.step()
            assert opt._master_weights, "masters should exist under O2"
            shardable = [v for v in opt._master_weights.values()
                         if any(d % 8 == 0 and d > 1 for d in v.shape)]
            assert shardable
            for v in shardable:
                spec = v.sharding.spec
                assert any(s == "dp" for s in spec if s), spec
        finally:
            dist.auto_parallel.set_mesh(None)

    def test_stage3_shards_params(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(8),
                                dim_names=["dp"])
        dist.auto_parallel.set_mesh(mesh)
        try:
            paddle.seed(0)
            net = MLP()
            opt = paddle.optimizer.AdamW(1e-3,
                                         parameters=net.parameters())
            opt = dist.shard_optimizer(opt, dist.ShardingStage3())
            spec = net.fc1.weight.value.sharding.spec
            assert any(s == "dp" for s in spec if s)
        finally:
            dist.auto_parallel.set_mesh(None)


class TestShardDataloader:
    def test_batches_placed(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(8),
                                dim_names=["dp"])
        loader = DataLoader(RegData(), batch_size=16)
        sl = dist.shard_dataloader(loader, mesh)
        batch = next(iter(sl))
        x = batch[0]
        spec = x.value.sharding.spec
        assert spec and spec[0] == "dp"


class TestShardDataloaderPartialBatch:
    def test_partial_final_batch_replicated(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(8),
                                dim_names=["dp"])
        # 20 samples, batch 16 -> final batch of 4 (not divisible by 8)
        class D20(RegData):
            def __init__(self):
                super().__init__(n=20)
        loader = DataLoader(D20(), batch_size=16, drop_last=False)
        sl = dist.shard_dataloader(loader, mesh)
        batches = list(sl)
        assert len(batches) == 2
        spec = batches[0][0].value.sharding.spec
        assert spec and spec[0] == "dp"
        spec_last = batches[1][0].value.sharding.spec
        assert not spec_last or spec_last[0] is None


class TestShardOptimizerCallable:
    def test_custom_shard_fn(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(8),
                                dim_names=["dp"])
        dist.auto_parallel.set_mesh(mesh)
        try:
            paddle.seed(0)
            net = MLP()
            opt = paddle.optimizer.AdamW(
                1e-3, parameters=net.parameters())
            seen = []

            def fn(key, param, value):
                seen.append(key)
                return [dist.Replicate()]  # user forces replication

            opt = dist.shard_optimizer(opt, fn)
            st = opt._init_state(net.fc1.weight)
            assert seen  # callable consulted
            spec = st["moment1"].sharding.spec
            assert not any(s == "dp" for s in spec if s)
        finally:
            dist.auto_parallel.set_mesh(None)

    def test_bad_shard_fn_rejected(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(8),
                                dim_names=["dp"])
        dist.auto_parallel.set_mesh(mesh)
        try:
            net = MLP()
            opt = paddle.optimizer.AdamW(
                1e-3, parameters=net.parameters())
            with pytest.raises(TypeError):
                dist.shard_optimizer(opt, "stage1")
        finally:
            dist.auto_parallel.set_mesh(None)


def test_static_limit_documented_and_enforced():
    """Round-5: the static facade's boundary is written down and pinned —
    the supported surface (tape replay + curated append_op) is
    documented, and op types outside the curated set refuse with
    guidance (the YAML-wide surface goes through the functional API,
    which records onto the tape)."""
    import paddle_tpu.static as static
    doc = static.__doc__
    assert "append_op" in doc and "to_static" in doc \
        and "Out of scope BY DESIGN" in doc
    prog = static.Program()
    with pytest.raises(NotImplementedError, match="to_static"):
        prog.append_op("fancy_unsupported_op")
    with pytest.raises(NotImplementedError):
        prog.global_block().append_op("fancy_unsupported_op")
