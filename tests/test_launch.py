"""Launcher / elastic tests: KV rendezvous, env wiring, gang relaunch.

Model: reference `test/collective/fleet/test_launch_coverage.py` and the
CPU fake-cluster strategy (SURVEY §4) — children are plain python scripts
that dump their PADDLE_* env to disk; no jax import needed in children.
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.launch import (
    CollectiveController, KVClient, KVServer, parse_args)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dump_script(tmp_path):
    p = tmp_path / "worker.py"
    p.write_text(textwrap.dedent("""
        import json, os, sys
        keys = [k for k in os.environ if k.startswith("PADDLE_")]
        out = {k: os.environ[k] for k in keys}
        path = os.path.join(os.environ["DUMP_DIR"],
                            "env.%s.json" % os.environ["PADDLE_TRAINER_ID"])
        with open(path, "w") as f:
            json.dump(out, f)
    """))
    return str(p)


class TestKVStore:
    def test_put_get_prefix(self):
        srv = KVServer(0).start()
        try:
            kv = KVClient(f"127.0.0.1:{srv.port}")
            assert kv.alive()
            assert kv.put("job/pods/a", "h1:1")
            assert kv.put("job/pods/b", "h2:2")
            assert kv.get("job/pods/a") == "h1:1"
            assert kv.get("missing") is None
            assert kv.prefix("job/pods") == {
                "job/pods/a": "h1:1", "job/pods/b": "h2:2"}
            got = kv.wait_n("job/pods", 2, timeout=5)
            assert len(got) == 2
            kv.delete("job/pods/a")
            assert kv.get("job/pods/a") is None
        finally:
            srv.stop()

    def test_wait_n_timeout(self):
        srv = KVServer(0).start()
        try:
            kv = KVClient(f"127.0.0.1:{srv.port}")
            with pytest.raises(TimeoutError):
                kv.wait_n("nobody", 2, timeout=0.5)
        finally:
            srv.stop()


class TestSingleNode:
    def test_two_procs_env_wiring(self, tmp_path):
        script = _dump_script(tmp_path)
        os.environ["DUMP_DIR"] = str(tmp_path)
        try:
            args = parse_args([
                "--nproc_per_node=2", f"--log_dir={tmp_path}/log",
                "--job_id=t1", script])
            rc = CollectiveController(args).run()
        finally:
            del os.environ["DUMP_DIR"]
        assert rc == 0
        envs = {}
        for r in (0, 1):
            with open(tmp_path / f"env.{r}.json") as f:
                envs[r] = json.load(f)
        for r in (0, 1):
            assert envs[r]["PADDLE_TRAINER_ID"] == str(r)
            assert envs[r]["PADDLE_TRAINERS_NUM"] == "2"
            assert envs[r]["PADDLE_LOCAL_RANK"] == str(r)
            assert envs[r]["PADDLE_NODE_RANK"] == "0"
            assert envs[r]["PADDLE_JOB_ID"] == "t1"

    def test_relaunch_on_failure(self, tmp_path):
        # child fails until PADDLE_RESTART_CNT >= 2
        script = tmp_path / "flaky.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            cnt = int(os.environ["PADDLE_RESTART_CNT"])
            with open(os.path.join(os.environ["DUMP_DIR"],
                                   "attempt.%d" % cnt), "w") as f:
                f.write("x")
            sys.exit(0 if cnt >= 2 else 7)
        """))
        os.environ["DUMP_DIR"] = str(tmp_path)
        try:
            args = parse_args([
                "--max_restart=3", f"--log_dir={tmp_path}/log",
                "--job_id=t2", str(script)])
            rc = CollectiveController(args).run()
        finally:
            del os.environ["DUMP_DIR"]
        assert rc == 0
        assert (tmp_path / "attempt.0").exists()
        assert (tmp_path / "attempt.1").exists()
        assert (tmp_path / "attempt.2").exists()

    def test_exhausted_restarts_propagates_exit(self, tmp_path):
        script = tmp_path / "dead.py"
        script.write_text("import sys; sys.exit(9)\n")
        args = parse_args([
            "--max_restart=1", f"--log_dir={tmp_path}/log",
            "--job_id=t3", str(script)])
        rc = CollectiveController(args).run()
        assert rc == 9


class TestTwoNodeRendezvous:
    def test_fake_cluster_through_cli(self, tmp_path):
        """Two launcher processes on localhost rendezvous via the KV
        master, assign node ranks, and wire coordinator env into workers
        (VERDICT #8 done-criterion)."""
        import socket
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        script = _dump_script(tmp_path)
        env = dict(os.environ, DUMP_DIR=str(tmp_path),
                   PYTHONPATH=REPO + os.pathsep + os.environ.get(
                       "PYTHONPATH", ""))
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               f"--master=127.0.0.1:{port}", "--nnodes=2",
               f"--log_dir={tmp_path}/log", "--job_id=t4",
               "--elastic_timeout=30", script]
        procs = [subprocess.Popen(cmd, env=env, cwd=str(tmp_path),
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT)
                 for _ in range(2)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out.decode())
        assert all(p.returncode == 0 for p in procs), outs
        envs = {}
        for r in (0, 1):
            with open(tmp_path / f"env.{r}.json") as f:
                envs[r] = json.load(f)
        for r in (0, 1):
            assert envs[r]["PADDLE_TRAINER_ID"] == str(r)
            assert envs[r]["PADDLE_TRAINERS_NUM"] == "2"
            assert "PADDLE_MASTER" in envs[r]
            eps = envs[r]["PADDLE_TRAINER_ENDPOINTS"].split(",")
            assert len(eps) == 2
            # coordinator is node 0's registered endpoint on both nodes
            assert envs[0]["PADDLE_MASTER"] == envs[1]["PADDLE_MASTER"]
            assert envs[r]["PADDLE_MASTER"] == eps[0]

    def test_dead_peer_detection(self):
        srv = KVServer(0).start()
        try:
            kv = KVClient(f"127.0.0.1:{srv.port}")
            args = parse_args(["--job_id=t5", "--nnodes=1", "x.py"])
            c = CollectiveController(args)
            c.kv = kv
            c.peer_pods = ["peerA", "peerB", "peerC"]
            kv.put("t5/heartbeat/peerA", str(time.time()))
            kv.put("t5/heartbeat/peerB", str(time.time() - 99))
            # peerC never heartbeat at all; an unadmitted pod's lease is
            # not judged
            kv.put("t5/heartbeat/straggler", str(time.time() - 99))
            assert c.dead_peers() == ["peerB", "peerC"]
        finally:
            srv.stop()

    def test_stale_pod_reaped_on_rendezvous(self):
        """A SIGKILLed launcher's leftover pod key must not poison the
        next rendezvous: entries with a lapsed heartbeat are reaped."""
        import json as _json
        srv = KVServer(0).start()
        try:
            kv = KVClient(f"127.0.0.1:{srv.port}")
            # leftover registration from a killed pod (no live heartbeat)
            kv.put("t6/pods/t00000000000001.000000.deadpod",
                   _json.dumps({"endpoint": "10.0.0.9:1", "pod": "deadpod"}))
            kv.put("t6/heartbeat/deadpod", str(time.time() - 99))
            args = parse_args([
                f"--master=127.0.0.1:{srv.port}", "--nnodes=1",
                "--job_id=t6", "--elastic_timeout=10", "x.py"])
            args.master = f"127.0.0.1:{srv.port}"
            c = CollectiveController(args)
            c.args.nnodes = 1  # force the master path despite nnodes==1
            c.kv = kv
            c.start_heartbeat()
            # directly exercise the liveness filter
            live = c._live_pods()
            assert live == {}
            assert kv.get("t6/pods/t00000000000001.000000.deadpod") is None
            c.stop()
        finally:
            srv.stop()

    def test_explicit_ranks_order_peers(self):
        """--rank pins node_rank AND the peer/coordinator ordering
        (previously peers stayed in registration order)."""
        import threading
        srv = KVServer(0).start()
        results = {}
        done = threading.Barrier(2, timeout=30)
        try:
            def run(rank):
                args = parse_args([
                    f"--master=127.0.0.1:{srv.port}", "--nnodes=2",
                    f"--rank={rank}", "--job_id=t7",
                    "--elastic_timeout=20", "x.py"])
                c = CollectiveController(args)
                c.rendezvous()
                results[rank] = (c.node_rank, list(c.peers), c.coordinator)
                done.wait()  # registration lives until all pods admitted
                c.stop()
            # register rank 1 FIRST so registration order disagrees with
            # the explicit ranks
            t1 = threading.Thread(target=lambda: run(1))
            t1.start()
            time.sleep(0.5)
            t0 = threading.Thread(target=lambda: run(0))
            t0.start()
            t1.join(30)
            t0.join(30)
            assert results[0][0] == 0 and results[1][0] == 1
            # both nodes agree on peer order and the coordinator is
            # rank 0's endpoint
            assert results[0][1] == results[1][1]
            assert results[0][2] == results[0][1][0]
        finally:
            srv.stop()

    def test_elastic_range_absorbs_extra_pod(self):
        """--nnodes=MIN:MAX admits pods beyond MIN up to MAX."""
        import threading
        srv = KVServer(0).start()
        results = []
        done = threading.Barrier(3, timeout=30)
        try:
            def run():
                args = parse_args([
                    f"--master=127.0.0.1:{srv.port}", "--nnodes=2:4",
                    "--job_id=t8", "--elastic_timeout=20", "x.py"])
                c = CollectiveController(args)
                c.rendezvous()
                results.append((c.node_rank, c.world_nodes))
                done.wait()
                c.stop()
            threads = [threading.Thread(target=run) for _ in range(3)]
            for t in threads:
                t.start()
                time.sleep(0.2)
            for t in threads:
                t.join(30)
            assert len(results) == 3
            assert sorted(r[0] for r in results) == [0, 1, 2]
            assert all(r[1] == 3 for r in results)
        finally:
            srv.stop()

    def test_rejected_straggler_does_not_poison_gang(self):
        """A pod beyond nnodes_max is rejected cleanly; the admitted gang
        agrees on membership and sees no dead peers afterwards."""
        import threading
        srv = KVServer(0).start()
        ok, rejected = [], []
        done = threading.Barrier(2, timeout=40)
        try:
            def run():
                args = parse_args([
                    f"--master=127.0.0.1:{srv.port}", "--nnodes=2",
                    "--job_id=t10", "--elastic_timeout=20", "x.py"])
                c = CollectiveController(args)
                try:
                    c.rendezvous()
                except RuntimeError:
                    rejected.append(c.pod_id)
                    c.stop()
                    return
                ok.append(c)
                done.wait()
            threads = [threading.Thread(target=run) for _ in range(3)]
            for t in threads:
                t.start()
                time.sleep(0.3)
            for t in threads:
                t.join(40)
            assert len(ok) == 2 and len(rejected) == 1
            assert sorted(c.node_rank for c in ok) == [0, 1]
            assert all(c.world_nodes == 2 for c in ok)
            # the straggler's withdrawn lease must not read as a dead peer
            time.sleep(0.5)
            assert all(c.dead_peers() == [] for c in ok)
            for c in ok:
                c.stop()
        finally:
            srv.stop()

    def test_signal_death_exit_code(self, tmp_path):
        # child killed by SIGKILL → launcher exits 128+9, not 256-9
        script = tmp_path / "sigdeath.py"
        script.write_text(
            "import os, signal; os.kill(os.getpid(), signal.SIGKILL)\n")
        args = parse_args([
            "--max_restart=0", f"--log_dir={tmp_path}/log",
            "--job_id=t9", str(script)])
        rc = CollectiveController(args).run()
        assert rc == 137


class TestArgPrecedence:
    def test_cli_beats_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_NNODES", "4")
        args = parse_args(["--nnodes=1", "x.py"])
        assert args.nnodes == 1

    def test_env_beats_default(self, monkeypatch):
        monkeypatch.setenv("PADDLE_NNODES", "4")
        monkeypatch.setenv("PADDLE_JOB_ID", "fromenv")
        args = parse_args(["--master=h:1", "x.py"])
        assert args.nnodes == 4
        assert args.job_id == "fromenv"

    def test_bad_elastic_range_rejected(self):
        with pytest.raises(ValueError):
            parse_args(["--nnodes=4:2", "x.py"])
