"""ISSUE 17: HybridParallelEngine — ONE composable strategy point over
the dp × mp × pp × sharding × sep mesh.

Parity contract (reference: test/collective/fleet/hybrid_parallel_*):
every composed strategy point on the 8-virtual-device CPU mesh matches
the single-device run to fp32 tolerance; the pure-dp / pure-sharding
points are byte-identical to a directly-built ShardedTrainStep.  The
static pre-flight (composed collective-order check), the hybrid_configs
validation, the Paddle-equivalent exports and the cost ledger's
per-axis exposed-comm columns are pinned here too.
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework import flags
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.parallel import (HybridParallelEngine, HybridConfigError,
                                 ShardedTrainStep, validate_hybrid_configs)
from paddle_tpu.parallel.hybrid_engine import modeled_axis_profiles
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, LayerDesc, PipelineLayer)
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup, build_mesh, set_hybrid_communicate_group)
from paddle_tpu.analysis.collectives import (CollectiveEvent,
                                             check_collective_order)


def _need8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")


@pytest.fixture(autouse=True)
def _fresh_hcg():
    set_hybrid_communicate_group(None)
    yield
    set_hybrid_communicate_group(None)


# ---------------------------------------------------------------------------
# llama helpers: the pp==1 SPMD strategy points

def _llama(seed=0):
    paddle.seed(seed)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128, num_attention_heads=4,
                            num_key_value_heads=4, vocab_size=128,
                            dtype="float32")
    m = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (8, 16)).astype(np.int32)
    return m, ids


def _base_losses(n=3):
    m, ids = _llama()
    opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
    step = ShardedTrainStep(m, opt, build_mesh(devices=jax.devices()[:1]))
    return [float(np.asarray(step(paddle.to_tensor(ids),
                                  paddle.to_tensor(ids)).value))
            for _ in range(n)]


def _engine_losses(n=3, **kw):
    m, ids = _llama()
    opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
    eng = HybridParallelEngine(m, opt, **kw)
    losses = [float(np.asarray(eng(paddle.to_tensor(ids),
                                   paddle.to_tensor(ids)).value))
              for _ in range(n)]
    return eng, losses, ids


class TestSPMDParity:
    """Composed pp==1 strategy points vs the single-device trainer."""

    def test_dp2_sharding4_matches_single(self):
        _need8()
        eng, losses, _ = _engine_losses(dp_degree=2, sharding_degree=4)
        assert eng.sharding_stage == 1          # default with sharding>1
        assert dict(eng.mesh.shape)["dp"] == 2 \
            and dict(eng.mesh.shape)["sharding"] == 4
        np.testing.assert_allclose(_base_losses(), losses,
                                   rtol=5e-4, atol=5e-4)

    def test_dp2_mp2_sharding2_matches_single(self):
        _need8()
        eng, losses, ids = _engine_losses(dp_degree=2, mp_degree=2,
                                          sharding_degree=2)
        np.testing.assert_allclose(_base_losses(), losses,
                                   rtol=5e-4, atol=5e-4)
        # static pre-flight holds on the composed point
        eng.verify(paddle.to_tensor(ids), paddle.to_tensor(ids))

    def test_mp2_sep2_dp2_ring_matches_single(self):
        """The sep axis with the ring-attention kernel live: explicit
        ppermute/psum collectives enter the schedule, parity holds,
        and the composed-order pre-flight proves the issue order."""
        _need8()
        flags.set_flags({"FLAGS_sep_ring_attention": True})
        try:
            eng, losses, ids = _engine_losses(dp_degree=2, mp_degree=2,
                                              sep_degree=2)
            np.testing.assert_allclose(_base_losses(), losses,
                                       rtol=5e-4, atol=5e-4)
            x = paddle.to_tensor(ids)
            sched = eng.collective_schedule(x, x)
            assert len(sched) == 8
            kinds = {ev.kind for ev in sched[0]}
            assert "ppermute" in kinds or "psum" in kinds, kinds
            eng.verify(x, x)
            lint = eng.lint(x, x)
            assert lint["donation"] == []
        finally:
            flags.set_flags({"FLAGS_sep_ring_attention": False})


# ---------------------------------------------------------------------------
# pipeline strategy points: pp composed with mp / sep / dp

def _mse(out, y):
    return ((out - y) ** 2).mean()


class TPBlock(nn.Layer):
    """Megatron pair: column-parallel up (sharded activations) into
    row-parallel down — real mp collectives inside each pp stage."""

    def __init__(self, d):
        super().__init__()
        self.up = ColumnParallelLinear(d, 2 * d, gather_output=False)
        self.down = RowParallelLinear(2 * d, d, input_is_parallel=True)
        self.norm = nn.LayerNorm(d)

    def forward(self, x):
        return self.norm(x + self.down(nn.functional.gelu(self.up(x))))


def _pp_model(d, depth):
    return PipelineLayer(
        [LayerDesc(TPBlock, d) for _ in range(depth)]
        + [LayerDesc(nn.Linear, d, d)], loss_fn=_mse)


def _eager_ref(d, depth, data, steps, lr=0.05):
    """Single-device eager baseline: degree-1 hcg makes the TP layers
    plain linears (full params, replicated)."""
    set_hybrid_communicate_group(
        HybridCommunicateGroup(devices=jax.devices()[:1]))
    paddle.seed(42)
    model = _pp_model(d, depth)
    opt = paddle.optimizer.SGD(lr, parameters=model.parameters())
    x, y = data
    losses = []
    for _ in range(steps):
        loss = _mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.value)))
    return losses


class TestPipelineParity:
    def _run(self, degrees, data, d=8, depth=3, steps=3, micro=4):
        hcg = HybridCommunicateGroup(**degrees)
        set_hybrid_communicate_group(hcg)
        paddle.seed(42)
        pl = _pp_model(d, depth)
        opt = paddle.optimizer.SGD(0.05, parameters=pl.parameters())
        eng = HybridParallelEngine(
            pl, opt, accumulate_steps=micro,
            **{k: v for k, v in degrees.items()})
        x, y = data
        losses = [float(np.asarray(eng(x, y).value))
                  for _ in range(steps)]
        return eng, losses

    def test_dp2_mp2_pp2_matches_single(self):
        _need8()
        d = 8
        rng = np.random.RandomState(7)
        data = (paddle.to_tensor(rng.randn(8, d).astype(np.float32)),
                paddle.to_tensor(rng.randn(8, d).astype(np.float32)))
        ref = _eager_ref(d, 3, data, 3)
        eng, losses = self._run(
            dict(dp_degree=2, mp_degree=2, pp_degree=2), data)
        np.testing.assert_allclose(ref, losses, rtol=5e-4, atol=5e-4)
        # each stage's submesh kept the non-pp axes
        sub = eng._engine.chunks[0].submesh
        assert dict(sub.shape).get("dp") == 2 \
            and dict(sub.shape).get("mp") == 2
        eng.verify(data[0], data[1])

    def test_mp2_sep2_pp2_matches_single(self):
        _need8()
        d = 8
        rng = np.random.RandomState(7)
        # 3-D activations: the sep axis shards the seq dim (8 % 2 == 0)
        data = (paddle.to_tensor(rng.randn(4, 8, d).astype(np.float32)),
                paddle.to_tensor(rng.randn(4, 8, d).astype(np.float32)))
        ref = _eager_ref(d, 3, data, 3)
        eng, losses = self._run(
            dict(mp_degree=2, sep_degree=2, pp_degree=2), data, micro=2)
        np.testing.assert_allclose(ref, losses, rtol=5e-4, atol=5e-4)

    def test_pp_requires_pipeline_layer(self):
        _need8()
        m, _ = _llama()
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        with pytest.raises(HybridConfigError, match="PipelineLayer"):
            HybridParallelEngine(m, opt, pp_degree=2)

    def test_pp_rejects_zero23(self):
        _need8()
        m, _ = _llama()
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        with pytest.raises(HybridConfigError, match="stage"):
            HybridParallelEngine(m, opt, pp_degree=2, sharding_degree=2,
                                 sharding_stage=2)


# ---------------------------------------------------------------------------
# bit-exactness: the trivial and pure points ARE the single-axis trainer

class TestBitExact:
    def _pair(self, degrees, stage_direct, mesh_direct):
        m, ids = _llama()
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        eng = HybridParallelEngine(m, opt, **degrees)
        m2, _ = _llama()
        opt2 = paddle.optimizer.AdamW(1e-2, parameters=m2.parameters())
        direct = ShardedTrainStep(m2, opt2, mesh_direct,
                                  sharding_stage=stage_direct)
        x = paddle.to_tensor(ids)
        return eng, direct, x

    def test_pure_dp_bit_exact(self):
        _need8()
        eng, direct, x = self._pair({"dp_degree": 8}, 0,
                                    build_mesh(dp=8))
        assert eng.step.compiled_hlo(x, x, optimized=False) \
            == direct.compiled_hlo(x, x, optimized=False)
        a = [float(np.asarray(eng(x, x).value)) for _ in range(3)]
        b = [float(np.asarray(direct(x, x).value)) for _ in range(3)]
        assert a == b            # same program, bit-exact trajectories

    def test_pure_sharding_bit_exact(self):
        _need8()
        eng, direct, x = self._pair({"sharding_degree": 8}, 1,
                                    build_mesh(sharding=8))
        assert eng.sharding_stage == 1
        assert eng.step.compiled_hlo(x, x, optimized=False) \
            == direct.compiled_hlo(x, x, optimized=False)
        a = [float(np.asarray(eng(x, x).value)) for _ in range(3)]
        b = [float(np.asarray(direct(x, x).value)) for _ in range(3)]
        assert a == b

    def test_trivial_point_flags_off_hlo_identical(self):
        """All-degrees-1 engine == plain single-device trainer, and
        FLAGS_sep_ring_attention with no sep axis leaves the program
        byte-identical (trace-time flag, inert off the sep mesh)."""
        m, ids = _llama()
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        x = paddle.to_tensor(ids)
        m2, _ = _llama()
        opt2 = paddle.optimizer.AdamW(1e-2, parameters=m2.parameters())
        direct = ShardedTrainStep(
            m2, opt2, build_mesh(devices=jax.devices()[:1]))
        hlo_direct = direct.compiled_hlo(x, x, optimized=False)
        eng = HybridParallelEngine(
            m, opt, devices=list(jax.devices())[:1])
        assert eng.step.compiled_hlo(x, x, optimized=False) == hlo_direct
        flags.set_flags({"FLAGS_sep_ring_attention": True})
        try:
            m3, _ = _llama()
            opt3 = paddle.optimizer.AdamW(1e-2,
                                          parameters=m3.parameters())
            eng3 = HybridParallelEngine(
                m3, opt3, devices=list(jax.devices())[:1])
            assert eng3.step.compiled_hlo(x, x, optimized=False) \
                == hlo_direct
        finally:
            flags.set_flags({"FLAGS_sep_ring_attention": False})


# ---------------------------------------------------------------------------
# satellite 1: hybrid_configs validation — named error, at config time

class TestValidation:
    def test_unknown_key_rejected_at_strategy_set(self):
        strategy = fleet.DistributedStrategy()
        with pytest.raises(HybridConfigError, match="dp_degre"):
            strategy.hybrid_configs = {"dp_degre": 2}     # the typo case

    def test_partial_assignment_merges_defaults(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"pp_degree": 2}
        assert strategy.hybrid_configs["pp_degree"] == 2
        assert strategy.hybrid_configs["dp_degree"] == 1

    @pytest.mark.parametrize("bad", [True, 0, -1, 2.5, "2"])
    def test_malformed_degree_rejected(self, bad):
        with pytest.raises(HybridConfigError):
            validate_hybrid_configs({"mp_degree": bad})

    def test_product_exceeding_devices_rejected_at_from_strategy(self):
        _need8()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 4}
        m, _ = _llama()
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        with pytest.raises(HybridConfigError, match="exceeds"):
            HybridParallelEngine.from_strategy(m, opt, strategy)

    def test_non_dividing_product_rejected_at_fleet_init(self):
        _need8()
        strategy = fleet.DistributedStrategy()
        # in-place mutation bypasses the setter — fleet.init (where the
        # mesh is about to exist) still validates
        strategy.hybrid_configs["dp_degree"] = 5
        with pytest.raises(HybridConfigError, match="divide"):
            fleet.init(is_collective=True, strategy=strategy)

    def test_from_strategy_composes_point(self):
        _need8()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 2, "sharding_degree": 2,
            "sharding_configs": {"stage": 2}}
        m, ids = _llama()
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        eng = HybridParallelEngine.from_strategy(m, opt, strategy)
        assert eng.degrees == {"dp": 2, "mp": 1, "pp": 1, "sep": 1,
                               "sharding": 2}
        assert eng.sharding_stage == 2
        x = paddle.to_tensor(ids)
        np.testing.assert_allclose(
            _base_losses(),
            [float(np.asarray(eng(x, x).value)) for _ in range(3)],
            rtol=5e-4, atol=5e-4)


# ---------------------------------------------------------------------------
# satellite 3: a misordered two-axis schedule is caught STATICALLY

class TestComposedOrderCheck:
    def test_sharding_rs_swapped_with_mp_ag_caught(self):
        """Rank 1 issues the mp all-gather before the sharding
        reduce-scatter; per-domain order is still consistent (one
        event per domain), so only the composed check can see the
        deadlock."""
        rs = CollectiveEvent("reduce_scatter", ("grads", (64,)),
                             ("sharding",))
        ag = CollectiveEvent("all_gather", ("w0", (64, 64)), ("mp",))
        good = {0: [rs, ag], 1: [rs, ag]}
        bad = {0: [rs, ag], 1: [ag, rs]}
        assert check_collective_order(good, composed=True) == []
        per_domain = check_collective_order(bad)      # composed=False
        assert per_domain == []                        # blind to it
        findings = check_collective_order(bad, composed=True)
        assert [f.code for f in findings] == ["composed-order-divergence"]
        assert "sharding" in findings[0].message \
            and "mp" in findings[0].message

    def test_engine_schedule_one_order_per_group(self):
        _need8()
        flags.set_flags({"FLAGS_sep_ring_attention": True})
        try:
            m, ids = _llama()
            opt = paddle.optimizer.AdamW(1e-2,
                                         parameters=m.parameters())
            eng = HybridParallelEngine(m, opt, mp_degree=2, sep_degree=2,
                                       dp_degree=2)
            x = paddle.to_tensor(ids)
            sched = eng.collective_schedule(x, x)
            assert check_collective_order(sched, composed=True) == []
        finally:
            flags.set_flags({"FLAGS_sep_ring_attention": False})


# ---------------------------------------------------------------------------
# satellite 6: per-axis additive exposed-comm columns in the cost ledger

class TestPerAxisLedger:
    def test_modeled_profiles_attribute_each_bucket_once(self):
        m, _ = _llama()
        params = [(tuple(p.value.shape), str(p.value.dtype))
                  for _, p in m.named_parameters()]
        profs = modeled_axis_profiles(
            params, m.config, {"dp": 2, "mp": 2, "sharding": 2},
            (8, 16), stage=1)
        axes = [tuple(p["axes"]) for p in profs]
        assert sorted(axes) == [("dp",), ("mp",), ("sharding",)]
        assert len(set(axes)) == len(axes)       # disjoint attribution
        for p in profs:
            assert sum(p["bucket_bytes"]) == p["bytes"] > 0
        by = {tuple(p["axes"]): p for p in profs}
        # dp all-reduces the already-scattered shard: half the grads
        assert by[("dp",)]["bytes"] == by[("sharding",)]["bytes"] // 2

    def test_two_axis_columns_add_to_program_totals(self):
        from paddle_tpu import telemetry
        from paddle_tpu.telemetry import costledger
        paddle.seed(0)
        m = paddle.nn.Sequential(paddle.nn.Linear(8, 8))
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = ShardedTrainStep(
            m, opt, build_mesh(devices=jax.devices()[:1]),
            loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        step(x, x)
        label = f"ShardedTrainStep.step.s{step.stage}"
        costledger.note_comm(label, {
            "bytes": 1000, "bucket_bytes": [500, 500], "buckets": 2,
            "overlap": True, "stage": 1, "axes": ["sharding"],
            "comm_dtype": "auto", "world": 8})
        costledger.note_comm(label, {
            "bytes": 600, "bucket_bytes": [600], "buckets": 1,
            "overlap": True, "stage": 1, "axes": ["mp"],
            "comm_dtype": "auto", "world": 8})
        rec = telemetry.cost_report()["programs"][label]
        by_axis = rec["exposed_comm_by_axis"]
        assert set(by_axis) == {"sharding", "mp"}
        assert rec["comm_bytes"] == 1600          # additive, no double
        assert rec["comm_buckets"] == 3
        assert rec["exposed_comm_ms"] == pytest.approx(
            sum(a["exposed_ms"] for a in by_axis.values()), abs=1e-3)
        assert rec["exposed_comm_ms_monolithic"] == pytest.approx(
            sum(a["exposed_ms_monolithic"] for a in by_axis.values()),
            abs=1e-3)
        # re-noting one axis REPLACES that column, never accumulates
        costledger.note_comm(label, {
            "bytes": 800, "bucket_bytes": [800], "buckets": 1,
            "overlap": True, "stage": 1, "axes": ["mp"],
            "comm_dtype": "auto", "world": 8})
        rec = telemetry.cost_report()["programs"][label]
        assert rec["comm_bytes"] == 1800

    def test_engine_registers_axis_profiles(self):
        _need8()
        from paddle_tpu import telemetry
        eng, _, ids = _engine_losses(n=1, dp_degree=2, mp_degree=2,
                                     sharding_degree=2)
        rec = telemetry.cost_report()["programs"][eng.cost_label()]
        by_axis = rec.get("exposed_comm_by_axis") or {}
        assert {"dp", "mp", "sharding"} <= set(by_axis)


# ---------------------------------------------------------------------------
# satellite 2: Paddle-equivalent export surface

class TestExports:
    def test_fleet_and_meta_parallel_names(self):
        from paddle_tpu.distributed.fleet import meta_parallel as mp
        assert mp.HybridParallel is HybridParallelEngine
        assert fleet.HybridParallel is HybridParallelEngine
        assert fleet.HybridParallelEngine is HybridParallelEngine
        assert fleet.HybridConfigError is HybridConfigError
        assert fleet.validate_hybrid_configs is validate_hybrid_configs
        from paddle_tpu import parallel as par
        assert par.HybridParallelEngine is HybridParallelEngine
