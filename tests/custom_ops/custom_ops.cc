// Test custom ops for the paddle_tpu custom-op ABI (reference model:
// test/custom_op/custom_relu_op.cc built through PD_BUILD_OP).
#include <cmath>
#include <cstdint>

#include "paddle_tpu_ext.h"

namespace ffi = xla::ffi;

static ffi::Error ReluImpl(ffi::Buffer<ffi::F32> x,
                           ffi::ResultBuffer<ffi::F32> y) {
  size_t n = x.element_count();
  const float* in = x.typed_data();
  float* out = y->typed_data();
  for (size_t i = 0; i < n; ++i) out[i] = in[i] > 0.0f ? in[i] : 0.0f;
  return ffi::Error::Success();
}
XLA_FFI_DEFINE_HANDLER_SYMBOL(
    ReluHandler, ReluImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>());
PD_REGISTER_OP(custom_relu, ReluHandler);

static ffi::Error ScaleImpl(ffi::Buffer<ffi::F32> x,
                            ffi::ResultBuffer<ffi::F32> y,
                            float factor) {
  size_t n = x.element_count();
  const float* in = x.typed_data();
  float* out = y->typed_data();
  for (size_t i = 0; i < n; ++i) out[i] = in[i] * factor;
  return ffi::Error::Success();
}
XLA_FFI_DEFINE_HANDLER_SYMBOL(
    ScaleHandler, ScaleImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>()
        .Attr<float>("factor"));
PD_REGISTER_OP(custom_scale, ScaleHandler);
