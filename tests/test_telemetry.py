"""Telemetry plane (paddle_tpu/telemetry) + persistent compile/AOT
cache — ISSUE 6.

The contracts under test:

  * a 3-step jit.TrainStep run with a JSONL sink attached emits
    per-step events carrying phase timings (acceptance criterion);
  * a SECOND process pointed at the same FLAGS_compile_cache_dir
    reports a cache hit — no recompile — via telemetry.compile_report()
    (acceptance criterion);
  * with no sink attached the plane is free: emit() is a no-op, span()
    allocates nothing, programs are byte-identical (bench.py asserts
    the HLO half; here the host half);
  * every producer (trainers, serving batcher, watchdog, fault
    registry, checkpoint runtime, io prefetcher) publishes its events;
  * ContinuousBatcher.stats() counters SURVIVE a forced program
    recompile, and the pre-recompile snapshot rides the
    serve.recompile event;
  * io.prefetch_to_device never hands a step a cold buffer when the
    producer outruns the consumer;
  * the profiler facade stays import-compatible;
  * tools/telemetry_report.py --selftest validates the schema (tier-1
    wiring, like verify_program --selftest).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends with no sinks attached and the
    compile cache disarmed (the plane is process-global)."""
    from paddle_tpu.framework.flags import set_flags
    for s in telemetry.sinks():
        telemetry.remove_sink(s)
    yield
    for s in telemetry.sinks():
        telemetry.remove_sink(s)
    set_flags({"FLAGS_compile_cache_dir": ""})
    telemetry.disable_persistent_cache()


def _mlp_step():
    class _MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 8)

        def forward(self, x):
            return self.fc(x)

    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    m = _MLP()
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    step = TrainStep(m, lambda o, y: paddle.nn.functional.mse_loss(o, y),
                     opt)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    return step, x


# ---------------------------------------------------------------------------
# registry + bus

class TestRegistry:
    def test_instruments(self):
        r = telemetry.MetricsRegistry()
        r.counter("a").inc()
        r.counter("a").inc(2)
        r.gauge("g").set(1.5)
        for v in (1.0, 2.0, 3.0, 4.0):
            r.histogram("h").observe(v)
        d = r.dump()
        assert d["counters"]["a"] == 3
        assert d["gauges"]["g"] == 1.5
        h = d["histograms"]["h"]
        assert h["count"] == 4 and h["min"] == 1.0 and h["max"] == 4.0
        assert h["p50"] in (2.0, 3.0)

    def test_histogram_window_bounded(self):
        h = telemetry.Histogram("h", window=8)
        for v in range(100):
            h.observe(float(v))
        assert h.count == 100
        assert len(h._window) == 8          # ring, not unbounded

    def test_emit_without_sink_is_noop_and_span_singleton(self):
        # no sink: emit returns without touching anything, span returns
        # THE shared no-op (no allocation on the hot path)
        telemetry.emit("x", a=1)
        s1 = telemetry.span("x")
        s2 = telemetry.span("y")
        assert s1 is s2

    def test_sink_receives_and_broken_sink_detached(self):
        good = telemetry.add_sink(telemetry.MemorySink())

        class Bad:
            def record(self, rec):
                raise RuntimeError("disk full")

        bad = telemetry.add_sink(Bad())
        telemetry.emit("ev", a=1)
        telemetry.emit("ev", a=2)
        telemetry.remove_sink(good)
        assert [r["a"] for r in good.records] == [1, 2]
        assert bad not in telemetry.sinks()  # detached, loop survived

    def test_span_emits_duration(self):
        sink = telemetry.add_sink(telemetry.MemorySink())
        with telemetry.span("work", tag="t"):
            time.sleep(0.01)
        telemetry.remove_sink(sink)
        (rec,) = sink.records
        assert rec["event"] == "work" and rec["tag"] == "t"
        assert rec["dur_ms"] >= 5

    def test_configure_rejects_unknown_key(self):
        with pytest.raises(KeyError):
            telemetry.configure(not_a_switch=True)

    def test_reset_restores_config_defaults(self):
        telemetry.configure(sync_steps=True, step_phases=False)
        telemetry.reset()
        assert telemetry.config("sync_steps") is False
        assert telemetry.config("step_phases") is True


# ---------------------------------------------------------------------------
# train-step events (acceptance: 3-step run + JSONL sink -> per-step
# events with phase timings)

class TestStepEvents:
    def test_three_step_trainstep_jsonl(self, tmp_path):
        log = str(tmp_path / "steps.jsonl")
        sink = telemetry.attach_jsonl(log)
        try:
            step, x = _mlp_step()
            for _ in range(3):
                step(x, x)
        finally:
            telemetry.remove_sink(sink)
        events = [json.loads(l) for l in open(log)]
        steps = [e for e in events if e["event"] == "train.step"]
        assert len(steps) == 3
        assert [e["step"] for e in steps] == [1, 2, 3]
        for e in steps:
            assert e["trainer"] == "jit" and e["k"] == 1
            assert e["wall_ms"] >= 0
            ph = e["phases"]
            for k in ("fwd_ms", "bwd_ms", "opt_ms", "n_params"):
                assert isinstance(ph[k], (int, float)), (k, e)
        assert steps[0].get("cold") is True
        assert "cold" not in steps[1]

    def test_sharded_step_and_run_steps_events(self):
        import jax
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh

        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            class _MLP(paddle.nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.fc = paddle.nn.Linear(8, 8)

                def forward(self, x):
                    return self.fc(x)

            paddle.seed(0)
            m = _MLP()
            opt = paddle.optimizer.AdamW(1e-3,
                                         parameters=m.parameters())
            step = ShardedTrainStep(
                m, opt, build_mesh(devices=jax.devices()[:1]),
                loss_fn=lambda o, y:
                paddle.nn.functional.mse_loss(o, y))
            x = paddle.to_tensor(np.ones((4, 8), np.float32))
            step(x, x)
            sx = paddle.to_tensor(np.ones((2, 4, 8), np.float32))
            step.run_steps(sx, sx)
        finally:
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records if r["event"] == "train.step"]
        assert [e["k"] for e in evs] == [1, 2]
        assert all(e["trainer"] == "sharded" for e in evs)
        assert evs[1]["step"] == 3          # 1 single + 2 fused

    def test_no_sink_no_phase_probe_state(self):
        # without a sink the trainer must not even cache phase-probe
        # state (the probe never ran)
        step, x = _mlp_step()
        step(x, x)
        assert not hasattr(step, "_tel_phases")


# ---------------------------------------------------------------------------
# compile cache (acceptance: second process reports a cache hit)

_CACHE_SCRIPT = r"""
import json
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import telemetry
from paddle_tpu.jit import TrainStep

class MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(8, 8)
    def forward(self, x):
        return self.fc(x)

paddle.seed(0)
m = MLP()
opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
step = TrainStep(m, lambda o, y: paddle.nn.functional.mse_loss(o, y),
                 opt)
x = paddle.to_tensor(np.ones((4, 8), np.float32))
for _ in range(2):
    loss = step(x, x)
print("RESULT " + json.dumps({
    "loss": float(np.asarray(loss.value)),
    "report": telemetry.compile_report(),
}))
"""


class TestCompileCache:
    def _run(self, cache_dir):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   FLAGS_compile_cache_dir=cache_dir,
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        out = subprocess.run([sys.executable, "-c", _CACHE_SCRIPT],
                             env=env, text=True, capture_output=True,
                             timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        line = next(l for l in out.stdout.splitlines()
                    if l.startswith("RESULT "))
        return json.loads(line[len("RESULT "):])

    def test_second_process_reports_cache_hit(self, tmp_path):
        cache = str(tmp_path / "cache")
        first = self._run(cache)
        progs = first["report"]["programs"]
        assert progs and all(p["cache"] == "miss" for p in progs)
        assert first["report"]["aot_misses"] >= 1
        second = self._run(cache)
        progs2 = second["report"]["programs"]
        # the SAME program key resolves to a hit: no recompile
        assert progs2 and all(p["cache"] == "hit" for p in progs2)
        assert second["report"]["hit_rate"] == 1.0
        assert all(p["compile_ms"] == 0.0 for p in progs2)
        assert {p["key"] for p in progs2} == {p["key"] for p in progs}
        # and the cached executable computes the same training step
        assert second["loss"] == pytest.approx(first["loss"])

    def test_aot_in_process_flags_off_identical(self, tmp_path):
        """Arming + disarming the cache leaves the flags-off path
        untouched, and the armed path really serves from the store."""
        from paddle_tpu.framework.flags import set_flags
        step, x = _mlp_step()
        l_off = float(np.asarray(step(x, x).value))
        telemetry.clear_report()
        set_flags({"FLAGS_compile_cache_dir": str(tmp_path / "c")})
        try:
            paddle.seed(0)
            step2, x2 = _mlp_step()
            l_on = float(np.asarray(step2(x2, x2).value))
            rep = telemetry.compile_report()
            assert rep["programs"], "armed flag produced no AOT records"
            assert os.path.isdir(str(tmp_path / "c" / "aot"))
        finally:
            set_flags({"FLAGS_compile_cache_dir": ""})
            telemetry.disable_persistent_cache()
        assert l_on == pytest.approx(l_off)

    def test_flag_clear_disarms_jax_cache(self, tmp_path):
        """Clearing FLAGS_compile_cache_dir must disarm the jax-level
        persistent cache on the next arming check — 'empty disables
        both layers' (regression: it used to stay pointed at the stale
        dir)."""
        import jax
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.telemetry import compile_cache as cc
        set_flags({"FLAGS_compile_cache_dir": str(tmp_path / "c")})
        try:
            assert cc.maybe_enable_persistent_cache() is not None
            assert jax.config.jax_compilation_cache_dir \
                == str(tmp_path / "c")
        finally:
            set_flags({"FLAGS_compile_cache_dir": ""})
        assert cc.maybe_enable_persistent_cache() is None
        assert jax.config.jax_compilation_cache_dir is None


# ---------------------------------------------------------------------------
# io.prefetch_to_device

class TestPrefetch:
    def test_never_cold_buffer(self):
        """Producer (instant) outruns consumer (sleeping): after the
        priming get, every step must find a WARM device-resident
        buffer."""
        from paddle_tpu.io import prefetch_to_device
        batches = [np.full((2, 4), i, np.float32) for i in range(8)]
        pf = prefetch_to_device(iter(batches), depth=2)
        # deterministic priming: wait for the pipeline to fill before
        # the first get (scheduling noise on a loaded box must not
        # masquerade as a cold buffer)
        deadline = time.time() + 10
        while pf._q.qsize() < 2 and time.time() < deadline:
            time.sleep(0.005)
        seen = []
        for b in pf:
            time.sleep(0.03)            # consumer slower than producer
            seen.append(float(np.asarray(b.value)[0, 0]))
        assert seen == [float(i) for i in range(8)]
        st = pf.stats()
        assert st["steps"] == 8
        assert st["cold_gets"] == 0, st

    def test_emits_host_wait_events_and_structure(self):
        from paddle_tpu.io import prefetch_to_device
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            batches = [(np.ones((2, 4), np.float32),
                        np.zeros((2,), np.int64)) for _ in range(3)]
            out = list(prefetch_to_device(iter(batches), depth=2))
        finally:
            telemetry.remove_sink(sink)
        assert len(out) == 3
        xb, yb = out[0]
        import jax
        assert isinstance(xb.value, jax.Array)     # device-resident
        evs = [r for r in sink.records if r["event"] == "io.step"]
        assert len(evs) == 3
        assert all("host_wait_ms" in e and "buffered" in e
                   for e in evs)

    def test_sharding_aware_with_mesh(self):
        import jax
        from paddle_tpu.io import prefetch_to_device
        from paddle_tpu.distributed.topology import build_mesh
        mesh = build_mesh(dp=4, devices=jax.devices()[:4])
        batches = [np.ones((8, 4), np.float32) for _ in range(2)]
        out = list(prefetch_to_device(iter(batches), depth=2,
                                      mesh=mesh))
        sh = out[0].value.sharding
        # batch dim sharded over the data axes
        assert sh.spec[0] is not None

    def test_slow_loader_host_wait_accounted(self):
        """Satellite (ISSUE 10): a loader slower than its consumer
        must show up as host-wait — io.step events carry growing
        host_wait_ms and the io.host_wait_ms histogram AND gauge are
        visible in telemetry.dump()."""
        from paddle_tpu.io import prefetch_to_device

        def slow_gen():
            for i in range(4):
                time.sleep(0.03)        # deliberately slow producer
                yield np.full((2,), i, np.float32)

        telemetry.registry().reset()    # instrument counts start clean
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            out = list(prefetch_to_device(slow_gen(), depth=2))
        finally:
            telemetry.remove_sink(sink)
        assert len(out) == 4
        evs = [r for r in sink.records if r["event"] == "io.step"]
        assert len(evs) == 4
        waits = [e["host_wait_ms"] for e in evs]
        # past the priming get, the consumer keeps blocking on the
        # slow producer — the wait accounting must show it
        assert sum(w > 10 for w in waits[1:]) >= 2, waits
        d = telemetry.dump()
        h = d["histograms"]["io.host_wait_ms"]
        assert h["count"] == 4 and h["max"] > 10
        assert "io.host_wait_ms" in d["gauges"]
        assert d["gauges"]["io.host_wait_ms"] \
            == pytest.approx(waits[-1], abs=0.001)

    def test_loader_error_propagates(self):
        from paddle_tpu.io import prefetch_to_device

        def gen():
            yield np.zeros((2,), np.float32)
            raise ValueError("planted")

        pf = prefetch_to_device(gen(), depth=2)
        next(pf)
        with pytest.raises(ValueError, match="planted"):
            for _ in pf:
                pass

    def test_close_on_abandon_stops_producer(self):
        """An abandoned iterator must release its producer thread and
        the parked device batches via close() (regression: the thread
        used to stay parked on the full queue forever)."""
        from paddle_tpu.io import prefetch_to_device

        def gen():
            for i in range(1000):
                yield np.full((2,), i, np.float32)

        pf = prefetch_to_device(gen(), depth=2)
        next(pf)                        # consume one, then abandon
        pf.close()
        pf._thread.join(timeout=2.0)
        assert not pf._thread.is_alive()
        # parked DATA batches dropped (at most the wake-up sentinel
        # remains), and further iteration raises instead of hanging
        assert pf._q.qsize() <= 1
        with pytest.raises(StopIteration):
            next(pf)
        # context-manager form does the same
        with prefetch_to_device(gen(), depth=2) as pf2:
            next(pf2)
        pf2._thread.join(timeout=2.0)
        assert not pf2._thread.is_alive()


# ---------------------------------------------------------------------------
# serving batcher: counters survive a forced recompile; snapshot event

@pytest.fixture(scope="module")
def serve_model():
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _serve_workload(model, force_recompile_at=None):
    from paddle_tpu.inference import ContinuousBatcher
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (4, 7, 5)]
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=32,
                            chunk=4)
    for p in prompts[:2]:
        bat.submit(p, 6)
    bat.step()
    bat.submit(prompts[2], 6)
    n = 0
    while bat.queued or bat.active:
        n += 1
        if force_recompile_at is not None and n == force_recompile_at:
            # forced program-cache miss: the next chunk re-traces
            model.__dict__.get("_gen_compiled", {}).clear()
        bat.step()
    return bat


class TestServeTelemetry:
    def test_stats_survive_forced_recompile(self, serve_model):
        """Regression (ISSUE 6 satellite): a program-cache miss
        mid-life must not lose the batcher's counters — counts across
        a forced recompile equal the undisturbed run's."""
        base = _serve_workload(serve_model)
        forced = _serve_workload(serve_model, force_recompile_at=2)
        b, f = base.stats(), forced.stats()
        for k in ("chunks", "decode_chunks", "admit_chunks",
                  "prefill_tokens", "decode_tokens", "tokens_produced"):
            assert f[k] == b[k], (k, f, b)
        # and the outputs are unchanged by the recompile
        assert {r: list(base._finished[r].tokens)
                for r in base._finished} \
            == {r: list(forced._finished[r].tokens)
                for r in forced._finished}

    def test_recompile_event_snapshots_stats(self, serve_model):
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            _serve_workload(serve_model, force_recompile_at=2)
        finally:
            telemetry.remove_sink(sink)
        recs = [r for r in sink.records
                if r["event"] == "serve.recompile"]
        assert recs, "forced recompile emitted no serve.recompile"
        snap = recs[0]
        # the snapshot carries the PRE-recompile counters
        assert snap["chunks"] >= 1
        assert "prefill_tokens" in snap and "decode_tokens" in snap
        chunks = [r for r in sink.records
                  if r["event"] == "serve.chunk"]
        assert len(chunks) >= snap["chunks"]
        assert any(c["first_use"] for c in chunks)

    def test_chunk_events(self, serve_model):
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            bat = _serve_workload(serve_model)
        finally:
            telemetry.remove_sink(sink)
        chunks = [r for r in sink.records if r["event"] == "serve.chunk"]
        assert len(chunks) == bat.stats()["chunks"]
        kinds = {c["kind"] for c in chunks}
        assert kinds <= {"admit", "decode"} and "admit" in kinds
        assert sum(c["prefill_tokens"] for c in chunks) \
            == bat.stats()["prefill_tokens"]


# ---------------------------------------------------------------------------
# runtime producers: watchdog, fault, checkpoint, pipeline/collectives

class TestRuntimeProducers:
    def test_watchdog_timeout_event(self):
        from paddle_tpu.distributed.watchdog import CommTaskManager
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            mgr = CommTaskManager(poll_interval=0.02)
            task = mgr.start_task("test hang", timeout=0.05)
            try:
                deadline = time.time() + 5
                while not mgr.timeout_log and time.time() < deadline:
                    time.sleep(0.02)
            finally:
                task.done()
                mgr.shutdown()
        finally:
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records
               if r["event"] == "watchdog.timeout"]
        assert evs and evs[0]["task"] == "test hang"
        assert evs[0]["age_s"] >= 0.05

    def test_fault_hit_event(self):
        from paddle_tpu.distributed import fault
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            with fault.scope("step.begin:mode=delay:secs=0"):
                fault.hit("step.begin", key="probe")
        finally:
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records if r["event"] == "fault.hit"]
        assert evs and evs[0]["point"] == "step.begin"
        assert evs[0]["mode"] == "delay"

    def test_checkpoint_commit_and_gc_events(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            root = str(tmp_path)
            for s in (1, 2, 3):
                ckpt.save_checkpoint(
                    {"w": paddle.to_tensor(
                        np.full((2, 2), s, np.float32))},
                    root, s, keep=2)
        finally:
            telemetry.remove_sink(sink)
        commits = [r for r in sink.records if r["event"] == "ckpt.commit"]
        gcs = [r for r in sink.records if r["event"] == "ckpt.gc"]
        assert [c["step"] for c in commits] == [1, 2, 3]
        assert gcs and gcs[-1]["removed"] == ["step_00000001"]

    def test_collective_schedule_event(self):
        import jax
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh

        class _MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = paddle.nn.Linear(8, 8)

            def forward(self, x):
                return self.fc(x)

        paddle.seed(0)
        m = _MLP()
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = ShardedTrainStep(
            m, opt, build_mesh(dp=4, devices=jax.devices()[:4]),
            loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))
        x = paddle.to_tensor(np.ones((8, 8), np.float32))
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            events = step.collective_schedule(x, x)
        finally:
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records
               if r["event"] == "collective.schedule"]
        assert evs and evs[0]["total"] == len(events)
        assert sum(evs[0]["kinds"].values()) == len(events)


# ---------------------------------------------------------------------------
# exporters + profiler facade + report CLI

class TestExportersAndFacade:
    def test_chrome_trace_sink(self, tmp_path):
        path = str(tmp_path / "trace.json")
        sink = telemetry.attach_chrome_trace(path)
        try:
            with telemetry.span("slice"):
                time.sleep(0.002)
            telemetry.emit("instant", a=1)
        finally:
            telemetry.remove_sink(sink)   # close() writes the doc
        doc = json.load(open(path))
        phs = {e["ph"] for e in doc["traceEvents"]}
        assert phs == {"X", "i"}
        sl = next(e for e in doc["traceEvents"] if e["ph"] == "X")
        assert sl["name"] == "slice" and sl["dur"] > 0

    def test_profiler_facade_names_and_record(self, tmp_path):
        # import-compat surface (deprecation shim over telemetry)
        from paddle_tpu.profiler import (Profiler, ProfilerState,
                                         ProfilerTarget, RecordEvent,
                                         make_scheduler,
                                         export_chrome_tracing,
                                         load_profiler_result,
                                         SummaryView, benchmark)
        assert ProfilerState.RECORD and ProfilerTarget.TPU \
            and SummaryView.OverView
        assert "deprecat" in sys.modules["paddle_tpu.profiler"] \
            .__doc__.lower()
        prof = Profiler(timer_only=True)
        with prof:
            with RecordEvent("my_op"):
                time.sleep(0.002)
            benchmark().step(4)
        out = str(tmp_path / "prof.json")
        prof.export(out)
        doc = load_profiler_result(out)
        names = [e["name"] for e in doc["traceEvents"]]
        assert "my_op" in names
        assert "my_op" in prof.summary()
        sched = make_scheduler(closed=1, ready=1, record=2)
        assert sched(0) == ProfilerState.CLOSED
        assert export_chrome_tracing(str(tmp_path))  # handler builds
        # the window detached its sink
        assert not telemetry.active()

    def test_record_event_outside_window_is_free(self):
        from paddle_tpu.profiler import RecordEvent
        with RecordEvent("noop"):
            pass                # no sink attached -> no-op span

    def test_profiler_scheduled_second_window_records(self):
        """Regression: a scheduled profiler's second RECORD window must
        attach a fresh sink (the first fix left self._sink set, so
        window 2 silently recorded nothing), and on_trace_ready fires
        once per closed window, not again at stop()."""
        from paddle_tpu.profiler import (Profiler, RecordEvent,
                                         make_scheduler)
        fired = []
        prof = Profiler(timer_only=True,
                        scheduler=make_scheduler(closed=1, ready=0,
                                                 record=1, repeat=2),
                        on_trace_ready=lambda p: fired.append(
                            len(p._events())))
        prof.start()                    # step 0: CLOSED
        for _ in range(4):              # steps 1..4: R, C, R, C
            with RecordEvent("op"):
                pass
            prof.step()
        prof.stop()
        assert len(fired) == 2          # one per closed window
        # windows ACCUMULATE: summary()/export() cover every window
        # since start(), and window 2 really recorded
        assert fired == [1, 2], fired
        assert not telemetry.active()

    def test_report_cli_selftest(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import telemetry_report as cli
        finally:
            sys.path.pop(0)
        assert cli.main(["--selftest"]) == 0

    def test_report_analyze(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import telemetry_report as cli
        finally:
            sys.path.pop(0)
        log = str(tmp_path / "s.jsonl")
        sink = telemetry.attach_jsonl(log)
        try:
            step, x = _mlp_step()
            for _ in range(4):
                step(x, x)
        finally:
            telemetry.remove_sink(sink)
        rep = cli.analyze(cli.load_events(log))
        assert rep["train_steps"] == 4 and rep["cold_steps"] == 1
        assert set(rep["phases"]) == {"fwd_ms", "bwd_ms", "opt_ms"}
        assert cli.render(rep)

    def test_dump_snapshot_and_bench_field(self, capsys):
        telemetry.counter("x").inc(5)
        d = telemetry.dump(compact=True)
        assert d["counters"]["x"] >= 5
        assert "programs" not in d["compile"]
        # bench.py JSON lines carry the snapshot (acceptance)
        sys.path.insert(0, REPO)
        try:
            import bench
        finally:
            sys.path.pop(0)
        bench._emit("m", 1.0, "u", 1.0, 0.0, [1.0])
        rec = json.loads(capsys.readouterr().out.strip())
        assert "telemetry" in rec and "counters" in rec["telemetry"]
