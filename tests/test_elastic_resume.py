"""End-to-end preemption recovery through the launch controller.

Scenario 1 (rank kill): a worker training under `Model.fit` +
`FaultTolerantCheckpoint` is SIGKILLed mid-run by an injected
`step.begin:mode=kill` fault; the launcher relaunches it; the fresh
process restores the newest complete checkpoint (params, optimizer, LR,
RNG, data cursor) and the combined loss-by-step sequence is BIT-EXACT
equal to an uninterrupted in-process run.

Scenario 2 (SIGTERM drain): the launcher receives a preemption SIGTERM,
forwards it to the worker, the worker finishes the in-flight step,
commits an emergency checkpoint and exits ELASTIC_EXIT_CODE — which the
controller propagates; a relaunch then resumes and completes, again
bit-exactly.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.distributed.launch.controller import ELASTIC_EXIT_CODE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, os, time
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.hapi.callbacks import Callback, FaultTolerantCheckpoint

mode = os.environ.get("FT_MODE", "none")
restart = int(os.environ.get("PADDLE_RESTART_CNT", "0"))
if mode == "kill" and restart == 0:
    # die hard (no epilogue) entering the 4th train step of THIS process
    paddle.set_flags(
        {"FLAGS_fault_injection": "step.begin:step=4:mode=kill"})


class MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(8, 16)
        self.fc2 = paddle.nn.Linear(16, 1)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


class DS(paddle.io.Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = rng.randn(n, 1).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Recorder(Callback):
    def __init__(self, path, slow=0.0):
        super().__init__()
        self.path = path
        self.slow = slow

    def on_train_batch_end(self, step, logs=None):
        rec = {"step": self.model._optimizer._step_count,
               "loss": logs["loss"]}
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\\n")
            f.flush()
        if self.slow:
            time.sleep(self.slow)


paddle.seed(7)
model = paddle.Model(MLP())
opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters())
model.prepare(opt, paddle.nn.MSELoss())
slow = float(os.environ.get("FT_SLOW", "0"))
losses = os.path.join(os.environ["DUMP_DIR"], "losses.jsonl")
# recorder runs BEFORE the checkpoint callback: a drained step is
# recorded, then checkpointed, then the process exits 101
model.fit(DS(), batch_size=4, epochs=int(os.environ.get("FT_EPOCHS", "2")),
          shuffle=False, verbose=0,
          callbacks=[Recorder(losses, slow),
                     FaultTolerantCheckpoint(os.environ["FT_CKPT"])])
"""


def _reference_losses(epochs=2):
    """Uninterrupted in-process run of the SAME training: step -> loss."""
    import paddle_tpu as paddle
    from paddle_tpu.hapi.callbacks import Callback

    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(8, 16)
            self.fc2 = paddle.nn.Linear(16, 1)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    class DS(paddle.io.Dataset):
        def __init__(self, n=32):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 8).astype(np.float32)
            self.y = rng.randn(n, 1).astype(np.float32)

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    out = {}

    class Rec(Callback):
        def on_train_batch_end(self, step, logs=None):
            out[self.model._optimizer._step_count] = logs["loss"]

    paddle.seed(7)
    model = paddle.Model(MLP())
    opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters())
    model.prepare(opt, paddle.nn.MSELoss())
    model.fit(DS(), batch_size=4, epochs=epochs, shuffle=False, verbose=0,
              callbacks=[Rec()])
    return out


def _worker_losses(path):
    """step -> loss from the worker's jsonl (later lines win: a step
    re-recorded after resume must equal the first recording anyway)."""
    out = {}
    dup_mismatch = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec["step"] in out and out[rec["step"]] != rec["loss"]:
                dup_mismatch.append(rec["step"])
            out[rec["step"]] = rec["loss"]
    assert not dup_mismatch, f"re-trained steps diverged: {dup_mismatch}"
    return out


def test_fleet_chaos_selftest():
    """ISSUE 13 acceptance: `chaos_check --fleet --selftest` runs a
    REAL 2-proc data-parallel job, kills rank 1 mid-run via the fault
    grammar, the surviving pod re-forms the gang at world 1, and the
    resumed job restores through reshard-on-load (two rank ShardSlices
    → full arrays) + the topology-aware cursor: all steps complete,
    post-resume losses BIT-EXACT vs an uninterrupted world-1 run
    restored from the same checkpoint, zero samples lost or duplicated,
    and the fleet.elastic event renders in fleet_report."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               JAX_PLATFORMS="cpu")
    env.pop("FLAGS_fault_injection", None)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_check.py"),
         "--fleet", "--selftest", "--json"],
        capture_output=True, text=True, timeout=600, env=env)
    tail = (p.stdout or "")[-2000:] + (p.stderr or "")[-1000:]
    assert p.returncode == 0, tail
    rep = json.loads(p.stdout)
    assert rep["ok"], tail
    by_name = {c["check"]: c for c in rep["checks"]}
    assert by_name["fleet.kill-shrink-resume"]["recovered"]
    assert by_name["fleet.elastic-event-rendered"]["recovered"]


def _launch(tmp_path, env_extra, max_restart=2):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(WORKER))
    env = dict(os.environ, DUMP_DIR=str(tmp_path),
               FT_CKPT=str(tmp_path / "ckpt"),
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               **env_extra)
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes=1", f"--max_restart={max_restart}",
         f"--log_dir={tmp_path}/log", "--job_id=ftres", str(script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def test_rank_kill_relaunch_resumes_bit_exact(tmp_path):
    """Injected hard kill mid-run; gang relaunch; losses continue
    bit-exactly from the last committed checkpoint."""
    proc = _launch(tmp_path, {"FT_MODE": "kill"})
    out, _ = proc.communicate(timeout=420)
    assert proc.returncode == 0, out.decode()[-3000:]
    assert b"restart 1/" in out          # the relaunch actually happened
    got = _worker_losses(tmp_path / "losses.jsonl")
    ref = _reference_losses()
    assert got == ref, (sorted(got)[-4:], sorted(ref)[-4:])


@pytest.mark.slow
def test_sigterm_drain_checkpoints_and_resumes(tmp_path):
    """Preemption notice: SIGTERM to the launcher drains the worker
    (finish step -> emergency checkpoint -> exit ELASTIC_EXIT_CODE,
    propagated by the controller); a relaunch completes the run
    bit-exactly.  Marked slow (two full launcher runs); the drain
    protocol's controller half has a fast in-process twin in
    test_fault_tolerance.py::TestSigtermDrainProtocol."""
    from paddle_tpu.distributed.checkpoint import latest_checkpoint
    env = {"FT_MODE": "drain", "FT_SLOW": "0.3", "FT_EPOCHS": "4",
           "PADDLE_DRAIN_GRACE": "60"}
    proc = _launch(tmp_path, env)
    losses = tmp_path / "losses.jsonl"
    deadline = time.time() + 180
    while time.time() < deadline and not losses.exists():
        time.sleep(0.3)
    assert losses.exists(), "worker never trained a step"
    time.sleep(1.0)                       # let it get a few steps in
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=180)
    assert proc.returncode == ELASTIC_EXIT_CODE, out.decode()[-3000:]
    assert b"draining" in out
    assert b"drain complete" in out
    wlogs = "".join(p.read_text(errors="replace")
                    for p in (tmp_path / "log").glob("workerlog.*"))
    assert "emergency checkpoint committed" in wlogs
    assert latest_checkpoint(str(tmp_path / "ckpt")) is not None
    drained_steps = len(_worker_losses(losses))
    # relaunch (the supervisor's reaction to exit 101): run to completion
    proc2 = _launch(tmp_path, dict(env, FT_SLOW="0"))
    out2, _ = proc2.communicate(timeout=420)
    assert proc2.returncode == 0, out2.decode()[-3000:]
    got = _worker_losses(losses)
    ref = _reference_losses(epochs=4)
    assert len(got) == len(ref) and got == ref
    assert 0 < drained_steps < len(ref)   # the drain really was mid-run
