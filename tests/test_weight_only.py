"""Weight-only int8/int4 decode path (ISSUE 11 tentpole):
quantization.weight_only packing, the ops.quant_matmul kernel/twin
pair, the model threading, and the program-cache fingerprint guard.

Reference: python/paddle/nn/quant/quantized_linear.py
(weight_quantize / weight_only_linear).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops as tpu_ops
from paddle_tpu.ops.pallas.quant_matmul import quant_matmul as pallas_qm
from paddle_tpu.quantization.weight_only import (
    quantize_weight, dequantize_weight, quantize_model,
    weight_pool_bytes, packed_bytes)
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config


def _tiny_llama(seed=0, dtype="float32"):
    paddle.seed(seed)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128,
                            dtype=dtype)
    return LlamaForCausalLM(cfg)


# ---------------------------------------------------------------------------
# packing / round trips


def test_pack_int4_roundtrip_exact():
    rng = np.random.RandomState(0)
    q = rng.randint(-8, 8, (64, 48))
    out = np.asarray(tpu_ops.unpack_int4(tpu_ops.pack_int4(q)))
    assert (out == q).all()


def test_quantize_weight_int4_grid_roundtrip():
    """Values already ON the int4 grid survive quantize->dequantize
    bit-close (absmax scaling reconstructs the grid when each group
    spans it): the packed path loses nothing beyond the grid."""
    rng = np.random.RandomState(1)
    g = 16
    scale = 0.05
    q = rng.randint(-7, 8, (64, 32)).astype(np.float32)
    # pin every group's absmax at 7 so the derived scale IS the grid
    # scale (amax/7 == 0.05) for every (group, column)
    q[::g, :] = 7
    w = q * scale
    packed, scales = quantize_weight(w, "int4", g)
    assert packed.shape == (32, 32) and packed.dtype == jnp.int8
    assert scales.shape == (64 // g, 32)
    back = np.asarray(dequantize_weight(packed, scales, "int4", g))
    np.testing.assert_allclose(back, w, rtol=0, atol=1e-6)


@pytest.mark.parametrize("fmt,group", [("int8", None), ("int4", 8),
                                       ("int4", 16), ("int4", 32)])
def test_dequant_error_bounded(fmt, group):
    rng = np.random.RandomState(2)
    w = rng.randn(64, 48).astype(np.float32)
    packed, scales = quantize_weight(w, fmt, group or 64)
    back = np.asarray(dequantize_weight(packed, scales, fmt,
                                        group or 64))
    # absmax grids bound the error at half a quantization step
    if fmt == "int8":
        bound = np.abs(w).max(axis=0) / 127.0
    else:
        bound = np.abs(w.reshape(64 // group, group, 48)).max(axis=1) \
            .repeat(group, axis=0).reshape(64, 48) / 7.0
    assert (np.abs(back - w) <= bound * 0.5001 + 1e-7).all()


def test_quantize_weight_int4_bad_group_raises():
    w = np.ones((64, 8), np.float32)
    with pytest.raises(ValueError):
        quantize_weight(w, "int4", 24)      # 24 does not divide 32


# ---------------------------------------------------------------------------
# kernel == twin (interpret mode off-TPU), across formats and shapes


@pytest.mark.parametrize("fmt,group", [("int8", None), ("int4", 8),
                                       ("int4", 16)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_kernel_matches_twin_bit_exact(fmt, group, dtype):
    rng = np.random.RandomState(3)
    w = rng.randn(64, 48).astype(np.float32)
    packed, scales = quantize_weight(w, fmt, group or 64)
    x = jnp.asarray(rng.randn(8, 64), dtype)
    twin = tpu_ops.xla_quant_matmul(x, packed, scales, fmt, group or 64)
    kern = pallas_qm(x, packed, scales, fmt, group or 64,
                     interpret=True)
    assert twin.dtype == x.dtype
    assert (np.asarray(twin, np.float32)
            == np.asarray(kern, np.float32)).all()


def test_kernel_matches_twin_3d_batch():
    rng = np.random.RandomState(4)
    w = rng.randn(32, 64).astype(np.float32)
    packed, scales = quantize_weight(w, "int4", 16)
    x = jnp.asarray(rng.randn(2, 5, 32).astype(np.float32))
    twin = tpu_ops.xla_quant_matmul(x, packed, scales, "int4", 16)
    kern = pallas_qm(x, packed, scales, "int4", 16, interpret=True)
    assert twin.shape == (2, 5, 64)
    assert (np.asarray(twin) == np.asarray(kern)).all()


def test_quant_matmul_rejects_unknown_format():
    x = jnp.ones((2, 8), jnp.float32)
    with pytest.raises(ValueError):
        tpu_ops.quant_matmul(x, jnp.ones((8, 8), jnp.int8),
                             jnp.ones((8,)), "int2")
    with pytest.raises(ValueError):
        tpu_ops.quant_matmul(x, jnp.ones((4, 8), jnp.int8),
                             jnp.ones((1, 8)), "int4")  # no group_size


# ---------------------------------------------------------------------------
# model pass: packing in place, logit tolerance, byte accounting


@pytest.mark.parametrize("fmt,group", [("int8", 16), ("int4", 16)])
def test_quantized_llama_decode_logits_close(fmt, group):
    """Two pins: (a) the quantized decode equals an fp decode through
    EXPLICITLY dequantized weights to float tolerance — packing,
    threading and the fused dequant are exactly the reference math;
    (b) the drift vs the ORIGINAL fp weights is quantization noise,
    not garbage (int4 on random N(0, 1/sqrt(h)) weights is coarse, so
    its bound is loose by construction)."""
    model = _tiny_llama()
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, 128, (2, 7)).astype(np.int32)
    cache = model.init_cache(2, 32)
    fp_lg, _ = model.forward_cached(jnp.asarray(prompt), cache,
                                    jnp.asarray(0, jnp.int32))
    # reference twin model: same init, weights overwritten with the
    # DEQUANTIZED values — its plain fp decode is the ground truth for
    # what the fused-dequant path must compute
    ref_model = _tiny_llama()
    quantize_model(model, fmt, group)
    # mirror every packed param back into ref_model, dequantized
    qsd = model.state_dict()
    rsd = ref_model.state_dict()
    for name, t in rsd.items():
        if name in qsd and name + "_scale" in qsd:
            deq = dequantize_weight(qsd[name].value,
                                    qsd[name + "_scale"].value,
                                    fmt, group)
            t._value = deq.astype(t.value.dtype)
    cache = model.init_cache(2, 32)
    q_lg, _ = model.forward_cached(jnp.asarray(prompt), cache,
                                   jnp.asarray(0, jnp.int32))
    cache = ref_model.init_cache(2, 32)
    d_lg, _ = ref_model.forward_cached(jnp.asarray(prompt), cache,
                                       jnp.asarray(0, jnp.int32))
    np.testing.assert_allclose(np.asarray(q_lg, np.float32),
                               np.asarray(d_lg, np.float32),
                               rtol=2e-5, atol=2e-5)
    ref = np.asarray(fp_lg, np.float32)
    err = np.abs(np.asarray(q_lg, np.float32) - ref).max()
    scale = max(np.abs(ref).max(), 1.0)
    tol = 0.05 if fmt == "int8" else 0.6
    assert err <= tol * scale, (err, scale)


def test_weight_bytes_reduction_and_packed_bytes():
    model = _tiny_llama()
    fp = weight_pool_bytes(model)
    pred8 = packed_bytes(model, "int8")
    pred4 = packed_bytes(model, "int4", 16)
    # fp32 storage: ~4x for int8, ~8x for int4 (scales overhead aside)
    assert pred8 < 0.3 * fp and pred4 < 0.2 * fp and pred4 < pred8
    quantize_model(model, "int8", 16)
    assert weight_pool_bytes(model) == pred8
    m4 = _tiny_llama(seed=1)
    quantize_model(m4, "int4", 16)
    assert weight_pool_bytes(m4) == pred4
    with pytest.raises(ValueError):
        packed_bytes(m4, "int8")            # already quantized


def test_quantize_model_idempotent_and_config_locked():
    model = _tiny_llama()
    quantize_model(model, "int8", 16)
    quantize_model(model, "int8", 16)       # idempotent no-op
    with pytest.raises(ValueError):
        quantize_model(model, "int4", 16)   # cannot re-pack


def test_quantized_gpt_decode_logits_close():
    paddle.seed(2)
    model = GPTForCausalLM(gpt_tiny_config())
    rng = np.random.RandomState(6)
    prompt = rng.randint(0, 256, (2, 6)).astype(np.int32)
    cache = model.init_cache(2, 24)
    fp_lg, _ = model.forward_cached(jnp.asarray(prompt), cache,
                                    jnp.asarray(0, jnp.int32))
    quantize_model(model, "int8", 16)
    cache = model.init_cache(2, 24)
    q_lg, _ = model.forward_cached(jnp.asarray(prompt), cache,
                                   jnp.asarray(0, jnp.int32))
    ref = np.asarray(fp_lg, np.float32)
    err = np.abs(np.asarray(q_lg, np.float32) - ref).max()
    assert err <= 0.02 * max(np.abs(ref).max(), 1.0), err


def test_quantized_generate_matches_greedy_recompute_mostly():
    """Greedy decode THROUGH the quantized weights is deterministic
    and self-consistent: two generate() calls agree, and the program
    re-built after quantization really reads the packed params (a
    stale fp program would zip-misaligned-crash or emit garbage
    shapes)."""
    model = _tiny_llama(seed=3)
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 128, (2, 5)).astype(np.int32)
    _ = model.generate(paddle.to_tensor(prompt), max_new_tokens=4)
    quantize_model(model, "int4", 16)
    a = np.asarray(model.generate(paddle.to_tensor(prompt),
                                  max_new_tokens=4).value)
    b = np.asarray(model.generate(paddle.to_tensor(prompt),
                                  max_new_tokens=4).value)
    assert a.shape == (2, 4) and (a == b).all()


# ---------------------------------------------------------------------------
# program-cache fingerprint guard (ISSUE 11 satellite): flag AND
# model-state flips rebuild, restored state hits warm


def test_program_cache_keys_guard_weight_only_flag():
    model = _tiny_llama(seed=4)
    from paddle_tpu.inference.generation import (
        _model_program_cache, _kv_layout_fingerprint)
    builds = []

    def build():
        builds.append(1)
        return lambda: None

    key = ("woguard_probe", 1)
    _model_program_cache(model, key, build)
    _model_program_cache(model, key, build)
    assert len(builds) == 1                       # warm hit
    fp0 = _kv_layout_fingerprint()
    paddle.set_flags({"FLAGS_weight_only_dtype": "int8"})
    try:
        assert _kv_layout_fingerprint() != fp0
        _model_program_cache(model, key, build)
        assert len(builds) == 2                   # flag flip rebuilds
        paddle.set_flags({"FLAGS_weight_only_group_size": 32})
        _model_program_cache(model, key, build)
        assert len(builds) == 3                   # group flip rebuilds
    finally:
        paddle.set_flags({"FLAGS_weight_only_dtype": "none",
                          "FLAGS_weight_only_group_size": 64})
    _model_program_cache(model, key, build)
    assert len(builds) == 3                       # restored: warm hit


def test_program_cache_keys_guard_model_quantization():
    """An EXPLICITLY quantized model (no flag set) must also miss
    programs traced against its fp weights — the packed state_dict
    carries extra scale entries, so a stale replay would misalign the
    swapped params."""
    model = _tiny_llama(seed=5)
    from paddle_tpu.inference.generation import (
        _model_program_cache, _program_cache_contains)
    builds = []

    def build():
        builds.append(1)
        return lambda: None

    key = ("woguard_model", 1)
    _model_program_cache(model, key, build)
    assert _program_cache_contains(model, key)
    quantize_model(model, "int8", 16)
    assert not _program_cache_contains(model, key)
    _model_program_cache(model, key, build)
    assert len(builds) == 2


def test_batcher_flag_auto_quantizes_and_serves():
    """FLAGS_weight_only_dtype threads the pass through the serving
    tier: a batcher constructed under the flag packs the model and the
    whole workload decodes through quant_matmul."""
    model = _tiny_llama(seed=6)
    from paddle_tpu.inference import ContinuousBatcher
    rng = np.random.RandomState(8)
    prompts = [rng.randint(1, 128, L).astype(np.int32) for L in (5, 8)]
    paddle.set_flags({"FLAGS_weight_only_dtype": "int8",
                      "FLAGS_weight_only_group_size": 16})
    try:
        bat = ContinuousBatcher(model, max_batch_size=2, max_len=32,
                                chunk=4, prefill_chunk=4)
        rids = [bat.submit(p, 5) for p in prompts]
        outs = bat.run()
    finally:
        paddle.set_flags({"FLAGS_weight_only_dtype": "none",
                          "FLAGS_weight_only_group_size": 64})
    assert getattr(model, "_weight_only")["dtype"] == "int8"
    assert bat.stats()["weight_only"] == "int8"
    assert all(len(outs[r]) == 5 for r in rids)
    assert bat.compiled_programs <= 2
