"""Distributed tests on the 8-virtual-device CPU mesh.

Reference patterns (SURVEY §4): fake-cluster multi-process harness →
here single-process SPMD over xla_force_host_platform_device_count=8;
reshard matrix tests (test/auto_parallel/reshard_*) → placement pairs via
device_put; hybrid-strategy equivalence (loss equality vs single-rank
baseline, test/collective/fleet).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.topology import build_mesh
from paddle_tpu.parallel import ShardedTrainStep


def _need8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")


class TestMesh:
    def test_build_mesh_axes(self):
        _need8()
        mesh = build_mesh(dp=2, mp=2, sharding=2)
        assert mesh.axis_names == ("pp", "sep", "sharding", "dp", "mp")
        assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 2

    def test_hybrid_communicate_group(self):
        _need8()
        hcg = dist.HybridCommunicateGroup(dp_degree=2, mp_degree=2,
                                          sharding_degree=2)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_sharding_parallel_world_size() == 2
        assert hcg.nranks == 8

    def test_topology_coords(self):
        topo = dist.CommunicateTopology(
            ["data", "pipe", "model"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, model=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]
        comm = topo.get_comm_list("model")
        assert [0, 1] in comm


class TestReshardMatrix:
    """Every (src,dst) placement pair — reference enumerates these as
    separate reshard functions (r_to_s, s_to_r, p_to_r, s_to_s...)."""

    def _mesh(self):
        _need8()
        return dist.ProcessMesh(np.arange(8).reshape(2, 4),
                                dim_names=["x", "y"])

    def test_r_to_s_to_r(self):
        mesh = self._mesh()
        x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
        xs = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Replicate()])
        np.testing.assert_array_equal(xs.numpy(), x.numpy())
        xr = dist.reshard(xs, mesh, [dist.Replicate(), dist.Replicate()])
        np.testing.assert_array_equal(xr.numpy(), x.numpy())

    def test_s_to_s_axis_move(self):
        mesh = self._mesh()
        x = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
        s0 = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Replicate()])
        s1 = dist.reshard(s0, mesh, [dist.Shard(1), dist.Replicate()])
        np.testing.assert_array_equal(s1.numpy(), x.numpy())

    def test_2d_sharding(self):
        mesh = self._mesh()
        x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
        s = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
        np.testing.assert_array_equal(s.numpy(), x.numpy())
        # sharded computation equals replicated computation
        y = paddle.matmul(s, paddle.transpose(s, [1, 0]))
        np.testing.assert_allclose(y.numpy(), x.numpy() @ x.numpy().T,
                                   rtol=1e-5)

    def test_placement_roundtrip_all_pairs(self):
        mesh = self._mesh()
        x = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
        placements = [
            [dist.Replicate(), dist.Replicate()],
            [dist.Shard(0), dist.Replicate()],
            [dist.Shard(1), dist.Replicate()],
            [dist.Replicate(), dist.Shard(0)],
            [dist.Shard(0), dist.Shard(1)],
            [dist.Shard(1), dist.Shard(0)],
        ]
        for src in placements:
            for dst in placements:
                xs = dist.shard_tensor(x, mesh, src)
                xd = dist.reshard(xs, mesh, dst)
                np.testing.assert_array_equal(xd.numpy(), x.numpy())


class TestCollectiveAPI:
    def test_single_controller_semantics(self):
        # world_size==1 process: allreduce/broadcast are identity, like the
        # reference with nranks=1
        t = paddle.to_tensor([1.0, 2.0])
        dist.all_reduce(t)
        np.testing.assert_array_equal(t.numpy(), [1.0, 2.0])
        outs = []
        dist.all_gather(outs, t)
        assert len(outs) == 1
        dist.broadcast(t, src=0)
        dist.barrier()

    def test_new_group(self):
        g = dist.new_group([0, 1])
        assert g.nranks == 2


class TestTPLayersSPMD:
    """Column/Row parallel linears over the mp axis must match the dense
    computation (reference: hybrid_parallel_mp_layers test)."""

    def test_column_row_pair(self):
        _need8()
        import paddle_tpu.distributed.fleet as fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["mp_degree"] = 8
        fleet.init(is_collective=True, strategy=strategy)
        try:
            col = fleet.ColumnParallelLinear(16, 32, has_bias=True,
                                             gather_output=False)
            row = fleet.RowParallelLinear(32, 16, has_bias=True,
                                          input_is_parallel=True)
            x = paddle.to_tensor(np.random.rand(4, 16).astype(np.float32))
            out = row(col(x))
            # dense reference
            ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()
                   ) @ row.weight.numpy() + row.bias.numpy()
            np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4,
                                       atol=1e-5)
            # weights are actually sharded over mp
            sh = col.weight.value.sharding
            assert isinstance(sh, NamedSharding)
            assert sh.spec == P(None, "mp")
        finally:
            from paddle_tpu.distributed.topology import \
                set_hybrid_communicate_group
            set_hybrid_communicate_group(None)

    def test_vocab_parallel_embedding(self):
        _need8()
        import paddle_tpu.distributed.fleet as fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["mp_degree"] = 8
        fleet.init(is_collective=True, strategy=strategy)
        try:
            emb = fleet.VocabParallelEmbedding(64, 16)
            idx = paddle.to_tensor(np.array([0, 5, 63]))
            out = emb(idx)
            np.testing.assert_allclose(out.numpy(),
                                       emb.weight.numpy()[[0, 5, 63]],
                                       rtol=1e-6)
        finally:
            from paddle_tpu.distributed.topology import \
                set_hybrid_communicate_group
            set_hybrid_communicate_group(None)


class TestShardedTrainerEquivalence:
    """Loss trajectory under dp/TP/ZeRO must equal the single-device run
    (reference: test_parallel_dygraph_* loss-equality checks)."""

    def _make_model_and_data(self, seed=0):
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(seed)
        cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                                intermediate_size=128,
                                num_attention_heads=4,
                                num_key_value_heads=4, vocab_size=128,
                                dtype="float32")
        model = LlamaForCausalLM(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (8, 16)).astype(np.int32)
        return model, ids

    def _run_steps(self, mesh, stage, tp=False, n=3):
        model, ids = self._make_model_and_data()
        if tp:
            from paddle_tpu.models.llama import shard_llama_tp
            shard_llama_tp(model, mesh)
        opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters())
        step = ShardedTrainStep(model, opt, mesh, sharding_stage=stage)
        losses = []
        for _ in range(n):
            losses.append(float(np.asarray(
                step(paddle.to_tensor(ids), paddle.to_tensor(ids)).value)))
        return losses

    def test_dp_matches_single(self):
        _need8()
        base = self._run_steps(build_mesh(devices=jax.devices()[:1]), 0)
        dp = self._run_steps(build_mesh(dp=8), 0)
        np.testing.assert_allclose(base, dp, rtol=2e-4, atol=2e-4)

    def test_zero1_matches_single(self):
        _need8()
        base = self._run_steps(build_mesh(devices=jax.devices()[:1]), 0)
        z1 = self._run_steps(build_mesh(sharding=8), 1)
        np.testing.assert_allclose(base, z1, rtol=2e-4, atol=2e-4)

    def test_zero2_matches_single(self):
        _need8()
        base = self._run_steps(build_mesh(devices=jax.devices()[:1]), 0)
        z2 = self._run_steps(build_mesh(sharding=8), 2)
        np.testing.assert_allclose(base, z2, rtol=2e-4, atol=2e-4)

    def test_zero3_matches_single(self):
        _need8()
        base = self._run_steps(build_mesh(devices=jax.devices()[:1]), 0)
        z3 = self._run_steps(build_mesh(sharding=8), 3)
        np.testing.assert_allclose(base, z3, rtol=2e-4, atol=2e-4)

    def _make_step(self, stage):
        model, ids = self._make_model_and_data()
        opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters())
        step = ShardedTrainStep(model, opt, build_mesh(sharding=8),
                                sharding_stage=stage)
        return step, ids

    def test_zero_stage_mechanisms(self):
        """The three stages must differ by mechanism, not just docstring:
        - stage>=1: optimizer states SHARDED over 'sharding' after a step
          (stage 0: replicated)
        - stage 2: grads carry explicit sharding constraints in the
          StableHLO (reduce-scatter on TPU; CPU XLA may lower them as
          all-reduce+slice, so we assert the constraint, not the op)
        - stage 3: params themselves sharded."""
        _need8()

        def sharded_axes(arr):
            from jax.sharding import NamedSharding
            sh = arr.sharding
            if not isinstance(sh, NamedSharding):
                return set()
            out = set()
            for e in sh.spec:
                if e is None:
                    continue
                out.update(e if isinstance(e, tuple) else (e,))
            return out

        for stage in (0, 1, 2, 3):
            step, ids = self._make_step(stage)
            step(paddle.to_tensor(ids), paddle.to_tensor(ids))
            opt_axes = set()
            for st in step._opt_states:
                for v in st.values():
                    opt_axes |= sharded_axes(v)
            param_axes = set()
            for n in step._names:
                param_axes |= sharded_axes(
                    step.model.state_dict()[n].value)
            if stage == 0:
                assert "sharding" not in opt_axes
                assert "sharding" not in param_axes
            else:
                assert "sharding" in opt_axes, (stage, opt_axes)
                assert ("sharding" in param_axes) == (stage == 3)

        # stage-2 grad constraints visible pre-SPMD: strictly more
        # @Sharding custom calls than stage 1 (one per gradient)
        s1, ids = self._make_step(1)
        s2, _ = self._make_step(2)
        t1 = s1.compiled_hlo(paddle.to_tensor(ids), paddle.to_tensor(ids),
                             optimized=False)
        t2 = s2.compiled_hlo(paddle.to_tensor(ids), paddle.to_tensor(ids),
                             optimized=False)
        n_params = len(s2._names)

        def n_constraints(txt):
            # Shardy dialect (sdy.sharding_constraint) or pre-Shardy
            # (@Sharding custom call)
            return (txt.count("sdy.sharding_constraint")
                    + txt.count("@Sharding"))

        assert n_constraints(t2) >= n_constraints(t1) + n_params, (
            n_constraints(t1), n_constraints(t2), n_params)

    def test_tp_matches_single(self):
        _need8()
        base = self._run_steps(build_mesh(devices=jax.devices()[:1]), 0)
        tp = self._run_steps(build_mesh(mp=8), 0, tp=True)
        np.testing.assert_allclose(base, tp, rtol=2e-4, atol=2e-4)

    def test_hybrid_2x2x2(self):
        _need8()
        base = self._run_steps(build_mesh(devices=jax.devices()[:1]), 0)
        hy = self._run_steps(build_mesh(dp=2, sharding=2, mp=2), 3,
                             tp=True)
        np.testing.assert_allclose(base, hy, rtol=5e-4, atol=5e-4)


class TestDistributedCheckpoint:
    def test_save_load_reshard(self, tmp_path):
        _need8()
        mesh = dist.ProcessMesh(np.arange(8).reshape(8), dim_names=["x"])
        x = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32))
        xs = dist.shard_tensor(x, mesh, [dist.Shard(0)])
        sd = {"w": xs}
        from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                       load_state_dict)
        save_state_dict(sd, str(tmp_path))
        # load into a DIFFERENT placement (reshard-on-load)
        y = dist.shard_tensor(
            paddle.zeros([16, 8]), mesh, [dist.Shard(1)])
        load_state_dict({"w": y}, str(tmp_path))
        np.testing.assert_array_equal(y.numpy(), x.numpy())


class TestDistributedSampler:
    def test_disjoint_shards(self):
        from paddle_tpu.io import DistributedBatchSampler

        class DS:
            def __len__(self):
                return 20
        samplers = [DistributedBatchSampler(DS(), batch_size=2,
                                            num_replicas=4, rank=r)
                    for r in range(4)]
        seen = []
        for s in samplers:
            for batch in s:
                seen += batch
        assert sorted(set(seen)) == list(range(20))


def test_zero3_host_offload_roundtrip():
    """ZeRO-3 + offload: optimizer state lives in pinned_host memory
    between steps, streams through HBM inside the step, and training
    matches the non-offloaded run exactly (reference:
    group_sharded_stage3.py `offload`)."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    def make(offload):
        paddle.seed(21)
        m = nn.Sequential(nn.Linear(16, 16), nn.Tanh(),
                          nn.Linear(16, 4))
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        mesh = build_mesh(sharding=8)
        st = ShardedTrainStep(m, opt, mesh, sharding_stage=3,
                              offload=offload,
                              loss_fn=lambda o, y:
                              nn.functional.cross_entropy(o, y))
        return m, st

    rng = np.random.RandomState(0)
    xs = rng.randn(8, 16).astype(np.float32)
    ys = rng.randint(0, 4, (8,)).astype(np.int64)

    m1, s1 = make(False)
    base = [float(np.asarray(s1(paddle.to_tensor(xs),
                                paddle.to_tensor(ys)).value))
            for _ in range(3)]
    m2, s2 = make(True)
    off = [float(np.asarray(s2(paddle.to_tensor(xs),
                               paddle.to_tensor(ys)).value))
           for _ in range(3)]
    np.testing.assert_allclose(off, base, rtol=1e-5, atol=1e-6)

    # placement round-trips: state is pinned_host AFTER the step.
    # Backends without the pinned_host/device memory kinds (this CPU
    # runtime) run the same math with plain placement — parity above
    # is the invariant there.
    from paddle_tpu.parallel.offload_pipeline import supports_memory_kinds
    if supports_memory_kinds():
        for st_dict in s2._opt_states:
            for k, v in st_dict.items():
                assert v.sharding.memory_kind == "pinned_host", \
                    (k, v.sharding)
        # params stayed in device memory
        for n, p in m2.named_parameters():
            assert p.value.sharding.memory_kind == "device"
    w1 = np.asarray(m1.state_dict()["0.weight"].value)
    w2 = np.asarray(m2.state_dict()["0.weight"].value)
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)


def test_zero3_param_offload_roundtrip():
    """ZeRO-3 + PARAM offload (offload="params"): parameters AND
    optimizer state park in pinned_host between steps; training matches
    the non-offloaded run exactly (reference: group_sharded_stage3.py
    offload=True parks param slices on host, :110,127,294)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh

    def make(offload):
        paddle.seed(29)
        m = nn.Sequential(nn.Linear(16, 16), nn.Tanh(),
                          nn.Linear(16, 4))
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        mesh = build_mesh(sharding=8)
        st = ShardedTrainStep(m, opt, mesh, sharding_stage=3,
                              offload=offload,
                              loss_fn=lambda o, y:
                              nn.functional.cross_entropy(o, y))
        return m, st

    rng = np.random.RandomState(0)
    xs = rng.randn(8, 16).astype(np.float32)
    ys = rng.randint(0, 4, (8,)).astype(np.int64)

    m1, s1 = make(False)
    base = [float(np.asarray(s1(paddle.to_tensor(xs),
                                paddle.to_tensor(ys)).value))
            for _ in range(3)]
    m2, s2 = make("params")
    off = [float(np.asarray(s2(paddle.to_tensor(xs),
                               paddle.to_tensor(ys)).value))
           for _ in range(3)]
    np.testing.assert_allclose(off, base, rtol=1e-5, atol=1e-6)

    # placement round-trips: params AND opt state pinned_host AFTER the
    # step; the two runs' final weights agree.  Placement asserts are
    # TPU-only (no pinned_host memory kind on this CPU runtime).
    from paddle_tpu.parallel.offload_pipeline import supports_memory_kinds
    if supports_memory_kinds():
        for n, p in m2.named_parameters():
            assert p.value.sharding.memory_kind == "pinned_host", n
        for st_dict in s2._opt_states:
            for k, v in st_dict.items():
                assert v.sharding.memory_kind == "pinned_host", k
    sd1, sd2 = m1.state_dict(), m2.state_dict()
    for n in sd1:
        np.testing.assert_allclose(np.asarray(sd2[n].value),
                                   np.asarray(sd1[n].value),
                                   rtol=1e-5, atol=1e-6)
