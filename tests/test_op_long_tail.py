"""Long-tail op coverage: the full reference paddle.__all__ surface,
the extras module semantics vs numpy, and in-place write-back variants.

Reference: python/paddle/__init__.py __all__ (418 names);
tensor/manipulation.py, math.py; yaml `inplace:` annotations.
"""
import re

import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle


def test_reference_all_surface_complete():
    src = open("/root/reference/python/paddle/__init__.py").read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    names = re.findall(r"'([^']+)'", m.group(1))
    missing = [n for n in names if not hasattr(paddle, n)]
    assert not missing, f"missing {len(missing)}: {missing[:20]}"


class TestExtras:
    def _t(self, a):
        return paddle.to_tensor(np.asarray(a))

    def test_stacks(self):
        a, b = np.ones((2, 3), np.float32), np.zeros((2, 3), np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.hstack([self._t(a), self._t(b)]).value),
            np.hstack([a, b]))
        np.testing.assert_allclose(
            np.asarray(paddle.vstack([self._t(a), self._t(b)]).value),
            np.vstack([a, b]))
        np.testing.assert_allclose(
            np.asarray(paddle.dstack([self._t(a), self._t(b)]).value),
            np.dstack([a, b]))

    def test_unbind_reverse_addn(self):
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        outs = paddle.unbind(self._t(x), axis=0)
        assert len(outs) == 2
        np.testing.assert_allclose(np.asarray(outs[1].value), x[1])
        np.testing.assert_allclose(
            np.asarray(paddle.reverse(self._t(x), axis=1).value),
            x[:, ::-1])
        np.testing.assert_allclose(
            np.asarray(paddle.add_n([self._t(x), self._t(x)]).value),
            2 * x)

    def test_histogram_bin_edges(self):
        x = np.array([0.0, 1.0, 2.0, 3.0], np.float32)
        got = np.asarray(paddle.histogram_bin_edges(self._t(x),
                                                    bins=4).value)
        np.testing.assert_allclose(got, np.histogram_bin_edges(x, 4),
                                   atol=1e-6)

    def test_special_functions(self):
        x = np.array([0.5, 1.5, 3.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.gammaln(self._t(x)).value),
            sps.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.gammainc(self._t(x), self._t(x)).value),
            sps.gammainc(x, x), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.multigammaln(self._t(x + 2), 2).value),
            sps.multigammaln(x + 2, 2), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.sinc(self._t(x)).value), np.sinc(x),
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.polygamma(self._t(x), 1).value),
            sps.polygamma(1, x), rtol=1e-4)
        p = np.array([0.2, 0.8], np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.logit(self._t(p)).value),
            sps.logit(p), rtol=1e-5)

    def test_ldexp_renorm(self):
        x = np.array([1.0, 2.0], np.float32)
        e = np.array([2.0, 3.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.ldexp(self._t(x), self._t(e)).value),
            np.ldexp(x, e.astype(np.int32)), rtol=1e-6)
        w = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
        out = np.asarray(paddle.renorm(self._t(w), 2.0, 0, 1.0).value)
        norms = np.linalg.norm(out, axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        np.testing.assert_allclose(out[1], w[1], rtol=1e-5)  # untouched

    def test_reduce_as_unfold_asstrided(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        tgt = np.zeros((1, 4), np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.reduce_as(self._t(x), self._t(tgt)).value),
            x.sum(axis=0, keepdims=True))
        u = np.asarray(paddle.unfold(self._t(x[0]), 0, 2, 1).value)
        np.testing.assert_allclose(u, np.stack([x[0][i:i + 2]
                                                for i in range(3)]))
        s = np.asarray(paddle.as_strided(self._t(x.ravel()), [2, 2],
                                         [4, 1]).value)
        np.testing.assert_allclose(
            s, np.lib.stride_tricks.as_strided(
                x.ravel(), (2, 2), (16, 4)).copy())

    def test_diagonal_scatter(self):
        x = np.zeros((3, 3), np.float32)
        y = np.array([1.0, 2.0, 3.0], np.float32)
        got = np.asarray(paddle.diagonal_scatter(self._t(x),
                                                 self._t(y)).value)
        np.testing.assert_allclose(got, np.diag(y))

    def test_random_families(self):
        paddle.seed(0)
        g = paddle.standard_gamma(self._t(np.full((2000,), 3.0,
                                                  np.float32)))
        assert abs(float(np.asarray(g.value).mean()) - 3.0) < 0.3
        ln = paddle.log_normal(mean=0.0, std=0.25, shape=[2000])
        assert abs(float(np.log(np.asarray(ln.value)).mean())) < 0.1
        t = self._t(np.zeros(2000, np.float32))
        paddle.geometric_(t, 0.5)
        assert abs(float(np.asarray(t.value).mean()) - 2.0) < 0.3
        t2 = self._t(np.zeros(100, np.float32))
        paddle.cauchy_(t2)
        assert np.asarray(t2.value).std() > 0


class TestInplace:
    def test_write_back_semantics(self):
        x = paddle.to_tensor(np.array([1.0, 4.0, 9.0], np.float32))
        out = paddle.sqrt_(x)
        assert out is x
        np.testing.assert_allclose(np.asarray(x.value), [1.0, 2.0, 3.0])

    def test_tensor_method_form(self):
        x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
        x.abs_()
        np.testing.assert_allclose(np.asarray(x.value), [1.0, 2.0])

    def test_binary_inplace(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        y = paddle.to_tensor(np.array([10.0, 20.0], np.float32))
        paddle.add_(x, y)
        np.testing.assert_allclose(np.asarray(x.value), [11.0, 22.0])
        np.testing.assert_allclose(np.asarray(y.value), [10.0, 20.0])

    def test_inplace_on_grad_leaf_rejected(self):
        x = paddle.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        with pytest.raises(RuntimeError, match="in-place"):
            paddle.exp_(x)

    def test_t_and_flatten(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        paddle.t_(x)
        assert tuple(x.shape) == (3, 2)
        paddle.flatten_(x)
        assert tuple(x.shape) == (6,)
