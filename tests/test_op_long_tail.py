"""Long-tail op coverage: the full reference paddle.__all__ surface,
the extras module semantics vs numpy, and in-place write-back variants.

Reference: python/paddle/__init__.py __all__ (418 names);
tensor/manipulation.py, math.py; yaml `inplace:` annotations.
"""
import re

import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle


def test_reference_all_surface_complete():
    src = open("/root/reference/python/paddle/__init__.py").read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    names = re.findall(r"'([^']+)'", m.group(1))
    missing = [n for n in names if not hasattr(paddle, n)]
    assert not missing, f"missing {len(missing)}: {missing[:20]}"


class TestExtras:
    def _t(self, a):
        return paddle.to_tensor(np.asarray(a))

    def test_stacks(self):
        a, b = np.ones((2, 3), np.float32), np.zeros((2, 3), np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.hstack([self._t(a), self._t(b)]).value),
            np.hstack([a, b]))
        np.testing.assert_allclose(
            np.asarray(paddle.vstack([self._t(a), self._t(b)]).value),
            np.vstack([a, b]))
        np.testing.assert_allclose(
            np.asarray(paddle.dstack([self._t(a), self._t(b)]).value),
            np.dstack([a, b]))

    def test_unbind_reverse_addn(self):
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        outs = paddle.unbind(self._t(x), axis=0)
        assert len(outs) == 2
        np.testing.assert_allclose(np.asarray(outs[1].value), x[1])
        np.testing.assert_allclose(
            np.asarray(paddle.reverse(self._t(x), axis=1).value),
            x[:, ::-1])
        np.testing.assert_allclose(
            np.asarray(paddle.add_n([self._t(x), self._t(x)]).value),
            2 * x)

    def test_histogram_bin_edges(self):
        x = np.array([0.0, 1.0, 2.0, 3.0], np.float32)
        got = np.asarray(paddle.histogram_bin_edges(self._t(x),
                                                    bins=4).value)
        np.testing.assert_allclose(got, np.histogram_bin_edges(x, 4),
                                   atol=1e-6)

    def test_special_functions(self):
        x = np.array([0.5, 1.5, 3.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.gammaln(self._t(x)).value),
            sps.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.gammainc(self._t(x), self._t(x)).value),
            sps.gammainc(x, x), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.multigammaln(self._t(x + 2), 2).value),
            sps.multigammaln(x + 2, 2), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.sinc(self._t(x)).value), np.sinc(x),
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.polygamma(self._t(x), 1).value),
            sps.polygamma(1, x), rtol=1e-4)
        p = np.array([0.2, 0.8], np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.logit(self._t(p)).value),
            sps.logit(p), rtol=1e-5)

    def test_ldexp_renorm(self):
        x = np.array([1.0, 2.0], np.float32)
        e = np.array([2.0, 3.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.ldexp(self._t(x), self._t(e)).value),
            np.ldexp(x, e.astype(np.int32)), rtol=1e-6)
        w = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
        out = np.asarray(paddle.renorm(self._t(w), 2.0, 0, 1.0).value)
        norms = np.linalg.norm(out, axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        np.testing.assert_allclose(out[1], w[1], rtol=1e-5)  # untouched

    def test_reduce_as_unfold_asstrided(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        tgt = np.zeros((1, 4), np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.reduce_as(self._t(x), self._t(tgt)).value),
            x.sum(axis=0, keepdims=True))
        u = np.asarray(paddle.unfold(self._t(x[0]), 0, 2, 1).value)
        np.testing.assert_allclose(u, np.stack([x[0][i:i + 2]
                                                for i in range(3)]))
        s = np.asarray(paddle.as_strided(self._t(x.ravel()), [2, 2],
                                         [4, 1]).value)
        np.testing.assert_allclose(
            s, np.lib.stride_tricks.as_strided(
                x.ravel(), (2, 2), (16, 4)).copy())

    def test_diagonal_scatter(self):
        x = np.zeros((3, 3), np.float32)
        y = np.array([1.0, 2.0, 3.0], np.float32)
        got = np.asarray(paddle.diagonal_scatter(self._t(x),
                                                 self._t(y)).value)
        np.testing.assert_allclose(got, np.diag(y))

    def test_random_families(self):
        paddle.seed(0)
        g = paddle.standard_gamma(self._t(np.full((2000,), 3.0,
                                                  np.float32)))
        assert abs(float(np.asarray(g.value).mean()) - 3.0) < 0.3
        ln = paddle.log_normal(mean=0.0, std=0.25, shape=[2000])
        assert abs(float(np.log(np.asarray(ln.value)).mean())) < 0.1
        t = self._t(np.zeros(2000, np.float32))
        paddle.geometric_(t, 0.5)
        assert abs(float(np.asarray(t.value).mean()) - 2.0) < 0.3
        t2 = self._t(np.zeros(100, np.float32))
        paddle.cauchy_(t2)
        assert np.asarray(t2.value).std() > 0


class TestInplace:
    def test_write_back_semantics(self):
        x = paddle.to_tensor(np.array([1.0, 4.0, 9.0], np.float32))
        out = paddle.sqrt_(x)
        assert out is x
        np.testing.assert_allclose(np.asarray(x.value), [1.0, 2.0, 3.0])

    def test_tensor_method_form(self):
        x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
        x.abs_()
        np.testing.assert_allclose(np.asarray(x.value), [1.0, 2.0])

    def test_binary_inplace(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        y = paddle.to_tensor(np.array([10.0, 20.0], np.float32))
        paddle.add_(x, y)
        np.testing.assert_allclose(np.asarray(x.value), [11.0, 22.0])
        np.testing.assert_allclose(np.asarray(y.value), [10.0, 20.0])

    def test_inplace_on_grad_leaf_rejected(self):
        x = paddle.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        with pytest.raises(RuntimeError, match="in-place"):
            paddle.exp_(x)

    def test_t_and_flatten(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        paddle.t_(x)
        assert tuple(x.shape) == (3, 2)
        paddle.flatten_(x)
        assert tuple(x.shape) == (6,)


def test_box_coder_decode_center_size():
    """decode path vs direct formula (encode path is registry-tested)."""
    prior = np.array([[0., 0., 4., 4.], [2., 2., 8., 8.]], np.float32)
    deltas = np.random.RandomState(0).randn(3, 2, 4).astype(np.float32) * 0.3
    out = paddle.box_coder(paddle.to_tensor(prior),
                           paddle.to_tensor(deltas),
                           code_type="decode_center_size",
                           variance=[0.1, 0.1, 0.2, 0.2])
    got = np.asarray(out.value)
    assert got.shape == (3, 2, 4)
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    cx = 0.1 * deltas[..., 0] * pw + pcx
    cy = 0.1 * deltas[..., 1] * ph + pcy
    w = np.exp(0.2 * deltas[..., 2]) * pw
    h = np.exp(0.2 * deltas[..., 3]) * ph
    want = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_yolo_box_iou_aware():
    """iou_aware layout: first an_num channels are IoU predictions."""
    rs = np.random.RandomState(3)
    x = rs.randn(1, 16, 3, 3).astype(np.float32)    # 2 anchors, 2 cls
    img = np.array([[96, 64]], np.float32)
    b, s = paddle.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                           anchors=[10, 13, 16, 30], class_num=2,
                           conf_thresh=0.0, downsample_ratio=32,
                           iou_aware=True, iou_aware_factor=0.4)
    b, s = np.asarray(b.value), np.asarray(s.value)
    assert b.shape == (1, 18, 4) and s.shape == (1, 18, 2)
    # spot-check one cell against the reference formulas
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    an, cls, k, l, j = 2, 2, 1, 2, 1                # anchor 1, cell (1,2)
    e = lambda ent: x[0, an + j * (5 + cls) + ent, k, l]
    conf = sig(e(4)) ** 0.6 * sig(x[0, j, k, l]) ** 0.4
    cx = (l + sig(e(0))) * 64 / 3
    np.testing.assert_allclose(b[0, j * 9 + k * 3 + l, 0],
                               max(cx - np.exp(e(2)) * 16 * 64 /
                                   (32 * 3) / 2, 0), rtol=1e-4)
    np.testing.assert_allclose(s[0, j * 9 + k * 3 + l, 1],
                               conf * sig(e(6)), rtol=1e-4)
