"""Custom-op ABI: C++ XLA-FFI kernels through cpp_extension.load.

Reference test model: test/custom_op/test_custom_relu_op_setup.py —
compile, load, run eager + jit, gradient via custom vjp.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "custom_ops", "custom_ops.cc")


@pytest.fixture(scope="module")
def mod(tmp_path_factory):
    from paddle_tpu.utils.cpp_extension import load
    build = str(tmp_path_factory.mktemp("ext"))
    return load("pd_test_ops", [SRC], build_directory=build, verbose=False)


class TestCustomOps:
    def test_registry_discovered(self, mod):
        assert set(mod.__ops__) == {"custom_relu", "custom_scale"}

    def test_eager(self, mod):
        x = paddle.to_tensor(
            np.array([-1.0, 0.5, 2.0], np.float32))
        out = mod.custom_relu(x)
        np.testing.assert_allclose(np.asarray(out.value), [0.0, 0.5, 2.0])

    def test_attr(self, mod):
        x = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
        out = mod.custom_scale(x, factor=np.float32(3.0))
        np.testing.assert_allclose(np.asarray(out.value), [3.0, -6.0])

    def test_under_jit(self, mod):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(v):
            return jax.ffi.ffi_call(
                "pd_test_ops.custom_relu",
                jax.ShapeDtypeStruct(v.shape, v.dtype))(v) * 2
        out = f(jnp.asarray([-1.0, 4.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [0.0, 8.0])

    def test_custom_vjp(self, mod):
        import jax

        def build(fwd):
            @jax.custom_vjp
            def relu(x):
                return fwd(x)

            def f(x):
                return fwd(x), x

            def b(x, g):
                return (jax.numpy.where(x > 0, g, 0.0),)
            relu.defvjp(f, b)
            return relu

        mod.register_vjp("custom_relu", build)
        x = paddle.to_tensor(np.array([-1.0, 0.5, 2.0], np.float32))
        x.stop_gradient = False
        out = mod.custom_relu(x)
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   [0.0, 1.0, 1.0])

    def test_custom_vjp_with_attr(self, mod):
        import jax

        def build(fwd):
            @jax.custom_vjp
            def scale(x):
                return fwd(x)

            def f(x):
                return fwd(x), x

            def b(x, g):
                return (g,)  # deliberately identity grad to spot the rule
            scale.defvjp(f, b)
            return scale

        mod.register_vjp("custom_scale", build)
        x = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
        x.stop_gradient = False
        out = mod.custom_scale(x, factor=np.float32(3.0))
        np.testing.assert_allclose(np.asarray(out.value), [3.0, -6.0])
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value), [1.0, 1.0])

    def test_cache_reuse(self, mod, tmp_path):
        # same sources -> same artifact path (content-hash cache)
        from paddle_tpu.utils.cpp_extension import load
        m2 = load("pd_test_ops", [SRC],
                  build_directory=os.path.dirname(mod.__library__))
        assert m2.__library__ == mod.__library__


def test_native_flags_registry():
    """csrc/flags_native.cc builds and mirrors python set_flags."""
    import paddle_tpu._native as native
    if native.lib is None:
        pytest.skip("toolchain unavailable")
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        assert native.lib.get("check_nan_inf") == "True"
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
    assert native.lib.get("check_nan_inf") == "False"
    assert native.lib.count() >= 1


def test_native_io_engine_roundtrip(tmp_path):
    """csrc/io_native.cc: parallel pwrite/pread + crc32 round-trips
    byte-exactly, and the checkpoint v2 container uses it."""
    from paddle_tpu import _native
    io = _native.io_lib()
    if io is None:
        import pytest
        pytest.skip("native toolchain unavailable")
    rng = np.random.RandomState(0)
    blob = rng.bytes(6 * 1024 * 1024)
    p = str(tmp_path / "blob.bin")
    io.write(p, b"HDR0", 0, 1)
    io.write(p, blob, 4, 8)
    assert io.read(p, 4, 0) == b"HDR0"
    got = io.read(p, len(blob), 4, 8)
    assert got == blob
    import zlib
    assert io.crc32(blob) == (zlib.crc32(blob) & 0xFFFFFFFF)


def test_checkpoint_v2_container_roundtrip(tmp_path):
    """save_state_dict writes the v2 native container; load reshards it
    back; corruption is detected by crc."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                   load_state_dict)
    rng = np.random.RandomState(1)
    sd = {"w": paddle.to_tensor(rng.randn(64, 32).astype(np.float32)),
          "b": paddle.to_tensor(rng.randn(32).astype(np.float32))}
    path = str(tmp_path / "ckpt")
    save_state_dict(sd, path)
    raw = open(path + "/0.distcp", "rb").read()
    assert raw.startswith(b"PDCP2\x00")
    dst = {"w": paddle.to_tensor(np.zeros((64, 32), np.float32)),
           "b": paddle.to_tensor(np.zeros((32,), np.float32))}
    load_state_dict(dst, path)
    np.testing.assert_array_equal(np.asarray(dst["w"].value),
                                  np.asarray(sd["w"].value))
    # flip a payload byte -> crc failure on load
    import os
    with open(path + "/0.distcp", "r+b") as f:
        f.seek(os.path.getsize(path + "/0.distcp") - 1)
        last = f.read(1)
        f.seek(-1, 2)
        f.write(bytes([last[0] ^ 0xFF]))
    import pytest
    with pytest.raises(Exception, match="crc|corrupt"):
        load_state_dict(dst, path)
