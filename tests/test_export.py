"""jit.save / jit.load executable artifacts + inference Predictor.

Reference test model: test_jit_save_load.py (save->load->run equality)
and the inference C API tests (handle protocol).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _mk(tmp_path):
    paddle.seed(3)
    net = Net()
    path = str(tmp_path / "m" / "net")
    paddle.jit.save(net, path,
                    input_spec=[InputSpec([None, 8], "float32", name="x")])
    return net, path


class TestJitSaveLoad:
    def test_save_load_run_equality(self, tmp_path):
        net, path = _mk(tmp_path)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        ref = np.asarray(net(x).value)
        loaded = paddle.jit.load(path)
        out = np.asarray(loaded(x).value)
        np.testing.assert_allclose(out, ref, atol=1e-6, rtol=1e-6)

    def test_symbolic_batch_dim(self, tmp_path):
        net, path = _mk(tmp_path)
        loaded = paddle.jit.load(path)
        for b in (1, 7):
            x = paddle.to_tensor(np.ones((b, 8), np.float32))
            assert loaded(x).shape == [b, 4]

    def test_artifact_survives_weight_mutation(self, tmp_path):
        # the exported function is a snapshot: mutating the live layer
        # after save must not change the artifact
        net, path = _mk(tmp_path)
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        ref = np.asarray(net(x).value)
        net.fc1.weight.set_value(
            np.zeros_like(np.asarray(net.fc1.weight.value)))
        out = np.asarray(paddle.jit.load(path)(x).value)
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_load_state_dict(self, tmp_path):
        net, path = _mk(tmp_path)
        loaded = paddle.jit.load(path)
        sd = loaded.state_dict()
        np.testing.assert_allclose(
            np.asarray(sd["fc1.weight"].value),
            np.asarray(net.fc1.weight.value))

    def test_save_without_spec_raises(self, tmp_path):
        net = Net()
        with pytest.raises(ValueError):
            paddle.jit.save(net, str(tmp_path / "x"))


class TestInferencePredictor:
    def test_handle_protocol(self, tmp_path):
        net, path = _mk(tmp_path)
        from paddle_tpu.inference import Config, create_predictor
        config = Config(path + ".pdmodel", path + ".pdiparams")
        pred = create_predictor(config)
        names = pred.get_input_names()
        assert names == ["x"]
        h = pred.get_input_handle("x")
        xin = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        h.copy_from_cpu(xin)
        pred.run()
        out = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        ref = np.asarray(net(paddle.to_tensor(xin)).value)
        np.testing.assert_allclose(out, ref, atol=1e-6, rtol=1e-6)

    def test_run_list_style(self, tmp_path):
        net, path = _mk(tmp_path)
        from paddle_tpu.inference import Config, Predictor
        pred = Predictor(Config(path))
        xin = np.ones((2, 8), np.float32)
        outs = pred.run([xin])
        assert outs[0].shape == (2, 4)

    def test_params_only_artifact_rejected(self, tmp_path):
        # a params-only save (framework.io) can't serve
        import pickle
        p = tmp_path / "legacy"
        with open(str(p) + ".pdparams", "wb") as f:
            pickle.dump({"w": np.ones((2, 2), np.float32)}, f)
        from paddle_tpu.inference import Config, create_predictor
        with pytest.raises(ValueError):
            create_predictor(Config(str(p)))


def test_predictor_clone_and_pool(tmp_path):
    """Predictor.clone / PredictorPool share the loaded executable
    (reference AnalysisPredictor::Clone, paddle_infer.PredictorPool)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec
    from paddle_tpu import inference

    paddle.seed(0)
    layer = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "m")
    paddle.jit.save(layer, path,
                    input_spec=[InputSpec([-1, 4], "float32")])
    cfg = inference.Config(path)
    pred = inference.create_predictor(cfg)
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    want = pred.run([x])[0]

    clone = pred.clone()
    assert clone._layer is pred._layer  # shared weights/executable
    np.testing.assert_allclose(clone.run([x])[0], want)

    pool = inference.PredictorPool(cfg, size=3)
    for i in range(3):
        np.testing.assert_allclose(pool.retrieve(i).run([x])[0], want)


def test_predictor_low_precision_io(tmp_path):
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec
    from paddle_tpu import inference

    paddle.seed(1)
    layer = nn.Linear(4, 2)
    layer.to(dtype="bfloat16")
    path = str(tmp_path / "m16")
    paddle.jit.save(layer, path,
                    input_spec=[InputSpec([-1, 4], "bfloat16")])
    cfg = inference.Config(path)
    cfg.enable_low_precision_io()
    assert "low_precision_io=True" in cfg.summary()
    pred = inference.create_predictor(cfg)
    # fp32 input is cast to bf16 at the boundary instead of erroring
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    out = pred.run([x])[0]
    assert out.shape == (2, 2)
