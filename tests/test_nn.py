"""nn.Layer + layers + functional tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def r(*shape):
    return np.random.rand(*shape).astype(np.float32)


class TestLayerBase:
    def test_parameters_and_naming(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(self.fc1(x))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
        assert len(net.parameters()) == 4
        assert len(net.sublayers()) == 2

    def test_state_dict_roundtrip(self):
        net = nn.Linear(3, 5)
        sd = net.state_dict()
        net2 = nn.Linear(3, 5)
        net2.set_state_dict({k: v.numpy() for k, v in sd.items()})
        np.testing.assert_allclose(net2.weight.numpy(), net.weight.numpy())

    def test_train_eval_mode(self):
        d = nn.Dropout(0.5)
        x = paddle.ones([100])
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), x.numpy())
        d.train()
        out = d(x).numpy()
        assert (out == 0).any()  # some dropped

    def test_hooks(self):
        net = nn.Linear(2, 2)
        calls = []
        net.register_forward_pre_hook(lambda l, inp: calls.append("pre"))
        net.register_forward_post_hook(
            lambda l, inp, out: calls.append("post"))
        net(paddle.ones([1, 2]))
        assert calls == ["pre", "post"]

    def test_buffers(self):
        net = nn.BatchNorm1D(4)
        buf_names = [n for n, _ in net.named_buffers()]
        assert "_mean" in buf_names and "_variance" in buf_names
        sd = net.state_dict()
        assert "_mean" in sd

    def test_to_dtype(self):
        net = nn.Linear(2, 2)
        net.to(dtype="bfloat16")
        assert net.weight.dtype == paddle.bfloat16

    def test_containers(self):
        seq = nn.Sequential(nn.Linear(2, 3), nn.ReLU(), nn.Linear(3, 1))
        out = seq(paddle.ones([1, 2]))
        assert out.shape == [1, 1]
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3 and len(ll.parameters()) == 6


class TestFunctional:
    def test_activations(self):
        x = np.array([-2.0, -0.5, 0.0, 1.5], np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(F.relu(t).numpy(), np.maximum(x, 0))
        np.testing.assert_allclose(
            F.sigmoid(t).numpy(), 1 / (1 + np.exp(-x)), rtol=1e-5)
        np.testing.assert_allclose(
            F.softmax(t).numpy(),
            np.exp(x) / np.exp(x).sum(), rtol=1e-5)
        np.testing.assert_allclose(
            F.leaky_relu(t, 0.1).numpy(),
            np.where(x > 0, x, 0.1 * x), rtol=1e-6)

    def test_linear_layout(self):
        # weight [in, out] (reference layout)
        x = r(2, 3)
        w = r(3, 4)
        b = r(4)
        out = F.linear(paddle.to_tensor(x), paddle.to_tensor(w),
                       paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), x @ w + b, rtol=1e-5)

    def test_layer_norm_values(self):
        x = r(2, 5)
        out = F.layer_norm(paddle.to_tensor(x), 5)
        mean = out.numpy().mean(-1)
        std = out.numpy().std(-1)
        np.testing.assert_allclose(mean, 0.0, atol=1e-5)
        np.testing.assert_allclose(std, 1.0, atol=1e-3)

    def test_rms_norm(self):
        x = r(2, 8)
        w = np.ones(8, np.float32)
        out = F.rms_norm(paddle.to_tensor(x), paddle.to_tensor(w))
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4)

    def test_cross_entropy_ignore_index(self):
        logits = r(4, 5)
        labels = np.array([0, 1, -100, 3])
        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels))
        # manual
        lp = logits - logits.max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        vals = [-lp[i, l] for i, l in enumerate(labels) if l != -100]
        np.testing.assert_allclose(float(out), np.mean(vals), rtol=1e-5)

    def test_cross_entropy_soft_label(self):
        logits = r(3, 4)
        soft = np.full((3, 4), 0.25, np.float32)
        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(soft), soft_label=True)
        lp = logits - logits.max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        ref = -(soft * lp).sum(-1).mean()
        np.testing.assert_allclose(float(out), ref, rtol=1e-5)

    def test_mse_l1(self):
        a, b = r(3, 4), r(3, 4)
        np.testing.assert_allclose(
            float(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b))),
            ((a - b) ** 2).mean(), rtol=1e-5)
        np.testing.assert_allclose(
            float(F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b))),
            np.abs(a - b).mean(), rtol=1e-5)

    def test_bce_with_logits(self):
        x, t = r(6) * 4 - 2, (r(6) > 0.5).astype(np.float32)
        out = F.binary_cross_entropy_with_logits(
            paddle.to_tensor(x), paddle.to_tensor(t))
        p = 1 / (1 + np.exp(-x))
        ref = -(t * np.log(p) + (1 - t) * np.log(1 - p)).mean()
        np.testing.assert_allclose(float(out), ref, rtol=1e-4)

    def test_embedding(self):
        w = r(10, 4)
        idx = np.array([[1, 3], [5, 9]])
        out = F.embedding(paddle.to_tensor(idx), paddle.to_tensor(w))
        np.testing.assert_allclose(out.numpy(), w[idx])

    def test_embedding_grad_scatter(self):
        w = paddle.to_tensor(r(5, 3))
        w.stop_gradient = False
        idx = paddle.to_tensor(np.array([1, 1, 2]))
        out = F.embedding(idx, w)
        paddle.sum(out).backward()
        g = w.grad.numpy()
        assert g[1].sum() == pytest.approx(6.0)  # row 1 used twice
        assert g[0].sum() == 0

    def test_dropout_scaling(self):
        x = paddle.ones([10000])
        out = F.dropout(x, 0.3, training=True)
        # upscale_in_train: E[out] == 1
        assert abs(out.numpy().mean() - 1.0) < 0.05

    def test_interpolate_nearest(self):
        x = r(1, 1, 2, 2)
        out = F.interpolate(paddle.to_tensor(x), size=[4, 4],
                            mode="nearest")
        assert out.shape == [1, 1, 4, 4]
        np.testing.assert_allclose(out.numpy()[0, 0, :2, :2].mean(),
                                   x[0, 0, 0, 0], rtol=1e-5)


class TestConvPool:
    def test_conv2d_identity(self):
        x = r(1, 1, 5, 5)
        w = np.zeros((1, 1, 3, 3), np.float32)
        w[0, 0, 1, 1] = 1.0  # identity kernel
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=1)
        np.testing.assert_allclose(out.numpy(), x, rtol=1e-5)

    def test_conv2d_vs_numpy(self):
        x = r(2, 3, 8, 8)
        w = r(4, 3, 3, 3)
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w))
        assert out.shape == [2, 4, 6, 6]
        # check one output position against manual correlation
        ref = (x[0, :, 0:3, 0:3] * w[1]).sum()
        np.testing.assert_allclose(out.numpy()[0, 1, 0, 0], ref, rtol=1e-4)

    def test_conv_groups(self):
        x = r(1, 4, 6, 6)
        w = r(4, 2, 3, 3)
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), groups=2)
        assert out.shape == [1, 4, 4, 4]

    def test_conv_transpose_shape(self):
        x = r(1, 3, 4, 4)
        w = r(3, 5, 3, 3)  # [in, out, kh, kw]
        out = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                                 stride=2)
        assert out.shape == [1, 5, 9, 9]

    def test_pools(self):
        x = r(1, 2, 4, 4)
        mp = F.max_pool2d(paddle.to_tensor(x), 2)
        ref = x.reshape(1, 2, 2, 2, 2, 2).max((3, 5))
        np.testing.assert_allclose(mp.numpy(), ref, rtol=1e-6)
        ap = F.avg_pool2d(paddle.to_tensor(x), 2)
        refa = x.reshape(1, 2, 2, 2, 2, 2).mean((3, 5))
        np.testing.assert_allclose(ap.numpy(), refa, rtol=1e-6)

    def test_adaptive_pool(self):
        x = r(1, 3, 8, 8)
        out = F.adaptive_avg_pool2d(paddle.to_tensor(x), 1)
        np.testing.assert_allclose(out.numpy()[..., 0, 0],
                                   x.mean((2, 3)), rtol=1e-5)


class TestNorms:
    def test_batch_norm_train_stats(self):
        bn = nn.BatchNorm1D(4)
        x = paddle.to_tensor(r(16, 4) * 3 + 1)
        bn.train()
        out = bn(x)
        np.testing.assert_allclose(out.numpy().mean(0), 0, atol=1e-4)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), 0)

    def test_batch_norm_eval_uses_running(self):
        bn = nn.BatchNorm1D(2)
        bn.eval()
        x = paddle.to_tensor(r(4, 2))
        out = bn(x)  # running mean 0, var 1 → identity-ish
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-4)

    def test_group_norm(self):
        gn = nn.GroupNorm(2, 4)
        x = paddle.to_tensor(r(2, 4, 3, 3))
        out = gn(x)
        v = out.numpy().reshape(2, 2, -1)
        np.testing.assert_allclose(v.mean(-1), 0, atol=1e-4)

    def test_layer_norm_layer(self):
        ln = nn.LayerNorm(6)
        out = ln(paddle.to_tensor(r(2, 6)))
        np.testing.assert_allclose(out.numpy().mean(-1), 0, atol=1e-5)


class TestTransformer:
    def test_mha_shapes(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.to_tensor(r(2, 5, 16))
        out = mha(x, x, x)
        assert out.shape == [2, 5, 16]

    def test_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.to_tensor(r(2, 5, 16))
        out = enc(x)
        assert out.shape == [2, 5, 16]

    def test_sdpa_matches_naive(self):
        q = r(1, 4, 2, 8)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q))
        # naive
        qq = q.transpose(0, 2, 1, 3)  # b h s d
        logits = qq @ qq.transpose(0, 1, 3, 2) / np.sqrt(8)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        ref = (w @ qq).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)

    def test_causal_mask(self):
        q = r(1, 4, 1, 4)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True)
        # first position can only attend to itself → output == value[0]
        np.testing.assert_allclose(out.numpy()[0, 0], q[0, 0], atol=1e-5)


class TestRNN:
    def test_lstm_shapes(self):
        lstm = nn.LSTM(8, 16, num_layers=2)
        x = paddle.to_tensor(r(4, 6, 8))
        out, (h, c) = lstm(x)
        assert out.shape == [4, 6, 16]
        assert h.shape == [2, 4, 16]

    def test_gru_cell(self):
        cell = nn.GRUCell(4, 8)
        out, h = cell(paddle.to_tensor(r(2, 4)))
        assert out.shape == [2, 8]


class TestClip:
    def test_global_norm_clip(self):
        from paddle_tpu.nn import ClipGradByGlobalNorm
        p1 = paddle.framework.Parameter(np.zeros(3, np.float32))
        g1 = paddle.to_tensor(np.array([3.0, 0.0, 0.0], np.float32))
        p2 = paddle.framework.Parameter(np.zeros(1, np.float32))
        g2 = paddle.to_tensor(np.array([4.0], np.float32))
        clip = ClipGradByGlobalNorm(1.0)
        out = clip([(p1, g1), (p2, g2)])
        total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)
