"""Compute cost ledger + roofline drift tests (ISSUE 12).

Covers: per-program FLOP/byte resolution for every trainer and both
serve programs riding the memory ledger's providers (zero extra
compiles, probe contract pinned), measured-wall feeds from the live
train.step/serve.chunk events, the FLAGS_mfu_floor drift check
(perf.drift events + analysis.lint_mfu_floor), the named_scope
per-layer attribution census, the shared FLOP-accounting derivations
(paddle.flops / tools.profile_mfu regression pins), and the
memory_report share=None graceful degrade (satellite bugfix).
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import telemetry
from paddle_tpu.telemetry import costledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_plane():
    telemetry.reset()
    yield
    telemetry.reset()


def _mlp_step():
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
        paddle.nn.Linear(16, 8))
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    step = TrainStep(
        model, lambda o, y: paddle.nn.functional.mse_loss(o, y), opt)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    return step, x


def _tiny_llama(n_layers=1):
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    paddle.seed(3)
    cfg = llama_tiny_config(num_hidden_layers=n_layers, hidden_size=32,
                            intermediate_size=64,
                            num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=64)
    return LlamaForCausalLM(cfg)


# ---------------------------------------------------------------------------
# shared derivations (satellite 1: one FLOP accounting, pinned)

class TestSharedDerivations:
    def test_model_train_flops_pins_profile_mfu_accounting(self):
        """The analytic accounting tools/profile_mfu.py always used —
        2N/4N/6N per token, remat added to the backward — must come
        back out of the shared helper unchanged."""
        n, tok, remat = 1.5e9, 8192.0, 3.0e6
        f = costledger.model_train_flops
        assert f(n, tok, "fwd") == 2.0 * n * tok
        assert f(n, tok, "bwd") == 4.0 * n * tok
        assert f(n, tok, "full") == 6.0 * n * tok
        assert f(n, tok, "bwd", remat_flops_per_token=remat) \
            == (4.0 * n + remat) * tok
        # remat replays buy nothing in the forward
        assert f(n, tok, "fwd", remat_flops_per_token=remat) \
            == 2.0 * n * tok
        with pytest.raises(KeyError):
            f(n, tok, "warp")

    def test_cost_of_matches_raw_cost_analysis(self):
        import jax
        import jax.numpy as jnp
        compiled = jax.jit(lambda a, b: a @ b).lower(
            jnp.ones((16, 16)), jnp.ones((16, 16))).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        got = costledger.cost_of(compiled)
        assert got["flops"] == float(ca.get("flops", 0.0)) > 0
        assert got["bytes_accessed"] \
            == float(ca.get("bytes accessed", 0.0)) > 0

    def test_paddle_flops_unchanged_by_unification(self):
        """paddle.flops() now reads through costledger.cost_of — the
        value must equal the old ad-hoc extraction (regression pin)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.jit import _swapped_state
        from paddle_tpu.framework.tensor import Tensor
        paddle.seed(0)
        net = paddle.nn.Linear(8, 16)
        total = paddle.flops(net, [4, 8])
        # the old derivation, inline
        sd = net.state_dict()
        names = list(sd)
        vals = [sd[n].value for n in names]

        def fwd(params, x):
            with _swapped_state(net, names, list(params)):
                out = net(Tensor(x))
            return out.value if isinstance(out, Tensor) else out

        compiled = jax.jit(fwd).lower(
            vals, jnp.zeros((4, 8), jnp.float32)).compile()
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        assert total == float((cost or {}).get("flops", 0.0)) > 0


# ---------------------------------------------------------------------------
# ledger resolution: every trainer + both serve programs

class TestLedgerResolution:
    def test_trainstep_cost_resolved_with_roofline_fields(self):
        step, x = _mlp_step()
        step(x, x)
        rep = telemetry.cost_report()
        rec = rep["programs"]["jit.TrainStep.step"]
        assert rec["status"] == "ok"
        assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
        assert rec["intensity"] == pytest.approx(
            rec["flops"] / rec["bytes_accessed"], rel=1e-2)
        assert rec["bound"] in ("compute", "memory")
        assert rec["predicted_ms"] == max(
            rec["predicted_compute_ms"], rec["predicted_memory_ms"]) > 0
        peaks = rep["peaks"]
        assert peaks["flops_per_sec"] > 0 \
            and peaks["hbm_bytes_per_sec"] > 0
        assert peaks["ridge_intensity"] == pytest.approx(
            peaks["flops_per_sec"] / peaks["hbm_bytes_per_sec"])

    def test_sharded_trainer_cost_resolved(self):
        import jax
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh
        paddle.seed(0)
        m = paddle.nn.Sequential(paddle.nn.Linear(8, 8))
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = ShardedTrainStep(
            m, opt, build_mesh(devices=jax.devices()[:1]),
            loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        step(x, x)
        rep = telemetry.cost_report()
        rec = rep["programs"][f"ShardedTrainStep.step.s{step.stage}"]
        assert rec["status"] == "ok" and rec["flops"] > 0

    def test_serve_programs_cost_resolved_probe_contract(self):
        """Both serve-step programs resolve through the side-effect-
        free lower_step probe: cost_report() must not inflate
        compiled_programs or defeat first-use timing (the memledger
        probe contract, pinned for the cost twin)."""
        from paddle_tpu.inference import ContinuousBatcher
        model = _tiny_llama()
        bat = ContinuousBatcher(model, max_batch_size=1, max_len=32,
                                chunk=4, prefill_chunk=4)
        rep = telemetry.cost_report()
        for label in ("serve_step.decode", "serve_step.admit"):
            rec = rep["programs"][label]
            assert rec["status"] == "ok", rec
            assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
        assert bat.compiled_programs == 0
        rng = np.random.RandomState(0)
        bat.submit(rng.randint(1, 64, 4).astype(np.int32), 4)
        bat.run()
        assert bat.stats()["compiled_programs"] <= 2

    def test_one_resolution_fills_both_ledgers_no_extra_compiles(self):
        """ONE provider resolution serves memory AND cost: after
        memory_report() the cost entries are already ok, and a
        subsequent cost_report(resolve=True) compiles nothing."""
        from paddle_tpu.analysis import recompile_guard
        step, x = _mlp_step()
        step(x, x)
        telemetry.memory_report(top_buffers=0)
        snap = costledger.snapshot()
        assert snap["programs"]["jit.TrainStep.step"]["status"] == "ok"
        with recompile_guard(0, label="cost resolve"):
            rep = telemetry.cost_report()
        assert rep["programs"]["jit.TrainStep.step"]["status"] == "ok"

    def test_cost_report_alone_resolves_memory_too(self):
        step, x = _mlp_step()
        step(x, x)
        assert telemetry.memledger.snapshot()["programs"][
            "jit.TrainStep.step"]["status"] == "pending"
        telemetry.cost_report()
        assert telemetry.memledger.snapshot()["programs"][
            "jit.TrainStep.step"]["status"] == "ok"

    def test_cost_program_events_published_on_resolve(self):
        step, x = _mlp_step()
        step(x, x)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            telemetry.cost_report()
        finally:
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records if r["event"] == "cost.program"]
        assert evs and evs[0]["label"] == "jit.TrainStep.step"
        assert evs[0]["flops"] > 0

    def test_dump_embeds_cost_snapshot_without_resolving(self):
        step, x = _mlp_step()
        step(x, x)
        d = telemetry.dump()
        assert "cost" not in d        # nothing ingested yet: dump
        #                               never compiles
        telemetry.cost_report()
        d = telemetry.dump(compact=True)
        assert d["cost"]["programs"] >= 1
        assert d["cost"]["drifts"] == 0


# ---------------------------------------------------------------------------
# measured walls + drift

class TestMeasuredAndDrift:
    def test_step_events_feed_measured_walls_warm_only(self):
        step, x = _mlp_step()
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            for _ in range(3):
                step(x, x)
        finally:
            telemetry.remove_sink(sink)
        # 3 steps, first cold (may include the compile) -> 2 samples
        assert costledger._measured_total["jit.TrainStep.step"] == 2
        assert costledger.measured_ms("jit.TrainStep.step") > 0
        rep = telemetry.cost_report()
        rec = rep["programs"]["jit.TrainStep.step"]
        assert rec["measured_ms"] > 0 and rec["measured_n"] == 2
        assert rec["attained"] == pytest.approx(
            rec["predicted_ms"] / rec["measured_ms"], abs=1e-3)
        assert rec["achieved_flops_per_sec"] > 0

    def test_no_sink_no_measured_walls(self):
        step, x = _mlp_step()
        for _ in range(2):
            step(x, x)
        assert costledger.measured_ms("jit.TrainStep.step") is None
        rec = telemetry.cost_report()["programs"][
            "jit.TrainStep.step"]
        assert "measured_ms" not in rec and "attained" not in rec

    def test_serve_chunks_feed_measured_walls(self):
        from paddle_tpu.inference import ContinuousBatcher
        model = _tiny_llama()
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            bat = ContinuousBatcher(model, max_batch_size=1,
                                    max_len=32, chunk=4,
                                    prefill_chunk=4)
            rng = np.random.RandomState(0)
            bat.submit(rng.randint(1, 64, 4).astype(np.int32), 10)
            bat.run()
        finally:
            telemetry.remove_sink(sink)
        # >=3 decode chunks ran (10 tokens / chunk=4); the first is
        # first_use (compile wall) and excluded
        assert costledger.measured_ms("serve_step.decode") > 0

    def test_drift_event_and_counter_below_floor(self):
        from paddle_tpu.framework.flags import set_flags
        step, x = _mlp_step()
        step(x, x)
        telemetry.cost_report()               # resolve (no drift yet)
        costledger.observe("jit.TrainStep.step", 1e9)  # planted crawl
        before = telemetry.counter("perf.drift").value
        sink = telemetry.add_sink(telemetry.MemorySink())
        set_flags({"FLAGS_mfu_floor": 0.99})
        try:
            rep = telemetry.cost_report()
        finally:
            set_flags({"FLAGS_mfu_floor": 0.0})
            telemetry.remove_sink(sink)
        rec = rep["programs"]["jit.TrainStep.step"]
        assert rec["drift"] is True and rec["attained"] < 0.99
        assert rep["mfu_floor"] == 0.99
        evs = [r for r in sink.records if r["event"] == "perf.drift"]
        assert len(evs) == 1
        assert evs[0]["label"] == "jit.TrainStep.step"
        assert evs[0]["floor"] == 0.99
        assert evs[0]["measured_ms"] == rec["measured_ms"]
        assert telemetry.counter("perf.drift").value == before + 1

    def test_drift_edge_triggered_not_per_poll(self):
        """A monitoring loop polling cost_report() while one program
        sits below the floor counts ONE detection, not one per poll;
        recovery re-arms the edge."""
        from paddle_tpu.framework.flags import set_flags
        step, x = _mlp_step()
        step(x, x)
        telemetry.cost_report()
        before = telemetry.counter("perf.drift").value
        sink = telemetry.add_sink(telemetry.MemorySink())
        set_flags({"FLAGS_mfu_floor": 0.99})
        try:
            slow = {"jit.TrainStep.step": 1e9}
            for _ in range(3):                 # sustained drift: 1 event
                telemetry.cost_report(measured=slow)
            assert telemetry.counter("perf.drift").value == before + 1
            # recovery (attained >= floor) re-arms the edge
            telemetry.cost_report(
                measured={"jit.TrainStep.step": 1e-9})
            telemetry.cost_report(measured=slow)   # relapse: fires again
            assert telemetry.counter("perf.drift").value == before + 2
        finally:
            set_flags({"FLAGS_mfu_floor": 0.0})
            telemetry.remove_sink(sink)
        evs = [r for r in sink.records if r["event"] == "perf.drift"]
        assert len(evs) == 2

    def test_no_floor_no_drift(self):
        step, x = _mlp_step()
        step(x, x)
        costledger.observe("jit.TrainStep.step", 1e9)
        rep = telemetry.cost_report()
        rec = rep["programs"]["jit.TrainStep.step"]
        assert "drift" not in rec and rep["mfu_floor"] is None

    def test_explicit_measured_overrides_window(self):
        step, x = _mlp_step()
        step(x, x)
        rep = telemetry.cost_report(
            measured={"jit.TrainStep.step": 123.0})
        assert rep["programs"]["jit.TrainStep.step"][
            "measured_ms"] == 123.0

    def test_lint_mfu_floor_planted_and_clean(self):
        from paddle_tpu.analysis import lint_mfu_floor
        step, x = _mlp_step()
        step(x, x)
        costledger.observe("jit.TrainStep.step", 1e9)
        findings = lint_mfu_floor(floor=0.99)
        assert findings
        assert all(f.code == "mfu-floor" for f in findings)
        assert any("jit.TrainStep.step" in f.message for f in findings)
        # floor=0 (the default flag value) disables the lint entirely
        assert lint_mfu_floor() == []
        # a generous floor on a fast program stays clean
        assert lint_mfu_floor(
            report=telemetry.cost_report(
                measured={"jit.TrainStep.step": 1e-9}),
            floor=0.5) == []

    def test_cold_observations_excluded(self):
        costledger.observe("x", 5.0, cold=True)
        assert costledger.measured_ms("x") is None
        costledger.observe("x", 5.0)
        assert costledger.measured_ms("x") == 5.0

    def test_label_reuse_drops_stale_walls(self):
        """Ledger labels are class-constant: a SECOND trainer of the
        same class re-registers the label, and the first trainer's
        walls (a different program!) must not corrupt the new
        program's measured_ms/attained."""
        step, x = _mlp_step()
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            for _ in range(3):
                step(x, x)
            assert costledger.measured_ms("jit.TrainStep.step") > 0
            step2, x2 = _mlp_step()         # new program, same label
            step2(x2, x2)                   # re-registers on 1st call
            # old walls gone; the new program's first (cold) call
            # contributes nothing
            assert costledger.measured_ms(
                "jit.TrainStep.step") is None
            step2(x2, x2)
            assert costledger._measured_total[
                "jit.TrainStep.step"] == 1
        finally:
            telemetry.remove_sink(sink)

    def test_retrace_resets_walls_and_reregisters(self):
        """run_steps at a NEW K retraces the multi program mid-life:
        the ledger must re-register (entry describes the current
        program) and the old K's walls must not mix in — and the
        retrace call's own wall (it pays the compile) counts as
        cold."""
        from paddle_tpu.jit import TrainStep
        paddle.seed(0)
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 8))
        opt = paddle.optimizer.AdamW(1e-3,
                                     parameters=model.parameters())
        step = TrainStep(
            model, lambda o, y: paddle.nn.functional.mse_loss(o, y),
            opt)
        label = "jit.TrainStep.multi"

        def stack(k):
            arr = np.ones((k, 4, 8), np.float32)
            return paddle.to_tensor(arr), paddle.to_tensor(arr)
        sink = telemetry.add_sink(telemetry.MemorySink())
        try:
            x2, y2 = stack(2)
            step.run_steps(x2, y2)             # cold (first use)
            step.run_steps(x2, y2)             # warm wall
            assert costledger._measured_total[label] == 1
            x8, y8 = stack(8)
            step.run_steps(x8, y8)             # retrace: resets, cold
            assert costledger.measured_ms(label) is None
            assert telemetry.memledger.snapshot()["programs"][
                label]["status"] == "pending"  # re-registered
            step.run_steps(x8, y8)             # the k=8 warm wall
            assert costledger._measured_total[label] == 1
            # flip BACK to k=2: alternation must also reset
            step.run_steps(x2, y2)
            assert costledger.measured_ms(label) is None
        finally:
            telemetry.remove_sink(sink)

    def test_attained_uses_unrounded_prediction(self):
        """A program whose predicted_ms displays as 0.0000 (sub-50ns)
        must not read attained == 0.0 — that would drift
        unconditionally under any floor."""
        class Fake:
            def cost_analysis(self):
                # 40k flops at 1e12 flop/s (eff 1.0) -> 4e-5 ms
                return [{"flops": 40000.0, "bytes accessed": 1.0}]

            def as_text(self):
                return ""
        costledger.ingest("tiny", Fake())
        costledger.configure_peaks(flops_per_sec=1e12,
                                   hbm_bytes_per_sec=1e12,
                                   efficiency=1.0)
        rec = telemetry.cost_report(
            resolve=False, measured={"tiny": 8e-5})["programs"]["tiny"]
        assert rec["predicted_ms"] == 0.0       # display rounds away
        assert rec["attained"] == pytest.approx(0.5, abs=1e-3)


# ---------------------------------------------------------------------------
# roofline verdicts under controlled peaks

class TestRooflineVerdict:
    def _ingest_matmul(self):
        import jax
        import jax.numpy as jnp
        compiled = jax.jit(lambda a, b: a @ b).lower(
            jnp.ones((32, 32)), jnp.ones((32, 32))).compile()
        return costledger.ingest("probe", compiled)

    def test_bound_flips_with_peak_ratio(self):
        entry = self._ingest_matmul()
        intensity = entry["flops"] / entry["bytes_accessed"]
        # ridge far above the program's intensity -> memory-bound
        costledger.configure_peaks(flops_per_sec=1e15,
                                   hbm_bytes_per_sec=1e9,
                                   efficiency=1.0)
        rec = telemetry.cost_report(resolve=False)["programs"]["probe"]
        assert rec["bound"] == "memory"
        assert rec["predicted_ms"] == rec["predicted_memory_ms"]
        # ridge far below -> compute-bound
        costledger.configure_peaks(flops_per_sec=1e9,
                                   hbm_bytes_per_sec=1e15)
        rec = telemetry.cost_report(resolve=False)["programs"]["probe"]
        assert rec["bound"] == "compute"
        assert rec["predicted_ms"] == rec["predicted_compute_ms"]
        assert intensity == pytest.approx(rec["intensity"], rel=1e-2)

    def test_efficiency_scales_prediction(self):
        self._ingest_matmul()
        # peaks low enough that predicted_ms survives 4-decimal
        # rounding on a 32x32 matmul
        costledger.configure_peaks(flops_per_sec=1e9,
                                   hbm_bytes_per_sec=1e9,
                                   efficiency=1.0)
        t1 = telemetry.cost_report(resolve=False)["programs"][
            "probe"]["predicted_ms"]
        costledger.configure_peaks(efficiency=0.5)
        t2 = telemetry.cost_report(resolve=False)["programs"][
            "probe"]["predicted_ms"]
        assert t2 == pytest.approx(2 * t1, rel=1e-3)

    def test_reset_clears_overrides(self):
        costledger.configure_peaks(flops_per_sec=123.0)
        costledger.reset()
        assert costledger.backend_peaks()["flops_per_sec"] != 123.0

    def test_bench_peak_delegates_to_ledger_table(self, monkeypatch):
        """ONE peak table for the whole repo: bench.chip_peak_flops
        and the ledger sniffing must agree, including the
        PALLAS_AXON_TPU_GEN relay hint and the PEAK_FLOPS override."""
        sys.path.insert(0, REPO)
        try:
            import bench
        finally:
            sys.path.pop(0)
        monkeypatch.delenv("PEAK_FLOPS", raising=False)
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        assert bench.chip_peak_flops() \
            == costledger.chip_peak_flops(default="v5e")
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p-8")
        assert bench.chip_peak_flops() \
            == costledger.PEAK_FLOPS["v5p"] \
            == costledger.chip_peak_flops()
        assert costledger.backend_peaks()["chip"] == "v5p"
        monkeypatch.setenv("PEAK_FLOPS", "123.0")
        assert bench.chip_peak_flops() == 123.0


# ---------------------------------------------------------------------------
# named_scope per-layer attribution

class TestNamedScopeAttribution:
    def test_llama_train_program_carries_layer_scopes(self):
        from paddle_tpu.jit import TrainStep
        model = _tiny_llama(n_layers=2)
        opt = paddle.optimizer.AdamW(1e-3,
                                     parameters=model.parameters())
        step = TrainStep(model,
                         lambda o, y: model.compute_loss(o, y), opt)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 64, (2, 8)).astype(np.int32))
        step(ids, ids)
        rec = telemetry.cost_report()["programs"][
            "jit.TrainStep.step"]
        scopes = rec.get("scopes", {})
        for name in ("llama.embed", "llama.layer0", "llama.layer1",
                     "llama.norm"):
            assert scopes.get(name, 0) > 0, (name, scopes)

    def test_serve_decode_program_carries_layer_scopes(self):
        from paddle_tpu.inference import ContinuousBatcher
        model = _tiny_llama()
        # keep the batcher alive: the ledger's serve providers are
        # weakrefs
        bat = ContinuousBatcher(model, max_batch_size=1, max_len=32,
                                chunk=4, prefill_chunk=4)
        rec = telemetry.cost_report()["programs"]["serve_step.decode"]
        assert bat.compiled_programs == 0
        assert rec.get("scopes", {}).get("llama.layer0", 0) > 0

    def test_census_ignores_source_file_paths(self):
        # ".../models/llama.py" appears in op metadata source
        # locations; the census must only count the scope vocabulary
        text = ('op_name="jit(f)/llama.layer0/dot" '
                'source_file="/repo/paddle_tpu/models/llama.py"')

        class Fake:
            def as_text(self):
                return text
        assert costledger.scope_census(Fake()) == {"llama.layer0": 1}


# ---------------------------------------------------------------------------
# the report CLI's cost/roofline section (satellite 4)

class TestReportCostSection:
    def _analyze(self, events):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import telemetry_report as cli
        finally:
            sys.path.pop(0)
        return cli.analyze(events)

    def test_latest_measure_state_wins(self):
        """perf.drift is the edge alarm; cost.measure carries the
        drift STATE — a recovery after a drift episode must clear the
        rendered flag, a persisting drift must keep it."""
        mk = lambda ev, **kw: dict(event=ev, label="p", **kw)
        events = [
            mk("cost.program", flops=10.0, bytes_accessed=20.0),
            mk("cost.measure", predicted_ms=1.0, measured_ms=10.0,
               attained=0.1, bound="compute", drift=True),
            mk("perf.drift", predicted_ms=1.0, measured_ms=10.0,
               attained=0.1, floor=0.5),
            mk("cost.measure", predicted_ms=1.0, measured_ms=1.1,
               attained=0.9, bound="compute", drift=False),
        ]
        rep = self._analyze(events)
        p = rep["cost"]["programs"]["p"]
        assert p["flops"] == 10.0 and p["attained"] == 0.9
        assert "drift" not in p            # recovered: latest wins
        assert rep["cost"]["drifts"] == 1  # the episode still counted
        # persisting drift: the latest measure keeps the flag
        rep = self._analyze(events + [
            mk("cost.measure", predicted_ms=1.0, measured_ms=10.0,
               attained=0.1, bound="compute", drift=True)])
        assert rep["cost"]["programs"]["p"]["drift"] is True


# ---------------------------------------------------------------------------
# satellite bugfix: memory_report share degrades gracefully

class TestMemoryShareGraceful:
    def test_share_none_when_backend_lacks_memory_stats(self,
                                                        monkeypatch):
        import jax
        step, x = _mlp_step()
        step(x, x)
        telemetry.memory_report(top_buffers=0)   # resolve on real jax

        class _NoStatsDev:
            def memory_stats(self):
                raise NotImplementedError("no memory_stats here")

        monkeypatch.setattr(jax, "devices",
                            lambda *a, **kw: [_NoStatsDev()])
        rep = telemetry.memory_report(top_buffers=0)
        rec = rep["programs"]["jit.TrainStep.step"]
        assert rec["status"] == "ok" and rec["peak_share"] is None
        assert rep["device_hbm_bytes"] is None
        assert rep["peak_hbm_share"] is None
        assert rep["peak_hbm_bytes"] > 0

    def test_share_none_when_memory_stats_empty(self, monkeypatch):
        import jax
        step, x = _mlp_step()
        step(x, x)
        telemetry.memory_report(top_buffers=0)

        class _EmptyStatsDev:
            def memory_stats(self):
                return {}            # CPU backends often report {}
        monkeypatch.setattr(jax, "devices",
                            lambda *a, **kw: [_EmptyStatsDev()])
        rep = telemetry.memory_report(top_buffers=0)
        assert rep["programs"]["jit.TrainStep.step"][
            "peak_share"] is None
        assert rep["peak_hbm_share"] is None

    def test_share_present_with_bytes_limit(self, monkeypatch):
        import jax
        step, x = _mlp_step()
        step(x, x)
        telemetry.memory_report(top_buffers=0)

        class _Dev:
            def memory_stats(self):
                return {"bytes_limit": 10 ** 12}
        monkeypatch.setattr(jax, "devices", lambda *a, **kw: [_Dev()])
        rep = telemetry.memory_report(top_buffers=0)
        rec = rep["programs"]["jit.TrainStep.step"]
        assert rec["peak_share"] == pytest.approx(
            rec["peak_bytes"] / 10 ** 12, abs=1e-4)
        assert rep["peak_hbm_share"] == pytest.approx(
            rep["peak_hbm_bytes"] / 10 ** 12, abs=1e-4)
