"""Autograd tape tests (reference: test/legacy_test grad checks +
eager autograd behavior)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_scalar_backward():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x + 2.0 * x
    y.backward()
    assert np.isclose(float(x.grad), 2 * 3.0 + 2.0)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    for _ in range(3):
        y = paddle.sum(x * x)
        y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 3 * 2 * x.numpy())


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0])  # stop_gradient=True
    z = paddle.sum(x * y)
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), y.numpy())
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).detach()
    z = y * x
    paddle.sum(z).backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])  # only through z


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._ref.node is None


def test_paddle_grad_api():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = paddle.sum(paddle.exp(x))
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), np.exp(x.numpy()), rtol=1e-5)
    assert x.grad is None  # paddle.grad must not touch .grad


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.random.rand(4, 6).astype(np.float32),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=1)
    loss = paddle.sum(a) + 2.0 * paddle.sum(b)
    loss.backward()
    g = x.grad.numpy()
    np.testing.assert_allclose(g[:, :3], 1.0)
    np.testing.assert_allclose(g[:, 3:], 2.0)


def test_inplace_versioning():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2.0
    y.add_(paddle.to_tensor([1.0, 1.0]))
    loss = paddle.sum(y * y)
    loss.backward()
    # y = 2x+1, loss = sum((2x+1)^2), dloss/dx = 2*(2x+1)*2
    expect = 4 * (2 * x.numpy() + 1)
    np.testing.assert_allclose(x.grad.numpy(), expect)


def test_backward_non_scalar_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 1.5])


def test_backward_non_scalar_raises():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    with pytest.raises(RuntimeError):
        y.backward()


def test_register_hook():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    y = x * 2.0
    y.register_hook(lambda g: g * 10.0)
    paddle.sum(y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])


def test_retain_grads():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2.0
    y.retain_grads()
    z = y * 3.0
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Square(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor
            return gy * 2.0 * x

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Square.apply(x)
    paddle.sum(y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_grad_through_integer_blocked():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    idx = paddle.argmax(x)  # int output → no grad path
    assert idx.stop_gradient


def test_diamond_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    a = x * 3.0
    b = x * 4.0
    y = a * b  # y = 12 x^2, dy/dx = 24x
    y.backward()
    assert np.isclose(float(x.grad), 24 * 2.0)


def test_clear_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    paddle.sum(x * x).backward()
    assert x.grad is not None
    x.clear_grad()
    assert x.grad is None
