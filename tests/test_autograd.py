"""Autograd tape tests (reference: test/legacy_test grad checks +
eager autograd behavior)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_scalar_backward():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x + 2.0 * x
    y.backward()
    assert np.isclose(float(x.grad), 2 * 3.0 + 2.0)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    for _ in range(3):
        y = paddle.sum(x * x)
        y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 3 * 2 * x.numpy())


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0])  # stop_gradient=True
    z = paddle.sum(x * y)
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), y.numpy())
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).detach()
    z = y * x
    paddle.sum(z).backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])  # only through z


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._ref.node is None


def test_paddle_grad_api():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = paddle.sum(paddle.exp(x))
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), np.exp(x.numpy()), rtol=1e-5)
    assert x.grad is None  # paddle.grad must not touch .grad


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.random.rand(4, 6).astype(np.float32),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=1)
    loss = paddle.sum(a) + 2.0 * paddle.sum(b)
    loss.backward()
    g = x.grad.numpy()
    np.testing.assert_allclose(g[:, :3], 1.0)
    np.testing.assert_allclose(g[:, 3:], 2.0)


def test_inplace_versioning():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2.0
    y.add_(paddle.to_tensor([1.0, 1.0]))
    loss = paddle.sum(y * y)
    loss.backward()
    # y = 2x+1, loss = sum((2x+1)^2), dloss/dx = 2*(2x+1)*2
    expect = 4 * (2 * x.numpy() + 1)
    np.testing.assert_allclose(x.grad.numpy(), expect)


def test_backward_non_scalar_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 1.5])


def test_backward_non_scalar_raises():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    with pytest.raises(RuntimeError):
        y.backward()


def test_register_hook():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    y = x * 2.0
    y.register_hook(lambda g: g * 10.0)
    paddle.sum(y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])


def test_retain_grads():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2.0
    y.retain_grads()
    z = y * 3.0
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Square(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor
            return gy * 2.0 * x

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Square.apply(x)
    paddle.sum(y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_grad_through_integer_blocked():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    idx = paddle.argmax(x)  # int output → no grad path
    assert idx.stop_gradient


def test_diamond_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    a = x * 3.0
    b = x * 4.0
    y = a * b  # y = 12 x^2, dy/dx = 24x
    y.backward()
    assert np.isclose(float(x.grad), 24 * 2.0)


def test_clear_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    paddle.sum(x * x).backward()
    assert x.grad is not None
    x.clear_grad()
    assert x.grad is None


# ---------------------------------------------------------------------------
# higher-order autograd (create_graph=True)
# Reference: test/autograd/ + eager_gen.py:1399 double-grad node generation
# ---------------------------------------------------------------------------
def test_create_graph_scalar_third_order():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x * x * x                      # 4x^3 -> 12x^2 -> 24x
    (g1,) = paddle.grad(y, [x], create_graph=True)
    assert not g1.stop_gradient
    (g2,) = paddle.grad(g1, [x], create_graph=True)
    (g3,) = paddle.grad(g2, [x])
    assert np.isclose(float(g1), 32.0)
    assert np.isclose(float(g2), 48.0)
    assert np.isclose(float(g3), 48.0)


def test_create_graph_mlp_matches_jax():
    """grad-of-grad of an MLP w.r.t. the input == jax.grad(jax.grad)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu.nn as nn
    paddle.seed(7)
    m = nn.Sequential(nn.Linear(3, 4), nn.Tanh(), nn.Linear(4, 1))
    xv = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    xt = paddle.to_tensor(xv, stop_gradient=False)
    (gx,) = paddle.grad(m(xt).sum(), [xt], create_graph=True)
    (ggx,) = paddle.grad(gx.sum(), [xt])

    p = {n: np.asarray(t.value) for n, t in m.state_dict().items()}

    def f(xa):
        h = jnp.tanh(xa @ p['0.weight'] + p['0.bias'])
        return (h @ p['2.weight'] + p['2.bias']).sum()
    np.testing.assert_allclose(np.asarray(gx.value), jax.grad(f)(xv),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ggx.value),
        jax.grad(lambda xa: jax.grad(f)(xa).sum())(xv),
        rtol=1e-4, atol=1e-5)


def test_gradient_penalty_training_step():
    """WGAN-GP-style loss: ||d critic/d x|| penalty differentiated
    w.r.t. the critic parameters via backward() through a
    create_graph grad."""
    import paddle_tpu.nn as nn
    paddle.seed(3)
    critic = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    opt = paddle.optimizer.SGD(0.05, parameters=critic.parameters())
    rng = np.random.RandomState(1)
    xv = rng.randn(16, 4).astype(np.float32)
    penalties = []
    for _ in range(25):
        x = paddle.to_tensor(xv, stop_gradient=False)
        out = critic(x).sum()
        (gx,) = paddle.grad(out, [x], create_graph=True)
        gp = ((gx * gx).sum(axis=1) ** 0.5 - 1.0)
        loss = (gp * gp).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        penalties.append(float(loss))
    assert penalties[-1] < penalties[0] * 0.5, penalties[::6]


def test_create_graph_param_hvp():
    """Hessian-vector product w.r.t. parameters through two taped walks."""
    import paddle_tpu.nn as nn
    paddle.seed(5)
    lin = nn.Linear(3, 1)
    w = lin.weight
    xv = np.random.RandomState(2).randn(6, 3).astype(np.float32)
    x = paddle.to_tensor(xv)
    y = (lin(x) ** 2).sum()              # quadratic in w
    (gw,) = paddle.grad(y, [w], create_graph=True)
    v = paddle.to_tensor(np.ones(gw.shape, np.float32))
    (hvp,) = paddle.grad((gw * v).sum(), [w])
    # analytic: y = sum_i (x_i . w + b)^2 ; H = 2 X^T X ; Hv = 2 X^T X v
    expect = 2.0 * xv.T @ xv @ np.ones((3, 1), np.float32)
    np.testing.assert_allclose(np.asarray(hvp.value), expect,
                               rtol=1e-4, atol=1e-4)


def test_create_graph_pylayer():
    """PyLayer with a differentiable backward participates in
    second-order grad (re-entrant user backward)."""
    from paddle_tpu.autograd import PyLayer

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor
            return gy * 3.0 * x * x

    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = Cube.apply(x)
    (g1,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad(g1, [x])
    assert np.isclose(float(g1), 12.0)
    assert np.isclose(float(g2), 12.0)


def test_create_graph_inplace_mutated_leaf_keeps_grad_path():
    """A leaf whose _value was swapped in place (optimizer idiom) must
    still accumulate .grad after a create_graph walk resurrected a
    wrapper for its recorded version (weakref must not be stolen)."""
    p = paddle.to_tensor(np.float32([2.0]), stop_gradient=False)
    y = (p * p).sum()
    p._value = p._value + 0
    paddle.grad(y, [p], create_graph=True)
    z = (p * p * p).sum()
    z.backward()
    assert p.grad is not None
    assert np.isclose(float(np.asarray(p.grad.value)[0]), 12.0)


def test_create_graph_under_amp():
    """Gradient penalty through an AMP O1 (bf16) forward: cotangents
    must be cast to each node's recorded output dtype in both walks."""
    import paddle_tpu.nn as nn
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 4).astype(np.float32),
        stop_gradient=False)
    with paddle.amp.auto_cast(level='O1'):
        out = m(x).sum()
    (gx,) = paddle.grad(out, [x], create_graph=True)
    loss = (gx * gx).sum()
    loss.backward()
    assert m[0].weight.grad is not None
    assert np.isfinite(float(np.asarray(loss.value)))
