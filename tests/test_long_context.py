"""Long-context stack: Megatron SP, SEP all2all attention, ring attention.

Equivalence strategy (reference test pattern: hybrid_parallel_mp_layers /
sep tests): every parallel form must match the dense single-device math.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup, set_hybrid_communicate_group, build_mesh)
from paddle_tpu.distributed.fleet.meta_parallel import sep_alltoall_attention
from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
    ScatterOp, GatherOp, ColumnSequenceParallelLinear,
    RowSequenceParallelLinear)
from paddle_tpu.ops import xla_attention
from paddle_tpu.ops.ring_attention import ring_attention


def _set_mesh(**kw):
    hcg = HybridCommunicateGroup(**kw)
    set_hybrid_communicate_group(hcg)
    return hcg.mesh


def test_sequence_parallel_linear_pair_matches_dense():
    mesh = _set_mesh(mp_degree=2)
    d, ff, b, s = 8, 16, 2, 4
    rng = np.random.RandomState(0)
    w1 = rng.randn(d, ff).astype(np.float32) * 0.1
    b1 = rng.randn(ff).astype(np.float32) * 0.1
    w2 = rng.randn(ff, d).astype(np.float32) * 0.1
    b2 = rng.randn(d).astype(np.float32) * 0.1
    x = rng.randn(b, s, d).astype(np.float32)

    col = ColumnSequenceParallelLinear(d, ff, has_bias=True)
    row = RowSequenceParallelLinear(ff, d, has_bias=True)
    col.weight.set_value(w1)
    col.bias.set_value(b1)
    row.weight.set_value(w2)
    row.bias.set_value(b2)

    xt = ScatterOp.apply(paddle.to_tensor(x))
    out = GatherOp.apply(row(col(xt)))
    expect = (x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out.value), expect, rtol=2e-5,
                               atol=1e-5)


def test_sequence_parallel_emits_seq_collectives():
    """The compiled HLO of the SP pair must contain the megatron pattern:
    an all-gather feeding the column matmul and a reduce-scatter after the
    row matmul (reference sequence_parallel_utils semantics)."""
    _set_mesh(mp_degree=2)
    d, ff = 8, 16
    col = ColumnSequenceParallelLinear(d, ff, has_bias=False)
    row = RowSequenceParallelLinear(ff, d, has_bias=False)

    def f(xv):
        out = row(col(paddle.to_tensor(xv)))
        return out.value

    x = jnp.ones((2, 4, d), jnp.float32)
    txt = jax.jit(f).lower(x).compile().as_text()
    assert "all-gather" in txt or "all-to-all" in txt, txt[:2000]
    assert "reduce-scatter" in txt or "all-reduce" in txt


def test_sep_alltoall_attention_matches_dense():
    mesh = _set_mesh(sep_degree=4)
    rng = np.random.RandomState(1)
    b, s, h, d = 2, 16, 4, 8
    q = rng.randn(b, s, h, d).astype(np.float32)
    k = rng.randn(b, s, h, d).astype(np.float32)
    v = rng.randn(b, s, h, d).astype(np.float32)
    for causal in (False, True):
        ref = xla_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            causal=causal)
        out = sep_alltoall_attention(paddle.to_tensor(q),
                                     paddle.to_tensor(k),
                                     paddle.to_tensor(v), causal=causal)
        np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_sep_alltoall_attention_gqa():
    """kv_heads < sep_degree (common GQA long-context config) must work."""
    _set_mesh(sep_degree=4)
    rng = np.random.RandomState(5)
    b, s, h, hk, d = 2, 16, 4, 2, 8
    q = rng.randn(b, s, h, d).astype(np.float32)
    k = rng.randn(b, s, hk, d).astype(np.float32)
    v = rng.randn(b, s, hk, d).astype(np.float32)
    ref = xla_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                        causal=True)
    out = sep_alltoall_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v), causal=True)
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sep_attention_emits_all_to_all():
    mesh = _set_mesh(sep_degree=4)
    b, s, h, d = 2, 16, 4, 8

    def f(q, k, v):
        out = sep_alltoall_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        return out.value

    from jax.sharding import NamedSharding, PartitionSpec as P
    sharded = NamedSharding(mesh, P(None, "sep", None, None))
    args = [jax.device_put(jnp.ones((b, s, h, d), jnp.float32), sharded)
            for _ in range(3)]
    txt = jax.jit(f).lower(*args).compile().as_text()
    assert "all-to-all" in txt, txt[:2000]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hk", [4, 2])
def test_ring_attention_matches_dense(causal, hk):
    mesh = build_mesh(sep=4, devices=jax.devices()[:4])
    rng = np.random.RandomState(2)
    b, s, h, d = 2, 32, 4, 8
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, hk, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, hk, d).astype(np.float32))

    ref = xla_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients_match_dense():
    mesh = build_mesh(sep=4, devices=jax.devices()[:4])
    rng = np.random.RandomState(3)
    b, s, h, d = 1, 16, 2, 4
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    ct = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) * ct)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) * ct)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_ring_attention_long_seq_sharded_input():
    """Input already sharded on the sep axis stays sharded (no gather)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = build_mesh(sep=8, devices=jax.devices()[:8])
    b, s, h, d = 1, 64, 2, 8
    rng = np.random.RandomState(4)
    sh = NamedSharding(mesh, P(None, "sep", None, None))
    q = jax.device_put(jnp.asarray(rng.randn(b, s, h, d), jnp.float32), sh)
    k = jax.device_put(jnp.asarray(rng.randn(b, s, h, d), jnp.float32), sh)
    v = jax.device_put(jnp.asarray(rng.randn(b, s, h, d), jnp.float32), sh)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh,
                                                 causal=True))(q, k, v)
    assert out.sharding.is_equivalent_to(sh, out.ndim)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
