"""Group-correct eager collectives across real processes.

Reference test pattern: test/collective/* — spawn N local processes with
fake-cluster env and compare collective results against numpy (SURVEY
§4).  Here the processes are launched through the repo's OWN launcher
(paddle_tpu.distributed.launch), and the collectives ride the launcher's
KV store (the control-plane backend, host_collectives.py).

The key assertion (VERDICT round-2 #4): an mp-GROUP allreduce must
reduce over exactly the group — NOT the world — and both must match
numpy.
"""
import json
import os
import textwrap

import numpy as np

from paddle_tpu.distributed.launch import parse_args, CollectiveController

WORKER = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import jax
jax.config.update("jax_platforms", "cpu")
import json
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.topology import HybridCommunicateGroup

rank = int(os.environ["PADDLE_TRAINER_ID"])

# 4 processes as a dp=2 x mp=2 grid: mp groups [0,1] and [2,3]
hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2)
mp_group = hcg.get_model_parallel_group()
dp_group = hcg.get_data_parallel_group()

x = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
world = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))

dist.all_reduce(x, group=mp_group)
dist.all_reduce(world)

gathered = []
dist.all_gather(gathered, paddle.to_tensor(
    np.array([float(rank)], np.float32)), group=dp_group)

# reduce_scatter over the world: rank r gets the reduced r-th chunk
rs = paddle.to_tensor(np.zeros((1,), np.float32))
dist.reduce_scatter(rs, [paddle.to_tensor(
    np.array([float(rank * 10 + j)], np.float32)) for j in range(4)])

# alltoall over the mp group
a2a = dist.alltoall([paddle.to_tensor(
    np.array([float(rank * 100 + j)], np.float32)) for j in range(2)],
    group=mp_group)

# broadcast within the mp group from GLOBAL rank (dp*2 + 1): the src arg
# is a global rank per reference semantics, mapped to the group index
bsrc = (rank // 2) * 2 + 1
bc = paddle.to_tensor(np.full((2,), float(rank), np.float32))
dist.broadcast(bc, src=bsrc, group=mp_group)

# p2p ring: send to (rank+1) % 4, recv from (rank-1) % 4
dist.send(paddle.to_tensor(np.array([float(rank)], np.float32)),
          dst=(rank + 1) % 4)
pr = paddle.to_tensor(np.zeros((1,), np.float32))
dist.recv(pr, src=(rank - 1) % 4)

out = {
    "rank": rank,
    "mp_ranks": mp_group.ranks,
    "mp_allreduce": np.asarray(x.value).tolist(),
    "world_allreduce": np.asarray(world.value).tolist(),
    "dp_gather": [float(np.asarray(t.value)[0]) for t in gathered],
    "reduce_scatter": np.asarray(rs.value).tolist(),
    "alltoall": [float(np.asarray(t.value)[0]) for t in a2a],
    "broadcast": np.asarray(bc.value).tolist(),
    "p2p_recv": float(np.asarray(pr.value)[0]),
    "stage_ranks": [hcg.get_data_parallel_rank(),
                    hcg.get_model_parallel_rank()],
}
with open(os.path.join(os.environ["DUMP_DIR"], f"out.{rank}.json"),
          "w") as f:
    json.dump(out, f)
"""


def test_group_scoped_collectives_4proc(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(WORKER))
    os.environ["DUMP_DIR"] = str(tmp_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.environ["PYTHONPATH"] = repo + os.pathsep \
        + os.environ.get("PYTHONPATH", "")
    try:
        args = parse_args([
            "--nproc_per_node=4", f"--log_dir={tmp_path}/log",
            "--job_id=coll", str(script)])
        rc = CollectiveController(args).run()
    finally:
        del os.environ["DUMP_DIR"]
    assert rc == 0
    outs = {}
    for r in range(4):
        with open(tmp_path / f"out.{r}.json") as f:
            outs[r] = json.load(f)

    # mesh order (pp, sep, sharding, dp, mp): rank = dp*2 + mp
    # mp groups: [0,1] (dp=0) and [2,3] (dp=1)
    assert outs[0]["mp_ranks"] == [0, 1]
    assert outs[2]["mp_ranks"] == [2, 3]

    # mp allreduce: group [0,1] -> 1+2 = 3; group [2,3] -> 3+4 = 7
    for r in (0, 1):
        assert outs[r]["mp_allreduce"] == [3.0] * 4, outs[r]
    for r in (2, 3):
        assert outs[r]["mp_allreduce"] == [7.0] * 4, outs[r]
    # world allreduce: 1+2+3+4 = 10 — DIFFERENT from the group result
    for r in range(4):
        assert outs[r]["world_allreduce"] == [10.0] * 4

    # dp groups: [0,2] (mp=0) and [1,3] (mp=1); gather collects dp peers
    assert outs[0]["dp_gather"] == [0.0, 2.0]
    assert outs[1]["dp_gather"] == [1.0, 3.0]

    # world reduce_scatter: chunk j = sum_r (r*10 + j)
    for r in range(4):
        want = sum(rr * 10 + r for rr in range(4))
        assert outs[r]["reduce_scatter"] == [float(want)]

    # mp alltoall: rank r gets [peer*100 + my_group_index for each peer]
    assert outs[0]["alltoall"] == [0.0, 100.0]
    assert outs[1]["alltoall"] == [1.0, 101.0]
    assert outs[2]["alltoall"] == [200.0, 300.0]
    assert outs[3]["alltoall"] == [201.0, 301.0]

    # broadcast from global rank 1 in group [0,1], global 3 in [2,3]
    for r in (0, 1):
        assert outs[r]["broadcast"] == [1.0, 1.0]
    for r in (2, 3):
        assert outs[r]["broadcast"] == [3.0, 3.0]

    # p2p ring
    for r in range(4):
        assert outs[r]["p2p_recv"] == float((r - 1) % 4)

    # rank getters derive from the process coordinate (VERDICT #5 weak)
    assert outs[3]["stage_ranks"] == [1, 1]
    assert outs[1]["stage_ranks"] == [0, 1]


class TestMpOpsEager:
    """TP eager prims (reference mp_ops.py:91-293): world size 1 —
    forward identities with the reference's fwd/bwd collective pairing."""

    def test_c_identity_bwd_allreduce(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.fleet.layers.mpu import _c_identity
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        x.stop_gradient = False
        out = _c_identity(x)
        np.testing.assert_allclose(np.asarray(out.value),
                                   np.asarray(x.value))
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   np.ones((2, 4)))

    def test_mp_allreduce_bwd_identity(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.fleet.layers.mpu import _mp_allreduce
        x = paddle.to_tensor(np.full((3,), 2.0, np.float32))
        x.stop_gradient = False
        out = _mp_allreduce(x)
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value), np.ones(3))

    def test_c_split_concat_roundtrip(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.fleet.layers.mpu import (_c_split,
                                                             _c_concat)
        x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(2, 4))
        x.stop_gradient = False
        out = _c_concat(_c_split(x))
        np.testing.assert_allclose(np.asarray(out.value),
                                   np.asarray(x.value))
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   np.ones((2, 4)))

    def test_distributed_split_linear(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.fleet.layers.mpu import split
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        out = split(x, (8, 6), "linear", axis=1, gather_out=True)
        assert tuple(out.shape) == (2, 6)


class TestComposedOrderEdgeCases:
    """check_collective_order(composed=True) satellite: degenerate
    domains (a size-1 axis traces as a (None,)/empty domain) and
    single-rank domains are no-ops — not KeyErrors, not divergences."""

    @staticmethod
    def _ev(kind, key, domain):
        from paddle_tpu.analysis.collectives import CollectiveEvent
        return CollectiveEvent(kind, key, domain)

    def test_size1_axis_domain_is_noop(self):
        from paddle_tpu.analysis.collectives import check_collective_order
        ev = self._ev("psum", ("g",), (None,))
        # rank 1 never traced the degenerate collective: still clean
        assert check_collective_order({0: [ev], 1: []},
                                      composed=True) == []

    def test_empty_and_all_none_domains_are_noops(self):
        from paddle_tpu.analysis.collectives import check_collective_order
        evs = [self._ev("psum", ("a",), ()),
               self._ev("ppermute", ("b",), (None, None))]
        assert check_collective_order({0: evs, 1: [], 2: []},
                                      composed=True) == []

    def test_single_rank_domain_is_noop(self):
        from paddle_tpu.analysis.collectives import check_collective_order
        ev = self._ev("psum", ("g",), ("dp",))
        # only one rank participates in the 'dp' domain: nothing to
        # cross-check (and no KeyError from the participants lookup)
        assert check_collective_order(
            {0: [ev]}, participants={("dp",): [0]}, composed=True) == []

    def test_dict_participants_missing_degenerate_domain(self):
        from paddle_tpu.analysis.collectives import check_collective_order
        good = self._ev("psum", ("g",), ("dp",))
        degen = self._ev("psum", ("skip",), (None,))
        # participants dict only knows the real domain: the degenerate
        # one must fall back instead of raising KeyError
        out = check_collective_order(
            {0: [degen, good], 1: [good]},
            participants={("dp",): [0, 1]}, composed=True)
        assert out == []

    def test_real_divergence_still_caught_composed(self):
        from paddle_tpu.analysis.collectives import check_collective_order
        a = self._ev("psum", ("a",), ("dp",))
        b = self._ev("psum", ("b",), ("dp",))
        out = check_collective_order({0: [a, b], 1: [b, a]},
                                     composed=True)
        assert out, "misordered composed schedules must be flagged"
        assert any("divergence" in f.code for f in out)
