"""Test config: force CPU with 8 virtual devices BEFORE jax imports.

Mirrors the reference's fake-cluster strategy (SURVEY §4: multi-process on
localhost) — here SPMD needs no processes, just a virtual 8-device mesh via
xla_force_host_platform_device_count.
"""
import os

# force CPU unconditionally: unit tests must not burn (or depend on) the
# real TPU; the driver's bench run uses the chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon TPU plugin overrides JAX_PLATFORMS; force CPU via config too
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield
