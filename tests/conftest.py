"""Test config: force CPU with 8 virtual devices BEFORE jax imports.

Mirrors the reference's fake-cluster strategy (SURVEY §4: multi-process on
localhost) — here SPMD needs no processes, just a virtual 8-device mesh via
xla_force_host_platform_device_count.
"""
import os

# force CPU unconditionally: unit tests must not burn (or depend on) the
# real TPU; the driver's bench run uses the chip.
os.environ["JAX_PLATFORMS"] = "cpu"
# deterministic fast lease-lapse in launcher/elastic tests (production
# default is 45s for saturated-host robustness; tests simulate death
# explicitly and need not wait that long)
os.environ.setdefault("PADDLE_HEARTBEAT_TTL", "20")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon TPU plugin overrides JAX_PLATFORMS; force CPU via config too
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield


@pytest.fixture(autouse=True)
def _verify_built_programs():
    """Every static Program built during a test must END the test
    verifier-clean (the PIR every-pass-leaves-verifiable-IR contract,
    enforced suite-wide).  Flag-gated: FLAGS_verify_built_programs=0
    disables; planted-defect tests opt out one program at a time via
    `prog._no_autoverify = True`."""
    if os.environ.get("FLAGS_verify_built_programs", "1") != "1":
        yield
        return
    import weakref
    import paddle_tpu.static as static
    created = []
    orig_init = static.Program.__init__

    def patched(self, *a, **k):
        orig_init(self, *a, **k)
        created.append(weakref.ref(self))

    static.Program.__init__ = patched
    try:
        yield
    finally:
        static.Program.__init__ = orig_init
    from paddle_tpu.analysis import verify_program
    for r in created:
        p = r()
        if p is None or getattr(p, "_no_autoverify", False):
            continue
        findings = verify_program(p, level="full")
        assert not findings, (
            "a static Program built during this test is not "
            "verifier-clean:\n" + "\n".join(
                f"  [{f.code}] {f.message}" for f in findings))


@pytest.fixture(autouse=True)
def _lint_fused_ce_logits():
    """Every ShardedTrainStep a test runs while FLAGS_fused_ce is on
    must END the test clean under lint_materialized_logits — the
    fused-loss contract (no [B, S, vocab] fp32 buffer anywhere in the
    jitted step), enforced suite-wide alongside the Program verifier.
    Zero cost for tests that never arm the flag.  Planted-defect tests
    opt out per step via `step._no_autolint = True`."""
    import weakref
    from paddle_tpu.framework.flags import get_flag
    from paddle_tpu.parallel.sharded_trainer import ShardedTrainStep
    recorded = []
    orig_prepare = ShardedTrainStep._prepare

    def patched(self, batch):
        if get_flag("fused_ce") and not any(
                r() is self for r, _ in recorded):
            recorded.append((weakref.ref(self), batch))
        return orig_prepare(self, batch)

    ShardedTrainStep._prepare = patched
    try:
        yield
    finally:
        ShardedTrainStep._prepare = orig_prepare
    if not recorded:
        return
    # linting RE-TRACES the step's python body, which reads the flag —
    # re-arm it so the trace takes the same fused path the test ran
    # (test-local flag fixtures tear down before this autouse one)
    from paddle_tpu.framework.flags import set_flags
    prev = get_flag("fused_ce")
    set_flags({"FLAGS_fused_ce": True})
    try:
        for ref, batch in recorded:
            step = ref()
            if step is None or getattr(step, "_no_autolint", False) \
                    or step._pipeline is not None:
                continue
            vocab = getattr(getattr(step.model, "config", None),
                            "vocab_size", None)
            if not vocab:
                continue
            # the fused forward gate is flag AND training — a test that
            # eval()s the model after its fused train steps must not
            # flip the retrace onto the unfused (lint-tripping) path
            was_training = step.model.training
            if not was_training:
                step.model.train()
            try:
                findings = step.lint(*batch, donation=False,
                                     transfers=False,
                                     logits=True).get("logits", [])
            finally:
                if not was_training:
                    step.model.eval()
            assert not findings, (
                "a fused-CE (FLAGS_fused_ce) train step built during "
                "this test materializes full fp32 logits:\n" + "\n".join(
                    f"  [{f.code}] {f.message}" for f in findings))
    finally:
        set_flags({"FLAGS_fused_ce": prev})


# ---------------------------------------------------------------------------
# fast tier (VERDICT r3 item 10): `-m fast` runs a <5-minute subset that
# still touches every subsystem; the full suite stays the completeness
# bar.  Modules are fast by default; the denylists below carve out the
# expensive compile/multiprocess/schedule-zoo tests.
# ---------------------------------------------------------------------------
_SLOW_MODULES = {
    # multi-process launch/elastic walls (heartbeat TTL waits)
    "test_elastic", "test_launch", "test_rpc", "test_elastic_resume",
    # trainer-compile zoo (checkpoint/guard planted-fault coverage)
    "test_fault_tolerance",
    # XLA CPU compile walls (model zoo, UNet, scanned pipelines)
    "test_vision_models", "test_unet", "test_gpt", "test_moe",
    "test_pipeline", "test_recompute", "test_long_context",
    "test_generation", "test_distributed", "test_op_registry",
    "test_distribution", "test_pallas_kernels",
    "test_eager_collectives",
}
# one representative per slow module keeps every subsystem in the tier
_FAST_PICKS = {
    "test_elastic": "test_elastic_exit_code_triggers_reform",
    "test_fault_tolerance": "test_sharded_trainer_resume_parity",
    "test_launch": "test_two_procs_env_wiring",
    "test_rpc": "test_rpc_two_workers",
    "test_vision_models": "test_forward_shape[squeezenet1_1]",
    "test_unet": "test_unet_forward_shape",
    "test_gpt": "test_gpt_trains",
    "test_moe": "test_naive_gate_dense_path_equals_dense",
    "test_pipeline": "test_pp_loss_matches_single_device[2-4-1F1B]",
    "test_recompute": "test_matches_plain_backward",
    "test_long_context":
        "test_sequence_parallel_linear_pair_matches_dense",
    "test_generation": "test_prefill_matches_full_forward",
    "test_distributed": "test_dp_matches_single",
    "test_op_registry": "test_registry_op_output[affine_channel]",
    "test_distribution": "test_sample_moments[normal]",
    "test_pallas_kernels": "test_forward[False]",
    "test_eager_collectives": "test_group_scoped_collectives_4proc",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "fast: <5-minute CPU subset covering every subsystem")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow') "
        "— heavy multi-process end-to-end walls; covered in tier-1 by "
        "fast in-process twins")


def pytest_collection_modifyitems(config, items):
    seen_mods, matched = set(), set()
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        seen_mods.add(mod)
        if mod not in _SLOW_MODULES:
            item.add_marker(pytest.mark.fast)
            continue
        pick = _FAST_PICKS.get(mod)
        if pick and item.name == pick:
            item.add_marker(pytest.mark.fast)
            matched.add(mod)
    # a renamed test must not silently drop its subsystem from the tier
    # — but only judge modules collected IN FULL (node-id / -k /
    # --deselect subsets legitimately omit the pick)
    sel = [a for a in config.invocation_params.args
           if isinstance(a, str)]
    partial = (bool(config.getoption("keyword", "") or "")
               or bool(config.getoption("deselect", None))
               or any("::" in a for a in sel))
    stale = [m for m in seen_mods & set(_SLOW_MODULES)
             if _FAST_PICKS.get(m) and m not in matched]
    if stale and not partial:
        raise pytest.UsageError(
            f"fast-tier picks no longer match a collected test: "
            f"{sorted(stale)} — update _FAST_PICKS in conftest.py")
