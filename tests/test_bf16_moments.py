"""bf16 AdamW moments with error feedback (FLAGS_bf16_adamw_moments).

What is being validated (ops/pallas/fused_adamw.py, optimizer/):
  * the twin-lockstep satellite: the Pallas kernel (interpret mode),
    its jnp twin `adamw_hostside`, and the optimizer's pure `_update`
    rule produce identical updates across param dtypes, moment dtypes,
    multi_precision and ef on/off — the three implementations cannot
    drift silently;
  * error feedback actually integrates: with (1-β₂)·g² below bf16
    resolution, plain bf16 v stalls while v+ef tracks the fp32 value;
  * N-step training parity: bf16+ef moments stay within documented
    tolerance of fp32 moments on a real model;
  * bit-exact checkpoint round-trip of the bf16 moments AND the ef
    residual through train_state()/load_train_state() and the on-disk
    checkpoint (PR 4's TrainState machinery).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.pallas.fused_adamw import fused_adamw, adamw_hostside
from paddle_tpu.optimizer.optimizer import Adam

_rng = np.random.RandomState(0)


@pytest.fixture
def bf16_moments_flag():
    set_flags({"FLAGS_bf16_adamw_moments": True})
    yield
    set_flags({"FLAGS_bf16_adamw_moments": False})


def _state(shape, moment_dtype, ef):
    g = jnp.asarray(_rng.randn(*shape).astype(np.float32)) * 0.01
    m = (jnp.asarray(_rng.randn(*shape).astype(np.float32)) * 0.01) \
        .astype(moment_dtype)
    v = jnp.abs(jnp.asarray(_rng.randn(*shape).astype(np.float32)) * 0.01) \
        .astype(moment_dtype)
    mst = jnp.asarray(_rng.randn(*shape).astype(np.float32))
    e = jnp.zeros(shape, moment_dtype) if ef else None
    return g, m, v, mst, e


class TestTwinLockstep:
    """Parameterized lockstep: fused kernel == jnp twin == pure rule."""

    @pytest.mark.parametrize("out_dtype", [jnp.float32, jnp.bfloat16],
                             ids=["fp32-params", "bf16+master"])
    @pytest.mark.parametrize("moment_dtype", [jnp.float32, jnp.bfloat16],
                             ids=["m-fp32", "m-bf16"])
    @pytest.mark.parametrize("ef", [False, True], ids=["no-ef", "ef"])
    @pytest.mark.parametrize("wd,decoupled", [(0.0, True), (0.01, True),
                                              (0.01, False)])
    def test_kernel_vs_hostside_vs_pure(self, out_dtype, moment_dtype,
                                        ef, wd, decoupled):
        if ef and moment_dtype == jnp.float32:
            pytest.skip("ef pairs with sub-fp32 moments")
        g, m, v, mst, e = _state((64, 32), moment_dtype, ef)
        lr, step = jnp.float32(1e-3), jnp.int32(3)
        kw = dict(b1=0.9, b2=0.999, eps=1e-8, wd=wd, decoupled=decoupled,
                  out_dtype=out_dtype)
        a = fused_adamw(g, m, v, mst, lr, step, ef=e, **kw)
        b = adamw_hostside(g, m, v, mst, lr, step, ef=e, **kw)
        assert len(a) == len(b) == (5 if ef else 4)
        for x, y in zip(a, b):
            np.testing.assert_allclose(
                np.asarray(x.astype(jnp.float32)),
                np.asarray(y.astype(jnp.float32)), atol=2e-7, rtol=1e-6)
        # pure rule (master indirection done by hand, like apply_update)
        st = {"moment1": m, "moment2": v}
        if e is not None:
            st["ef"] = e
        new_mst, ns = Adam._update(mst, g, st, lr, wd, step, b1=0.9,
                                   b2=0.999, eps=1e-8,
                                   decoupled=decoupled)
        np.testing.assert_allclose(
            np.asarray(new_mst), np.asarray(a[3].astype(jnp.float32)),
            atol=2e-7, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(ns["moment1"].astype(jnp.float32)),
            np.asarray(a[1].astype(jnp.float32)), atol=2e-7, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(ns["moment2"].astype(jnp.float32)),
            np.asarray(a[2].astype(jnp.float32)), atol=2e-7, rtol=1e-6)
        if ef:
            np.testing.assert_allclose(
                np.asarray(ns["ef"].astype(jnp.float32)),
                np.asarray(a[4].astype(jnp.float32)), atol=2e-7,
                rtol=1e-6)


class TestErrorFeedback:
    def test_ef_integrates_where_plain_bf16_stalls(self):
        """(1-β₂)·g² ≈ 2.5e-4 against v=1.0 is below bf16's ~4e-3
        relative resolution: plain bf16 v never moves; v+ef must track
        the fp32 recursion."""
        shape = (8, 8)
        g = jnp.full(shape, 0.5, jnp.float32)
        m = jnp.zeros(shape, jnp.bfloat16)
        mst = jnp.zeros(shape, jnp.float32)
        v_ef = v_plain = jnp.ones(shape, jnp.bfloat16)
        ef = jnp.zeros(shape, jnp.bfloat16)
        v_true = 1.0
        for i in range(1, 150):
            _, _, v_ef, _, ef = adamw_hostside(
                g, m, v_ef, mst, 0.0, jnp.int32(i), ef=ef,
                out_dtype=jnp.float32)
            _, _, v_plain, _ = adamw_hostside(
                g, m, v_plain, mst, 0.0, jnp.int32(i),
                out_dtype=jnp.float32)
            v_true = 0.999 * v_true + 0.001 * 0.25
        recon = float(v_ef.astype(jnp.float32)[0, 0]) \
            + float(ef.astype(jnp.float32)[0, 0])
        assert abs(recon - v_true) < 1e-3
        assert float(v_plain.astype(jnp.float32)[0, 0]) == 1.0, \
            "without ef, bf16 v should stall (that's the motivation)"


def _trainer(seed=0, flag=False):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.distributed.topology import build_mesh
    set_flags({"FLAGS_bf16_adamw_moments": flag})
    try:
        paddle.seed(seed)
        m = LlamaForCausalLM(llama_tiny_config())
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters(),
                                     weight_decay=0.1)
        step = ShardedTrainStep(m, opt,
                                build_mesh(devices=jax.devices()[:1]),
                                sharding_stage=0)
    finally:
        set_flags({"FLAGS_bf16_adamw_moments": False})
    return m, step


def _ids():
    return paddle.to_tensor(_rng.randint(0, 512, (2, 16))
                            .astype(np.int32))


class TestTrainingParity:
    def test_nstep_parity_vs_fp32_moments(self):
        """Documented tolerance: 6 steps of tiny-llama training with
        bf16+ef moments stay within 5e-3 absolute of fp32-moment losses
        (measured drift ~3e-3 by step 6; the moments carry ~bf16 ulp of
        noise into the update direction, not a bias)."""
        ids = _ids()
        _, s32 = _trainer(flag=False)
        ref = [float(np.asarray(s32(ids, ids).value)) for _ in range(6)]
        _, s16 = _trainer(flag=True)
        got = [float(np.asarray(s16(ids, ids).value)) for _ in range(6)]
        assert set(s16._opt_states[0]) == {"moment1", "moment2", "ef"}
        assert s16._opt_states[0]["moment1"].dtype == jnp.bfloat16
        np.testing.assert_allclose(got, ref, atol=5e-3)

    def test_checkpoint_roundtrip_bit_exact(self, tmp_path,
                                            bf16_moments_flag):
        """bf16 moments + ef residual survive train_state() →
        save_train_checkpoint → restore into a FRESH trainer bit-exactly,
        and training continues bit-exactly (PR 4's resume bar)."""
        from paddle_tpu.distributed import checkpoint as ckpt
        ids = _ids()
        _, s_ref = _trainer(flag=True)
        ref = [float(np.asarray(s_ref(ids, ids).value)) for _ in range(6)]
        _, s_a = _trainer(flag=True)
        first = [float(np.asarray(s_a(ids, ids).value)) for _ in range(3)]
        arrays_a, _ = s_a.train_state()
        ef_keys = [k for k in arrays_a if k.endswith(".ef")]
        assert ef_keys, "ef residual missing from the train state"
        ckpt.save_train_checkpoint(s_a, str(tmp_path))
        _, s_b = _trainer(seed=31337, flag=True)
        ckpt.restore_train_checkpoint(s_b, str(tmp_path))
        arrays_b, _ = s_b.train_state()
        for k in ef_keys + [k for k in arrays_a if ".moment" in k]:
            a = np.asarray(arrays_a[k].astype(jnp.float32))
            b = np.asarray(arrays_b[k].astype(jnp.float32))
            assert (a == b).all(), f"{k} not bit-exact after restore"
        rest = [float(np.asarray(s_b(ids, ids).value)) for _ in range(3)]
        assert ref == first + rest, "resume is not bit-exact"

    def test_offload_pipeline_carries_ef(self, bf16_moments_flag):
        """The streamed ZeRO-3 pipeline's per-layer in-scan update must
        thread the ef residual (adamw_hostside ef path) — state stacks
        gain the key and a step runs."""
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             LlamaConfig)
        from paddle_tpu.parallel import OffloadPipelineStep
        from paddle_tpu.distributed.topology import build_mesh
        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2,
                          max_position_embeddings=32, dtype="float32")
        m = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters(),
                                     weight_decay=0.1)
        st = OffloadPipelineStep(m, opt,
                                 build_mesh(devices=jax.devices()[:1]),
                                 cast_dtype=None)
        x = paddle.to_tensor(_rng.randint(0, 64, (2, 16))
                             .astype(np.int32))
        l1 = float(np.asarray(st(x, x).value))
        l2 = float(np.asarray(st(x, x).value))
        assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1
        arrays, _ = st.train_state()
        assert any(k.endswith(".ef") for k in arrays), \
            "pipeline train state must include the ef residual"
