"""KV-cached decode path (inference/generation.py + llama
forward_cached).

Reference: incubate block_multihead_attention (paged-KV serving) +
paddlenlp GenerationMixin.generate — here the whole decode is one
jitted lax.scan program over a static ring-buffer cache.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config


@pytest.fixture()
def tiny():
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128, num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128,
                            max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


def test_prefill_matches_full_forward(tiny):
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 128, (2, 9)).astype(np.int32)
    cache = tiny.init_cache(2, 32)
    lg, _ = tiny.forward_cached(jnp.asarray(prompt), cache,
                                jnp.asarray(0, jnp.int32))
    full = tiny(paddle.to_tensor(prompt)).value
    np.testing.assert_allclose(np.asarray(lg, np.float32),
                               np.asarray(full, np.float32),
                               rtol=1e-4, atol=1e-4)


def test_incremental_decode_matches_recompute(tiny):
    """Greedy decode through the KV cache must emit exactly the tokens
    a full-recompute greedy loop emits."""
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 128, (2, 7)).astype(np.int32)
    cache = tiny.init_cache(2, 24)
    lg, cache = tiny.forward_cached(jnp.asarray(prompt), cache,
                                    jnp.asarray(0, jnp.int32))
    last = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
    toks, pos = [last], 7
    for _ in range(3):
        lg, cache = tiny.forward_cached(last[:, None], cache,
                                        jnp.asarray(pos, jnp.int32))
        last = jnp.argmax(lg[:, 0], -1).astype(jnp.int32)
        toks.append(last)
        pos += 1

    cur = prompt.copy()
    for i in range(4):
        lg = tiny(paddle.to_tensor(cur)).value
        nxt = np.asarray(jnp.argmax(lg[:, -1], -1)).astype(np.int32)
        assert (np.asarray(toks[i]) == nxt).all(), i
        cur = np.concatenate([cur, nxt[:, None]], 1)


def test_generate_jitted_scan(tiny):
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, 128, (2, 5)).astype(np.int32)
    out = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=6)
    assert tuple(out.shape) == (2, 6)
    # deterministic (greedy default): second call identical
    out2 = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=6)
    assert (np.asarray(out.value) == np.asarray(out2.value)).all()


def test_generate_eos_padding(tiny):
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 128, (1, 4)).astype(np.int32)
    out = np.asarray(tiny.generate(paddle.to_tensor(prompt),
                                   max_new_tokens=8,
                                   eos_token_id=int(np.asarray(
                                       tiny.generate(
                                           paddle.to_tensor(prompt),
                                           max_new_tokens=1).value)[0, 0])
                                   ).value)
    # first emitted token IS eos → everything after stays eos
    assert (out == out[0, 0]).all()


def test_generate_sampling_top_p(tiny):
    rng = np.random.RandomState(4)
    prompt = rng.randint(0, 128, (2, 5)).astype(np.int32)
    out = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                        temperature=0.8, top_p=0.9, seed=7)
    out2 = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                         temperature=0.8, top_p=0.9, seed=7)
    assert (np.asarray(out.value) == np.asarray(out2.value)).all()
    assert np.asarray(out.value).max() < 128


def test_predictor_from_model_generate(tiny):
    from paddle_tpu.inference import Predictor
    pred = Predictor.from_model(tiny)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, 128, (1, 4)).astype(np.int32)
    out = pred.generate(paddle.to_tensor(prompt), max_new_tokens=3)
    ref = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=3)
    assert (np.asarray(out.value) == np.asarray(ref.value)).all()
