"""KV-cached decode path (inference/generation.py + llama
forward_cached).

Reference: incubate block_multihead_attention (paged-KV serving) +
paddlenlp GenerationMixin.generate — here the whole decode is one
jitted lax.scan program over a static ring-buffer cache.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config


@pytest.fixture()
def tiny():
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128, num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128,
                            max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


def test_prefill_matches_full_forward(tiny):
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 128, (2, 9)).astype(np.int32)
    cache = tiny.init_cache(2, 32)
    lg, _ = tiny.forward_cached(jnp.asarray(prompt), cache,
                                jnp.asarray(0, jnp.int32))
    full = tiny(paddle.to_tensor(prompt)).value
    np.testing.assert_allclose(np.asarray(lg, np.float32),
                               np.asarray(full, np.float32),
                               rtol=1e-4, atol=1e-4)


def test_incremental_decode_matches_recompute(tiny):
    """Greedy decode through the KV cache must emit exactly the tokens
    a full-recompute greedy loop emits."""
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 128, (2, 7)).astype(np.int32)
    cache = tiny.init_cache(2, 24)
    lg, cache = tiny.forward_cached(jnp.asarray(prompt), cache,
                                    jnp.asarray(0, jnp.int32))
    last = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
    toks, pos = [last], 7
    for _ in range(3):
        lg, cache = tiny.forward_cached(last[:, None], cache,
                                        jnp.asarray(pos, jnp.int32))
        last = jnp.argmax(lg[:, 0], -1).astype(jnp.int32)
        toks.append(last)
        pos += 1

    cur = prompt.copy()
    for i in range(4):
        lg = tiny(paddle.to_tensor(cur)).value
        nxt = np.asarray(jnp.argmax(lg[:, -1], -1)).astype(np.int32)
        assert (np.asarray(toks[i]) == nxt).all(), i
        cur = np.concatenate([cur, nxt[:, None]], 1)


def test_generate_jitted_scan(tiny):
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, 128, (2, 5)).astype(np.int32)
    out = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=6)
    assert tuple(out.shape) == (2, 6)
    # deterministic (greedy default): second call identical
    out2 = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=6)
    assert (np.asarray(out.value) == np.asarray(out2.value)).all()


def test_generate_eos_padding(tiny):
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 128, (1, 4)).astype(np.int32)
    out = np.asarray(tiny.generate(paddle.to_tensor(prompt),
                                   max_new_tokens=8,
                                   eos_token_id=int(np.asarray(
                                       tiny.generate(
                                           paddle.to_tensor(prompt),
                                           max_new_tokens=1).value)[0, 0])
                                   ).value)
    # first emitted token IS eos → everything after stays eos
    assert (out == out[0, 0]).all()


def test_generate_sampling_top_p(tiny):
    rng = np.random.RandomState(4)
    prompt = rng.randint(0, 128, (2, 5)).astype(np.int32)
    out = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                        temperature=0.8, top_p=0.9, seed=7)
    out2 = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                         temperature=0.8, top_p=0.9, seed=7)
    assert (np.asarray(out.value) == np.asarray(out2.value)).all()
    assert np.asarray(out.value).max() < 128


def test_predictor_from_model_generate(tiny):
    from paddle_tpu.inference import Predictor
    pred = Predictor.from_model(tiny)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, 128, (1, 4)).astype(np.int32)
    out = pred.generate(paddle.to_tensor(prompt), max_new_tokens=3)
    ref = tiny.generate(paddle.to_tensor(prompt), max_new_tokens=3)
    assert (np.asarray(out.value) == np.asarray(ref.value)).all()


# ---------------------------------------------------------------------------
# speculative decoding (ISSUE 11): the serve scan's draft/verify loop


def _spec_workload(model, **kw):
    from paddle_tpu.inference import ContinuousBatcher
    rng = np.random.RandomState(6)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (6, 11, 4, 9)]
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4, **kw)
    rids = [bat.submit(p, 6) for p in prompts[:2]]
    bat.step()
    rids += [bat.submit(p, 6) for p in prompts[2:]]
    outs = bat.run()
    return bat, rids, outs


def test_speculative_greedy_bit_exact_vs_plain(tiny):
    """Greedy speculative decode must emit EXACTLY the plain batcher's
    tokens — for an identity draft (accepts everything) AND a weak
    early-exit self-draft (accepts almost nothing): acceptance only
    moves throughput, never the output."""
    _, r0, o0 = _spec_workload(tiny)
    for kw in (dict(spec_tokens=3, draft_model=tiny),
               dict(spec_tokens=2, draft_layers=1)):
        _, r1, o1 = _spec_workload(tiny, **kw)
        for a, b in zip(r0, r1):
            assert (o0[a] == o1[b]).all(), kw


def test_speculative_acceptance_accounting(tiny):
    """accepted + rejected == drafted, and the identity draft accepts
    everything: accepted_per_step == K+1 on every active step."""
    bat, _, _ = _spec_workload(tiny, spec_tokens=3, draft_model=tiny)
    st = bat.stats()
    assert st["spec_drafted"] > 0
    assert 0.0 <= st["spec_accept_rate"] <= 1.0
    assert st["spec_accepted"] <= st["spec_drafted"]
    assert st["spec_accept_rate"] == 1.0          # identity draft
    assert st["spec_accepted_per_step"]["p50"] == 4.0
    # a weak draft still satisfies the partition
    bat2, _, _ = _spec_workload(tiny, spec_tokens=2, draft_layers=1)
    st2 = bat2.stats()
    rejected = st2["spec_drafted"] - st2["spec_accepted"]
    assert rejected >= 0
    assert st2["spec_accepted"] + rejected == st2["spec_drafted"]


def test_speculative_two_programs_and_donation(tiny):
    """The r6 contracts hold with the verify width folded into the
    chunk axis: exactly 2 compiled programs (spec decode + admit) and
    every carry — including the draft cache — donated."""
    from paddle_tpu.analysis import lint_serve_programs
    bat, _, _ = _spec_workload(tiny, spec_tokens=3, draft_model=tiny,
                               kv_layout="paged")
    assert bat.compiled_programs == 2
    assert not lint_serve_programs(bat)


def test_speculative_paged_rollback_leak_free(tiny):
    """Paged KV under speculation with a faulted slot mid-decode: the
    requeued request re-decodes bit-exactly, and the pool ends the run
    with zero mapped pages and reconciled trie refcounts — the
    rejected draft rows and the fault rollback leak nothing."""
    import paddle_tpu as pd
    from paddle_tpu.distributed import fault
    _, r0, o0 = _spec_workload(tiny, kv_layout="paged")
    pd.set_flags({"FLAGS_fault_injection":
                  "serve.decode:step=3:mode=error"})
    fault.reset()
    try:
        bat, r1, o1 = _spec_workload(tiny, spec_tokens=3,
                                     draft_model=tiny,
                                     kv_layout="paged")
        fired = fault.fired_counts().get("serve.decode", 0)
    finally:
        pd.set_flags({"FLAGS_fault_injection": ""})
        fault.reset()
    assert fired >= 1
    st = bat.stats()
    assert st["requests_requeued"] >= 1
    for a, b in zip(r0, r1):
        if not bat._finished[b].shed:
            assert (o0[a] == o1[b]).all()
    # leak-free pool: every page unmapped (cached prefix pages are
    # refcount-0 by definition) and no dangling refcounts
    assert bat._alloc.pages_used == bat._alloc.pages_cached
    assert all(v == 0 for v in bat._alloc._ref.values())


def test_speculative_needs_a_draft(tiny):
    from paddle_tpu.inference import ContinuousBatcher
    with pytest.raises(ValueError):
        ContinuousBatcher(tiny, max_batch_size=2, max_len=32,
                          spec_tokens=2)


def test_early_exit_draft_validates_layers(tiny):
    with pytest.raises(ValueError):
        tiny.early_exit_draft(0)
    with pytest.raises(ValueError):
        tiny.early_exit_draft(99)
    d = tiny.early_exit_draft(1)
    cache = d.init_cache(2, 16)
    assert len(cache) == 1
    lg, cache = d.forward_cached(
        jnp.zeros((2, 3), jnp.int32), cache,
        jnp.zeros((2,), jnp.int32))
    assert lg.shape == (2, 3, tiny.config.vocab_size)


def test_speculative_flag_defaults(tiny):
    """FLAGS_serve_spec_tokens / FLAGS_serve_draft_layers arm
    speculation without constructor args (the bench/env interface)."""
    paddle.set_flags({"FLAGS_serve_spec_tokens": 2,
                      "FLAGS_serve_draft_layers": 1})
    try:
        bat, _, outs = _spec_workload(tiny)
        assert bat.spec_k == 2
        assert bat.stats()["spec_drafted"] > 0
    finally:
        paddle.set_flags({"FLAGS_serve_spec_tokens": 0,
                          "FLAGS_serve_draft_layers": 0})
