"""paddle.distribution: densities vs closed forms/sampling moments, KL
identities, transforms, gradient flow through log_prob.

Reference test model: test/distribution/test_distribution_*.py (numeric
checks against scipy); here closed-form + Monte-Carlo cross-checks.
"""
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def a(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


SAMPLE_N = 20000


class TestMomentsAndDensities:
    """sample moments ≈ analytic mean/variance; log_prob integrates."""

    CASES = [
        ("normal", lambda: D.Normal(1.5, 2.0)),
        ("uniform", lambda: D.Uniform(-1.0, 3.0)),
        ("laplace", lambda: D.Laplace(0.5, 1.5)),
        ("gumbel", lambda: D.Gumbel(0.0, 2.0)),
        ("exponential", lambda: D.Exponential(2.0)),
        ("gamma", lambda: D.Gamma(3.0, 2.0)),
        ("beta", lambda: D.Beta(2.0, 5.0)),
        ("lognormal", lambda: D.LogNormal(0.2, 0.4)),
        ("bernoulli", lambda: D.Bernoulli(0.3)),
        ("geometric", lambda: D.Geometric(0.4)),
        ("poisson", lambda: D.Poisson(3.0)),
        ("binomial", lambda: D.Binomial(10, 0.3)),
    ]

    @pytest.mark.parametrize("name,mk", CASES, ids=[c[0] for c in CASES])
    def test_sample_moments(self, name, mk):
        paddle.seed(0)
        d = mk()
        s = a(d.sample((SAMPLE_N,)))
        mean = a(d.mean)
        var = a(d.variance)
        np.testing.assert_allclose(s.mean(0), mean, rtol=0.1, atol=0.05)
        np.testing.assert_allclose(s.var(0), var, rtol=0.15, atol=0.08)

    @pytest.mark.parametrize("name,mk", CASES, ids=[c[0] for c in CASES])
    def test_log_prob_finite_at_samples(self, name, mk):
        paddle.seed(1)
        d = mk()
        s = d.sample((64,))
        lp = a(d.log_prob(s))
        assert np.isfinite(lp).all()

    def test_normal_log_prob_value(self):
        d = D.Normal(0.0, 1.0)
        lp = float(a(d.log_prob(paddle.to_tensor(0.0))))
        assert abs(lp - (-0.5 * math.log(2 * math.pi))) < 1e-6

    def test_entropy_vs_monte_carlo(self):
        paddle.seed(2)
        for d in [D.Normal(0.0, 2.0), D.Laplace(1.0, 0.5),
                  D.Gamma(2.0, 1.0), D.Beta(2.0, 3.0),
                  D.Exponential(1.5), D.Gumbel(0.0, 1.0)]:
            s = d.sample((SAMPLE_N,))
            mc = -a(d.log_prob(s)).mean()
            np.testing.assert_allclose(a(d.entropy()), mc, rtol=0.05,
                                       atol=0.03)

    def test_categorical(self):
        paddle.seed(0)
        probs = np.array([0.2, 0.3, 0.5], np.float32)
        d = D.Categorical(logits=np.log(probs))
        s = a(d.sample((SAMPLE_N,)))
        freq = np.bincount(s.astype(int), minlength=3) / SAMPLE_N
        np.testing.assert_allclose(freq, probs, atol=0.02)
        lp = a(d.log_prob(paddle.to_tensor(np.array([0, 1, 2]))))
        np.testing.assert_allclose(lp, np.log(probs), atol=1e-5)
        ent = a(d.entropy())
        np.testing.assert_allclose(ent, -(probs * np.log(probs)).sum(),
                                   atol=1e-5)

    def test_dirichlet(self):
        paddle.seed(0)
        c = np.array([2.0, 3.0, 5.0], np.float32)
        d = D.Dirichlet(c)
        s = a(d.sample((SAMPLE_N,)))
        np.testing.assert_allclose(s.mean(0), c / c.sum(), atol=0.01)
        assert np.allclose(s.sum(-1), 1.0, atol=1e-5)
        lp = a(d.log_prob(paddle.to_tensor(
            np.array([0.2, 0.3, 0.5], np.float32))))
        assert np.isfinite(lp)

    def test_multivariate_normal(self):
        paddle.seed(0)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        d = D.MultivariateNormal(np.zeros(2, np.float32),
                                 covariance_matrix=cov)
        s = a(d.sample((SAMPLE_N,)))
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.1)
        # entropy closed form
        ref = 0.5 * np.log(np.linalg.det(2 * math.pi * math.e * cov))
        np.testing.assert_allclose(a(d.entropy()), ref, rtol=1e-5)

    def test_student_t_chi2(self):
        paddle.seed(0)
        t = D.StudentT(5.0, 1.0, 2.0)
        s = a(t.sample((SAMPLE_N,)))
        np.testing.assert_allclose(s.mean(), 1.0, atol=0.1)
        c = D.Chi2(4.0)
        np.testing.assert_allclose(a(c.mean), 4.0, atol=1e-5)
        np.testing.assert_allclose(a(c.variance), 8.0, atol=1e-4)


class TestKL:
    def test_kl_normal_closed_form(self):
        p = D.Normal(0.0, 1.0)
        q = D.Normal(1.0, 2.0)
        kl = float(a(D.kl_divergence(p, q)))
        ref = math.log(2.0) + (1 + 1) / 8.0 - 0.5
        assert abs(kl - ref) < 1e-6

    def test_kl_self_zero(self):
        for d in [D.Normal(0.5, 1.5), D.Beta(2.0, 3.0),
                  D.Gamma(2.0, 2.0), D.Exponential(1.0),
                  D.Bernoulli(0.3), D.Geometric(0.4), D.Poisson(2.0),
                  D.Laplace(0.0, 1.0),
                  D.Categorical(logits=np.zeros(4, np.float32))]:
            kl = a(D.kl_divergence(d, d))
            np.testing.assert_allclose(kl, 0.0, atol=1e-5)

    @pytest.mark.parametrize("p,q", [
        (lambda: D.Normal(0.0, 1.0), lambda: D.Normal(0.7, 1.4)),
        (lambda: D.Gamma(2.0, 1.0), lambda: D.Gamma(3.0, 2.0)),
        (lambda: D.Beta(2.0, 2.0), lambda: D.Beta(3.0, 1.5)),
        (lambda: D.Exponential(1.0), lambda: D.Exponential(2.5)),
        (lambda: D.Laplace(0.0, 1.0), lambda: D.Laplace(0.5, 2.0)),
    ], ids=["normal", "gamma", "beta", "exponential", "laplace"])
    def test_kl_vs_monte_carlo(self, p, q):
        paddle.seed(3)
        p, q = p(), q()
        s = p.sample((SAMPLE_N,))
        mc = (a(p.log_prob(s)) - a(q.log_prob(s))).mean()
        np.testing.assert_allclose(a(D.kl_divergence(p, q)), mc,
                                   rtol=0.1, atol=0.02)

    def test_kl_mvn(self):
        p = D.MultivariateNormal(np.zeros(2, np.float32),
                                 covariance_matrix=np.eye(2, dtype=np.float32))
        q = D.MultivariateNormal(np.ones(2, np.float32),
                                 covariance_matrix=2 * np.eye(2, dtype=np.float32))
        # closed form: 0.5*(tr(S2^-1 S1) + dTS2^-1d - k + ln det S2/S1)
        #            = 0.5*(1 + 1 - 2 + ln 4)
        ref = 0.5 * (1.0 + 1.0 - 2 + 2 * math.log(2.0))
        np.testing.assert_allclose(float(a(D.kl_divergence(p, q))), ref,
                                   rtol=1e-5)

    def test_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Normal(0.0, 1.0), D.Beta(1.0, 1.0))


class TestGradients:
    def test_log_prob_grad_wrt_params(self):
        loc = paddle.to_tensor(np.float32(0.5))
        scale = paddle.to_tensor(np.float32(1.0))
        loc.stop_gradient = False
        scale.stop_gradient = False
        d = D.Normal(loc, scale)
        lp = d.log_prob(paddle.to_tensor(np.float32(1.5)))
        lp.backward()
        # d/dloc log N(x;loc,s) = (x-loc)/s^2 = 1.0
        np.testing.assert_allclose(a(loc.grad), 1.0, atol=1e-6)

    def test_rsample_pathwise_grad(self):
        paddle.seed(0)
        loc = paddle.to_tensor(np.float32(0.0))
        loc.stop_gradient = False
        d = D.Normal(loc, 1.0)
        s = d.rsample((256,))
        loss = (s ** 2).mean()
        loss.backward()
        assert loc.grad is not None
        assert np.isfinite(a(loc.grad))


class TestTransforms:
    @pytest.mark.parametrize("t,x", [
        (D.ExpTransform(), 0.7),
        (D.AffineTransform(1.0, 3.0), 0.7),
        (D.SigmoidTransform(), 0.7),
        (D.TanhTransform(), 0.3),
        (D.PowerTransform(2.0), 0.7),
    ], ids=["exp", "affine", "sigmoid", "tanh", "power"])
    def test_roundtrip_and_jacobian(self, t, x):
        xv = paddle.to_tensor(np.float32(x))
        y = t.forward(xv)
        back = t.inverse(y)
        np.testing.assert_allclose(a(back), x, rtol=1e-5, atol=1e-6)
        # fldj vs autodiff of forward
        f = lambda v: t._forward(v)
        num = float(jnp.log(jnp.abs(jax.grad(f)(jnp.float32(x)))))
        np.testing.assert_allclose(float(a(
            t.forward_log_det_jacobian(xv))), num, rtol=1e-4, atol=1e-5)

    def test_chain(self):
        t = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                              D.ExpTransform()])
        x = paddle.to_tensor(np.float32(0.5))
        y = t.forward(x)
        np.testing.assert_allclose(a(y), math.exp(1.0), rtol=1e-6)
        np.testing.assert_allclose(a(t.inverse(y)), 0.5, rtol=1e-5)

    def test_stickbreaking_simplex(self):
        t = D.StickBreakingTransform()
        x = paddle.to_tensor(np.array([0.3, -0.2, 0.8], np.float32))
        y = a(t.forward(x))
        assert y.shape == (4,)
        np.testing.assert_allclose(y.sum(), 1.0, atol=1e-6)
        np.testing.assert_allclose(a(t.inverse(paddle.to_tensor(y))),
                                   a(x), atol=1e-5)

    def test_transformed_distribution_lognormal(self):
        paddle.seed(0)
        td = D.TransformedDistribution(D.Normal(0.2, 0.4),
                                       [D.ExpTransform()])
        ln = D.LogNormal(0.2, 0.4)
        x = paddle.to_tensor(np.array([0.5, 1.0, 2.0], np.float32))
        np.testing.assert_allclose(a(td.log_prob(x)), a(ln.log_prob(x)),
                                   rtol=1e-5)

    def test_independent(self):
        d = D.Independent(D.Normal(np.zeros(3, np.float32),
                                   np.ones(3, np.float32)), 1)
        assert d.event_shape == (3,)
        lp = d.log_prob(paddle.to_tensor(np.zeros(3, np.float32)))
        np.testing.assert_allclose(
            a(lp), 3 * (-0.5 * math.log(2 * math.pi)), rtol=1e-6)
