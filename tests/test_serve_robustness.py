"""Serve-plane fault tolerance (ISSUE 9): SLO-aware admission,
deadlines, load shedding, fault recovery and SIGTERM drain around
`ContinuousBatcher`.

The contracts under test:

  * SLO — admission walks classes in priority order, strict FIFO by
    arrival within a class; a class head deferred by KV-pool pressure
    blocks its own and lower classes (starvation freedom: a stream of
    short prompts can never indefinitely bypass a deferred long one).
  * SHEDDING — a bounded queue (`FLAGS_serve_queue_depth`) sheds the
    lowest-SLO newest-arrival QUEUED request; a queued request past
    its deadline sheds as a deadline miss; an in-flight decode is
    NEVER shed.  Every submitted id still surfaces in run()'s results.
  * RECOVERY — injected faults at the four serve points
    (`serve.admit`, `serve.kv_alloc`, `serve.chunk`, `serve.decode`)
    fire and recover: retried admissions, deferred allocations,
    retried chunks (carries untouched), and poisoned slots evicted +
    requeued — with every surviving request's output BIT-EXACT equal
    to its isolated fault-free run, and `tokens_produced` deduped by
    request id across requeues (satellite regression).
  * DRAIN — `guard.drain_requested()` closes admissions (queued shed
    with reason "drain"), in-flight decodes finish within
    PADDLE_DRAIN_GRACE, grace expiry flushes partial results.
  * CONTRACT — robustness flags on, a mixed-SLO multi-length workload
    still compiles exactly 2 serve-step programs (the r6 pin).
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fault, guard
from paddle_tpu.inference import ContinuousBatcher, SLO_CLASSES
from paddle_tpu.models.llama import (LlamaForCausalLM,
                                     llama_tiny_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


@pytest.fixture(autouse=True)
def _clean_drain():
    guard.clear_drain()
    yield
    guard.clear_drain()


def _isolated(model, ids, n):
    out = model.generate(paddle.to_tensor(np.asarray([ids], np.int32)),
                         max_new_tokens=n)
    return np.asarray(out.value)[0]


def _bat(model, **kw):
    geom = dict(max_batch_size=2, max_len=64, chunk=4, prefill_chunk=4)
    geom.update(kw)
    return ContinuousBatcher(model, **geom)


def _assert_no_leak(bat):
    st = bat.stats()
    assert st["requests_submitted"] == st["requests_completed"] \
        + st["requests_shed"], st
    assert sorted(bat._finished) \
        == sorted(range(st["requests_submitted"])), st
    return st


# ---------------------------------------------------------------------------
# SLO classes and admission order


def test_slo_priority_admission_order(model):
    """With one slot, a later-submitted interactive request is
    admitted before earlier batch/best_effort ones — and everything
    still matches isolation."""
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 6, 7, 4)]
    bat = _bat(model, max_batch_size=1)
    r_busy = bat.submit(prompts[0], 6, slo="batch")
    bat.step()                                # r_busy in flight
    r_be = bat.submit(prompts[1], 4, slo="best_effort")
    r_b = bat.submit(prompts[2], 4, slo="batch")
    r_int = bat.submit(prompts[3], 4, slo="interactive")
    admit_order = []
    seen = {req.req_id for req in bat._slots if req is not None}
    while bat.queued or bat.active:
        bat.step()
        for req in bat._slots:
            if req is not None and req.req_id not in seen:
                seen.add(req.req_id)
                admit_order.append(req.req_id)
    assert admit_order[0] == r_int, admit_order
    assert admit_order.index(r_b) < admit_order.index(r_be)
    outs = {rid: bat._finished[rid].output()
            for rid in (r_busy, r_be, r_b, r_int)}
    for rid, p, n in ((r_busy, prompts[0], 6), (r_be, prompts[1], 4),
                      (r_b, prompts[2], 4), (r_int, prompts[3], 4)):
        np.testing.assert_array_equal(outs[rid],
                                      _isolated(model, p, n))
    _assert_no_leak(bat)


def test_deferred_long_prompt_not_starved_by_short_stream(model):
    """Satellite regression (starvation freedom): a long prompt
    deferred under KV-pool pressure keeps its FIFO position — a stream
    of later short prompts in the SAME class must not be admitted past
    it, even though they would fit the free pages."""
    rng = np.random.RandomState(9)
    short0 = rng.randint(1, 128, 4).astype(np.int32)
    long_p = rng.randint(1, 128, 32).astype(np.int32)
    shorts = [rng.randint(1, 128, 4).astype(np.int32)
              for _ in range(3)]
    # 7 usable pages @8 rows: the running short holds 2, the long
    # needs 6 -> deferred; the later shorts (2 pages each) WOULD fit
    bat = _bat(model, page_size=8, num_pages=8)
    r0 = bat.submit(short0, 4, slo="batch")
    bat.step()
    r_long = bat.submit(long_p, 4, slo="batch")
    r_shorts = [bat.submit(p, 4, slo="batch") for p in shorts]
    admit_step = {}
    step_no = 0
    while bat.queued or bat.active:
        bat.step()
        step_no += 1
        for req in bat._slots:
            if req is not None and req.req_id not in admit_step:
                admit_step[req.req_id] = step_no
    assert all(admit_step[r_long] <= admit_step[r]
               for r in r_shorts), admit_step
    np.testing.assert_array_equal(bat._finished[r_long].output(),
                                  _isolated(model, long_p, 4))
    for r, p in zip(r_shorts, shorts):
        np.testing.assert_array_equal(bat._finished[r].output(),
                                      _isolated(model, p, 4))
    st = _assert_no_leak(bat)
    assert st["requests_shed"] == 0
    assert r0 in bat._finished


# ---------------------------------------------------------------------------
# load shedding: bounded queue + deadlines


def test_queue_depth_sheds_lowest_slo_newest_first(model):
    """Overflow sheds best_effort before batch before interactive,
    newest arrival first — and never an in-flight request."""
    rng = np.random.RandomState(5)
    mk = lambda L: rng.randint(1, 128, L).astype(np.int32)
    paddle.set_flags({"FLAGS_serve_queue_depth": 2})
    try:
        bat = _bat(model, max_batch_size=1)
        r_fly = bat.submit(mk(5), 4, slo="best_effort")
        bat.step()                         # best_effort IN FLIGHT
        r_be = bat.submit(mk(4), 4, slo="best_effort")
        r_int = bat.submit(mk(6), 4, slo="interactive")
        # queue full: the queued best_effort sheds (NOT the in-flight
        # best_effort, NOT the incoming interactive)
        r_b = bat.submit(mk(7), 4, slo="batch")
        outs = bat.run()
    finally:
        paddle.set_flags({"FLAGS_serve_queue_depth": 0})
    fin = bat._finished
    assert fin[r_be].shed and fin[r_be].shed_reason == "queue_full"
    assert not fin[r_fly].shed and not fin[r_int].shed \
        and not fin[r_b].shed
    assert len(outs[r_be]) == 0
    st = _assert_no_leak(bat)
    assert st["requests_shed"] == 1
    assert st["shed_by_class"]["best_effort"] == 1


def test_queue_depth_incoming_lowest_sheds_itself(model):
    """When the incoming request IS the lowest-priority newest, it is
    the victim; higher-priority queued requests are untouched."""
    rng = np.random.RandomState(6)
    mk = lambda L: rng.randint(1, 128, L).astype(np.int32)
    paddle.set_flags({"FLAGS_serve_queue_depth": 1})
    try:
        bat = _bat(model, max_batch_size=1)
        r1 = bat.submit(mk(5), 4, slo="interactive")
        bat.step()
        r2 = bat.submit(mk(4), 4, slo="interactive")
        r3 = bat.submit(mk(6), 4, slo="best_effort")   # sheds itself
        bat.run()
    finally:
        paddle.set_flags({"FLAGS_serve_queue_depth": 0})
    assert bat._finished[r3].shed \
        and bat._finished[r3].shed_reason == "queue_full"
    assert not bat._finished[r2].shed and not bat._finished[r1].shed
    _assert_no_leak(bat)


def test_deadline_miss_sheds_queued_only(model):
    """A queued request past its deadline sheds as a deadline miss;
    the in-flight request (even with an already-expired deadline) is
    never touched."""
    rng = np.random.RandomState(8)
    p1, p2, p3 = (rng.randint(1, 128, L).astype(np.int32)
                  for L in (5, 7, 4))
    bat = _bat(model, max_batch_size=1)
    r1 = bat.submit(p1, 8, deadline_ms=1000.0)
    bat.step()                                  # r1 admitted
    # jump the batcher's clock: r1's deadline is now LONG past while
    # it is in flight — still untouchable; r2's tiny deadline expires
    # in the queue deterministically
    real_now = bat._now
    bat._now = lambda: real_now() + 10.0
    r2 = bat.submit(p2, 4, deadline_ms=0.001, slo="interactive")
    r3 = bat.submit(p3, 4)                      # no deadline
    outs = bat.run()
    fin = bat._finished
    assert not fin[r1].shed                     # in flight: untouched
    assert fin[r2].shed and fin[r2].shed_reason == "deadline"
    assert not fin[r3].shed
    np.testing.assert_array_equal(outs[r1], _isolated(model, p1, 8))
    np.testing.assert_array_equal(outs[r3], _isolated(model, p3, 4))
    st = _assert_no_leak(bat)
    assert st["deadline_misses"] == 1


def test_default_deadline_flag(model):
    """FLAGS_serve_default_deadline_ms applies to requests that pass
    no explicit deadline."""
    rng = np.random.RandomState(12)
    bat = _bat(model)
    paddle.set_flags({"FLAGS_serve_default_deadline_ms": 60000.0})
    try:
        rid = bat.submit(rng.randint(1, 128, 4).astype(np.int32), 4)
    finally:
        paddle.set_flags({"FLAGS_serve_default_deadline_ms": 0.0})
    req = next(r for q in bat._queues.values() for r in q
               if r.req_id == rid)
    assert req.deadline is not None
    bat.run()


# ---------------------------------------------------------------------------
# fault recovery at the four serve points


def test_decode_fault_evicts_requeues_bitexact(model):
    """A poisoned slot mid-generation: pages evicted, request requeued
    at its arrival position, re-decode bit-exact — while the other
    slot keeps decoding.  Satellite regression: the discarded
    pre-fault tokens never reach tokens_produced (dedupe by request
    id)."""
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 9, 7, 4)]
    new = [6, 5, 7, 4]
    with fault.scope("serve.decode:step=3:mode=error"):
        bat = _bat(model)
        rids = [bat.submit(p, n) for p, n in zip(prompts, new)]
        outs = bat.run()
        st = bat.stats()
        fired = fault.fired_counts().get("serve.decode", 0)
    assert fired == 1
    assert st["requests_requeued"] >= 1, st
    for rid, p, n in zip(rids, prompts, new):
        np.testing.assert_array_equal(outs[rid],
                                      _isolated(model, p, n))
    # emitted-token accounting dedupes the requeued request's
    # re-decoded tokens: the total equals exactly what the outputs
    # hold, not old + re-decoded
    assert st["tokens_produced"] == sum(len(outs[r]) for r in rids), st
    assert st["requests_shed"] == 0
    _assert_no_leak(bat)


def test_decode_fault_dense_layout(model):
    """The evict+requeue path has no paged dependency: the dense
    layout recovers the same way."""
    rng = np.random.RandomState(14)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 8)]
    with fault.scope("serve.decode:step=2:mode=error"):
        bat = _bat(model, kv_layout="dense")
        rids = [bat.submit(p, 5) for p in prompts]
        outs = bat.run()
        st = bat.stats()
    assert st["requests_requeued"] >= 1
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid],
                                      _isolated(model, p, 5))
    _assert_no_leak(bat)


def test_decode_fault_budget_exhaustion_sheds(model):
    """A slot that faults on EVERY chunk exhausts its retry budget
    (FLAGS_serve_retry_budget) and is shed instead of spinning the
    batch forever; the co-resident request still completes."""
    rng = np.random.RandomState(15)
    p_ok = rng.randint(1, 128, 4).astype(np.int32)
    p_bad = rng.randint(1, 128, 5).astype(np.int32)
    with fault.scope("serve.decode:times=*:mode=error:match=slot1"):
        bat = _bat(model)
        r_ok = bat.submit(p_ok, 5)        # slot 0
        r_bad = bat.submit(p_bad, 5)      # slot 1 — always poisoned
        outs = bat.run()
        st = bat.stats()
    fin = bat._finished
    assert fin[r_bad].shed and fin[r_bad].shed_reason == "decode_fault"
    assert not fin[r_ok].shed
    np.testing.assert_array_equal(outs[r_ok],
                                  _isolated(model, p_ok, 5))
    assert st["requests_requeued"] >= 1
    _assert_no_leak(bat)


def test_admit_fault_retries_then_completes(model):
    rng = np.random.RandomState(16)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 7, 6)]
    with fault.scope("serve.admit:step=2:mode=error"):
        bat = _bat(model)
        rids = [bat.submit(p, 5) for p in prompts]
        outs = bat.run()
        st = bat.stats()
        fired = fault.fired_counts().get("serve.admit", 0)
    assert fired == 1
    assert st["requests_shed"] == 0 and st["requests_completed"] == 3
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid],
                                      _isolated(model, p, 5))
    _assert_no_leak(bat)


def test_admit_reject_sheds_request(model):
    rng = np.random.RandomState(17)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 7)]
    with fault.scope("serve.admit:step=1:mode=skip"):
        bat = _bat(model)
        rids = [bat.submit(p, 5) for p in prompts]
        outs = bat.run()
    fin = bat._finished
    assert fin[rids[0]].shed \
        and fin[rids[0]].shed_reason == "admit_fault"
    np.testing.assert_array_equal(outs[rids[1]],
                                  _isolated(model, prompts[1], 5))
    _assert_no_leak(bat)


def test_kv_alloc_fault_defers_fifo(model):
    """A transient allocator fault defers the head FIFO-in-place: the
    deferred request is still admitted BEFORE later arrivals of its
    class once the fault clears."""
    rng = np.random.RandomState(18)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (6, 5, 4)]
    with fault.scope("serve.kv_alloc:step=1:times=2:mode=error"):
        bat = _bat(model, max_batch_size=1)
        rids = [bat.submit(p, 4) for p in prompts]
        admit_order = []
        seen = set()
        while bat.queued or bat.active:
            bat.step()
            for req in bat._slots:
                if req is not None and req.req_id not in seen:
                    seen.add(req.req_id)
                    admit_order.append(req.req_id)
        st = bat.stats()
        fired = fault.fired_counts().get("serve.kv_alloc", 0)
    assert fired == 2
    assert admit_order == rids            # FIFO held through the fault
    assert st["requests_shed"] == 0
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(bat._finished[rid].output(),
                                      _isolated(model, p, 4))
    _assert_no_leak(bat)


def test_chunk_fault_retries_without_losing_state(model):
    """serve.chunk fires BEFORE the donated carries are touched: the
    chunk simply retries at the next boundary and every output is
    bit-exact."""
    rng = np.random.RandomState(19)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 9, 6)]
    with fault.scope("serve.chunk:step=2:times=2:mode=error"):
        bat = _bat(model)
        rids = [bat.submit(p, 5) for p in prompts]
        outs = bat.run()
        st = bat.stats()
    assert st["chunk_retries"] == 2, st
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid],
                                      _isolated(model, p, 5))
    _assert_no_leak(bat)


def test_explicit_zero_deadline_means_none(model):
    """Regression (review): deadline_ms=0 passed EXPLICITLY follows
    the same '0 = no deadline' convention as the flag — the request
    must complete, not be shed at the first boundary."""
    rng = np.random.RandomState(28)
    p = rng.randint(1, 128, 5).astype(np.int32)
    bat = _bat(model, max_batch_size=1)
    rid = bat.submit(p, 4, deadline_ms=0)
    outs = bat.run()
    assert not bat._finished[rid].shed
    np.testing.assert_array_equal(outs[rid], _isolated(model, p, 4))


def test_persistent_chunk_fault_raises_past_budget(model):
    """Regression (review): a times=* serve.chunk fault cannot spin
    run() forever — past FLAGS_serve_retry_budget consecutive chunk
    faults the FaultError surfaces to the caller."""
    rng = np.random.RandomState(29)
    with fault.scope("serve.chunk:times=*:mode=error"):
        bat = _bat(model)
        bat.submit(rng.randint(1, 128, 5).astype(np.int32), 4)
        with pytest.raises(fault.FaultError):
            bat.run()
    assert bat.stats()["chunk_retries"] > 1


def test_watched_last_reported_resets_per_entry():
    """Regression (review): one reported hang must not leak
    last_reported=True into later entries — especially entries made
    AFTER the watchdog is disabled (start_task returns None)."""
    import time as _time
    from paddle_tpu.distributed.watchdog import watched
    w = watched("serve.chunk", timeout=0.05)
    with w:
        _time.sleep(0.6)                  # ages past the deadline
    assert w.last_reported
    w.timeout = None
    paddle.set_flags({"FLAGS_stop_check_timeout": 0})
    with w:                               # watchdog disabled
        pass
    assert not w.last_reported


def test_hung_chunk_detected_by_watchdog(model):
    """A chunk that ages past FLAGS_stop_check_timeout while in flight
    is reported by the comm watchdog and counted as hung; the outputs
    are unaffected."""
    from paddle_tpu.distributed.watchdog import get_comm_task_manager
    rng = np.random.RandomState(20)
    p = rng.randint(1, 128, 5).astype(np.int32)
    mgr = get_comm_task_manager()
    n_reports = len(mgr.timeout_log)
    paddle.set_flags({"FLAGS_stop_check_timeout": 0.05})
    try:
        with fault.scope("serve.chunk:step=1:mode=delay:secs=0.8"):
            bat = _bat(model)
            rid = bat.submit(p, 5)
            outs = bat.run()
            st = bat.stats()
    finally:
        paddle.set_flags({"FLAGS_stop_check_timeout": 0})
    assert st["hung_chunks"] >= 1, st
    assert len(mgr.timeout_log) > n_reports
    assert any(name == "serve.chunk"
               for name, _, _ in mgr.timeout_log[n_reports:])
    np.testing.assert_array_equal(outs[rid], _isolated(model, p, 5))


# ---------------------------------------------------------------------------
# SIGTERM drain


def test_drain_sheds_queue_finishes_in_flight(model):
    rng = np.random.RandomState(22)
    p1, p2 = (rng.randint(1, 128, L).astype(np.int32) for L in (5, 7))
    bat = _bat(model, max_batch_size=1)
    r1 = bat.submit(p1, 6)
    r2 = bat.submit(p2, 6)
    bat.step()                            # r1 in flight, r2 queued
    guard.request_drain()
    outs = bat.run()
    assert bat.drained
    fin = bat._finished
    assert fin[r2].shed and fin[r2].shed_reason == "drain"
    # the in-flight decode FINISHED inside the grace window
    assert not fin[r1].partial
    np.testing.assert_array_equal(outs[r1], _isolated(model, p1, 6))
    st = _assert_no_leak(bat)
    assert st["drained"]


def test_drain_closes_submissions(model):
    """A submit() after the drain engaged is accounted and immediately
    shed — admissions are closed."""
    rng = np.random.RandomState(23)
    bat = _bat(model, max_batch_size=1)
    r1 = bat.submit(rng.randint(1, 128, 4).astype(np.int32), 4)
    bat.step()
    guard.request_drain()
    bat.step()                            # drain engages
    r2 = bat.submit(rng.randint(1, 128, 5).astype(np.int32), 4)
    outs = bat.run()
    assert bat._finished[r2].shed \
        and bat._finished[r2].shed_reason == "drain"
    assert len(outs[r2]) == 0 and r1 in outs
    _assert_no_leak(bat)


def test_drain_grace_expiry_flushes_partial(model, monkeypatch):
    """Grace 0: the in-flight request is flushed as a PARTIAL result —
    delivered with the tokens it produced, marked partial, counted as
    completed (not shed)."""
    monkeypatch.setenv("PADDLE_DRAIN_GRACE", "0")
    rng = np.random.RandomState(24)
    p = rng.randint(1, 128, 5).astype(np.int32)
    bat = _bat(model, max_batch_size=1)
    rid = bat.submit(p, 24)               # needs many decode chunks
    bat.step()
    guard.request_drain()
    outs = bat.run()
    req = bat._finished[rid]
    assert req.partial and not req.shed
    assert 0 < len(outs[rid]) < 24
    # the partial prefix is bit-exact: flushed tokens came from
    # completed chunks
    np.testing.assert_array_equal(
        outs[rid], _isolated(model, p, 24)[: len(outs[rid])])
    st = _assert_no_leak(bat)
    assert st["requests_completed"] == 1


# ---------------------------------------------------------------------------
# telemetry + program contract + CLI wiring


def test_shed_requeue_deadline_events(model):
    from paddle_tpu import telemetry
    rng = np.random.RandomState(25)
    mk = lambda L: rng.randint(1, 128, L).astype(np.int32)
    sink = telemetry.add_sink(telemetry.MemorySink())
    try:
        paddle.set_flags({"FLAGS_serve_queue_depth": 1})
        try:
            with fault.scope("serve.decode:step=2:mode=error"):
                bat = _bat(model, max_batch_size=1)
                bat.submit(mk(5), 4)
                bat.step()
                bat.submit(mk(6), 4, deadline_ms=0.001)
                bat.submit(mk(4), 4, slo="best_effort")  # overflow
                bat.run()
        finally:
            paddle.set_flags({"FLAGS_serve_queue_depth": 0})
    finally:
        telemetry.remove_sink(sink)
    evs = {}
    for r in sink.records:
        evs.setdefault(r["event"], []).append(r)
    assert "serve.shed" in evs and "serve.requeue" in evs, sorted(evs)
    assert "serve.deadline_miss" in evs, sorted(evs)
    shed = evs["serve.shed"]
    assert all({"req", "slo", "reason"} <= set(e) for e in shed)
    reasons = {e["reason"] for e in shed}
    assert "queue_full" in reasons and "deadline" in reasons
    st = bat.stats()
    assert st["requests_shed"] == len(shed)
    assert st["requests_requeued"] == len(evs["serve.requeue"])
    assert st["deadline_misses"] == len(evs["serve.deadline_miss"])


def test_flags_on_slo_mix_never_recompiles(model):
    """Acceptance pin: with the robustness flags ON, prompt length and
    SLO mix still never reach a program shape — exactly 2 compiled
    serve-step programs (the recompile_guard raises with avals on
    violation)."""
    from paddle_tpu.analysis import recompile_guard
    rng = np.random.RandomState(26)
    paddle.set_flags({"FLAGS_serve_queue_depth": 16,
                      "FLAGS_serve_default_deadline_ms": 60000.0})
    try:
        bat = _bat(model)
        for L, slo in ((3, "interactive"), (6, "batch"),
                       (9, "best_effort"), (12, "interactive"),
                       (15, "batch"), (18, "best_effort")):
            bat.submit(rng.randint(1, 128, L).astype(np.int32), 4,
                       slo=slo)
        with recompile_guard(max_programs=2, match="serve_step"):
            bat.run()
    finally:
        paddle.set_flags({"FLAGS_serve_queue_depth": 0,
                          "FLAGS_serve_default_deadline_ms": 0.0})
    st = _assert_no_leak(bat)
    assert st["compiled_programs"] == 2
    assert st["requests_shed"] == 0


def test_chaos_serve_selftest_cli():
    """Tier-1 wiring (ISSUE 9 satellite): one planted fault per serve
    injection point + the SIGTERM drain e2e, all must fire and
    recover — `chaos_check --serve --selftest` exits 0."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check as cli
    finally:
        sys.path.pop(0)
    assert cli.main(["--serve", "--selftest"]) == 0


def test_slo_validation_and_api(model):
    rng = np.random.RandomState(27)
    bat = _bat(model)
    with pytest.raises(ValueError, match="SLO"):
        bat.submit(rng.randint(1, 128, 4).astype(np.int32), 4,
                   slo="platinum")
    assert SLO_CLASSES == ("interactive", "batch", "best_effort")
