"""Serve-fleet router (ISSUE 15): prefix-aware, SLO-aware routing
across N ContinuousBatcher replicas with lossless drain-and-requeue.

The contracts under test:

  * POLICY — pick_replica() in isolation over synthetic views: prefix
    hit beats a shorter queue, the interactive SLO-attainment floor
    overrides prefix affinity, a draining replica is never picked,
    ties break deterministically.
  * PROBE — PageAllocator.prefix_match_len is a pure read-only trie
    walk: no page pinned, no LRU clock tick, the eviction order
    byte-identical with or without a probe in between.
  * ATOMIC QUEUES — the batcher's per-class queue snapshot is one
    consistent view against a concurrent submit storm (the ISSUE 15
    torn-read bugfix).
  * FLEET — a 2-replica router serves the workload bit-exact vs a
    single-replica reference; replica kill migrates queued AND
    mid-decode requests losslessly (no duplicate streamed tokens,
    survivor KV pools leak-free) — `chaos_check --serve
    --replica-kill` wired tier-1 through run_router_kill.
  * HOST-PLANE — per-replica compiled serve programs stay exactly 2
    per shape (shared through the model program cache), program keys
    untouched by routing.
  * KV PLANE — ReplicaPublisher/discover_replicas round-trip the
    router views through a real launch KVServer (the r14 FleetSink
    key schema).
"""
import os
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.inference import (ContinuousBatcher, ServeRouter,
                                  fleet_serve, pick_replica)
from paddle_tpu.inference.paged_kv import PageAllocator
from paddle_tpu.models.llama import (LlamaForCausalLM,
                                     llama_tiny_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _bat(model, **kw):
    geom = dict(max_batch_size=1, max_len=64, chunk=4, prefill_chunk=4)
    geom.update(kw)
    return ContinuousBatcher(model, **geom)


def _view(replica, hit=0, queued=0, active=0, slots=1, draining=False,
          shed_rate=0.0, interactive_att=None):
    return {"replica": replica, "prefix_hit_tokens": hit,
            "queued": queued, "active": active, "slots": slots,
            "draining": draining, "shed_rate": shed_rate,
            "attainment": {"interactive": interactive_att,
                           "batch": None, "best_effort": None}}


# ---------------------------------------------------------------------------
# routing policy in isolation (no batcher construction)
# ---------------------------------------------------------------------------

def test_pick_prefix_hit_beats_shorter_queue():
    # replica 0 idle but cold; replica 1 queues 1 deep but holds a
    # 64-token resident prefix — the skipped prefill outweighs the wait
    views = [_view(0, hit=0, queued=0), _view(1, hit=64, queued=1)]
    assert pick_replica(views, prefix_weight=1.0) == 1


def test_pick_load_wins_when_prefix_small():
    # a 4-token hit does not buy a 3-deep queue
    views = [_view(0, hit=0, queued=0), _view(1, hit=4, queued=3)]
    assert pick_replica(views, prefix_weight=1.0) == 0


def test_pick_prefix_weight_zero_disables_affinity():
    views = [_view(0, hit=0, queued=0), _view(1, hit=512, queued=1)]
    assert pick_replica(views, prefix_weight=0.0) == 0


def test_pick_attainment_floor_overrides_prefix():
    # interactive traffic never lands on a replica missing its floor
    # while another has headroom — even against a huge prefix hit
    views = [_view(0, hit=256, interactive_att=0.3),
             _view(1, hit=0, interactive_att=0.99)]
    assert pick_replica(views, slo="interactive",
                        attainment_floor=0.9) == 1
    # batch traffic is not floored: the prefix wins
    assert pick_replica(views, slo="batch",
                        attainment_floor=0.9) == 0
    # no attainment signal yet = headroom, not failure
    views = [_view(0, hit=256, interactive_att=None),
             _view(1, hit=0, interactive_att=0.99)]
    assert pick_replica(views, slo="interactive",
                        attainment_floor=0.9) == 0


def test_pick_floor_waived_when_everyone_below():
    # degraded service beats no service: all below floor -> best score
    views = [_view(0, hit=32, interactive_att=0.2),
             _view(1, hit=0, interactive_att=0.1)]
    assert pick_replica(views, slo="interactive",
                        attainment_floor=0.9) == 0


def test_pick_draining_never_picked():
    views = [_view(0, hit=512, draining=True), _view(1, queued=5)]
    assert pick_replica(views) == 1
    assert pick_replica([_view(0, draining=True),
                         _view(1, draining=True)]) is None


def test_pick_deterministic_tie_break():
    # identical scores -> lowest replica id, every time
    views = [_view(2), _view(0), _view(1)]
    assert all(pick_replica(list(views)) == 0 for _ in range(8))
    # fewer queued breaks a score tie before the id does (hit pays
    # exactly for the queue difference at queue_cost=16)
    views = [_view(0, hit=16, queued=1), _view(1, hit=0, queued=0)]
    assert pick_replica(views, prefix_weight=1.0, queue_cost=16.0) == 1


def test_pick_shed_rate_penalized():
    views = [_view(0, shed_rate=0.5), _view(1, shed_rate=0.0)]
    assert pick_replica(views) == 1


# ---------------------------------------------------------------------------
# the read-only prefix probe (satellite 1)
# ---------------------------------------------------------------------------

def _filled_alloc():
    """An allocator with one 3-page prompt registered + completed."""
    alloc = PageAllocator(num_pages=8, page_size=4)
    prompt = list(range(100, 112))          # 3 full pages
    plan = alloc.admit(prompt + [1], covered_pages=4)
    assert plan is not None
    for node in plan.nodes:
        alloc.complete_node(node)
    alloc.release_plan(plan)                # pages go cached
    return alloc, prompt


def test_prefix_match_len_counts_full_and_partial():
    alloc, prompt = _filled_alloc()
    assert alloc.prefix_match_len(prompt + [1, 2]) == 12
    # mid-page divergence: 2 full pages + 2 partial tokens
    assert alloc.prefix_match_len(prompt[:8] + [108, 109, 7, 7]) == 10
    assert alloc.prefix_match_len([9, 9, 9, 9, 9]) == 0
    # the cap mirrors admit(): the final token always prefills, so a
    # prompt that IS the cached chunk matches len-1
    assert alloc.prefix_match_len(prompt[:4]) == 3
    assert alloc.prefix_match_len([]) == 0
    assert alloc.prefix_match_len([5]) == 0


def test_prefix_probe_is_pure():
    """Probing pins nothing and never perturbs eviction order."""
    alloc, prompt = _filled_alloc()
    ref = dict(alloc._ref)
    clock = alloc._clock
    lru = {n.page: n.lru for n in alloc._node_of.values()}
    for _ in range(16):
        alloc.prefix_match_len(prompt + [3])
        alloc.prefix_match_len(prompt[:6])
    assert dict(alloc._ref) == ref          # no page pinned
    assert alloc._clock == clock            # no LRU touch
    assert {n.page: n.lru
            for n in alloc._node_of.values()} == lru
    # and the accounting counters never move: a probe is not a hit
    assert alloc.cow_copies == 0 and alloc.prefix_hit_tokens == 0


def test_prefix_probe_does_not_change_eviction_order():
    # two identical allocators; one is probed between admissions —
    # pressure must evict the SAME victim pages in the same order
    def scenario(probe):
        alloc = PageAllocator(num_pages=6, page_size=2)
        order = []
        for base in (10, 20):               # two cached 2-page chains
            plan = alloc.admit([base, base + 1, base + 2, base + 3,
                                base + 9], covered_pages=2)
            for node in plan.nodes:
                alloc.complete_node(node)
            alloc.release_plan(plan)
        if probe:
            alloc.prefix_match_len([10, 11, 12, 13, 99])
            alloc.prefix_match_len([20, 21, 99])
        evicted_before = alloc.evictions
        got = alloc.alloc(4)                # forces evictions
        order.append((sorted(got), alloc.evictions - evicted_before))
        return order
    assert scenario(False) == scenario(True)


def test_batcher_prefix_match_len(model):
    bat = _bat(model, page_size=8)
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, 128, 20).astype(np.int32)
    assert bat.prefix_match_len(prompt) == 0
    bat.submit(prompt, 4)
    bat.run()
    got = bat.prefix_match_len(prompt)
    assert got == 16                        # 2 complete 8-token pages
    dense = _bat(model, kv_layout="dense")
    assert dense.prefix_match_len(prompt) == 0


# ---------------------------------------------------------------------------
# atomic queue snapshot (satellite 2)
# ---------------------------------------------------------------------------

def test_queue_snapshot_consistent_under_submit_storm(model):
    bat = _bat(model, max_batch_size=2)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, 5).astype(np.int32)
               for _ in range(60)]
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            snap = bat.queue_snapshot()
            st_q = bat.queued
            # the snapshot itself is internally consistent, and the
            # aggregate property can never run AHEAD of a later
            # snapshot (submissions only grow the queue here)
            snap2 = bat.queue_snapshot()
            if sum(snap.values()) > sum(snap2.values()):
                torn.append((snap, snap2))
            if st_q > sum(snap2.values()):
                torn.append((st_q, snap2))

    t = threading.Thread(target=reader)
    t.start()
    try:
        for i, p in enumerate(prompts):
            bat.submit(p, 2, slo=("interactive", "batch",
                                  "best_effort")[i % 3])
    finally:
        stop.set()
        t.join()
    assert not torn, torn[:3]
    snap = bat.queue_snapshot()
    assert sum(snap.values()) == 60
    st = bat.stats()
    assert st["queued"] == 60
    assert st["queued_by_class"] == snap
    bat.run()


def test_router_view_schema(model):
    bat = _bat(model)
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, 128, 6).astype(np.int32)
    bat.submit(prompt, 3)
    v = bat.router_view(prompt)
    for k in ("queued", "queued_by_class", "active", "slots",
              "draining", "shed_rate", "attainment",
              "prefix_hit_tokens"):
        assert k in v, (k, v)
    assert v["queued"] == 1 and v["slots"] == 1
    assert not v["draining"]
    bat.run()
    v2 = bat.router_view()
    assert v2["queued"] == 0 and "prefix_hit_tokens" not in v2
    assert v2["attainment"]["batch"] == 1.0


# ---------------------------------------------------------------------------
# the fleet: routing, kill, requeue (tentpole + satellite 5 wiring)
# ---------------------------------------------------------------------------

def _workload(rng, n=6):
    lens = (6, 11, 4, 9, 13, 5)[:n]
    news = (6, 5, 7, 4, 6, 5)[:n]
    return [rng.randint(1, 128, L).astype(np.int32) for L in lens], news


def test_fleet_bit_exact_vs_single_replica(model):
    rng = np.random.RandomState(5)
    prompts, news = _workload(rng)
    ref_bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                                chunk=4, prefill_chunk=4)
    rids = [ref_bat.submit(p, n) for p, n in zip(prompts, news)]
    ref_outs = ref_bat.run()

    router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
    gids = [router.submit(p, n) for p, n in zip(prompts, news)]
    outs = router.run()
    st = router.stats()
    for rid, gid in zip(rids, gids):
        assert (outs[gid] == ref_outs[rid]).all()
    assert st["requests_completed"] == len(gids)
    assert st["requests_shed"] == 0
    assert all(v > 0 for v in st["routed_by_replica"].values()), st
    assert st["decision_ms"]["count"] == len(gids)


def test_fleet_two_programs_per_shape(model):
    """Acceptance pin (ISSUE 15): N same-geometry replicas share the
    model-level program cache — a whole 3-replica fleet run at a FRESH
    shape compiles exactly 2 serve-step programs total
    (recompile_guard raises with avals past the bound), each batcher
    reports <= 2, and no key beyond the single-batcher pair exists."""
    from paddle_tpu.analysis import recompile_guard
    rng = np.random.RandomState(6)
    prompts, news = _workload(rng, 4)
    bats = [_bat(model, max_len=56) for _ in range(3)]   # fresh shape
    keys = {bats[0]._program_key(1, bats[0].chunk),
            bats[0]._program_key(bats[0].prefill_chunk,
                                 bats[0].admit_steps)}
    router = ServeRouter(batchers=bats)
    for p, n in zip(prompts, news):
        router.submit(p, n)
    with recompile_guard(max_programs=2, match="serve_step"):
        router.run()
    for b in bats:
        assert b.compiled_programs <= 2
        assert b._programs_used <= keys, b._programs_used


def test_kill_replica_requeues_queued_lossless(model):
    rng = np.random.RandomState(5)
    prompts, news = _workload(rng)
    router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
    gids = [router.submit(p, n, slo=s) for p, n, s in
            zip(prompts, news, ("interactive", "batch", "best_effort",
                                "interactive", "batch", "batch"))]
    victim = max(range(2), key=lambda i: router._reps[i].bat.queued)
    assert router._reps[victim].bat.queued > 0
    migrated = router.kill_replica(victim)
    assert migrated > 0
    outs = router.run()
    st = router.stats()
    assert st["requests_requeued"] == migrated
    assert st["requests_shed"] == 0
    assert st["requests_completed"] == len(gids)
    assert st["live_replicas"] == 1
    ref2 = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                             chunk=4, prefill_chunk=4)
    rids = [ref2.submit(p, n) for p, n in zip(prompts, news)]
    ref_outs = ref2.run()
    for rid, gid in zip(rids, gids):
        assert (outs[gid] == ref_outs[rid]).all()


def test_kill_mid_decode_no_duplicate_streamed_tokens(model):
    rng = np.random.RandomState(5)
    prompts, news = _workload(rng)
    streams = {}

    def cb(gid, toks, done):
        streams.setdefault(gid, []).extend(toks)

    router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
    gids = [router.submit(p, n, on_token=cb)
            for p, n in zip(prompts, news)]
    victim = None
    for _ in range(32):
        router.step()
        for i, rep in enumerate(router._reps):
            live = [r for r in rep.bat._slots if r is not None]
            if any(r.delivered for r in live):
                victim = i
                break
        if victim is not None:
            break
    assert victim is not None
    migrated = router.kill_replica(victim)
    assert migrated > 0
    outs = router.run()
    for gid in gids:
        got = list(map(int, outs[gid]))
        assert streams.get(gid, []) == got, \
            f"gid {gid}: streamed {streams.get(gid)} vs output {got}"
    # survivor pools leak-free: slots freed, only cached prefix pages
    for rep in router._reps:
        if not rep.dead:
            assert rep.bat._alloc.pages_used \
                == rep.bat._alloc.pages_cached


def test_requeue_preserves_arrival_order_and_deadline(model):
    router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, 128, 6).astype(np.int32)
               for _ in range(4)]
    gids = [router.submit(p, 3, deadline_ms=60000.0) for p in prompts]
    victim = max(range(2), key=lambda i: router._reps[i].bat.queued)
    rr_deadlines = {g: router._reqs[g].deadline for g in gids}
    router.kill_replica(victim)
    survivor = next(r for r in router._reps if not r.dead)
    with survivor.bat._qlock:
        arrivals = [r.arrival for q in survivor.bat._queues.values()
                    for r in q]
        deadlines = {survivor.local2g[r.req_id]: r.deadline
                     for q in survivor.bat._queues.values() for r in q}
    assert arrivals == sorted(arrivals)     # global FIFO survived
    for g, dl in deadlines.items():
        assert dl == rr_deadlines[g]        # absolute deadline kept
    router.run()


def test_drain_replica_graceful(model):
    """drain_replica migrates only QUEUED work; in-flight finishes on
    the replica, which then retires — nothing re-decoded or lost."""
    rng = np.random.RandomState(8)
    prompts, news = _workload(rng, 4)
    router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
    gids = [router.submit(p, n) for p, n in zip(prompts, news)]
    router.step()
    victim = max(range(2), key=lambda i: router._reps[i].bat.queued)
    in_flight = router._reps[victim].bat.active
    migrated = router.drain_replica(victim)
    outs = router.run()
    st = router.stats()
    assert st["requests_completed"] == len(gids)
    assert router._reps[victim].dead          # retired once empty
    assert st["requests_requeued"] == migrated
    if in_flight:
        # the in-flight decode finished on the draining replica
        assert st["requests_requeued"] < len(gids)
    assert sorted(outs) == sorted(gids)


def test_drain_lands_between_pick_and_place(model, monkeypatch):
    """ISSUE 19 satellite: drain_replica interleaved between
    pick_replica choosing a replica and _place enqueueing on it — the
    re-pick guard must route to the survivor, never shed, output
    bit-exact vs a fault-free single-replica reference."""
    router = ServeRouter(batchers=[_bat(model), _bat(model)])
    orig = ServeRouter._place
    hit = {}

    def racing(self, rr, rep):
        if "victim" not in hit:
            hit["victim"] = rep.idx
            self.drain_replica(rep.idx)     # the race, exactly here
        return orig(self, rr, rep)

    monkeypatch.setattr(ServeRouter, "_place", racing)
    rng = np.random.RandomState(5)
    p = rng.randint(1, 128, 6).astype(np.int32)
    gid = router.submit(p, 6, slo="interactive")
    outs = router.run()
    rr = router._reqs[gid]
    assert "victim" in hit
    assert not rr.shed, rr.shed_reason
    assert rr.replica != hit["victim"]        # landed on the survivor
    assert router.stats()["requests_shed"] == 0
    ref = _bat(model)
    ref.submit(p, 6)
    (ref_out,) = ref.run().values()
    np.testing.assert_array_equal(outs[gid], ref_out)


def test_drain_lands_just_after_place(model, monkeypatch):
    """The other interleaving: the request is already enqueued when
    the drain arrives — it migrates losslessly to the survivor instead
    of being shed with the drained replica."""
    router = ServeRouter(batchers=[_bat(model), _bat(model)])
    orig = ServeRouter._place
    hit = {}

    def racing(self, rr, rep):
        out = orig(self, rr, rep)
        if "victim" not in hit:
            hit["victim"] = rep.idx
            self.drain_replica(rep.idx)
        return out

    monkeypatch.setattr(ServeRouter, "_place", racing)
    rng = np.random.RandomState(6)
    p = rng.randint(1, 128, 6).astype(np.int32)
    gid = router.submit(p, 6, slo="interactive")
    outs = router.run()
    rr = router._reqs[gid]
    assert not rr.shed, rr.shed_reason
    assert rr.replica != hit["victim"]        # migrated off the drain
    assert router.stats()["requests_shed"] == 0
    assert router.stats()["requests_requeued"] >= 1
    ref = _bat(model)
    ref.submit(p, 6)
    (ref_out,) = ref.run().values()
    np.testing.assert_array_equal(outs[gid], ref_out)


def test_all_replicas_draining_sheds_with_no_leak(model):
    router = ServeRouter(batchers=[_bat(model)])
    router.drain_replica(0)
    rng = np.random.RandomState(9)
    gid = router.submit(rng.randint(1, 128, 5).astype(np.int32), 3)
    outs = router.run()
    st = router.stats()
    assert gid in outs and len(outs[gid]) == 0
    assert st["requests_shed"] == 1
    assert st["requests_submitted"] == st["requests_completed"] \
        + st["requests_shed"]


def test_rebalance_moves_queued_to_idle(model):
    set_flags({"FLAGS_router_rebalance_ms": 0.001})
    try:
        router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
        rng = np.random.RandomState(4)
        # pin every submit onto replica 0 by faking replica 1 as
        # draining during submission, then un-drain it: the rebalance
        # sweep must move queued work across
        router._reps[1].draining = True
        prompts, news = _workload(rng, 4)
        gids = [router.submit(p, n) for p, n in zip(prompts, news)]
        assert router.stats()["routed_by_replica"][1] == 0
        router._reps[1].draining = False
        outs = router.run()
        st = router.stats()
        assert st["rebalanced"] > 0
        assert st["requests_completed"] == len(gids)
        assert sorted(outs) == sorted(gids)
    finally:
        set_flags({"FLAGS_router_rebalance_ms": 0.0})


def test_direct_batcher_request_survives_kill_and_rebalance(model):
    """A request submitted STRAIGHT to an underlying batcher (not
    through the router) is not router-managed: rebalance must never
    move it, a graceful drain leaves it to finish in place, and a
    kill sheds it through the batcher so the batcher's own no-leak
    accounting stays whole — it can never silently vanish."""
    set_flags({"FLAGS_router_rebalance_ms": 0.001})
    try:
        rng = np.random.RandomState(12)
        bats = [_bat(model) for _ in range(2)]
        router = ServeRouter(batchers=bats)
        direct = bats[0].submit(rng.randint(1, 128, 6)
                                .astype(np.int32), 3)
        gids = [router.submit(rng.randint(1, 128, 5).astype(np.int32),
                              3) for _ in range(3)]
        router.run()
        assert direct in bats[0]._finished          # finished in place
        assert not bats[0]._finished[direct].shed
        # and under a kill: the direct request sheds ON the batcher
        bats2 = [_bat(model) for _ in range(2)]
        router2 = ServeRouter(batchers=bats2)
        direct2 = bats2[0].submit(rng.randint(1, 128, 6)
                                  .astype(np.int32), 3)
        g = router2.submit(rng.randint(1, 128, 5).astype(np.int32), 3)
        router2.kill_replica(0)
        outs = router2.run()
        assert g in outs
        st0 = bats2[0].stats()
        assert st0["requests_submitted"] \
            == st0["requests_completed"] + st0["requests_shed"]
        assert bats2[0]._finished[direct2].shed
    finally:
        set_flags({"FLAGS_router_rebalance_ms": 0.0})


def test_prefix_probe_skipped_when_weight_zero(model, monkeypatch):
    """FLAGS_router_prefix_weight=0 disables prefix affinity — the
    routing hot path must not pay the O(replicas x prompt) trie
    probes whose result it would multiply by zero."""
    calls = []
    orig = ContinuousBatcher.prefix_match_len

    def counting(self, ids):
        calls.append(1)
        return orig(self, ids)

    monkeypatch.setattr(ContinuousBatcher, "prefix_match_len",
                        counting)
    router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
    rng = np.random.RandomState(13)
    prompt = rng.randint(1, 128, 6).astype(np.int32)
    set_flags({"FLAGS_router_prefix_weight": 0.0})
    try:
        router.submit(prompt, 3)
        assert not calls
    finally:
        set_flags({"FLAGS_router_prefix_weight": 1.0})
    router.submit(prompt, 3)
    assert len(calls) == 2          # flag back on: one probe/replica
    router.run()


def test_fleet_serve_helper_reads_flag(model):
    set_flags({"FLAGS_serve_replicas": 3})
    try:
        router = fleet_serve(model, max_batch_size=1, max_len=64,
                             chunk=4, prefill_chunk=4)
        assert router.replicas == 3
    finally:
        set_flags({"FLAGS_serve_replicas": 0})
    router = fleet_serve(model, replicas=2, max_batch_size=1,
                         max_len=64, chunk=4, prefill_chunk=4)
    assert router.replicas == 2


# ---------------------------------------------------------------------------
# KV-plane discovery (replica-per-rank mode)
# ---------------------------------------------------------------------------

def test_kv_publish_discover_roundtrip(model):
    from paddle_tpu.distributed.launch.master import KVServer, KVClient
    from paddle_tpu.inference.router import (ReplicaPublisher,
                                             discover_replicas)
    srv = KVServer(0).start()
    try:
        kv = KVClient(f"127.0.0.1:{srv.port}")
        router = ServeRouter(batchers=[_bat(model) for _ in range(2)],
                             kv=kv, job_id="routertest")
        rng = np.random.RandomState(3)
        prompts, news = _workload(rng, 4)
        for p, n in zip(prompts, news):
            router.submit(p, n)
        router.run()
        views = discover_replicas(kv, "routertest")
        assert sorted(views) == [0, 1]
        for rid, v in views.items():
            assert v["replica"] == rid
            for k in ("queued", "active", "slots", "attainment"):
                assert k in v, (rid, v)
        # the discovered views feed the same policy function
        assert pick_replica(list(views.values())) in (0, 1)
        # heartbeats stamped with the master clock
        for rid in (0, 1):
            assert kv.get(f"routertest/serve/{rid}/hb") is not None
        # a standalone worker-side publisher (subprocess mode) lands
        # in the same namespace
        pub = ReplicaPublisher(kv, job_id="routertest", replica=7)
        bat = _bat(model)
        assert pub.publish(bat.router_view())
        assert 7 in discover_replicas(kv, "routertest")
    finally:
        srv.stop()


def test_publisher_retire_tombstones_discovery(model):
    """ISSUE 19 satellite: a retired replica tombstones itself on the
    KV plane — discover_replicas drops it even though its stale view/
    heartbeat keys are still there (a scale-in must not look like a
    crashed replica to any discoverer)."""
    from paddle_tpu.distributed.launch.master import KVServer, KVClient
    from paddle_tpu.inference.router import (ReplicaPublisher,
                                             discover_replicas)
    srv = KVServer(0).start()
    try:
        kv = KVClient(f"127.0.0.1:{srv.port}")
        pubs = {i: ReplicaPublisher(kv, job_id="retiretest", replica=i)
                for i in (0, 3)}
        bat = _bat(model)
        for pub in pubs.values():
            assert pub.publish(bat.router_view())
        assert sorted(discover_replicas(kv, "retiretest")) == [0, 3]
        assert pubs[3].retire()
        views = discover_replicas(kv, "retiretest")
        assert sorted(views) == [0], views
        # the stale view key is STILL on the plane — the tombstone wins
        assert kv.get("retiretest/serve/3/latest") is not None
        assert kv.get("retiretest/serve/3/tombstone") is not None
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# chaos CLI wiring (satellite 5) + host-plane contract
# ---------------------------------------------------------------------------

def test_chaos_replica_kill_specs():
    """The two chaos_check --serve replica-kill specs pass: queued
    requeue and mid-decode requeue, both bit-exact vs the fault-free
    single-replica reference."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check as cli
    finally:
        sys.path.pop(0)
    for mode in ("queued", "mid_decode"):
        rep = cli.run_router_kill(mode)
        assert rep["fired"], rep
        assert rep["ok"], rep


def test_router_emits_telemetry_events(model):
    from paddle_tpu import telemetry

    class Probe:
        def __init__(self):
            self.records = []

        def record(self, rec):
            self.records.append(rec)

    probe = Probe()
    telemetry.add_sink(probe)
    try:
        router = ServeRouter(batchers=[_bat(model) for _ in range(2)])
        rng = np.random.RandomState(1)
        prompts, news = _workload(rng, 3)
        for p, n in zip(prompts, news):
            router.submit(p, n)
        victim = max(range(2),
                     key=lambda i: router._reps[i].bat.queued)
        router.kill_replica(victim)
        router.run()
    finally:
        telemetry.remove_sink(probe)
    kinds = {}
    for r in probe.records:
        kinds.setdefault(r.get("event"), []).append(r)
    assert len(kinds.get("router.route", [])) == 3
    for e in kinds["router.route"]:
        for k in ("req", "slo", "replica", "prefix_hit",
                  "decision_ms"):
            assert k in e, e
    assert kinds.get("router.kill"), kinds.keys()
    assert kinds["router.kill"][0]["replica"] == victim
    for e in kinds.get("router.requeue", []):
        assert e["frm"] == victim and "delivered" in e
