"""Scale/calibration tooling: the analytic artifacts stay derivable.

Pins the round-5 scale-evidence chain (tools/scale_report.py,
tools/calibrate_cost_model.py): the north-star strategy must keep
fitting v5p HBM and clearing the MFU bar under the calibrated
assumption, so a cost/memory-model regression that silently breaks the
claim fails here.
"""
import numpy as np

from tools.scale_report import (LLAMA_7B, LLAMA_13B, V5P_HBM,
                                candidates_128, evaluate, render)


class TestScaleReport:
    def test_north_star_fits_and_meets_mfu(self):
        name, strat = candidates_128()[0]
        assert "ZeRO-3" in name
        mem, t06, tcal, mfu06, mfucal = evaluate(LLAMA_7B, strat, 512)
        assert mem.total < V5P_HBM
        assert mfucal >= 0.40
        # calibrated projection must stay below the matmul ceiling —
        # a projection above it would mean the model lost a cost term
        assert mfucal < 0.70

    def test_13b_needs_stage3_for_headroom(self):
        _, z3 = candidates_128()[0]
        mem3, *_ = evaluate(LLAMA_13B, z3, 512)
        no_shard = dict(z3, sharding=1, dp=128, sharding_stage=0)
        mem0, *_ = evaluate(LLAMA_13B, no_shard, 512)
        assert mem3.total < V5P_HBM < mem0.total

    def test_mp_strategy_costs_more_than_pure_zero3(self):
        """Exposed mp collectives must make mp8 slower than pure
        data-ways sharding at equal chip count (the planner's ranking
        rationale)."""
        (_, z3), _, (_, mp8), _ = candidates_128()
        _, t_z3, *_ = evaluate(LLAMA_7B, z3, 512)
        _, t_mp, *_ = evaluate(LLAMA_7B, mp8, 512)
        assert t_mp > t_z3

    def test_render_mentions_all_anchors(self):
        md = render()
        for anchor in ("CALIBRATION_r05", "4.49B", "deep", "MEETS"):
            assert anchor in md, anchor


class TestCalibrationMath:
    def test_implied_mfu_solves_linear_form(self):
        """e(m) = C/m + F extraction used by the calibration tool."""
        from paddle_tpu.distributed.auto_tuner.cost_model import (
            estimate_step_time)
        cfg = dict(LLAMA_7B)
        strat = candidates_128()[0][1]
        e06 = estimate_step_time(cfg, strat, 512, chip="v5p",
                                 mfu_assumption=0.6)
        e10 = estimate_step_time(cfg, strat, 512, chip="v5p",
                                 mfu_assumption=1.0)
        C = (e06 - e10) / (1 / 0.6 - 1.0)
        F = e10 - C
        # reconstruct a third point exactly
        e08 = estimate_step_time(cfg, strat, 512, chip="v5p",
                                 mfu_assumption=0.8)
        np.testing.assert_allclose(C / 0.8 + F, e08, rtol=1e-9)
