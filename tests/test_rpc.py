"""RPC (reference: python/paddle/distributed/rpc/rpc.py — init_rpc,
rpc_sync/rpc_async over the worker gang).  Two real workers over the
launcher KV store; in-process master."""
import os
import textwrap

import numpy as np
import pytest

from paddle_tpu.distributed.launch import parse_args, CollectiveController

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, json
import paddle_tpu.distributed.rpc as rpc

rank = int(os.environ["PADDLE_TRAINER_ID"])
rpc.init_rpc(f"worker{rank}")
# snapshot the gang BEFORE issuing calls: a fast peer may shutdown (and
# deregister) while we are still collecting results
workers = [w.name for w in rpc.get_all_worker_infos()]

def add(a, b):
    return a + b

def whoami():
    return rpc.get_current_worker_info().name

peer = f"worker{1 - rank}"
out = {
    "sum": rpc.rpc_sync(peer, add, args=(rank * 10, 5)),
    "peer_name": rpc.rpc_sync(peer, whoami),
    "async": rpc.rpc_async(peer, add, args=(1, 2)).result(),
    "workers": workers,
}
with open(os.path.join(os.environ["DUMP_DIR"],
                       f"rpc.{rank}.json"), "w") as f:
    json.dump(out, f)
rpc.shutdown()
"""


def test_rpc_two_workers(tmp_path):
    import json
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(WORKER))
    os.environ["DUMP_DIR"] = str(tmp_path)
    os.environ["PYTHONPATH"] = REPO + os.pathsep \
        + os.environ.get("PYTHONPATH", "")
    try:
        args = parse_args([
            "--nproc_per_node=2", f"--log_dir={tmp_path}/log",
            "--job_id=rpc", str(script)])
        rc = CollectiveController(args).run()
    finally:
        del os.environ["DUMP_DIR"]
    assert rc == 0
    outs = {}
    for r in (0, 1):
        with open(tmp_path / f"rpc.{r}.json") as f:
            outs[r] = json.load(f)
    # rank 0 asked worker1 to add(0, 5); rank 1 asked worker0 add(10, 5)
    assert outs[0]["sum"] == 5
    assert outs[1]["sum"] == 15
    assert outs[0]["peer_name"] == "worker1"
    assert outs[1]["peer_name"] == "worker0"
    assert outs[0]["async"] == 3
    assert sorted(outs[0]["workers"]) == ["worker0", "worker1"]


def test_rpc_exception_propagates(tmp_path):
    """A remote exception is re-raised at the caller (reference: brpc
    error propagation)."""
    from paddle_tpu.distributed.launch.master import KVServer
    import paddle_tpu.distributed.rpc as rpc
    srv = KVServer(0).start()
    try:
        rpc.init_rpc("solo", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{srv.port}")

        def boom():
            raise ValueError("remote kaboom")

        with pytest.raises(ValueError, match="remote kaboom"):
            rpc.rpc_sync("solo", boom, timeout=10)
        assert rpc.rpc_sync("solo", lambda: 42, timeout=10) == 42
    finally:
        rpc.shutdown()
        srv.stop()
