"""paddle.text (viterbi + datasets) and paddle.geometric (segment ops,
message passing).

Reference test model: test_viterbi_decode_op.py (vs a numpy dynamic
program), test_graph_send_recv_op.py, test_segment_ops.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import text, geometric


def a(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def np_viterbi(pot, trans, length, bos_eos):
    """Reference dynamic program for one sequence."""
    n = trans.shape[0]
    if bos_eos:
        alpha = pot[0] + trans[n - 2]
    else:
        alpha = pot[0].copy()
    back = []
    for t in range(1, length):
        scores = alpha[:, None] + trans
        back.append(scores.argmax(0))
        alpha = scores.max(0) + pot[t]
    if bos_eos:
        alpha = alpha + trans[:, n - 1]
    best = int(alpha.argmax())
    path = [best]
    for bk in reversed(back):
        path.append(int(bk[path[-1]]))
    path.reverse()
    return float(alpha.max()), path


class TestViterbi:
    @pytest.mark.parametrize("bos_eos", [True, False])
    def test_matches_numpy_dp(self, bos_eos):
        rng = np.random.RandomState(0)
        B, L, T = 3, 6, 5
        pot = rng.randn(B, L, T).astype(np.float32)
        trans = rng.randn(T, T).astype(np.float32)
        lens = np.array([L, L, L], np.int64)
        scores, paths = text.viterbi_decode(
            paddle.to_tensor(pot), trans, lens,
            include_bos_eos_tag=bos_eos)
        for b in range(B):
            s_ref, p_ref = np_viterbi(pot[b], trans, L, bos_eos)
            np.testing.assert_allclose(a(scores)[b], s_ref, atol=1e-4)
            assert list(a(paths)[b]) == p_ref

    def test_decoder_layer(self):
        rng = np.random.RandomState(1)
        pot = rng.randn(2, 4, 4).astype(np.float32)
        trans = rng.randn(4, 4).astype(np.float32)
        dec = text.ViterbiDecoder(trans)
        scores, paths = dec(paddle.to_tensor(pot),
                            np.array([4, 4], np.int64))
        assert a(paths).shape == (2, 4)


class TestTextDatasets:
    @pytest.mark.parametrize("cls", [text.Imdb, text.Imikolov,
                                     text.Movielens, text.UCIHousing,
                                     text.WMT14, text.WMT16])
    def test_dataset_shapes(self, cls):
        d = cls(mode="train")
        assert len(d) > 0
        item = d[0]
        assert isinstance(item, tuple)
        # deterministic across constructions
        d2 = cls(mode="train")
        np.testing.assert_array_equal(np.asarray(item[0]),
                                      np.asarray(d2[0][0]))


class TestGeometric:
    def test_segment_ops(self):
        data = paddle.to_tensor(np.array(
            [[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32))
        seg = np.array([0, 0, 1, 1], np.int64)
        np.testing.assert_allclose(a(geometric.segment_sum(data, seg)),
                                   [[4., 6.], [12., 14.]])
        np.testing.assert_allclose(a(geometric.segment_mean(data, seg)),
                                   [[2., 3.], [6., 7.]])
        np.testing.assert_allclose(a(geometric.segment_max(data, seg)),
                                   [[3., 4.], [7., 8.]])
        np.testing.assert_allclose(a(geometric.segment_min(data, seg)),
                                   [[1., 2.], [5., 6.]])

    def test_send_u_recv(self):
        x = paddle.to_tensor(np.array(
            [[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]], np.float32))
        src = np.array([0, 1, 2, 0], np.int64)
        dst = np.array([1, 2, 1, 0], np.int64)
        out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
        ref = np.zeros((3, 3), np.float32)
        for s, d in zip(src, dst):
            ref[d] += a(x)[s]
        np.testing.assert_allclose(a(out), ref)

    def test_send_ue_recv(self):
        x = paddle.to_tensor(np.array([[1., 1.], [2., 2.]], np.float32))
        e = np.array([10., 20., 30.], np.float32)
        src = np.array([0, 1, 0], np.int64)
        dst = np.array([1, 0, 0], np.int64)
        out = geometric.send_ue_recv(x, e, src, dst, message_op="mul",
                                     reduce_op="sum")
        ref = np.zeros((2, 2), np.float32)
        for s, d, w in zip(src, dst, e):
            ref[d] += a(x)[s] * w
        np.testing.assert_allclose(a(out), ref)

    def test_segment_grad(self):
        data = paddle.to_tensor(np.ones((4, 2), np.float32))
        data.stop_gradient = False
        out = geometric.segment_sum(data, np.array([0, 0, 1, 1]))
        (out ** 2).sum().backward()
        np.testing.assert_allclose(a(data.grad), 4 * np.ones((4, 2)))

    def test_reindex_graph(self):
        x = np.array([5, 9], np.int64)
        neighbors = np.array([9, 7, 5, 8], np.int64)
        count = np.array([2, 2], np.int64)
        rn, rd, nodes = geometric.reindex_graph(x, neighbors, count)
        assert list(a(nodes)) == [5, 9, 7, 8]
        assert list(a(rn)) == [1, 2, 0, 3]
        assert list(a(rd)) == [0, 0, 1, 1]
