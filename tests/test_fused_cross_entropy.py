"""Fused chunked linear+cross-entropy (ISSUE 5 tentpole).

What is being validated:
  * kernel/grad parity: every fused-CE variant (jnp twin, Pallas
    interpret, online vocab-chunked, vocab-sharded psum) produces the
    reference loss AND gradients to fp32 tolerance;
  * the dedup satellite: llama/gpt/bert's compute_loss — now all routed
    through nn.functional.fused_cross_entropy — pins the exact values
    of the old hand-rolled per-model formulas;
  * the no-materialization acceptance bar: with FLAGS_fused_ce on, the
    jitted llama train step contains NO [B, S, V] fp32 intermediate
    (lint_materialized_logits clean) while the legacy path trips the
    same lint;
  * fused-vs-legacy loss/training parity within fp32-accumulation
    tolerance, eager-tape backward included.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.pallas.fused_cross_entropy import (
    fused_linear_cross_entropy)

_rng = np.random.RandomState(0)


@pytest.fixture
def fused_ce_flag():
    set_flags({"FLAGS_fused_ce": True})
    yield
    set_flags({"FLAGS_fused_ce": False})


def _data(n=30, h=16, v=64, ignore=0):
    h_ = jnp.asarray(_rng.randn(n, h).astype(np.float32))
    w = jnp.asarray(_rng.randn(h, v).astype(np.float32) * 0.1)
    b = jnp.asarray(_rng.randn(v).astype(np.float32) * 0.1)
    lbl = _rng.randint(0, v, n).astype(np.int32)
    if ignore:
        lbl[:ignore] = -1
    return h_, w, b, jnp.asarray(lbl)


def _ref_loss(h, w, b, lbl):
    lg = jnp.dot(h, w, preferred_element_type=jnp.float32)
    if b is not None:
        lg = lg + b.astype(jnp.float32)
    lse = jax.nn.logsumexp(lg, axis=-1)
    safe = jnp.maximum(lbl, 0)
    picked = jnp.take_along_axis(lg, safe[:, None], axis=-1)[:, 0]
    mask = (lbl >= 0).astype(jnp.float32)
    return jnp.sum((lse - picked) * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class TestKernelParity:
    @pytest.mark.parametrize("variant", ["jnp", "pallas", "online"])
    @pytest.mark.parametrize("ignore", [0, 5])
    def test_loss_and_grads(self, variant, ignore):
        h, w, b, lbl = _data(ignore=ignore)
        kw = {"jnp": {}, "pallas": {"use_pallas": True},
              "online": {"vocab_chunk": 16}}[variant]

        def fused(h, w, b):
            return fused_linear_cross_entropy(h, w, lbl, bias=b,
                                              chunk_rows=8, **kw)

        def ref(h, w, b):
            return _ref_loss(h, w, b, lbl)

        np.testing.assert_allclose(float(fused(h, w, b)),
                                   float(ref(h, w, b)), rtol=1e-6)
        gf = jax.jit(jax.grad(fused, argnums=(0, 1, 2)))(h, w, b)
        gr = jax.grad(ref, argnums=(0, 1, 2))(h, w, b)
        for a, c in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       atol=2e-6, rtol=2e-5)

    def test_transpose_weight_tied_embedding_layout(self):
        h, w, _, lbl = _data()

        def fused(h, wT):
            return fused_linear_cross_entropy(h, wT, lbl,
                                              transpose_weight=True,
                                              chunk_rows=8)

        def ref(h, wT):
            return _ref_loss(h, wT.T, None, lbl)

        wT = w.T
        np.testing.assert_allclose(float(fused(h, wT)),
                                   float(ref(h, wT)), rtol=1e-6)
        gf = jax.grad(fused, argnums=(0, 1))(h, wT)
        gr = jax.grad(ref, argnums=(0, 1))(h, wT)
        for a, c in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       atol=2e-6, rtol=2e-5)

    def test_ragged_rows_pad_and_ignore_index(self):
        # 30 rows with chunk 8 → padded to 32; pad rows must not leak
        # into loss, dh, or the valid-count denominator
        h, w, b, lbl = _data(n=30)
        l1 = float(fused_linear_cross_entropy(h, w, lbl, bias=b,
                                              chunk_rows=8))
        l2 = float(fused_linear_cross_entropy(h, w, lbl, bias=b,
                                              chunk_rows=30))
        np.testing.assert_allclose(l1, l2, rtol=1e-6)
        # ignore_index remap: labels equal to it drop from the mean
        lbl_ig = jnp.where(jnp.arange(30) < 4, 63, lbl)
        li = float(fused_linear_cross_entropy(h, w, lbl_ig, bias=b,
                                              ignore_index=63,
                                              chunk_rows=8))
        ref = float(_ref_loss(h, w, b, jnp.where(lbl_ig == 63, -1,
                                                 lbl_ig)))
        np.testing.assert_allclose(li, ref, rtol=1e-6)

    def test_vocab_sharded_psum_path(self):
        """ParallelCrossEntropy contract: each shard holds a [H, V/n]
        weight slice; per-shard max/denominator/picked merge with one
        pmax + psum, dh is a psum of per-shard partials.  Gradients to
        hidden AND the local weight shard must match the unsharded
        reference."""
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        n_shards = 4
        h, w, _, lbl = _data(n=16, h=8, v=64)
        devs = np.array(jax.devices()[:n_shards])
        mesh = Mesh(devs, ("mp",))

        # grads taken INSIDE the shard_map — the TP-layer contract:
        # each shard differentiates its replicated-h / local-w-slice
        # loss; the kernel's internal psum makes dh full and replicated,
        # dw stays the local shard's slice
        def local(h_, w_, lbl_):
            def loss(h__, w__):
                return fused_linear_cross_entropy(
                    h__, w__, lbl_, chunk_rows=8, axis_name="mp")
            l, (dh, dw) = jax.value_and_grad(
                loss, argnums=(0, 1))(h_, w_)
            return l, dh, dw

        loss, dh, dw = jax.jit(shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(None, "mp"), P()),
            out_specs=(P(), P(), P(None, "mp")),
            check_rep=False))(h, w, lbl)

        def ref(h, w):
            return _ref_loss(h, w, None, lbl)

        np.testing.assert_allclose(float(loss), float(ref(h, w)),
                                   rtol=1e-6)
        rh, rw = jax.grad(ref, argnums=(0, 1))(h, w)
        np.testing.assert_allclose(np.asarray(dh), np.asarray(rh),
                                   atol=2e-6, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(rw),
                                   atol=2e-6, rtol=2e-5)


# ---------------------------------------------------------------------------
# dedup satellite: the shared functional pins the old per-model values

def _llama():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny_config()), 512


def _gpt():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
    paddle.seed(0)
    return GPTForCausalLM(gpt_tiny_config()), 256


def _bert():
    from paddle_tpu.models.bert import BertForMaskedLM, bert_tiny_config
    paddle.seed(0)
    return BertForMaskedLM(bert_tiny_config()), 128


class TestModelLossDedup:
    def test_llama_pins_old_formula(self):
        m, vocab = _llama()
        ids = paddle.to_tensor(_rng.randint(0, vocab, (2, 16))
                               .astype(np.int32))
        logits = m(ids)
        new = float(np.asarray(m.compute_loss(logits, ids).value))
        lgf = logits.value[:, :-1].astype(jnp.float32)
        tgt = ids.value[:, 1:].astype(jnp.int32)
        logp = jax.nn.log_softmax(lgf, axis=-1)
        old = float(-jnp.mean(jnp.take_along_axis(
            logp, tgt[..., None], axis=-1)[..., 0]))
        np.testing.assert_allclose(new, old, rtol=1e-6)

    def test_gpt_pins_old_formula(self):
        m, vocab = _gpt()
        ids = paddle.to_tensor(_rng.randint(0, vocab, (2, 12))
                               .astype(np.int32))
        logits = m(ids)
        new = float(np.asarray(m.compute_loss(logits, ids).value))
        lgf = logits.value[:, :-1].astype(jnp.float32)
        tgt = ids.value[:, 1:].astype(jnp.int32)
        logp = jax.nn.log_softmax(lgf, axis=-1)
        old = float(-jnp.mean(jnp.take_along_axis(
            logp, tgt[..., None], axis=-1)[..., 0]))
        np.testing.assert_allclose(new, old, rtol=1e-6)

    def test_bert_pins_old_formula(self):
        m, vocab = _bert()
        ids_np = _rng.randint(0, vocab, (2, 16)).astype(np.int32)
        lbl = ids_np.copy()
        lbl[0, :8] = -100                       # unmasked positions
        ids = paddle.to_tensor(ids_np)
        logits = m(ids)
        new = float(np.asarray(
            m.compute_loss(logits, paddle.to_tensor(lbl)).value))
        lg = logits.value
        tgt = jnp.maximum(jnp.asarray(lbl).astype(jnp.int32), 0)
        picked = jnp.take_along_axis(lg, tgt[..., None], axis=-1)[..., 0]
        lse = jax.nn.logsumexp(lg.astype(jnp.float32), axis=-1)
        mask = (jnp.asarray(lbl) != -100).astype(jnp.float32)
        old = float(jnp.sum((lse - picked.astype(jnp.float32)) * mask)
                    / jnp.maximum(jnp.sum(mask), 1.0))
        np.testing.assert_allclose(new, old, rtol=1e-6)


class TestFusedModelPath:
    @pytest.mark.parametrize("make", [_llama, _gpt, _bert],
                             ids=["llama", "gpt", "bert"])
    def test_fused_matches_legacy_loss(self, make, fused_ce_flag):
        m, vocab = make()
        ids = paddle.to_tensor(_rng.randint(0, vocab, (2, 16))
                               .astype(np.int32))
        set_flags({"FLAGS_fused_ce": False})
        legacy = float(np.asarray(m.compute_loss(m(ids), ids).value))
        set_flags({"FLAGS_fused_ce": True})
        out = m(ids)
        assert out.shape[-1] != vocab, \
            "fused-mode training forward must return hidden states"
        fused = float(np.asarray(m.compute_loss(out, ids).value))
        np.testing.assert_allclose(fused, legacy, atol=5e-4, rtol=1e-5)

    def test_eval_mode_keeps_logits(self, fused_ce_flag):
        m, vocab = _llama()
        ids = paddle.to_tensor(_rng.randint(0, vocab, (2, 8))
                               .astype(np.int32))
        m.eval()
        assert m(ids).shape[-1] == vocab

    def test_eager_tape_backward(self, fused_ce_flag):
        m, vocab = _llama()
        ids = paddle.to_tensor(_rng.randint(0, vocab, (2, 8))
                               .astype(np.int32))
        loss = m.compute_loss(m(ids), ids)
        loss.backward()
        head = m.lm_head if not m.config.tie_word_embeddings \
            else m.llama.embed_tokens
        assert head.grad is not None
        assert float(jnp.sum(jnp.abs(head.grad.value))) > 0


class TestNoMaterializedLogits:
    """Acceptance bar: jaxpr inspection of the jitted llama train step."""

    def _step(self):
        from paddle_tpu.parallel import ShardedTrainStep
        from paddle_tpu.distributed.topology import build_mesh
        m, vocab = _llama()
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters(),
                                     weight_decay=0.1)
        step = ShardedTrainStep(m, opt,
                                build_mesh(devices=jax.devices()[:1]),
                                sharding_stage=0)
        ids = paddle.to_tensor(_rng.randint(0, vocab, (2, 16))
                               .astype(np.int32))
        return step, ids

    def test_fused_step_has_no_full_logits(self, fused_ce_flag):
        step, ids = self._step()
        float(np.asarray(step(ids, ids).value))   # build + run
        findings = step.lint(ids, ids, donation=False, transfers=False,
                             logits=True)["logits"]
        assert not findings, [f.message for f in findings]

    def test_legacy_step_trips_the_lint(self):
        step, ids = self._step()
        float(np.asarray(step(ids, ids).value))
        findings = step.lint(ids, ids, donation=False, transfers=False,
                             logits=True)["logits"]
        assert findings, "legacy fp32 log_softmax must be flagged"
        assert any("512" in f.message for f in findings)

    def test_fused_training_tracks_legacy(self, fused_ce_flag):
        set_flags({"FLAGS_fused_ce": False})
        step_l, ids = self._step()
        legacy = [float(np.asarray(step_l(ids, ids).value))
                  for _ in range(4)]
        set_flags({"FLAGS_fused_ce": True})
        step_f, _ = self._step()
        fused = [float(np.asarray(step_f(ids, ids).value))
                 for _ in range(4)]
        np.testing.assert_allclose(fused, legacy, atol=5e-3)
