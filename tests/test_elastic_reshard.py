"""Reshard-on-load checkpoints + topology-aware data cursor (ISSUE 13).

The elastic resume contract promoted from the MULTICHIP_r05 dryrun to a
production API:

* `save_state_dict` under FLAGS_ckpt_save_sharded writes mesh-sharded
  arrays as per-shard slices with global index metadata (and ShardSlice
  values always — the host-plane fleet path);
* `load_state_dict` assembles each target (Tensor with its OWN mesh
  sharding, or a ShardSlice of a new world) from the overlapping slices
  of ANY saved topology — dp=8 → dp=2×mp=4, stage-3 sharded →
  unsharded, world W → W′ rank slices — bit-exact vs a
  gather-then-reshard reference;
* a topology the save cannot satisfy raises the named ReshardError
  (the satellite replacing the opaque shard-count failure);
* `io.ElasticDataCursor`/`ElasticBatchSampler` give a world-independent
  (epoch, global_sample_offset) data position that rides train_state
  meta, so a resume at a different dp degree replays exactly the
  unseen samples;
* retention GC at a shrunk world keeps the old-world step dir the
  resume restored from until a new complete step commits.

The multi-process half (a REAL 2-proc job killed mid-run, gang
re-formed at world 1, bit-exact elastic resume) lives in
tools/chaos_check.py --fleet, tier-1-wired via test_elastic_resume.py.
"""
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import fault
from paddle_tpu.distributed.checkpoint import (ReshardError, ShardSlice,
                                               load_checkpoint,
                                               load_state_dict,
                                               restore_train_checkpoint,
                                               save_checkpoint,
                                               save_state_dict,
                                               save_train_checkpoint)
from paddle_tpu.distributed.checkpoint.reshard import (assemble,
                                                       overlap_index,
                                                       split_index)
from paddle_tpu.distributed.topology import build_mesh
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.io import ElasticBatchSampler, ElasticDataCursor
from paddle_tpu.parallel import ShardedTrainStep


def _need8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")


# ---------------------------------------------------------------------------
# slice primitives
# ---------------------------------------------------------------------------

class TestReshardPrimitives:
    def test_split_index_even_uneven_degenerate(self):
        assert split_index((8, 4), 0, 2) == ((0, 4), (0, 4))
        assert split_index((8, 4), 1, 2) == ((4, 8), (0, 4))
        # uneven: 7 rows over 3 ranks -> 3, 2, 2
        sizes = [split_index((7, 2), r, 3)[0] for r in range(3)]
        assert sizes == [(0, 3), (3, 5), (5, 7)]
        # degenerate: more ranks than rows -> trailing ranks empty
        assert split_index((1, 2), 1, 2)[0] == (1, 1)
        with pytest.raises(ReshardError):
            split_index((4,), 3, 2)

    def test_overlap_and_assemble_across_worlds(self):
        y = np.arange(7 * 3, dtype=np.float32).reshape(7, 3)
        pieces = []
        for r in range(3):
            idx = split_index(y.shape, r, 3)
            sl = tuple(slice(s, e) for s, e in idx)
            pieces.append((idx, (lambda a=y[sl]: a)))
        # every world-2 target assembles exactly from world-3 pieces
        for r in range(2):
            tidx = split_index(y.shape, r, 2)
            out = np.zeros(tuple(e - s for s, e in tidx), np.float32)
            assemble(tidx, pieces, out, key="y")
            np.testing.assert_array_equal(
                out, y[tidx[0][0]:tidx[0][1]])
        assert overlap_index(((0, 3), (0, 3)), ((3, 7), (0, 3))) is None

    def test_assemble_gap_raises_named_error(self):
        y = np.ones((6, 2), np.float32)
        idx0 = split_index(y.shape, 0, 2)
        with pytest.raises(ReshardError, match="cover only"):
            assemble(split_index(y.shape, 0, 1),
                     [(idx0, (lambda: y[:3]))],
                     np.zeros((6, 2), np.float32), key="y")

    def test_partial_overlap_cannot_fool_coverage(self):
        """Volume summing double-counts partially-overlapping pieces;
        the fill-mask fallback must still flag the real gap."""
        y = np.arange(10, dtype=np.float32).reshape(10, 1)
        pieces = [(((0, 6), (0, 1)), lambda: y[0:6]),
                  (((4, 8), (0, 1)), lambda: y[4:8])]
        out = np.zeros((10, 1), np.float32)
        with pytest.raises(ReshardError, match="cover"):
            assemble(((0, 10), (0, 1)), pieces, out, key="y")
        # and genuinely-covering overlapping pieces still pass
        pieces.append((((6, 10), (0, 1)), lambda: y[6:10]))
        assemble(((0, 10), (0, 1)), pieces,
                 np.zeros((10, 1), np.float32), key="y")

    def test_malformed_rank_env_raises(self, monkeypatch):
        from paddle_tpu.distributed.checkpoint import _proc_rank_world
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "two")
        with pytest.raises(ValueError, match="PADDLE_TRAINER"):
            _proc_rank_world()

    def test_shardslice_validates(self):
        with pytest.raises(ReshardError):
            ShardSlice(np.zeros((2, 2)), ((0, 3), (0, 2)), (6, 2))
        ss = ShardSlice.of(np.arange(6).reshape(6, 1), 1, 2)
        assert ss.index[0] == (3, 6) and ss.local_shape == (3, 1)


# ---------------------------------------------------------------------------
# sharded save format + reshard-on-load across mesh topologies
# ---------------------------------------------------------------------------

class _MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(16, 32)
        self.fc2 = paddle.nn.Linear(32, 8)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _trainer(mesh, stage=0, tp=False, seed=3):
    paddle.seed(seed)
    m = _MLP()
    if tp:
        # column-parallel fc1 / row-parallel fc2: attach mp shardings
        # the way shard_llama_tp does — ShardedTrainStep merges them
        sd = m.state_dict()
        sd["fc1.weight"]._value = jax.device_put(
            sd["fc1.weight"].value, NamedSharding(mesh, P(None, "mp")))
        sd["fc1.bias"]._value = jax.device_put(
            sd["fc1.bias"].value, NamedSharding(mesh, P("mp")))
        sd["fc2.weight"]._value = jax.device_put(
            sd["fc2.weight"].value, NamedSharding(mesh, P("mp", None)))
    opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters(),
                                 weight_decay=0.1)
    return ShardedTrainStep(
        m, opt, mesh, sharding_stage=stage,
        loss_fn=lambda o, y: paddle.nn.functional.mse_loss(o, y))


def _batch(i=0):
    rng = np.random.RandomState(100 + i)
    return (paddle.to_tensor(rng.randn(8, 16).astype(np.float32)),
            paddle.to_tensor(rng.randn(8, 8).astype(np.float32)))


@pytest.fixture
def sharded_save_flag():
    paddle.set_flags({"FLAGS_ckpt_save_sharded": True})
    yield
    paddle.set_flags({"FLAGS_ckpt_save_sharded": False})


class TestShardedSaveFormat:
    def test_manifest_carries_layout_and_slices(self, tmp_path,
                                                sharded_save_flag):
        _need8()
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("x",))
        x = np.random.rand(16, 8).astype(np.float32)
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("x")))
        rep = jax.device_put(jnp.asarray(x),
                             NamedSharding(mesh, P(None)))
        save_state_dict({"w": Tensor(xs), "r": Tensor(rep)},
                        str(tmp_path))
        meta = json.load(open(tmp_path / "metadata.json"))
        # sharded key: global shape + per-slice layout in the manifest
        assert meta["w"]["sharded"] and meta["w"]["global_shape"] == [16, 8]
        assert len(meta["w"]["layout"]) == 8
        starts = sorted(l[0] for l in meta["w"]["layout"])
        assert starts[0] == [0, 2] and starts[-1] == [14, 16]
        # replicated key still saves ONE full copy, no layout
        assert "layout" not in meta["r"]
        assert meta["__world__"] == 1

    def test_flags_off_format_unchanged(self, tmp_path):
        _need8()
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("x",))
        x = np.random.rand(16, 8).astype(np.float32)
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("x")))
        save_state_dict({"w": Tensor(xs)}, str(tmp_path))
        meta = json.load(open(tmp_path / "metadata.json"))
        assert "layout" not in meta["w"] and "sharded" not in meta["w"]

    def test_shardslice_always_sharded(self, tmp_path):
        y = np.arange(12, dtype=np.float32).reshape(6, 2)
        save_state_dict({"m": ShardSlice.of(y, 0, 2)}, str(tmp_path),
                        rank=0, world=2)
        save_state_dict({"m": ShardSlice.of(y, 1, 2)}, str(tmp_path),
                        rank=1, world=2)
        meta = json.load(open(tmp_path / "metadata.json"))
        assert meta["__world__"] == 2
        assert meta["m"]["layout"] == [[[0, 3], [0, 2]]]
        t = Tensor(np.zeros((6, 2), np.float32))
        load_state_dict({"m": t}, str(tmp_path))
        np.testing.assert_array_equal(np.asarray(t.value), y)


class TestReshardOnLoad:
    """The acceptance criterion: a checkpoint saved at one topology
    restores at another bit-exactly vs a gather-then-reshard
    reference."""

    def _save_stage3(self, tmp_path, steps=2):
        mesh8 = build_mesh(sharding=8)
        a = _trainer(mesh8, stage=3)
        for i in range(steps):
            a(*_batch(i))
        save_train_checkpoint(a, str(tmp_path))
        # the gather-then-reshard reference: host copies of the saved
        # state (np.asarray gathers each sharded array)
        arrays, meta = a.train_state()
        ref = {k: np.asarray(v) for k, v in arrays.items()}
        return ref, meta

    def test_stage3_dp8_restores_into_dp2_mp4(self, tmp_path,
                                              sharded_save_flag):
        _need8()
        ref, meta = self._save_stage3(tmp_path)
        # the save really is sharded: 2-D params carry slice layouts
        man = json.load(open(
            ckpt.latest_checkpoint(str(tmp_path)) + "/metadata.json"))
        sharded_keys = [k for k, v in man.items()
                        if isinstance(v, dict) and v.get("sharded")]
        assert any(k.startswith("model.") for k in sharded_keys), \
            sharded_keys
        b = _trainer(build_mesh(dp=2, mp=4), stage=0, tp=True, seed=9)
        got = restore_train_checkpoint(b, str(tmp_path))
        assert got is not None
        assert int(got["step_count"]) == int(meta["step_count"])
        arrays_b, _ = b.train_state()
        for k, v in ref.items():
            np.testing.assert_array_equal(
                np.asarray(arrays_b[k]), v,
                err_msg=f"{k} not bit-exact across dp8->dp2xmp4")
        # the restored arrays actually carry the NEW mesh's shardings
        fc1 = b.model.state_dict()["fc1.weight"].value
        assert "mp" in str(fc1.sharding.spec)
        # and the trainer still steps
        loss = float(np.asarray(b(*_batch(5)).value))
        assert np.isfinite(loss)

    def test_stage3_restores_into_unsharded(self, tmp_path,
                                            sharded_save_flag):
        _need8()
        ref, _ = self._save_stage3(tmp_path)
        c = _trainer(build_mesh(devices=jax.devices()[:1]), stage=0,
                     seed=11)
        assert restore_train_checkpoint(c, str(tmp_path)) is not None
        arrays_c, _ = c.train_state()
        for k, v in ref.items():
            np.testing.assert_array_equal(
                np.asarray(arrays_c[k]), v,
                err_msg=f"{k} not bit-exact stage3->unsharded")

    def test_roundtrip_same_topology_still_bit_exact(self, tmp_path,
                                                     sharded_save_flag):
        """N steps ≡ N/2 + sharded-save + restore + N/2 (the r9
        contract survives the sharded format)."""
        _need8()
        mesh8 = build_mesh(sharding=8)
        full = _trainer(mesh8, stage=3)
        want = [float(np.asarray(full(*_batch(i)).value))
                for i in range(4)]
        a = _trainer(mesh8, stage=3)
        got = [float(np.asarray(a(*_batch(i)).value)) for i in range(2)]
        save_train_checkpoint(a, str(tmp_path))
        b = _trainer(mesh8, stage=3, seed=17)
        restore_train_checkpoint(b, str(tmp_path))
        got += [float(np.asarray(b(*_batch(i)).value))
                for i in range(2, 4)]
        assert got == want

    def test_world_regroup_shardslices(self, tmp_path):
        """Host-plane fleet path: world-3 rank slices reassemble into
        world-2 slices (uneven boundaries force real overlap math)."""
        y = np.arange(7 * 4, dtype=np.float32).reshape(7, 4)
        for r in range(3):
            save_state_dict({"m": ShardSlice.of(y, r, 3)},
                            str(tmp_path), rank=r, world=3)
        for r in range(2):
            ss = ShardSlice.placeholder((7, 4), np.float32, r, 2)
            load_state_dict({"m": ss}, str(tmp_path))
            s, e = ss.index[0]
            np.testing.assert_array_equal(ss.data, y[s:e])

    def test_missing_rank_shard_raises_named_error(self, tmp_path):
        """The satellite: a world-size mismatch (stale dir missing a
        rank file) surfaces as ReshardError naming the gap and the
        target-sharding API — not an opaque shard-count failure."""
        y = np.arange(12, dtype=np.float32).reshape(6, 2)
        for r in range(2):
            save_state_dict({"m": ShardSlice.of(y, r, 2)},
                            str(tmp_path), rank=r, world=2)
        os.remove(tmp_path / "1.distcp")
        with pytest.raises(ReshardError) as ei:
            load_state_dict({"m": Tensor(np.zeros((6, 2), np.float32))},
                            str(tmp_path))
        msg = str(ei.value)
        assert "1.distcp" in msg and "world 2" in msg
        assert "target sharding" in msg or "ShardSlice" in msg

    def test_shape_mismatch_raises_named_error(self, tmp_path):
        save_state_dict(
            {"w": Tensor(np.ones((8, 4), np.float32))}, str(tmp_path))
        with pytest.raises(ReshardError, match="global shape"):
            load_state_dict({"w": Tensor(np.zeros((4, 4), np.float32))},
                            str(tmp_path))

    def test_pre_reshard_null_stop_index_loads(self, tmp_path):
        """Backward compat: pre-reshard v2 containers serialized a
        replicated dim's slice as [start, null] (a jax slice with stop
        None) — the lazy reader resolves the open stop from the blob's
        own local extent instead of crashing on int(None)."""
        y = np.arange(6 * 4, dtype=np.float32).reshape(6, 4)
        shards = {"w": {"local": [y[:3], y[3:]],
                        "index": [[(0, 3), (0, None)],
                                  [(3, None), (0, None)]]}}
        meta = {"w": {"global_shape": [6, 4], "dtype": "float32",
                      "rank": 0, "sharded": True},
                "__world__": 1}
        ckpt._write_files(str(tmp_path), 0, shards, meta, 0)
        tgt = {"w": Tensor(np.zeros((6, 4), np.float32))}
        load_state_dict(tgt, str(tmp_path))
        np.testing.assert_array_equal(np.asarray(tgt["w"].value), y)

    def test_reshard_failure_falls_back_to_older_step(self, tmp_path):
        """A newest COMPLETE step the target cannot reshard from falls
        back to the next newest complete step, exactly like corruption;
        when NO candidate satisfies the contract the named ReshardError
        surfaces instead of a silent cold-start None."""
        save_checkpoint({"w": Tensor(np.full((8, 4), 1.0, np.float32))},
                        str(tmp_path), step=1)
        save_checkpoint({"w": Tensor(np.full((4, 4), 2.0, np.float32))},
                        str(tmp_path), step=2)
        tgt = {"w": Tensor(np.zeros((8, 4), np.float32))}
        got = load_checkpoint(tgt, str(tmp_path))
        assert got is not None and got[0] == 1
        np.testing.assert_array_equal(
            np.asarray(tgt["w"].value), np.full((8, 4), 1.0, np.float32))
        with pytest.raises(ReshardError, match="global shape"):
            load_checkpoint(
                {"w": Tensor(np.zeros((5, 4), np.float32))},
                str(tmp_path))

    def test_elastic_resume_event_emitted(self, tmp_path, monkeypatch):
        """A restore at a different world than the save announces
        itself: fleet.elastic telemetry event + counter + warning."""
        from paddle_tpu import telemetry
        trainer = _trainer(build_mesh(devices=jax.devices()[:1]))
        trainer(*_batch(0))
        arrays, meta = trainer.train_state()
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        # a world-4 save: every rank writes its file, rank 0 commits
        for r in (1, 2, 3, 0):
            monkeypatch.setenv("PADDLE_TRAINER_ID", str(r))
            save_checkpoint(arrays, str(tmp_path), step=1, meta=meta)
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        telemetry.reset()
        probe = telemetry.MemorySink()
        telemetry.add_sink(probe)
        try:
            fresh = _trainer(build_mesh(devices=jax.devices()[:1]),
                             seed=23)
            with pytest.warns(RuntimeWarning, match="elastic resume"):
                meta = restore_train_checkpoint(fresh, str(tmp_path))
            assert meta is not None and int(meta["world"]) == 4
            events = [r for r in probe.records
                      if r.get("event") == "fleet.elastic"]
            assert events and events[0]["old_world"] == 4 \
                and events[0]["new_world"] == 1
        finally:
            telemetry.reset()


# ---------------------------------------------------------------------------
# retention GC under elastic shrink (satellite)
# ---------------------------------------------------------------------------

class TestGcUnderShrink:
    def _save_world2(self, root, step, monkeypatch):
        y = np.arange(12, dtype=np.float32).reshape(6, 2) + step
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        # rank 1 first (no commit), rank 0 commits after both landed
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        save_checkpoint({"m": ShardSlice.of(y, 1, 2)}, root, step,
                        keep=10)
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        save_checkpoint({"m": ShardSlice.of(y, 0, 2)}, root, step,
                        keep=10)
        monkeypatch.delenv("PADDLE_TRAINERS_NUM")
        monkeypatch.delenv("PADDLE_TRAINER_ID")
        return y

    def test_old_world_dir_survives_until_new_commit(self, tmp_path,
                                                     monkeypatch):
        root = str(tmp_path)
        y = self._save_world2(root, 3, monkeypatch)
        old_dir = os.path.join(root, "step_00000003")
        assert ckpt.is_complete(old_dir)
        # dp=2 -> dp=1 resume: restore reassembles the world-2 slices
        t = Tensor(np.zeros((6, 2), np.float32))
        got = load_checkpoint({"m": t}, root)
        assert got is not None and got[0] == 3
        np.testing.assert_array_equal(np.asarray(t.value), y)
        # a FAILED new-world save must leave the restore source alone
        paddle.set_flags({"FLAGS_ckpt_write_retries": 1})
        try:
            with fault.scope("ckpt.write:times=*:mode=error"):
                with pytest.raises((IOError, OSError)):
                    save_checkpoint({"m": Tensor(np.ones((6, 2),
                                                         np.float32))},
                                    root, 4, keep=1)
        finally:
            paddle.set_flags({"FLAGS_ckpt_write_retries": 3})
        assert os.path.isdir(old_dir) and ckpt.is_complete(old_dir)
        assert load_checkpoint(
            {"m": Tensor(np.zeros((6, 2), np.float32))}, root)[0] == 3
        # keep=2 new-world commit: the old-world dir is still retained
        save_checkpoint({"m": Tensor(np.ones((6, 2), np.float32))},
                        root, 5, keep=2)
        assert os.path.isdir(old_dir) and ckpt.is_complete(old_dir)
        # only once ANOTHER complete new-world step commits at keep=1
        # may retention reap the old-world dir
        save_checkpoint({"m": Tensor(np.ones((6, 2), np.float32))},
                        root, 6, keep=1)
        assert not os.path.isdir(old_dir)
        assert load_checkpoint(
            {"m": Tensor(np.zeros((6, 2), np.float32))}, root)[0] == 6


# ---------------------------------------------------------------------------
# topology-aware data cursor
# ---------------------------------------------------------------------------

class TestElasticEnvValidation:
    """Satellite: the controller's heartbeat/settle cadence knobs are
    documented PADDLE_ELASTIC_* envs that fail LOUDLY (naming the env)
    on malformed or inconsistent values."""

    def test_bad_values_named_loudly(self):
        import importlib
        from paddle_tpu.distributed.launch import controller as c
        knobs = ("PADDLE_ELASTIC_HEARTBEAT_TTL",
                 "PADDLE_ELASTIC_HEARTBEAT_INTERVAL",
                 "PADDLE_HEARTBEAT_TTL")
        # the module constants must be re-derived from the AMBIENT env
        # after this test (conftest pins PADDLE_HEARTBEAT_TTL=20 for
        # the whole suite — leaving the module at another TTL skews
        # every later rendezvous deadline), so env manipulation is
        # explicit and the final reload happens AFTER restoration
        orig = {k: os.environ.get(k) for k in knobs}
        ambient_ttl = float(os.environ.get("PADDLE_HEARTBEAT_TTL", 45))
        try:
            os.environ["PADDLE_ELASTIC_HEARTBEAT_TTL"] = "nope"
            with pytest.raises(ValueError,
                               match="PADDLE_ELASTIC_HEARTBEAT_TTL"):
                importlib.reload(c)
            os.environ["PADDLE_ELASTIC_HEARTBEAT_TTL"] = "-3"
            with pytest.raises(ValueError, match="must be >"):
                importlib.reload(c)
            # TTL <= interval reaps every pod: rejected as a pair
            os.environ["PADDLE_ELASTIC_HEARTBEAT_TTL"] = "0.5"
            os.environ["PADDLE_ELASTIC_HEARTBEAT_INTERVAL"] = "2"
            with pytest.raises(ValueError, match="must exceed"):
                importlib.reload(c)
            # the legacy spelling keeps working
            del os.environ["PADDLE_ELASTIC_HEARTBEAT_TTL"]
            del os.environ["PADDLE_ELASTIC_HEARTBEAT_INTERVAL"]
            os.environ["PADDLE_HEARTBEAT_TTL"] = "33"
            importlib.reload(c)
            assert c.HEARTBEAT_TTL == 33.0
        finally:
            for k, v in orig.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            importlib.reload(c)  # back to the ambient-env state
        assert c.HEARTBEAT_TTL == ambient_ttl

    def test_drain_grace_zero_accepted(self):
        """PADDLE_DRAIN_GRACE=0 is a sanctioned immediate-flush config
        (serving flushes partials on the spot) — the import-time
        validation admits the 0 boundary, rejects negatives."""
        import importlib
        from paddle_tpu.distributed.launch import controller as c
        orig = os.environ.get("PADDLE_DRAIN_GRACE")
        try:
            os.environ["PADDLE_DRAIN_GRACE"] = "0"
            importlib.reload(c)
            assert c.DRAIN_GRACE == 0.0
            os.environ["PADDLE_DRAIN_GRACE"] = "-1"
            with pytest.raises(ValueError, match="PADDLE_DRAIN_GRACE"):
                importlib.reload(c)
        finally:
            if orig is None:
                os.environ.pop("PADDLE_DRAIN_GRACE", None)
            else:
                os.environ["PADDLE_DRAIN_GRACE"] = orig
            importlib.reload(c)  # back to the ambient-env state


class TestElasticCursor:
    def test_world_independent_global_order(self):
        strides = {}
        for world in (1, 2, 4):
            got = []
            for step in range(3):
                parts = []
                for rank in range(world):
                    s = ElasticBatchSampler(
                        48, 12, cursor=ElasticDataCursor(0, step * 12),
                        rank=rank, world=world, shuffle=True, seed=5)
                    parts.extend(next(iter(s)))
                got.append(parts)
            strides[world] = got
        assert strides[1] == strides[2] == strides[4]

    def test_resume_at_new_world_replays_unseen_exactly(self):
        n, g = 48, 12
        ref = ElasticBatchSampler(n, g, rank=0, world=1, shuffle=True,
                                  seed=7)
        order = list(ref.global_order(0))
        cursor = ElasticDataCursor()
        # world 4 consumes two steps
        for _ in range(2):
            for rank in range(4):
                ElasticBatchSampler(n, g, cursor=ElasticDataCursor(
                    cursor.epoch, cursor.offset), rank=rank, world=4,
                    shuffle=True, seed=7)
            cursor.advance(g)
        # shrink to world 2: remaining yields cover EXACTLY the unseen
        seen = []
        for rank in range(2):
            s = ElasticBatchSampler(n, g, cursor=ElasticDataCursor(
                cursor.epoch, cursor.offset), rank=rank, world=2,
                shuffle=True, seed=7)
            for batch in s:
                seen.extend(batch)
        assert sorted(seen) == sorted(order[2 * g:])
        assert len(seen) == len(set(seen)) == n - 2 * g

    def test_validation(self):
        with pytest.raises(ValueError, match="divide"):
            ElasticBatchSampler(48, 10, rank=0, world=4)
        with pytest.raises(ValueError, match="world"):
            ElasticBatchSampler(48, 12, rank=4, world=4)

    def test_cursor_state_roundtrip(self):
        c = ElasticDataCursor()
        c.advance(24)
        c.next_epoch()
        c.advance(12)
        d = ElasticDataCursor()
        d.load_state_dict(c.state_dict())
        assert (d.epoch, d.offset) == (1, 12)

    def test_trainer_meta_carries_cursor(self, tmp_path):
        trainer = _trainer(build_mesh(devices=jax.devices()[:1]))
        cur = ElasticDataCursor()
        trainer.attach_data_cursor(cur)
        trainer(*_batch(0))
        cur.advance(8)
        save_train_checkpoint(trainer, str(tmp_path))
        fresh = _trainer(build_mesh(devices=jax.devices()[:1]), seed=23)
        cur2 = ElasticDataCursor()
        fresh.attach_data_cursor(cur2)
        meta = restore_train_checkpoint(fresh, str(tmp_path))
        assert meta["data_cursor"] == {"epoch": 0, "offset": 8}
        assert (cur2.epoch, cur2.offset) == (0, 8)


class TestFitCursorResume:
    """hapi Model.fit drives the cursor instead of iterator
    fast-forward: a crash + fresh-process resume replays bit-exactly."""

    def _fit(self, root, epochs=2, crash_spec=None, num_iters=None):
        from paddle_tpu.hapi.callbacks import (Callback,
                                               FaultTolerantCheckpoint)

        class DS(paddle.io.Dataset):
            def __init__(self, n=24):
                rng = np.random.RandomState(0)
                self.x = rng.randn(n, 8).astype(np.float32)
                self.y = rng.randn(n, 1).astype(np.float32)

            def __len__(self):
                return len(self.x)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        class MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = paddle.nn.Linear(8, 16)
                self.fc2 = paddle.nn.Linear(16, 1)

            def forward(self, x):
                return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

        out = {}

        class Rec(Callback):
            def on_train_batch_end(self, step, logs=None):
                out[self.model._optimizer._step_count] = logs["loss"]

        paddle.seed(7)
        model = paddle.Model(MLP())
        opt = paddle.optimizer.AdamW(1e-2,
                                     parameters=model.parameters())
        model.prepare(opt, paddle.nn.MSELoss())
        sampler = ElasticBatchSampler(DS(), 4, shuffle=True, seed=3)
        loader = paddle.io.DataLoader(DS(), batch_sampler=sampler)
        cbs = [Rec()]
        if root is not None:
            cbs.append(FaultTolerantCheckpoint(root))
        if crash_spec:
            paddle.set_flags({"FLAGS_fault_injection": crash_spec})
            fault.reset()
        try:
            model.fit(loader, epochs=epochs, verbose=0, callbacks=cbs,
                      num_iters=num_iters)
        finally:
            if crash_spec:
                paddle.set_flags({"FLAGS_fault_injection": ""})
                fault.reset()
        return out, sampler.cursor

    def test_num_iters_rejected_with_cursor(self):
        with pytest.raises(ValueError, match="num_iters"):
            self._fit(None, num_iters=2)

    def test_plain_loader_fit_clears_stale_cursor(self):
        """A fit with a PLAIN loader after an elastic fit must drop the
        previous sampler's cursor: a stale (epoch, offset) checkpointed
        beside plain-loader batches would route the next resume through
        the no-fast-forward elastic branch and replay consumed data."""

        class DS(paddle.io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return (np.full(4, i, np.float32),
                        np.zeros(1, np.float32))

        model = paddle.Model(paddle.nn.Linear(4, 1))
        opt = paddle.optimizer.SGD(1e-3, parameters=model.parameters())
        model.prepare(opt, paddle.nn.MSELoss())
        sampler = ElasticBatchSampler(DS(), 4, shuffle=False, seed=1)
        model.fit(paddle.io.DataLoader(DS(), batch_sampler=sampler),
                  epochs=1, verbose=0)
        assert model._data_cursor is sampler.cursor
        model.fit(paddle.io.DataLoader(DS(), batch_size=4),
                  epochs=1, verbose=0)
        assert model._data_cursor is None

    def test_crash_resume_bit_exact_and_sample_exact(self, tmp_path):
        ref, ref_cursor = self._fit(None)
        assert len(ref) == 12  # 2 epochs x 6 global batches
        root = str(tmp_path / "ckpt")
        with pytest.raises((IOError, OSError)):
            self._fit(root, crash_spec="step.begin:step=8:mode=error")
        got1, cur1 = self._fit(root)  # fresh "process": restores
        # the resume continued the stream mid-epoch: exactly the steps
        # after the last committed checkpoint re-ran, each bit-exact
        assert min(got1) == 8 and max(got1) == 12, sorted(got1)
        for k, v in got1.items():
            assert ref[k] == v, (k, v, ref[k])
        assert (cur1.epoch, cur1.offset) == (ref_cursor.epoch,
                                             ref_cursor.offset)
