"""SLO-driven elastic autoscaler (ISSUE 19): the policy state machine
in isolation, the lease/epoch fencing, crash recovery, fault rollback,
the deterministic load sim, the sliding-window shed-rate satellite, and
the CLI selftest wiring.

The contracts under test:

  * POLICY — `decide` over synthetic fleet views: the hysteresis
    window gates a scale-out, oscillating load never produces an
    action (streaks are CONSECUTIVE), an executed action's
    stabilization cooldown blocks the opposite kind (no flap by
    construction), floor repair bypasses every gate, the scale-in
    victim is least-work/newest-id, role repair flips the least-loaded
    donor.
  * FENCING — the lease is per-daemon advisory (second daemon gets
    no_lease; an expired lease is taken over), the per-epoch `put_new`
    journal claim is the true fence (a foreign record is stepped past,
    never rewritten).
  * RECOVERY — a daemon crashing between execute and commit leaves a
    pending record; the next incarnation completes it (status done,
    recovered_by) WITHOUT re-executing the drain.
  * ROLLBACK — exhausted retries on autoscale.drain / autoscale.reform
    roll the action back: the target returns to rotation, the fleet
    shape is unchanged, the journal records the error.
  * SIM — DiurnalLoadSim is reproducible from (seed, tick) alone,
    independent of call order.
  * SHED WINDOW (satellite) — a shed burst ages out of
    `shed_rate_window` as later terminals push it off, while the
    cumulative shed_rate keeps the history.
  * CLI (satellite) — `autoscale_report --selftest` and
    `chaos_check --autoscale --selftest` exit 0 (tier-1 wiring).
"""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import telemetry
from paddle_tpu.distributed import fault
from paddle_tpu.fleet import (Action, AutoscalePolicy, AutoscalerDaemon,
                              DiurnalLoadSim, PolicyState, after_action,
                              decide, fleet_view, observe)
from paddle_tpu.fleet.autoscaler import _LocalKV, _SimulatedCrash
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.inference import ContinuousBatcher, ServeRouter
from paddle_tpu.models.llama import (LlamaForCausalLM,
                                     llama_tiny_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _bat(model, **kw):
    geom = dict(max_batch_size=1, max_len=64, chunk=4, prefill_chunk=4)
    geom.update(kw)
    return ContinuousBatcher(model, **geom)


@pytest.fixture()
def autoscale_on():
    set_flags({"FLAGS_autoscale": True})
    try:
        yield
    finally:
        set_flags({"FLAGS_autoscale": False})


def _fv(occ, reps=2, draining=(), work=None, att=None, shed=0.0,
        roles=None):
    """Synthetic fleet view for the pure-policy tests."""
    replicas = []
    for i in range(reps):
        q = (work or {}).get(i, 0)
        replicas.append({"replica": i,
                         "role": (roles or {}).get(i, "serve"),
                         "draining": i in draining,
                         "queued": q, "active": 0,
                         "attainment_interactive": att})
    routable = reps - len(set(draining) & set(range(reps)))
    return {"replicas": replicas, "routable": routable,
            "slots": routable, "queued": sum(
                (work or {}).values()), "active": 0,
            "occupancy": occ, "attainment_interactive": att,
            "shed_rate_window": shed}


# ---------------------------------------------------------------------------
# policy state machine in isolation (no fleet, no KV)
# ---------------------------------------------------------------------------

def test_hysteresis_window_gates_scale_out():
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, window=2,
                          cooldown=0, queue_high=1.0, queue_low=0.2)
    st = PolicyState()
    observe(st, _fv(2.0), pol)
    assert decide(_fv(2.0), pol, st).kind == "none"
    observe(st, _fv(2.0), pol)
    act = decide(_fv(2.0), pol, st)
    assert act.kind == "scale_out", act


def test_oscillating_load_never_acts():
    """Pressured/idle alternating every tick: both streaks keep
    resetting, so a window-2 policy NEVER reaches an action — the
    hysteresis is what forbids the flap at the source."""
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, window=2,
                          cooldown=0, queue_high=1.0, queue_low=0.5)
    st = PolicyState()
    for t in range(20):
        view = _fv(2.0 if t % 2 == 0 else 0.0, reps=2)
        observe(st, view, pol)
        assert decide(view, pol, st).kind == "none", t


def test_stabilization_cooldown_blocks_opposite_kind():
    """After an executed scale_out, an immediate idle phase must wait
    out the cooldown before the opposite scale_in fires — the
    stabilization window covers BOTH directions."""
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, window=1,
                          cooldown=3, queue_high=1.0, queue_low=0.5)
    st = PolicyState()
    observe(st, _fv(2.0, reps=2), pol)
    act = decide(_fv(2.0, reps=2), pol, st)
    assert act.kind == "scale_out"
    after_action(st, act, pol)
    assert st.cooling("scale_in") and st.cooling("scale_out")
    idle = _fv(0.0, reps=3)
    kinds = []
    for _ in range(4):
        observe(st, idle, pol)
        kinds.append(decide(idle, pol, st).kind)
    assert kinds == ["none", "none", "scale_in", "scale_in"], kinds


def test_floor_repair_bypasses_every_gate():
    """routable < min is an availability incident: no hysteresis, no
    cooldown — and a draining replica is revived (undrain is free)
    over spawning fresh."""
    pol = AutoscalePolicy(min_replicas=2, max_replicas=4, window=5,
                          cooldown=5, queue_high=1.0, queue_low=0.2)
    st = PolicyState()
    st.cooldowns["scale_out"] = 99           # mid-cooldown, streak 0
    act = decide(_fv(0.0, reps=3, draining=(1, 2)), pol, st)
    assert act.kind == "scale_out" and act.replica == 1, act
    act = decide(_fv(0.0, reps=1), pol, st)
    assert act.kind == "scale_out" and act.replica is None, act


def test_scale_in_victim_least_work_newest_on_tie():
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, window=1,
                          cooldown=0, queue_high=9.0, queue_low=0.5)
    st = PolicyState()
    observe(st, _fv(0.0, reps=3), pol)
    act = decide(_fv(0.0, reps=3, work={0: 4, 1: 0, 2: 0}), pol, st)
    assert act.kind == "scale_in" and act.replica == 2, act


def test_role_repair_flips_least_loaded_donor():
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, window=1,
                          cooldown=0, queue_high=9.0, queue_low=0.0,
                          target_roles={"serve": 1, "decode": 1})
    st = PolicyState()
    act = decide(_fv(0.5, reps=2, work={0: 3, 1: 1}), pol, st)
    assert act.kind == "role_flip" and act.replica == 1 \
        and act.role == "decode", act


# ---------------------------------------------------------------------------
# lease + epoch fencing
# ---------------------------------------------------------------------------

def test_lease_second_daemon_fenced_out(model, autoscale_on):
    kv = _LocalKV()
    router = ServeRouter(batchers=[_bat(model)])
    pol = AutoscalePolicy(min_replicas=1, max_replicas=2,
                          lease_ttl_s=1000.0)
    d1 = AutoscalerDaemon(router, kv=kv, policy=pol, daemon_id="a")
    d2 = AutoscalerDaemon(router, kv=kv, policy=pol, daemon_id="b")
    assert d1.tick()["status"] != "no_lease"
    assert d2.tick()["status"] == "no_lease"
    assert d1.tick()["status"] != "no_lease"     # refresh still holds


def test_expired_lease_taken_over(model, autoscale_on):
    kv = _LocalKV()
    router = ServeRouter(batchers=[_bat(model)])
    d1 = AutoscalerDaemon(
        router, kv=kv, daemon_id="a",
        policy=AutoscalePolicy(min_replicas=1, max_replicas=2,
                               lease_ttl_s=0.0))
    d2 = AutoscalerDaemon(
        router, kv=kv, daemon_id="b",
        policy=AutoscalePolicy(min_replicas=1, max_replicas=2,
                               lease_ttl_s=1000.0))
    assert d1.tick()["status"] != "no_lease"
    takeovers = telemetry.counter("autoscaler.lease_takeovers").value
    assert d2.tick()["status"] != "no_lease"     # expired: taken over
    assert telemetry.counter("autoscaler.lease_takeovers").value \
        == takeovers + 1
    assert d1.tick()["status"] == "no_lease"     # b's lease is live


def test_epoch_claim_steps_past_foreign_record(model):
    """put_new on the journal key is the fence: a foreign epoch-0
    record survives byte-identical and the claim lands on epoch 1."""
    router = ServeRouter(batchers=[_bat(model)])
    d = AutoscalerDaemon(router)
    foreign = json.dumps({"epoch": 0, "owner": "other",
                          "status": "done", "kind": "scale_out"})
    assert d.kv.put_new(d._journal_key(0), foreign)
    epoch = d._claim_epoch(Action("scale_out"), {})
    assert epoch == 1
    assert d.kv.get(d._journal_key(0)) == foreign
    recs = d.journal()
    assert [r["epoch"] for r in recs] == [0, 1]
    assert recs[1]["status"] == "pending"


# ---------------------------------------------------------------------------
# crash recovery + fault rollback (real fleet, _LocalKV)
# ---------------------------------------------------------------------------

def _idle_policy(**kw):
    """Empty fleet reads as idle immediately: window 1, occ 0 < 0.9."""
    base = dict(min_replicas=1, max_replicas=3, window=1, cooldown=0,
                queue_high=9.0, queue_low=0.9, retry_budget=2,
                backoff_s=0.0, lease_ttl_s=0.0)
    base.update(kw)
    return AutoscalePolicy(**base)


def test_crash_before_commit_recovers_without_reexecution(
        model, autoscale_on):
    router = ServeRouter(batchers=[_bat(model), _bat(model)])
    d1 = AutoscalerDaemon(router, policy=_idle_policy(), daemon_id="a")
    d1._crash_before_commit = True
    with pytest.raises(_SimulatedCrash):
        d1.tick()
    (rec,) = d1.journal()
    assert rec["status"] == "pending" and rec["kind"] == "scale_in"
    victim = rec["replica"]
    assert router._reps[victim].draining      # the drain DID land
    drains = telemetry.counter("router.drains").value
    d2 = AutoscalerDaemon(router, kv=d1.kv, policy=_idle_policy(),
                          daemon_id="b")
    out = d2.tick()
    assert out["status"] != "no_lease", out
    (rec,) = d2.journal()
    assert rec["status"] == "done", rec       # completed, not redone
    assert rec["recovered_by"] == "b"
    assert telemetry.counter("router.drains").value == drains, \
        "recovery re-executed the drain (double-execution fence broke)"


def test_recover_rolls_back_scale_out_that_never_happened(
        model, autoscale_on):
    router = ServeRouter(batchers=[_bat(model)])
    d = AutoscalerDaemon(router, policy=_idle_policy(), daemon_id="a")
    d.kv.put_new(d._journal_key(0), json.dumps({
        "epoch": 0, "owner": "dead", "status": "pending",
        "kind": "scale_out", "replica": None,
        "fleet_before": len(router._reps)}))
    assert d.recover() == 1
    (rec,) = d.journal()
    assert rec["status"] == "rolled_back"
    assert rec["recovered_by"] == "a"
    assert len(router._reps) == 1             # nothing spawned


def test_drain_fault_rolls_back_and_returns_replica(
        model, autoscale_on):
    router = ServeRouter(batchers=[_bat(model), _bat(model)])
    d = AutoscalerDaemon(router, policy=_idle_policy(), daemon_id="a")
    rollbacks = telemetry.counter("autoscaler.rollback").value
    with fault.scope("autoscale.drain:times=*:mode=error"):
        out = d.tick()
    assert out["status"] == "rolled_back", out
    assert not any(r.draining for r in router._reps)
    assert len([r for r in router._reps if not r.dead]) == 2
    (rec,) = d.journal()
    assert rec["status"] == "rolled_back" and rec["error"], rec
    assert telemetry.counter("autoscaler.rollback").value \
        == rollbacks + 1


def test_reform_fault_rolls_back_scale_out(model, autoscale_on):
    router = ServeRouter(batchers=[_bat(model)])
    d = AutoscalerDaemon(
        router, spawn=lambda: _bat(model), daemon_id="a",
        policy=_idle_policy(queue_high=1.5, queue_low=0.1))
    rng = np.random.RandomState(4)
    for _ in range(3):
        router.submit(rng.randint(1, 128, 6).astype(np.int32), 4)
    with fault.scope("autoscale.reform:times=*:mode=error"):
        out = d.tick()
    assert out["status"] == "rolled_back", out
    assert len(router._reps) == 1             # fleet shape unchanged
    outs = router.run()                       # the work still completes
    assert len(outs) == 3 and router.stats()["requests_shed"] == 0


# ---------------------------------------------------------------------------
# DiurnalLoadSim determinism
# ---------------------------------------------------------------------------

def test_diurnal_sim_reproducible_and_order_independent():
    a = DiurnalLoadSim(vocab=128, seed=3, period=6, low=1, high=6)
    b = DiurnalLoadSim(vocab=128, seed=3, period=6, low=1, high=6)
    b.requests(5)                 # call order must not matter
    for t in (0, 3, 5):
        ra, rb = a.requests(t), b.requests(t)
        assert len(ra) == len(rb) == a.rate(t)
        for x, y in zip(ra, rb):
            np.testing.assert_array_equal(x["prompt"], y["prompt"])
            assert x["slo"] == y["slo"]
    assert a.rate(3) == 6 and a.rate(0) == 1  # peak/trough of the day
    c = DiurnalLoadSim(vocab=128, seed=4, period=6, low=1, high=6)
    assert any(not np.array_equal(x["prompt"], y["prompt"])
               for x, y in zip(a.requests(3), c.requests(3)))


# ---------------------------------------------------------------------------
# sliding-window shed rate (satellite): the burst ages out
# ---------------------------------------------------------------------------

def test_shed_window_ages_out_while_cumulative_persists(model):
    bat = _bat(model, max_batch_size=4, max_len=16)
    rng = np.random.RandomState(9)
    p = rng.randint(1, 128, 2).astype(np.int32)
    set_flags({"FLAGS_serve_queue_depth": 1})
    try:
        for _ in range(3):                    # 2 of these shed
            bat.submit(p, 1, slo="best_effort")
    finally:
        set_flags({"FLAGS_serve_queue_depth": 0})
    bat.run()
    assert bat.stats()["requests_shed"] == 2
    assert bat.shed_rate_window > 0.0
    for _ in range(256):                      # push the burst off
        bat.submit(p, 1)
    bat.run()
    view = bat.router_view()
    assert view["shed_rate_window"] == 0.0, view
    assert view["shed_rate"] > 0.0, view      # history NOT rewritten
    assert bat.stats()["requests_shed"] == 2


# ---------------------------------------------------------------------------
# CLI selftest wiring (satellite 5)
# ---------------------------------------------------------------------------

def test_autoscale_report_selftest_cli():
    """Tier-1 wiring: the journal report CLI drives a diurnal fleet
    in-process and validates >= 1 scale-out + >= 1 scale-in, flap
    count 0, every record terminal — exit 0."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import autoscale_report as cli
    finally:
        sys.path.pop(0)
    assert cli.main(["--selftest"]) == 0


def test_chaos_autoscale_selftest_cli():
    """Tier-1 wiring: daemon kill mid-drain, drained-replica kill,
    decide fault, reform fault — fleet converges, outputs bit-exact vs
    the fixed-fleet reference, no double-executed epoch — exit 0."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check as cli
    finally:
        sys.path.pop(0)
    assert cli.main(["--autoscale", "--selftest"]) == 0
