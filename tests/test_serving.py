"""Continuous batching (inference/serving.py — round-5 verdict item 8).

Reference analog: block_multihead_attention.py paged-KV scheduling.
The contract under test: staggered requests flowing through ONE
batcher produce EXACTLY the tokens each request gets from an isolated
greedy generate() run — admission, eviction, and slot reuse must never
leak state across sequences.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatcher
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _isolated(model, ids, n):
    out = model.generate(paddle.to_tensor(np.asarray([ids], np.int32)),
                         max_new_tokens=n)
    return np.asarray(out.value)[0]


def test_staggered_requests_match_isolated(model):
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (4, 7, 4, 11, 7)]
    new = [6, 9, 12, 5, 8]

    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4)
    # stagger: two submitted up-front, rest arrive while running
    ids = [bat.submit(prompts[0], new[0]), bat.submit(prompts[1], new[1])]
    bat.step()
    ids.append(bat.submit(prompts[2], new[2]))
    bat.step()
    ids.append(bat.submit(prompts[3], new[3]))
    ids.append(bat.submit(prompts[4], new[4]))
    outs = bat.run()

    assert sorted(outs) == sorted(ids)
    for rid, prompt, n in zip(ids, prompts, new):
        want = _isolated(model, prompt, n)
        got = outs[rid]
        np.testing.assert_array_equal(got, want[: len(got)])
        assert len(got) == n


def test_slot_reuse_no_state_leak(model):
    """A slot that served a LONG sequence must serve a later SHORT one
    identically to isolation (stale cache rows beyond the new prompt
    must stay invisible)."""
    rng = np.random.RandomState(9)
    long_p = rng.randint(1, 128, 20).astype(np.int32)
    short_p = rng.randint(1, 128, 5).astype(np.int32)

    bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                            chunk=8)
    r1 = bat.submit(long_p, 16)
    r2 = bat.submit(short_p, 10)      # queued until slot 0 frees
    outs = bat.run()
    np.testing.assert_array_equal(outs[r1],
                                  _isolated(model, long_p, 16))
    np.testing.assert_array_equal(outs[r2],
                                  _isolated(model, short_p, 10))


def test_eos_eviction(model):
    """eos finishes a sequence early; its slot frees for the queue."""
    rng = np.random.RandomState(1)
    p = rng.randint(1, 128, 6).astype(np.int32)
    ref = _isolated(model, p, 24)
    eos = int(ref[2])                  # force an early-ish stop token
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                            chunk=4, eos_token_id=eos)
    rid = bat.submit(p, 24)
    outs = bat.run()
    got = outs[rid]
    assert got[-1] == eos and len(got) <= 24
    np.testing.assert_array_equal(got, ref[: len(got)])


def test_mixed_lengths_aggregate(model):
    """Mixed prompt lengths in flight simultaneously (one shared
    admission program, one shared decode program)."""
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (3, 9, 15, 6)]
    bat = ContinuousBatcher(model, max_batch_size=4, max_len=64,
                            chunk=8)
    rids = [bat.submit(p, 8) for p in prompts]
    outs = bat.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid], _isolated(model, p, 8))


def test_chunked_admission_overlaps_decode(model):
    """Chunked-prefill parity: prompts LONGER than prefill_chunk are
    consumed across several admission-mode chunks while the resident
    slot keeps decoding (staggered arrival mid-decode); every request
    must still match its isolated greedy run bit-for-bit."""
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 13, 11, 9)]
    new = [10, 7, 9, 6]
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4)
    ids = [bat.submit(prompts[0], new[0])]
    bat.step()                      # slot 0 decoding alone
    # 13-token prompt = 4 admission chunks, admitted while decoding
    ids.append(bat.submit(prompts[1], new[1]))
    bat.step()
    ids.append(bat.submit(prompts[2], new[2]))
    ids.append(bat.submit(prompts[3], new[3]))
    outs = bat.run()
    for rid, p, n in zip(ids, prompts, new):
        np.testing.assert_array_equal(outs[rid], _isolated(model, p, n))
    st = bat.stats()
    # every prompt token consumed exactly once, through the scan
    assert st["prefill_tokens"] == sum(len(p) for p in prompts)
    assert st["admit_chunks"] > 0 and st["decode_chunks"] > 0
    assert 0.0 < st["avg_occupancy"] <= 1.0
    assert st["tokens_produced"] >= sum(new)


def test_admission_no_recompile_per_prompt_length(model):
    """Prompt length never reaches a program shape: a workload of many
    DISTINCT lengths runs through exactly two compiled scans (the C=1
    decode program + the C=prefill_chunk admission program).  The
    budget is enforced by analysis.recompile_guard — on violation it
    raises with the offending avals instead of a bare count — which
    also records the model-level program-cache misses."""
    from paddle_tpu.analysis import recompile_guard
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4)
    rng = np.random.RandomState(13)
    ids = []
    for L in (3, 5, 7, 9, 11, 14, 17, 21):   # 8 distinct lengths
        ids.append(bat.submit(rng.randint(1, 128, L).astype(np.int32),
                              4))
    with recompile_guard(max_programs=2, match="serve_step") as g:
        outs = bat.run()
    assert sorted(outs) == sorted(ids)
    assert bat.compiled_programs == 2
    assert len([k for k in g.cache_builds
                if isinstance(k, tuple) and k
                and k[0] == "serve_step"]) <= 2
    # and the programs live on the MODEL: a second batcher of the same
    # shape reuses them — ZERO compiles and ZERO cache misses allowed
    bat2 = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                             chunk=4, prefill_chunk=4)
    bat2.submit(rng.randint(1, 128, 6).astype(np.int32), 4)
    with recompile_guard(max_programs=0, match="serve_step") as g2:
        bat2.run()
    assert g2.count == 0
    assert [k for k in g2.cache_builds
            if isinstance(k, tuple) and k
            and k[0] == "serve_step"] == []
