"""Continuous batching (inference/serving.py — round-5 verdict item 8).

Reference analog: block_multihead_attention.py paged-KV scheduling.
The contract under test: staggered requests flowing through ONE
batcher produce EXACTLY the tokens each request gets from an isolated
greedy generate() run — admission, eviction, and slot reuse must never
leak state across sequences.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatcher
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _isolated(model, ids, n):
    out = model.generate(paddle.to_tensor(np.asarray([ids], np.int32)),
                         max_new_tokens=n)
    return np.asarray(out.value)[0]


def test_staggered_requests_match_isolated(model):
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (4, 7, 4, 11, 7)]
    new = [6, 9, 12, 5, 8]

    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4)
    # stagger: two submitted up-front, rest arrive while running
    ids = [bat.submit(prompts[0], new[0]), bat.submit(prompts[1], new[1])]
    bat.step()
    ids.append(bat.submit(prompts[2], new[2]))
    bat.step()
    ids.append(bat.submit(prompts[3], new[3]))
    ids.append(bat.submit(prompts[4], new[4]))
    outs = bat.run()

    assert sorted(outs) == sorted(ids)
    for rid, prompt, n in zip(ids, prompts, new):
        want = _isolated(model, prompt, n)
        got = outs[rid]
        np.testing.assert_array_equal(got, want[: len(got)])
        assert len(got) == n


def test_slot_reuse_no_state_leak(model):
    """A slot that served a LONG sequence must serve a later SHORT one
    identically to isolation (stale cache rows beyond the new prompt
    must stay invisible)."""
    rng = np.random.RandomState(9)
    long_p = rng.randint(1, 128, 20).astype(np.int32)
    short_p = rng.randint(1, 128, 5).astype(np.int32)

    bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                            chunk=8)
    r1 = bat.submit(long_p, 16)
    r2 = bat.submit(short_p, 10)      # queued until slot 0 frees
    outs = bat.run()
    np.testing.assert_array_equal(outs[r1],
                                  _isolated(model, long_p, 16))
    np.testing.assert_array_equal(outs[r2],
                                  _isolated(model, short_p, 10))


def test_eos_eviction(model):
    """eos finishes a sequence early; its slot frees for the queue."""
    rng = np.random.RandomState(1)
    p = rng.randint(1, 128, 6).astype(np.int32)
    ref = _isolated(model, p, 24)
    eos = int(ref[2])                  # force an early-ish stop token
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                            chunk=4, eos_token_id=eos)
    rid = bat.submit(p, 24)
    outs = bat.run()
    got = outs[rid]
    assert got[-1] == eos and len(got) <= 24
    np.testing.assert_array_equal(got, ref[: len(got)])


def test_mixed_lengths_aggregate(model):
    """Mixed prompt lengths in flight simultaneously (one shared
    admission program, one shared decode program)."""
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (3, 9, 15, 6)]
    bat = ContinuousBatcher(model, max_batch_size=4, max_len=64,
                            chunk=8)
    rids = [bat.submit(p, 8) for p in prompts]
    outs = bat.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid], _isolated(model, p, 8))


def test_chunked_admission_overlaps_decode(model):
    """Chunked-prefill parity: prompts LONGER than prefill_chunk are
    consumed across several admission-mode chunks while the resident
    slot keeps decoding (staggered arrival mid-decode); every request
    must still match its isolated greedy run bit-for-bit."""
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 13, 11, 9)]
    new = [10, 7, 9, 6]
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4)
    ids = [bat.submit(prompts[0], new[0])]
    bat.step()                      # slot 0 decoding alone
    # 13-token prompt = 4 admission chunks, admitted while decoding
    ids.append(bat.submit(prompts[1], new[1]))
    bat.step()
    ids.append(bat.submit(prompts[2], new[2]))
    ids.append(bat.submit(prompts[3], new[3]))
    outs = bat.run()
    for rid, p, n in zip(ids, prompts, new):
        np.testing.assert_array_equal(outs[rid], _isolated(model, p, n))
    st = bat.stats()
    # every prompt token consumed exactly once, through the scan
    assert st["prefill_tokens"] == sum(len(p) for p in prompts)
    assert st["admit_chunks"] > 0 and st["decode_chunks"] > 0
    assert 0.0 < st["avg_occupancy"] <= 1.0
    assert st["tokens_produced"] >= sum(new)


def test_admission_no_recompile_per_prompt_length(model):
    """Prompt length never reaches a program shape: a workload of many
    DISTINCT lengths runs through exactly two compiled scans (the C=1
    decode program + the C=prefill_chunk admission program).  The
    budget is enforced by analysis.recompile_guard — on violation it
    raises with the offending avals instead of a bare count — which
    also records the model-level program-cache misses."""
    from paddle_tpu.analysis import recompile_guard
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4)
    rng = np.random.RandomState(13)
    ids = []
    for L in (3, 5, 7, 9, 11, 14, 17, 21):   # 8 distinct lengths
        ids.append(bat.submit(rng.randint(1, 128, L).astype(np.int32),
                              4))
    with recompile_guard(max_programs=2, match="serve_step") as g:
        outs = bat.run()
    assert sorted(outs) == sorted(ids)
    assert bat.compiled_programs == 2
    assert len([k for k in g.cache_builds
                if isinstance(k, tuple) and k
                and k[0] == "serve_step"]) <= 2
    # and the programs live on the MODEL: a second batcher of the same
    # shape reuses them — ZERO compiles and ZERO cache misses allowed
    bat2 = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                             chunk=4, prefill_chunk=4)
    bat2.submit(rng.randint(1, 128, 6).astype(np.int32), 4)
    with recompile_guard(max_programs=0, match="serve_step") as g2:
        bat2.run()
    assert g2.count == 0
    assert [k for k in g2.cache_builds
            if isinstance(k, tuple) and k
            and k[0] == "serve_step"] == []


# ---------------------------------------------------------------------------
# streaming token callbacks (ISSUE 11 satellite: the r13 leftover)


def test_streaming_callbacks_match_outputs(model):
    """Every request's streamed bursts concatenate to EXACTLY its
    final output (EOS-trimmed, max_new-capped), done fires exactly
    once per request, and the first burst lands BEFORE run() returns
    everything (TTFT is a chunk boundary, not batch completion)."""
    rng = np.random.RandomState(21)
    prompts = [rng.randint(1, 128, L).astype(np.int32)
               for L in (5, 9, 4)]
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4)
    events = {}

    def cb(rid, toks, done):
        events.setdefault(rid, []).append((list(toks), done))

    rids = [bat.submit(p, 6, on_token=cb) for p in prompts]
    outs = bat.run()
    for rid in rids:
        bursts = events[rid]
        streamed = [t for ts, _ in bursts for t in ts]
        assert streamed == [int(t) for t in outs[rid]]
        assert [d for _, d in bursts].count(True) == 1
        assert bursts[-1][1] is True
        # chunked decode of 6 tokens through chunk=4 must take >1 burst
        assert len([b for b, _ in bursts if b]) >= 2


def test_streaming_never_delivers_past_eos(model):
    """A chunk can harvest tokens past EOS before the boundary evicts
    the slot — the stream must stop at EOS exactly like output()."""
    rng = np.random.RandomState(22)
    prompt = rng.randint(1, 128, 5).astype(np.int32)
    # find the greedy first token and use it as eos so the request
    # terminates mid-chunk
    first = int(_isolated(model, prompt, 1)[0])
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                            chunk=4, prefill_chunk=4,
                            eos_token_id=first)
    got = []
    rid = bat.submit(prompt, 8,
                     on_token=lambda r, t, d: got.extend(t))
    outs = bat.run()
    assert got == [int(t) for t in outs[rid]]
    assert got[-1] == first and len(got) == list(outs[rid]).index(
        first) + 1


def test_streaming_callback_errors_counted_not_fatal(model):
    rng = np.random.RandomState(23)
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                            chunk=4, prefill_chunk=4)

    def bad(rid, toks, done):
        raise RuntimeError("consumer went away")

    rid = bat.submit(rng.randint(1, 128, 5).astype(np.int32), 5,
                     on_token=bad)
    outs = bat.run()
    assert len(outs[rid]) == 5                 # batch unharmed
    assert bat.stats()["callback_errors"] >= 1


def test_streaming_requeue_no_duplicate_delivery(model):
    """A faulted-slot requeue discards the request's tokens for a
    bit-exact re-decode — the stream must NOT re-send the prefix the
    caller already has (delivered survives the requeue)."""
    from paddle_tpu.distributed import fault
    rng = np.random.RandomState(24)
    prompts = [rng.randint(1, 128, L).astype(np.int32) for L in (5, 7)]
    paddle.set_flags({"FLAGS_fault_injection":
                      "serve.decode:step=3:mode=error"})
    fault.reset()
    try:
        bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                                chunk=4, prefill_chunk=4)
        events = {}

        def cb(rid, toks, done):
            events.setdefault(rid, []).append((list(toks), done))

        rids = [bat.submit(p, 6, on_token=cb) for p in prompts]
        outs = bat.run()
        fired = fault.fired_counts().get("serve.decode", 0)
    finally:
        paddle.set_flags({"FLAGS_fault_injection": ""})
        fault.reset()
    assert fired >= 1 and bat.stats()["requests_requeued"] >= 1
    for rid in rids:
        if bat._finished[rid].shed:
            continue
        streamed = [t for ts, _ in events[rid] for t in ts]
        # no duplicates, full coverage: the stream is exactly the
        # final output once, even though the slot re-decoded
        assert streamed == [int(t) for t in outs[rid]]


def test_streaming_shed_after_fault_keeps_delivered_prefix(model):
    """A streaming request shed after repeated decode faults must not
    DISOWN tokens the consumer already holds: the delivered prefix
    survives as a partial result, so streamed == output even on the
    shed path (review fix: the no-retraction contract)."""
    from paddle_tpu.distributed import fault
    rng = np.random.RandomState(25)
    prompt = rng.randint(1, 128, 5).astype(np.int32)
    paddle.set_flags({"FLAGS_fault_injection":
                      "serve.decode:step=3:mode=error:times=*"})
    fault.reset()
    try:
        bat = ContinuousBatcher(model, max_batch_size=1, max_len=64,
                                chunk=4, prefill_chunk=4)
        events = []
        rid = bat.submit(prompt, 8,
                         on_token=lambda r, t, d: events.append(
                             (list(t), d)))
        outs = bat.run()
    finally:
        paddle.set_flags({"FLAGS_fault_injection": ""})
        fault.reset()
    req = bat._finished[rid]
    assert req.shed and req.partial
    streamed = [t for ts, _ in events for t in ts]
    assert streamed, "fault fired before any delivery — workload bug"
    assert streamed == [int(t) for t in outs[rid]]
    assert [d for _, d in events].count(True) == 1


def test_speculation_defaults_prefix_sharing_off(model):
    """Prefix sharing starves the DRAFT cache (skipped prefill chunks
    never reach it), so speculation defaults it off; explicit True
    warns but keeps both (review fix: silent accept-rate collapse)."""
    import warnings
    bat = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                            chunk=4, prefill_chunk=4,
                            kv_layout="paged", spec_tokens=2,
                            draft_model=model)
    assert bat.prefix_sharing is False
    plain = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                              chunk=4, prefill_chunk=4,
                              kv_layout="paged")
    assert plain.prefix_sharing is True
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        both = ContinuousBatcher(model, max_batch_size=2, max_len=64,
                                 chunk=4, prefill_chunk=4,
                                 kv_layout="paged", spec_tokens=2,
                                 draft_model=model,
                                 prefix_sharing=True)
    assert both.prefix_sharing is True
    assert any("accept_rate" in str(x.message) for x in w)


def test_identity_draft_ships_no_second_param_list(model):
    """Self-speculation (draft IS the target) must not re-ship the
    whole state_dict per chunk — the target's swap covers the draft
    (review fix)."""
    bat = ContinuousBatcher(model, max_batch_size=1, max_len=32,
                            chunk=4, prefill_chunk=4, spec_tokens=2,
                            draft_model=model)
    assert bat._draft_names == []
    assert bat._draft_param_vals() == []
    rng = np.random.RandomState(26)
    rid = bat.submit(rng.randint(1, 128, 5).astype(np.int32), 4)
    outs = bat.run()
    assert len(outs[rid]) == 4
