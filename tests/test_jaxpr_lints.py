"""Jaxpr lints + recompile_guard + collective-order deadlock detector.

Each lint gets a planted-defect test (the defect MUST be flagged) and a
clean-program test (no false positive on the intended pattern).  The
collective checker gets both the jaxpr extraction path and the pipeline
schedule path, including a deliberately misordered schedule caught
statically — before any device work.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.analysis import (
    lint_dtype_promotion, lint_transfers, lint_donation,
    lint_materialized_logits,
    recompile_guard, RecompileError, CollectiveOrderError,
    CollectiveEvent, collective_schedule, check_collective_order)


def _codes(findings):
    return {f.code for f in findings}


class TestDtypeLint:
    def test_silent_fp32_upcast_flagged(self):
        def amp_region(x):
            return x * np.float32(2.0)      # f32 constant promotes bf16
        f = lint_dtype_promotion(amp_region,
                                 jnp.ones((4,), jnp.bfloat16))
        assert "fp32-upcast" in _codes(f)
        assert any("bfloat16" in g.message and "float32" in g.message
                   for g in f)

    def test_clean_bf16_region_passes(self):
        def clean(x):
            y = x * jnp.bfloat16(2.0)
            return jnp.tanh(y) + x
        assert lint_dtype_promotion(clean,
                                    jnp.ones((4,), jnp.bfloat16)) == []

    def test_x64_creep_flagged(self):
        def creep(x):
            return x.astype(jnp.float64).sum()
        f = lint_dtype_promotion(creep, jnp.ones((4,), jnp.float32))
        assert "x64-creep" in _codes(f)

    def test_x64_input_flagged(self):
        f = lint_dtype_promotion(lambda x: x + 1,
                                 jnp.ones((4,), jnp.float64))
        assert "x64-input" in _codes(f)

    def test_ignore_prims_suppresses_intentional_cast(self):
        def loss_cast(x):
            return x.astype(jnp.float32).sum()
        assert "fp32-upcast" in _codes(
            lint_dtype_promotion(loss_cast, jnp.ones((4,), jnp.bfloat16)))
        assert lint_dtype_promotion(
            loss_cast, jnp.ones((4,), jnp.bfloat16),
            ignore_prims=("convert_element_type", "reduce_sum")) == []


class TestIterEqnsDedupe:
    def test_shared_subjaxpr_walked_once(self):
        # two pjit call sites of one jitted fn reference the SAME
        # ClosedJaxpr: the walk must yield its body once (r22 dedupe)
        from paddle_tpu.analysis.lints import iter_eqns
        inner = jax.jit(lambda x: jnp.sin(x) * 2.0)

        def outer(x):
            return inner(x) + inner(x)

        jaxpr = jax.make_jaxpr(outer)(jnp.float32(1.0))
        eqns = list(iter_eqns(jaxpr))
        assert len([e for e in eqns
                    if e.primitive.name == "pjit"]) == 2
        assert len([e for e in eqns
                    if e.primitive.name == "sin"]) == 1

    def test_lint_reports_shared_body_findings_once(self):
        inner = jax.jit(lambda x: x * np.float32(2.0))  # bf16 upcast
        x = jnp.ones((4,), jnp.bfloat16)
        once = lint_dtype_promotion(lambda v: inner(v), x)
        twice = lint_dtype_promotion(lambda v: inner(v) + inner(v), x)
        assert "fp32-upcast" in _codes(once)
        # each pjit CALL SITE is still its own finding, but the shared
        # body's convert_element_type must not double
        def body_hits(findings):
            return [g for g in findings
                    if "convert_element_type" in g.message]
        assert len(body_hits(once)) == 1
        assert len(body_hits(twice)) == 1


class TestTransferLint:
    def test_in_step_device_put_flagged(self):
        def step(x):
            return jax.device_put(x, jax.devices()[0]) + 1
        f = lint_transfers(step, jnp.ones((2,), jnp.float32))
        assert "in-step-transfer" in _codes(f)

    def test_clean_step_passes(self):
        def step(x):
            return (x * x).sum()
        assert lint_transfers(step, jnp.ones((2,), jnp.float32)) == []

    def test_allow_predicate_whitelists(self):
        def step(x):
            return jax.device_put(x, jax.devices()[0]) + 1
        assert lint_transfers(step, jnp.ones((2,), jnp.float32),
                              allow=lambda eqn: True) == []


class TestDonationLint:
    def test_unaliasable_donation_flagged(self):
        def step(x, y):                  # x donated but never aliased
            return (y.sum(),)
        f = lint_donation(step, jnp.ones((4,), jnp.float32),
                          jnp.ones((3,), jnp.float32),
                          donate_argnums=(0,))
        assert "donation-unaliased" in _codes(f)
        assert any("float32[4]" in g.message for g in f)

    def test_aliased_donation_passes(self):
        def step(x, y):
            return x + y
        assert lint_donation(step, jnp.ones((4,), jnp.float32),
                             jnp.ones((4,), jnp.float32),
                             donate_argnums=(0,)) == []

    def test_accepts_prelowered(self):
        def step(x, y):
            return (y.sum(),)
        lowered = jax.jit(step, donate_argnums=(0,)).lower(
            jnp.ones((4,), jnp.float32), jnp.ones((3,), jnp.float32))
        assert "donation-unaliased" in _codes(lint_donation(lowered))


class TestRecompileGuard:
    def test_violation_reports_offending_avals(self):
        def stepfn_lint_probe(x):
            return x * 2
        j = jax.jit(stepfn_lint_probe)
        with pytest.raises(RecompileError) as ei:
            with recompile_guard(max_programs=1,
                                 match="stepfn_lint_probe"):
                j(jnp.ones((2, 2), jnp.float32))
                j(jnp.ones((3, 3), jnp.float32))    # second program
        msg = str(ei.value)
        assert "max_programs=1" in msg
        # the offending avals are in the report
        assert "ShapedArray" in msg and "float32[3,3]" in msg

    def test_within_budget_passes_and_counts(self):
        def stepfn_lint_probe2(x):
            return x + 1
        j = jax.jit(stepfn_lint_probe2)
        with recompile_guard(max_programs=2,
                             match="stepfn_lint_probe2") as g:
            j(jnp.ones((2,), jnp.float32))
            j(jnp.ones((2,), jnp.float32))     # cache hit — no compile
            j(jnp.ones((5,), jnp.float32))
        assert g.count == 2

    def test_match_filters_unrelated_compiles(self):
        def other_probe(x):
            return x - 1
        with recompile_guard(max_programs=0, match="no_such_name") as g:
            jax.jit(other_probe)(jnp.ones((2,), jnp.float32))
        assert g.count == 0

    def test_generation_cache_builds_recorded(self):
        """inference.generation announces program-cache misses; the
        guard records them in .cache_builds (and a warm cache adds
        none).  Every announced key ends with the KV-layout/decode-
        precision fingerprint plus the model's weight-only state
        (ISSUE 7/11: toggling FLAGS_kv_cache_dtype, the pool geometry
        or FLAGS_weight_only_dtype mid-process — or packing the
        model's weights — re-keys, and thus rebuilds, every cached
        program)."""
        from paddle_tpu.inference.generation import _model_program_cache

        class M:
            pass

        m = M()
        with recompile_guard(max_programs=10, label="cache") as g:
            _model_program_cache(m, ("k", 1), lambda: "prog")
            _model_program_cache(m, ("k", 1), lambda: "prog")  # warm
            _model_program_cache(m, ("k", 2), lambda: "prog")
        assert [k[:2] for k in g.cache_builds] == [("k", 1), ("k", 2)]
        assert all(k[-2][0] == "kvcfg" for k in g.cache_builds)
        assert all(k[-1][0] == "wo" for k in g.cache_builds)


class TestCollectiveOrder:
    def _mesh(self):
        return Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp",))

    def test_schedule_extraction_in_program_order(self):
        from jax.experimental.shard_map import shard_map
        mesh = self._mesh()

        def f(x):
            s = jax.lax.psum(x, "dp")
            t = jax.lax.ppermute(
                x, "dp", [(i, (i + 1) % 4) for i in range(4)])
            return s + t

        fm = shard_map(f, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"))
        sched = collective_schedule(fm, jnp.ones((8,), jnp.float32))
        assert [e.kind for e in sched] == ["psum", "ppermute"]
        assert all(e.domain == ("dp",) for e in sched)

    def test_identical_schedules_pass(self):
        from jax.experimental.shard_map import shard_map
        mesh = self._mesh()
        fm = shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                       in_specs=P("dp"), out_specs=P())
        sched = collective_schedule(fm, jnp.ones((8,), jnp.float32))
        assert check_collective_order(
            {r: sched for r in range(4)}) == []

    def test_misordered_ranks_flagged_with_divergence_point(self):
        a = [CollectiveEvent("psum", (("dp",), (8,)), ("dp",)),
             CollectiveEvent("all_gather", (("dp",), (8,)), ("dp",))]
        f = check_collective_order({0: a, 1: list(reversed(a))})
        assert "collective-order-divergence" in _codes(f)
        assert f[0].op_index == 0           # diverges at the first eqn
        assert "psum" in f[0].message and "all_gather" in f[0].message

    def test_rank_skipping_a_collective_is_flagged(self):
        """The classic hang: one rank never enters the collective its
        peers are blocked in.  Every scheduled rank is presumed a
        participant of an axis-name domain, so an empty schedule
        diverges instead of silently passing."""
        ev = CollectiveEvent("psum", (("dp",), (8,)), ("dp",))
        f = check_collective_order({0: [ev], 1: []})
        assert "collective-order-divergence" in _codes(f)
        assert "sequence ends" in f[0].message

    def test_disjoint_domains_do_not_cross_talk(self):
        """Events in different ordering domains (different
        communicators) are not order-constrained against each other."""
        s0 = [CollectiveEvent("psum", ("k1",), ("dp",)),
              CollectiveEvent("psum", ("k2",), ("mp",))]
        s1 = [CollectiveEvent("psum", ("k2",), ("mp",)),
              CollectiveEvent("psum", ("k1",), ("dp",))]
        assert check_collective_order({0: s0, 1: s1}) == []


class _Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return nn.functional.gelu(self.fc(x))


def _engine(pp=2, vpp=1, depth=4):
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc, PipelineLayer)
    from paddle_tpu.parallel.pipeline import PipelineEngine
    d = 4
    pl = PipelineLayer([LayerDesc(_Block, d) for _ in range(depth)],
                       loss_fn=lambda o, y: ((o - y) ** 2).mean(),
                       num_stages=pp)
    return PipelineEngine(pl, num_stages=pp,
                          num_virtual_stages=vpp)


class TestPipelineScheduleChecker:
    @pytest.mark.parametrize("schedule,vpp", [
        ("1F1B", 1), ("FThenB", 1), ("ZB", 1), ("1F1B", 2),
        ("ZBVPP", 2),
    ])
    def test_shipped_schedules_verify_clean(self, schedule, vpp):
        eng = _engine(pp=2, vpp=vpp)
        assert eng.verify_schedule(4, schedule) is eng

    def test_misordered_backwards_caught_statically(self):
        """Swap two backward micro-batches on the LAST stage: its grad
        sends to stage 0 now cross micro order.  The host dispatcher
        happens to tolerate this (async inboxes), but rendezvous
        send/recv semantics — the NCCL-equivalent — would block stage 0
        on micro 0's grad while stage 1 blocks sending micro 1's: a
        deadlock.  verify_schedule proves it without running anything."""
        eng = _engine(pp=2)
        orders = eng._orders(4, "1F1B")
        s = 1
        b_pos = [k for k, (kind, _, _) in enumerate(orders[s])
                 if kind == "b"]
        i, j = b_pos[0], b_pos[1]
        orders[s][i], orders[s][j] = orders[s][j], orders[s][i]
        with pytest.raises(CollectiveOrderError) as ei:
            eng.verify_schedule(4, "1F1B", orders=orders)
        msg = str(ei.value)
        assert "collective-order-divergence" in msg
        assert "grad" in msg

    def test_missing_op_caught_as_divergence_or_stall(self):
        eng = _engine(pp=2)
        orders = eng._orders(4, "1F1B")
        # drop stage 1's last backward: stage 0 waits for a grad that
        # is never produced
        drop = next(k for k in range(len(orders[1]) - 1, -1, -1)
                    if orders[1][k][0] == "b")
        del orders[1][drop]
        with pytest.raises(CollectiveOrderError):
            eng.verify_schedule(4, "1F1B", orders=orders)

    def test_stalled_dependency_caught(self):
        eng = _engine(pp=2)
        orders = eng._orders(4, "1F1B")
        # reverse stage 0 entirely: its first op needs a grad that can
        # only exist after its own forwards — the dispatcher stalls
        orders[0] = list(reversed(orders[0]))
        with pytest.raises(CollectiveOrderError) as ei:
            eng.verify_schedule(4, "1F1B", orders=orders)
        assert "schedule-stall" in str(ei.value) \
            or "collective-order-divergence" in str(ei.value)

    def test_flag_gates_train_batch_verification(self):
        """FLAGS_check_collective_order wires verify_schedule into
        train_batch — exercised through a schedule the static checker
        rejects (unknown to _orders, so pass orders directly)."""
        eng = _engine(pp=2)
        # sanity: the flag-gated path runs the verifier on the real
        # schedule without error (no device work: m must divide batch)
        paddle.set_flags({"FLAGS_check_collective_order": True})
        try:
            eng.verify_schedule(4, "1F1B")
            x = paddle.to_tensor(
                np.random.RandomState(0).randn(4, 4).astype("float32"))
            y = paddle.to_tensor(
                np.random.RandomState(1).randn(4, 4).astype("float32"))
            loss = eng.train_batch([x, y], 2, schedule="1F1B")
            assert np.isfinite(float(np.asarray(loss.value)))
        finally:
            paddle.set_flags({"FLAGS_check_collective_order": False})


class TestTrainerIntegration:
    def _step(self, stage=0):
        from paddle_tpu.parallel import ShardedTrainStep
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                    ("dp", "sharding"))
        model = nn.Sequential(nn.Linear(6, 6), nn.Tanh(),
                              nn.Linear(6, 2))
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=model.parameters())
        loss = lambda o, y: ((o - y) ** 2).mean()   # noqa: E731
        return ShardedTrainStep(model, opt, mesh, loss_fn=loss,
                                sharding_stage=stage)

    def _batch(self):
        rng = np.random.RandomState(0)
        return (paddle.to_tensor(rng.randn(4, 6).astype("float32")),
                paddle.to_tensor(rng.randn(4, 2).astype("float32")))

    def test_collective_schedule_and_lint_on_clean_step(self):
        step = self._step()
        x, y = self._batch()
        sched = step.collective_schedule(x, y)
        assert isinstance(sched, list)      # 1-device mesh: no comm
        report = step.lint(x, y)
        assert report.get("transfers", []) == []
        # donated params/states/bufs must all be aliased by the module
        assert report.get("donation", []) == []

    def test_train_step_compiles_once_under_guard(self):
        """recompile_guard as the trainer's program-count assertion:
        repeat same-shape steps must reuse ONE compiled program."""
        step = self._step()
        x, y = self._batch()
        with recompile_guard(max_programs=1, match="step",
                             label="sharded train step") as g:
            step(x, y)
            step(x, y)
        assert g.count <= 1


class TestMaterializedLogitsLint:
    """lint_materialized_logits: the fused-CE contract checker — any
    [B, S, vocab] fp32 intermediate in a traced step is a full-logits
    materialization the chunked loss exists to eliminate."""

    V = 512

    def test_planted_defect_old_compute_loss(self):
        """The pre-dedup causal-LM loss (fp32 log_softmax over the full
        [B, S-1, V] logits) MUST trip the lint."""
        lbl = jnp.zeros((2, 16), jnp.int32)

        def legacy_loss(lg):
            lgf = lg[:, :-1].astype(jnp.float32)
            tgt = lbl[:, 1:]
            logp = jax.nn.log_softmax(lgf, axis=-1)
            return -jnp.mean(jnp.take_along_axis(
                logp, tgt[..., None], axis=-1)[..., 0])

        lg = jnp.zeros((2, 16, self.V), jnp.bfloat16)
        findings = lint_materialized_logits(legacy_loss, lg,
                                            vocab_size=self.V)
        assert findings and _codes(findings) == {"materialized-logits"}
        assert any("(2, 15, 512)" in str(f.detail) for f in findings)

    def test_fused_chunked_loss_is_clean(self):
        """The chunked fused loss's per-chunk [chunk, V] slices are 2-D
        and must stay below the radar."""
        from paddle_tpu.ops.pallas.fused_cross_entropy import (
            fused_linear_cross_entropy)
        lbl = jnp.zeros((32,), jnp.int32)

        def fused(h, w):
            return fused_linear_cross_entropy(h, w, lbl, chunk_rows=8)

        h = jnp.zeros((32, 64), jnp.float32)
        w = jnp.zeros((64, self.V), jnp.float32)
        assert lint_materialized_logits(fused, h, w,
                                        vocab_size=self.V) == []
        # the gradient pass does its vocab work per chunk too
        assert lint_materialized_logits(
            jax.grad(lambda h, w: fused(h, w), argnums=(0, 1)), h, w,
            vocab_size=self.V) == []

    def test_min_rows_catches_flattened_2d(self):
        """min_rows flags a flattened [B*S, V] fp32 buffer that the 3-D
        rule alone would miss, without flagging small chunks."""
        def flat(lg):
            return jnp.sum(jax.nn.log_softmax(
                lg.astype(jnp.float32), axis=-1))

        lg = jnp.zeros((32, self.V), jnp.bfloat16)
        assert lint_materialized_logits(flat, lg,
                                        vocab_size=self.V) == []
        findings = lint_materialized_logits(flat, lg, vocab_size=self.V,
                                            min_rows=32)
        assert findings and _codes(findings) == {"materialized-logits"}

    def test_weight_grad_shape_not_flagged(self):
        # [H, V] fp32 lm-head gradients share the vocab last dim but are
        # 2-D below min_rows — not a logits materialization
        def wgrad(h, d):
            return jnp.dot(h.T, d, preferred_element_type=jnp.float32)

        h = jnp.zeros((32, 64), jnp.bfloat16)
        d = jnp.zeros((32, self.V), jnp.bfloat16)
        assert lint_materialized_logits(wgrad, h, d,
                                        vocab_size=self.V) == []

    def test_recurses_into_scan(self):
        lbl = jnp.zeros((4, 2, 16), jnp.int32)

        def stepped(lgs):
            def body(c, xs):
                lg, tg = xs
                logp = jax.nn.log_softmax(lg.astype(jnp.float32),
                                          axis=-1)
                return c - jnp.mean(jnp.take_along_axis(
                    logp, tg[..., None], axis=-1)), None
            out, _ = jax.lax.scan(body, jnp.float32(0), (lgs, lbl))
            return out

        lgs = jnp.zeros((4, 2, 16, self.V), jnp.bfloat16)
        findings = lint_materialized_logits(stepped, lgs,
                                            vocab_size=self.V)
        assert findings, "per-iteration [B, S, V] fp32 must be flagged"
