"""Pallas kernel correctness vs XLA reference (interpret mode on CPU).

Reference test pattern: OpTest numeric checks; here compiled-kernel vs
reference-impl equivalence (SURVEY §4: compiled-vs-eager checks).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.rms_norm import (rms_norm as pallas_rms_norm,
                                            fused_add_rms_norm
                                            as pallas_add_rms_norm)
from paddle_tpu.ops.pallas.rope import rope_apply
from paddle_tpu.ops import (xla_attention, xla_rms_norm,
                            xla_fused_add_rms_norm, apply_rope,
                            rope_cos_sin)


_rng = np.random.RandomState(0)


def r(*shape):
    # one stream, drawn sequentially — q/k/v must be DISTINCT arrays so
    # operand swaps / transposition bugs cannot cancel out
    return jnp.asarray(_rng.randn(*shape).astype(np.float32))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward(self, causal):
        q, k, v = r(2, 256, 2, 128), r(2, 256, 2, 128), r(2, 256, 2, 128)
        out = flash_attention(q, k, v, causal=causal, block_q=128,
                              block_k=128)
        ref = xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward(self, causal):
        q, k, v = r(1, 256, 2, 128), r(1, 256, 2, 128), r(1, 256, 2, 128)

        def loss_p(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=128, block_k=128) ** 2)

        def loss_x(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

        gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa(self, causal):
        q = r(1, 256, 4, 128)
        k = r(1, 256, 2, 128)
        v = r(1, 256, 2, 128)
        out = flash_attention(q, k, v, causal=causal, block_q=128,
                              block_k=128)
        ref = xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa_backward(self, causal):
        # dk/dv must accumulate over the query-head group in-kernel
        q = r(1, 128, 4, 128)
        k = r(1, 128, 2, 128)
        v = r(1, 128, 2, 128)

        def loss_p(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=64, block_k=64) ** 2)

        def loss_x(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

        gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    def test_mqa_head_dim_64(self):
        # MQA (1 kv head) + head_dim 64 — previously fell back to XLA
        q = r(1, 128, 4, 64)
        k = r(1, 128, 1, 64)
        v = r(1, 128, 1, 64)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_blocked_path_matches_small(self, causal, monkeypatch):
        # force the long-context blocked kernels and check fwd+bwd against
        # the resident-KV path the other tests exercise
        import paddle_tpu.ops.pallas.flash_attention as fa
        q = r(1, 256, 4, 128)
        k = r(1, 256, 2, 128)
        v = r(1, 256, 2, 128)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=64, block_k=64) ** 2)

        o_small = flash_attention(q, k, v, causal=causal, block_q=64,
                                  block_k=64)
        g_small = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setattr(fa, "SMALL_KV_BYTES", 0)
        o_blk = flash_attention(q, k, v, causal=causal, block_q=64,
                                block_k=64)
        g_blk = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(o_small), np.asarray(o_blk),
                                   atol=1e-5, rtol=1e-5)
        for a, b in zip(g_small, g_blk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_auto_block_pick(self):
        # no explicit blocks: kernel picks pow2 divisors
        q, k, v = r(1, 384, 2, 128), r(1, 384, 2, 128), r(1, 384, 2, 128)
        out = flash_attention(q, k, v, causal=True)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_cross_attention_lengths(self):
        q = r(1, 128, 2, 128)
        k = r(1, 384, 2, 128)
        v = r(1, 384, 2, 128)
        out = flash_attention(q, k, v, block_q=128, block_k=128)
        ref = xla_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("bq,bk", [(256, 128), (128, 256)])
    def test_causal_mixed_blocks(self, bq, bk):
        # regression: causal K-block bound must cover the block's LAST row
        q, k, v = r(1, 512, 2, 128), r(1, 512, 2, 128), r(1, 512, 2, 128)
        out = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_cross_attention_rejected(self):
        # top-left vs bottom-right alignment would silently diverge
        q = r(1, 128, 2, 128)
        k = r(1, 384, 2, 128)
        with pytest.raises(ValueError):
            flash_attention(q, k, k, causal=True, block_q=128, block_k=128)

    def test_unsupported_shape_raises(self):
        q = r(1, 100, 2, 64)
        with pytest.raises(ValueError):
            flash_attention(q, q, q, block_q=128, block_k=128)


class TestRMSNorm:
    def test_forward(self):
        x = r(64, 256)
        w = r(256)
        out = pallas_rms_norm(x, w)
        ref = xla_rms_norm(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_forward_3d(self):
        x = r(2, 32, 256)
        w = r(256)
        out = pallas_rms_norm(x, w)
        ref = xla_rms_norm(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_backward(self):
        x = r(32, 256)
        w = r(256)

        def lp(x, w):
            return jnp.sum(pallas_rms_norm(x, w) ** 2)

        def lx(x, w):
            return jnp.sum(xla_rms_norm(x, w) ** 2)

        gp = jax.grad(lp, argnums=(0, 1))(x, w)
        gx = jax.grad(lx, argnums=(0, 1))(x, w)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestFusedAddRMSNorm:
    """Residual-add + RMSNorm fused into one pass: the residual output
    must be BIT-identical to the unfused `x + y` (it feeds the next
    block), the norm to fp32 tolerance, and the backward must fuse the
    residual cotangent into dx == dy."""

    def test_forward(self):
        x, y, w = r(32, 256), r(32, 256), r(256)
        r1, o1 = pallas_add_rms_norm(x, y, w)
        r2, o2 = xla_fused_add_rms_norm(x, y, w)
        assert (np.asarray(r1) == np.asarray(r2)).all()
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   atol=1e-5, rtol=1e-5)

    def test_forward_3d(self):
        x, y, w = r(2, 16, 256), r(2, 16, 256), r(256)
        r1, o1 = pallas_add_rms_norm(x, y, w)
        r2, o2 = xla_fused_add_rms_norm(x, y, w)
        assert (np.asarray(r1) == np.asarray(r2)).all()
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   atol=1e-5, rtol=1e-5)

    def test_backward_both_outputs(self):
        # cotangents flow through BOTH outputs (the residual feeds the
        # next block, the norm feeds the MLP)
        x, y, w = r(32, 256), r(32, 256), r(256)

        def lp(x, y, w):
            res, out = pallas_add_rms_norm(x, y, w)
            return jnp.sum(out ** 2) + 0.3 * jnp.sum(res)

        def lx(x, y, w):
            res, out = xla_fused_add_rms_norm(x, y, w)
            return jnp.sum(out ** 2) + 0.3 * jnp.sum(res)

        gp = jax.grad(lp, argnums=(0, 1, 2))(x, y, w)
        gx = jax.grad(lx, argnums=(0, 1, 2))(x, y, w)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_shape_mismatch_rejected(self):
        with pytest.raises(ValueError):
            pallas_add_rms_norm(r(32, 256), r(16, 256), r(256))


class TestRope:
    """Fused rope application: one VMEM pass rotates q AND k; the VJP is
    the same kernel with sin negated (orthogonal rotation)."""

    def _qk(self, b=2, s=16, h=4, hk=2, d=8):
        return r(b, s, h, d), r(b, s, hk, d)

    def test_forward_matches_xla(self):
        q, k = self._qk()
        cos, sin = rope_cos_sin(16, 8)
        oq, ok = rope_apply(q, k, cos, sin)
        # the XLA reference path, explicitly (apply_rope would dispatch
        # to the kernel on TPU)
        from paddle_tpu.ops import _rotate_half
        c4, s4 = cos[None, :, None, :], sin[None, :, None, :]
        rq = q * c4 + _rotate_half(q) * s4
        rk = k * c4 + _rotate_half(k) * s4
        np.testing.assert_allclose(np.asarray(oq), np.asarray(rq),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ok), np.asarray(rk),
                                   atol=1e-5, rtol=1e-5)

    def test_forward_batched_positions(self):
        # [b, s, d] cos/sin — the per-slot position form decode uses
        q, k = self._qk()
        pos = jnp.asarray(_rng.randint(0, 16, (2, 1)).astype(np.int32)) \
            + jnp.arange(16, dtype=jnp.int32)[None]
        cos, sin = rope_cos_sin(16, 8, position_ids=pos)
        oq, ok = rope_apply(q, k, cos, sin)
        rq, rk = apply_rope(q, k, cos, sin)
        np.testing.assert_allclose(np.asarray(oq), np.asarray(rq),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ok), np.asarray(rk),
                                   atol=1e-5, rtol=1e-5)

    def test_backward_matches_xla(self):
        q, k = self._qk()
        cos, sin = rope_cos_sin(16, 8)

        def lp(q, k):
            oq, ok = rope_apply(q, k, cos, sin)
            return jnp.sum(oq ** 2) + jnp.sum(ok ** 3)

        def lx(q, k):
            from paddle_tpu.ops import _rotate_half
            c4, s4 = cos[None, :, None, :], sin[None, :, None, :]
            oq = q * c4 + _rotate_half(q) * s4
            ok = k * c4 + _rotate_half(k) * s4
            return jnp.sum(oq ** 2) + jnp.sum(ok ** 3)

        gp = jax.grad(lp, argnums=(0, 1))(q, k)
        gx = jax.grad(lx, argnums=(0, 1))(q, k)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_backward_asymmetric_sin_halves(self):
        # regression: the half-split adjoint swaps which sin half
        # multiplies which gradient half (dx1 = g1·c1 + g2·s2, dx2 =
        # g2·c2 − g1·s1) — plain neg_sin alone is only correct when the
        # cache duplicates sin across halves (the rope_cos_sin layout);
        # a user-supplied cache with DIFFERING halves must still get
        # true gradients through ops.apply_rope on TPU
        q, k = self._qk()
        cos = jnp.asarray(_rng.randn(16, 8).astype(np.float32))
        sin = jnp.asarray(_rng.randn(16, 8).astype(np.float32))

        def lp(q, k):
            oq, ok = rope_apply(q, k, cos, sin)
            return jnp.sum(oq ** 2) + jnp.sum(ok ** 3)

        def lx(q, k):
            from paddle_tpu.ops import _rotate_half
            c4, s4 = cos[None, :, None, :], sin[None, :, None, :]
            oq = q * c4 + _rotate_half(q) * s4
            ok = k * c4 + _rotate_half(k) * s4
            return jnp.sum(oq ** 2) + jnp.sum(ok ** 3)

        gp = jax.grad(lp, argnums=(0, 1))(q, k)
        gx = jax.grad(lx, argnums=(0, 1))(q, k)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_tiny_rows_rejected(self):
        # batch*seq below the sublane granule → ValueError so the ops
        # dispatch falls back to XLA (the decode path)
        q, k = self._qk(b=1, s=4)
        cos, sin = rope_cos_sin(4, 8)
        with pytest.raises(ValueError):
            rope_apply(q, k, cos, sin)

    def test_odd_head_dim_rejected(self):
        q, k = r(2, 16, 4, 7), r(2, 16, 2, 7)
        cos = sin = jnp.zeros((16, 7), jnp.float32)
        with pytest.raises(ValueError):
            rope_apply(q, k, cos, sin)


class TestPagedAttention:
    """Paged-attention kernel (scalar-prefetch page gather) vs the
    take-gather jnp twin (ops.xla_paged_attention)."""

    def _pool(self, P=10, ps=8, L=2, n_kv=2, d=16, quant=False):
        if quant:
            kp = jnp.asarray(_rng.randint(-127, 128,
                                          (P, ps, L, n_kv, d)), jnp.int8)
            vp = jnp.asarray(_rng.randint(-127, 128,
                                          (P, ps, L, n_kv, d)), jnp.int8)
            ks = jnp.asarray(_rng.rand(P, L, n_kv) * 0.05 + 0.01,
                             jnp.float32)
            vs = jnp.asarray(_rng.rand(P, L, n_kv) * 0.05 + 0.01,
                             jnp.float32)
            return kp, vp, ks, vs
        return r(P, ps, L, n_kv, d), r(P, ps, L, n_kv, d), None, None

    @pytest.mark.parametrize("C,h", [(1, 4), (4, 8), (4, 2)])
    def test_forward_vs_twin(self, C, h):
        from paddle_tpu.ops.pallas.paged_attention import paged_attention
        from paddle_tpu.ops import xla_paged_attention
        B, P, ps, P_slot, L, n_kv, d = 3, 10, 8, 3, 2, 2, 16
        kp, vp, _, _ = self._pool(P, ps, L, n_kv, d)
        q = r(B, C, h, d)
        pt = jnp.asarray(_rng.permutation(P - 1)[:B * P_slot]
                         .reshape(B, P_slot) + 1, jnp.int32)
        pos = jnp.asarray([0, 5, 13], jnp.int32)
        for li in range(L):
            out = paged_attention(q, kp, vp, pt, pos, li,
                                  interpret=True)
            ref = xla_paged_attention(q, kp, vp, pt, pos, li)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, rtol=2e-5)

    def test_int8_dequant_fused(self):
        from paddle_tpu.ops.pallas.paged_attention import paged_attention
        from paddle_tpu.ops import xla_paged_attention
        B, P, ps, P_slot, L, n_kv, d = 2, 8, 8, 3, 2, 2, 16
        kp, vp, ks, vs = self._pool(P, ps, L, n_kv, d, quant=True)
        q = r(B, 4, 4, d)
        pt = jnp.asarray(_rng.permutation(P - 1)[:B * P_slot]
                         .reshape(B, P_slot) + 1, jnp.int32)
        pos = jnp.asarray([3, 11], jnp.int32)
        out = paged_attention(q, kp, vp, pt, pos, 1, ks, vs,
                              interpret=True)
        ref = xla_paged_attention(q, kp, vp, pt, pos, 1, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_int8_needs_scales(self):
        from paddle_tpu.ops.pallas.paged_attention import paged_attention
        kp, vp, _, _ = self._pool(quant=True)
        q = r(2, 1, 4, 16)
        pt = jnp.zeros((2, 3), jnp.int32)
        pos = jnp.zeros((2,), jnp.int32)
        with pytest.raises(ValueError, match="scale"):
            paged_attention(q, kp, vp, pt, pos, 0, interpret=True)

    def test_gqa_heads_must_divide(self):
        from paddle_tpu.ops.pallas.paged_attention import paged_attention
        kp, vp, _, _ = self._pool(n_kv=2)
        q = r(2, 1, 3, 16)      # 3 heads over 2 kv heads
        pt = jnp.zeros((2, 3), jnp.int32)
        pos = jnp.zeros((2,), jnp.int32)
        with pytest.raises(ValueError, match="multiple"):
            paged_attention(q, kp, vp, pt, pos, 0, interpret=True)


class TestPagedKVUpdate:
    """Windowed page write (ops.paged_kv_update): row-exact vs a dense
    reference, untouched pages byte-identical, int8 requant coherent."""

    def test_rows_land_exactly(self):
        from paddle_tpu.ops import paged_kv_update
        B, C, P, ps, P_slot, L, n_kv, d = 2, 3, 12, 4, 5, 2, 2, 8
        kp = jnp.zeros((P, ps, L, n_kv, d), jnp.float32)
        vp = jnp.zeros((P, ps, L, n_kv, d), jnp.float32)
        pt = jnp.asarray(_rng.permutation(P - 1)[:B * P_slot]
                         .reshape(B, P_slot) + 1, jnp.int32)
        pos = jnp.asarray([2, 6], jnp.int32)
        kn, vn = r(B, C, n_kv, d), r(B, C, n_kv, d)
        kp2, vp2, _, _ = paged_kv_update(kp, vp, None, None, pt, pos,
                                         kn, vn, layer=1)
        # logical view must hold exactly the written rows
        lg = np.asarray(jnp.take(kp2[:, :, 1], pt, axis=0)
                        .reshape(B, P_slot * ps, n_kv, d))
        for b in range(B):
            p0 = int(pos[b])
            np.testing.assert_array_equal(lg[b, p0:p0 + C],
                                          np.asarray(kn[b]))
        # layer 0 untouched
        assert not np.asarray(kp2[:, :, 0]).any()

    def test_untouched_pages_keep_bytes(self):
        from paddle_tpu.ops import paged_kv_update
        B, C, P, ps, P_slot, L, n_kv, d = 1, 2, 8, 4, 4, 1, 2, 8
        kp = r(P, ps, L, n_kv, d)
        vp = r(P, ps, L, n_kv, d)
        pt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        pos = jnp.asarray([5], jnp.int32)     # rows 5,6 → page 1 only
        kn, vn = r(B, C, n_kv, d), r(B, C, n_kv, d)
        kp2, _, _, _ = paged_kv_update(kp, vp, None, None, pt, pos,
                                       kn, vn, layer=0)
        # pages 3,4 (and every unmapped page) bit-identical
        for page in (3, 4, 5, 6, 7):
            np.testing.assert_array_equal(np.asarray(kp2[page]),
                                          np.asarray(kp[page]))

    def test_int8_requant_roundtrip(self):
        from paddle_tpu.ops import paged_kv_update, xla_paged_attention
        B, C, P, ps, P_slot, L, n_kv, d = 1, 4, 8, 4, 4, 1, 2, 8
        kp = jnp.zeros((P, ps, L, n_kv, d), jnp.int8)
        vp = jnp.zeros((P, ps, L, n_kv, d), jnp.int8)
        ks = jnp.ones((P, L, n_kv), jnp.float32)
        vs = jnp.ones((P, L, n_kv), jnp.float32)
        pt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        kn, vn = r(B, C, n_kv, d), r(B, C, n_kv, d)
        kp, vp, ks, vs = paged_kv_update(kp, vp, ks, vs, pt,
                                         jnp.asarray([0], jnp.int32),
                                         kn, vn, layer=0)
        lg = np.asarray(jnp.take(kp[:, :, 0], pt, axis=0)
                        .astype(np.float32)
                        * np.asarray(jnp.take(ks[:, 0], pt, axis=0)
                                     )[:, :, None, :, None]) \
            .reshape(B, P_slot * ps, n_kv, d)
        np.testing.assert_allclose(lg[0, :C], np.asarray(kn[0]),
                                   atol=0.03, rtol=0.05)
