"""Pallas kernel correctness vs XLA reference (interpret mode on CPU).

Reference test pattern: OpTest numeric checks; here compiled-kernel vs
reference-impl equivalence (SURVEY §4: compiled-vs-eager checks).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.rms_norm import rms_norm as pallas_rms_norm
from paddle_tpu.ops import xla_attention, xla_rms_norm


_rng = np.random.RandomState(0)


def r(*shape):
    # one stream, drawn sequentially — q/k/v must be DISTINCT arrays so
    # operand swaps / transposition bugs cannot cancel out
    return jnp.asarray(_rng.randn(*shape).astype(np.float32))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward(self, causal):
        q, k, v = r(2, 256, 2, 128), r(2, 256, 2, 128), r(2, 256, 2, 128)
        out = flash_attention(q, k, v, causal=causal, block_q=128,
                              block_k=128)
        ref = xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward(self, causal):
        q, k, v = r(1, 256, 2, 128), r(1, 256, 2, 128), r(1, 256, 2, 128)

        def loss_p(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=128, block_k=128) ** 2)

        def loss_x(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

        gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa(self, causal):
        q = r(1, 256, 4, 128)
        k = r(1, 256, 2, 128)
        v = r(1, 256, 2, 128)
        out = flash_attention(q, k, v, causal=causal, block_q=128,
                              block_k=128)
        ref = xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa_backward(self, causal):
        # dk/dv must accumulate over the query-head group in-kernel
        q = r(1, 128, 4, 128)
        k = r(1, 128, 2, 128)
        v = r(1, 128, 2, 128)

        def loss_p(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=64, block_k=64) ** 2)

        def loss_x(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

        gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    def test_mqa_head_dim_64(self):
        # MQA (1 kv head) + head_dim 64 — previously fell back to XLA
        q = r(1, 128, 4, 64)
        k = r(1, 128, 1, 64)
        v = r(1, 128, 1, 64)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_blocked_path_matches_small(self, causal, monkeypatch):
        # force the long-context blocked kernels and check fwd+bwd against
        # the resident-KV path the other tests exercise
        import paddle_tpu.ops.pallas.flash_attention as fa
        q = r(1, 256, 4, 128)
        k = r(1, 256, 2, 128)
        v = r(1, 256, 2, 128)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=64, block_k=64) ** 2)

        o_small = flash_attention(q, k, v, causal=causal, block_q=64,
                                  block_k=64)
        g_small = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setattr(fa, "SMALL_KV_BYTES", 0)
        o_blk = flash_attention(q, k, v, causal=causal, block_q=64,
                                block_k=64)
        g_blk = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(o_small), np.asarray(o_blk),
                                   atol=1e-5, rtol=1e-5)
        for a, b in zip(g_small, g_blk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_auto_block_pick(self):
        # no explicit blocks: kernel picks pow2 divisors
        q, k, v = r(1, 384, 2, 128), r(1, 384, 2, 128), r(1, 384, 2, 128)
        out = flash_attention(q, k, v, causal=True)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_cross_attention_lengths(self):
        q = r(1, 128, 2, 128)
        k = r(1, 384, 2, 128)
        v = r(1, 384, 2, 128)
        out = flash_attention(q, k, v, block_q=128, block_k=128)
        ref = xla_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("bq,bk", [(256, 128), (128, 256)])
    def test_causal_mixed_blocks(self, bq, bk):
        # regression: causal K-block bound must cover the block's LAST row
        q, k, v = r(1, 512, 2, 128), r(1, 512, 2, 128), r(1, 512, 2, 128)
        out = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_cross_attention_rejected(self):
        # top-left vs bottom-right alignment would silently diverge
        q = r(1, 128, 2, 128)
        k = r(1, 384, 2, 128)
        with pytest.raises(ValueError):
            flash_attention(q, k, k, causal=True, block_q=128, block_k=128)

    def test_unsupported_shape_raises(self):
        q = r(1, 100, 2, 64)
        with pytest.raises(ValueError):
            flash_attention(q, q, q, block_q=128, block_k=128)


class TestRMSNorm:
    def test_forward(self):
        x = r(64, 256)
        w = r(256)
        out = pallas_rms_norm(x, w)
        ref = xla_rms_norm(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_forward_3d(self):
        x = r(2, 32, 256)
        w = r(256)
        out = pallas_rms_norm(x, w)
        ref = xla_rms_norm(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_backward(self):
        x = r(32, 256)
        w = r(256)

        def lp(x, w):
            return jnp.sum(pallas_rms_norm(x, w) ** 2)

        def lx(x, w):
            return jnp.sum(xla_rms_norm(x, w) ** 2)

        gp = jax.grad(lp, argnums=(0, 1))(x, w)
        gx = jax.grad(lx, argnums=(0, 1))(x, w)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)
